"""Autoregressive decoding with a static-shape KV cache (VERDICT r4 #3).

Every reference zoo family ships usable inference
(``ObjectDetector.predictImageSet``, ``Recommender.recommendForUser`` —
zoo/.../models/image/objectdetection/ObjectDetector.scala,
recommendation/Recommender.scala:36-86); the LM flagship's analogue is
``TransformerLM.generate``: prefill the prompt in ONE batched causal
forward (MXU-sized matmuls, the pallas path), then decode token-by-token
against per-layer K/V caches under one ``jit`` — a ``lax.scan`` over
steps with static shapes (cache length = prompt + max_new), so the whole
generation is a single compiled computation with no per-token dispatch.

The decode math mirrors ``TransformerLM.build_model`` exactly (pre-norm
blocks, gelu MLP or Switch-MoE sublayer, final LN + lm_head); the
prefix-consistency tests in ``tests/test_generate.py`` pin the two paths
together position-by-position.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..ops.attention import attention_bhsd
from ..parallel.expert import MoEParams, expert_capacity, switch_moe
from ..pipeline.api.keras.activations import get as get_activation

_gelu = get_activation("gelu")


def _block_params(params, i, moe):
    """Collect layer-i block params from the TransformerLM param tree."""
    bp = {"ln_a": params[f"ln_attn_{i}"], "attn": params[f"attn_{i}"],
          "ln_m": params[f"ln_mlp_{i}"]}
    if moe:
        bp["moe"] = params[f"moe_{i}"]
    else:
        bp["up"] = params[f"mlp_up_{i}"]
        bp["down"] = params[f"mlp_down_{i}"]
    return bp


def _layer_norm(p, x, eps=1e-5):
    mean = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * p["gamma"] + p["beta"]


def _mlp(bp, f):
    if "moe" in bp:
        d = f.shape[-1]
        flat = f.reshape(-1, d)
        p = MoEParams(**{k: bp["moe"][k] for k in MoEParams._fields})
        # decode runs DROP-FREE (capacity = token count): with a handful
        # of tokens per step, train-time capacity limits would silently
        # zero sublayer outputs and degrade generation for nothing — the
        # Switch recipe raises capacity at inference
        out, _ = switch_moe(flat, p, capacity=flat.shape[0])
        return out.reshape(f.shape)
    return _gelu(f @ bp["up"]["W"] + bp["up"]["b"]) @ bp["down"]["W"] \
        + bp["down"]["b"]


def _head_logits(params, hidden):
    """Final LN + lm_head over a (b, d) hidden state — the one logits
    head both the sampling and beam builders share."""
    x = _layer_norm(params["ln_final"], hidden)
    return x @ params["lm_head"]["W"] + params["lm_head"]["b"]


def _embed_token(params, tok, pos):
    """Token + positional embedding for one decode step (tok: (rows,)
    int ids; pos: scalar shared position, or (rows,) per-row positions
    for ragged prompts)."""
    emb = jnp.take(params["tok_embed"]["embeddings"],
                   tok.astype(jnp.int32), axis=0)
    table = params["pos_embed"]["table"]
    if jnp.ndim(pos) == 0:
        p = lax.dynamic_index_in_dim(table, pos, keepdims=False)
    else:
        p = jnp.take(table, pos, axis=0)  # (rows, d)
    return emb + p.astype(emb.dtype)


def _prefill(params, hyper, prompt, cache_len):
    """Batched prompt pass: causal attention over the whole prompt in one
    forward (the training-shaped compute), writing each layer's K/V into
    position [0, s_p) of a (b, heads, cache_len, d) cache and returning
    the last position's hidden state."""
    n_layers, moe_every = hyper["n_layers"], hyper["moe_every"]
    s_p = prompt.shape[1]
    x = jnp.take(params["tok_embed"]["embeddings"],
                 prompt.astype(jnp.int32), axis=0)
    x = x + params["pos_embed"]["table"][:s_p].astype(
        x.dtype)
    caches = []
    for i in range(n_layers):
        moe = bool(moe_every) and (i + 1) % moe_every == 0
        bp = _block_params(params, i, moe)
        a = _layer_norm(bp["ln_a"], x)
        q = jnp.einsum("bse,ehd->bhsd", a, bp["attn"]["Wq"])
        k = jnp.einsum("bse,ehd->bhsd", a, bp["attn"]["Wk"])
        v = jnp.einsum("bse,ehd->bhsd", a, bp["attn"]["Wv"])
        o = attention_bhsd(q, k, v, causal=True)
        x = x + jnp.einsum("bhsd,hde->bse", o, bp["attn"]["Wo"])
        f = _layer_norm(bp["ln_m"], x)
        x = x + _mlp(bp, f)
        pad = [(0, 0), (0, 0), (0, cache_len - s_p), (0, 0)]
        caches.append((jnp.pad(k, pad), jnp.pad(v, pad)))
    return x, caches


def _cache_write(c, x_new, pos):
    """Write one step's (b, h, d) k or v into the (b, h, t, d) cache at
    ``pos`` — a shared scalar position, or (b,) per-row positions for
    ragged prompts."""
    xn = x_new[:, :, None, :]
    if jnp.ndim(pos) == 0:
        return lax.dynamic_update_slice_in_dim(c, xn, pos, axis=2)
    return jax.vmap(
        lambda cb, xb, pb: lax.dynamic_update_slice_in_dim(
            cb, xb, pb, axis=1))(c, xn, pos)


def _decode_step(params, hyper, caches, x_tok, pos):
    """One cached decode step: ``x_tok`` is the (b, d_model) embedding of
    the current token (token + positional), ``pos`` its position —
    scalar, or (b,) per-row for ragged prompts.
    Returns (logits, updated caches)."""
    n_layers, moe_every = hyper["n_layers"], hyper["moe_every"]
    n_heads = hyper["n_heads"]
    x = x_tok
    new_caches = []
    for i in range(n_layers):
        moe = bool(moe_every) and (i + 1) % moe_every == 0
        bp = _block_params(params, i, moe)
        ck, cv = caches[i]
        a = _layer_norm(bp["ln_a"], x)
        q = jnp.einsum("be,ehd->bhd", a, bp["attn"]["Wq"])
        k = jnp.einsum("be,ehd->bhd", a, bp["attn"]["Wk"])
        v = jnp.einsum("be,ehd->bhd", a, bp["attn"]["Wv"])
        ck = _cache_write(ck, k, pos)
        cv = _cache_write(cv, v, pos)
        d = q.shape[-1]
        scores = jnp.einsum("bhd,bhtd->bht", q, ck) / math.sqrt(d)
        t = ck.shape[2]
        posv = jnp.broadcast_to(pos, (ck.shape[0],))
        valid = jnp.arange(t)[None, None, :] <= posv[:, None, None]
        scores = jnp.where(valid, scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        o = jnp.einsum("bht,bhtd->bhd", probs.astype(cv.dtype), cv)
        x = x + jnp.einsum("bhd,hde->be", o, bp["attn"]["Wo"])
        f = _layer_norm(bp["ln_m"], x)
        x = x + _mlp(bp, f)
        new_caches.append((ck, cv))
    return _head_logits(params, x), new_caches


def _decode_window(params, hyper, caches, x_toks, pos):
    """k-query cached decode — the speculative-verify compute.

    ``x_toks`` is (b, k, d_model): the embeddings of k consecutive
    tokens per row, whose positions are ``pos + j`` (``pos``: (b,)).
    Each token's K/V is written at its OWN clamped position (per-entry
    ``min(pos + j, t - 1)``, never a block write — a block's clamp
    would SHIFT early entries and corrupt live cache lines), then all
    k queries attend in one batched einsum with a per-query causal
    mask.  Returns ((b, k, V) logits, updated caches).

    Numerics note: the k-query matmul shapes differ from
    :func:`_decode_step`'s single-query shapes, so logits agree with k
    sequential steps to ~1 ulp, not bit-for-bit — which is why the
    speculative plan derives each window's FIRST token from the exact
    single-query body and uses this window only to certify draft
    proposals (decode.py §speculative)."""
    n_layers, moe_every = hyper["n_layers"], hyper["moe_every"]
    k = x_toks.shape[1]
    t = caches[0][0].shape[2]
    x = x_toks
    qpos = jnp.minimum(pos[:, None] + jnp.arange(k)[None, :], t - 1)
    new_caches = []
    for i in range(n_layers):
        moe = bool(moe_every) and (i + 1) % moe_every == 0
        bp = _block_params(params, i, moe)
        ck, cv = caches[i]
        a = _layer_norm(bp["ln_a"], x)
        q = jnp.einsum("bke,ehd->bhkd", a, bp["attn"]["Wq"])
        kk = jnp.einsum("bke,ehd->bhkd", a, bp["attn"]["Wk"])
        vv = jnp.einsum("bke,ehd->bhkd", a, bp["attn"]["Wv"])
        for j in range(k):
            ck = _cache_write(ck, kk[:, :, j], qpos[:, j])
            cv = _cache_write(cv, vv[:, :, j], qpos[:, j])
        d = q.shape[-1]
        scores = jnp.einsum("bhkd,bhtd->bhkt", q, ck) / math.sqrt(d)
        valid = (jnp.arange(t)[None, None, None, :]
                 <= qpos[:, None, :, None])
        scores = jnp.where(valid, scores, -1e30)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        o = jnp.einsum("bhkt,bhtd->bhkd", probs.astype(cv.dtype), cv)
        x = x + jnp.einsum("bhkd,hde->bke", o, bp["attn"]["Wo"])
        f = _layer_norm(bp["ln_m"], x)
        x = x + _mlp(bp, f)
        new_caches.append((ck, cv))
    b = x.shape[0]
    logits = _head_logits(params, x.reshape(b * k, -1))
    return logits.reshape(b, k, -1), new_caches


def _prefill_ext(params, hyper, tail, prefix_kv, p_len: int):
    """Prefix-conditioned tail prefill — the prefix-KV-pool admit
    compute.  ``tail`` is (1, s_t) token ids occupying positions
    ``[p_len, p_len + s_t)``; ``prefix_kv`` the per-layer (k, v)
    blocks of the first ``p_len`` positions, each (1, heads, p_len,
    d_head) — pooled (a memcpy) or freshly computed by the same
    prefix-prefill plan (bit-identical either way, which is what makes
    pool hit vs miss streams indistinguishable).  Causal attention of
    the tail queries over prefix + tail in one batched forward.
    Returns (tail hidden states (1, s_t, d_model), per-layer tail
    (k, v) blocks (1, heads, s_t, d_head))."""
    n_layers, moe_every = hyper["n_layers"], hyper["moe_every"]
    s_t = tail.shape[1]
    x = jnp.take(params["tok_embed"]["embeddings"],
                 tail.astype(jnp.int32), axis=0)
    x = x + params["pos_embed"]["table"][p_len:p_len + s_t].astype(
        x.dtype)
    tail_caches = []
    # tail query j (position p_len + j) sees the whole prefix plus
    # tail positions <= j
    causal = (jnp.arange(s_t)[None, None, :, None]
              >= jnp.arange(s_t)[None, None, None, :])
    for i in range(n_layers):
        moe = bool(moe_every) and (i + 1) % moe_every == 0
        bp = _block_params(params, i, moe)
        pk, pv = prefix_kv[i]
        a = _layer_norm(bp["ln_a"], x)
        q = jnp.einsum("bse,ehd->bhsd", a, bp["attn"]["Wq"])
        k = jnp.einsum("bse,ehd->bhsd", a, bp["attn"]["Wk"])
        v = jnp.einsum("bse,ehd->bhsd", a, bp["attn"]["Wv"])
        d = q.shape[-1]
        sp = jnp.einsum("bhsd,bhtd->bhst", q, pk) / math.sqrt(d)
        st = jnp.einsum("bhsd,bhtd->bhst", q, k) / math.sqrt(d)
        st = jnp.where(causal, st, -1e30)
        scores = jnp.concatenate([sp, st], axis=-1)
        probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
        vall = jnp.concatenate([pv, v], axis=2)
        o = jnp.einsum("bhst,bhtd->bhsd", probs.astype(vall.dtype),
                       vall)
        x = x + jnp.einsum("bhsd,hde->bse", o, bp["attn"]["Wo"])
        f = _layer_norm(bp["ln_m"], x)
        x = x + _mlp(bp, f)
        tail_caches.append((k, v))
    return x, tail_caches


def _sample(logits, rng, temperature, top_k: Optional[int] = None,
            top_p: Optional[float] = None):
    """Greedy when temperature == 0, else temperature softmax with
    optional top-k and/or top-p (nucleus) truncation.

    The ONE sampling implementation both decode paths share: the
    compiled-scan path (``build_generate_fn``) passes Python values
    (static branch — greedy compiles to a bare argmax, exactly the
    pre-sampling plan), while the slot-array ``DecodeEngine`` passes
    traced per-slot scalars (``top_k <= 0`` / ``top_p >= 1`` disable),
    in which case greedy-vs-sampled is an in-graph select — a
    ``temperature == 0`` slot still yields the bit-exact argmax token,
    which is what keeps the engine's greedy streams identical to this
    function's static-greedy plan.

    The sampled path is ONE descending ``top_k(V)`` (values + source
    indices), both truncation thresholds off the same sorted array,
    and an inverse-CDF draw from ONE uniform per row — deliberately
    not V gumbels + two sorts: this runs per decode step (and per
    speculative window position), where the cheap transform keeps
    sampled decode within the bench's overhead bound of greedy."""
    greedy = jnp.argmax(logits, axis=-1)
    static_t = isinstance(temperature, (int, float))
    if static_t and float(temperature) == 0.0:
        return greedy
    t = jnp.maximum(jnp.asarray(temperature, jnp.float32), 1e-6)
    scaled = logits.astype(jnp.float32) / t
    V = scaled.shape[-1]
    srt, src = lax.top_k(scaled, V)  # descending values + indices
    # top-k threshold: the k-th sorted value (disabled -> -inf)
    if top_k is None:
        kth = -jnp.inf
    elif isinstance(top_k, int):
        kth = srt[..., top_k - 1:top_k]
    else:
        idx = jnp.clip(top_k - 1, 0, V - 1).astype(jnp.int32)
        kth = lax.dynamic_index_in_dim(srt, idx, axis=-1,
                                       keepdims=True)
        kth = jnp.where(top_k > 0, kth, -jnp.inf)
    # unnormalized sorted probabilities (shared by top-p + the draw)
    e = jnp.exp(srt - srt[..., :1])
    csum = jnp.cumsum(e, axis=-1)
    # nucleus threshold: keep the sorted prefix whose mass STRICTLY
    # BEFORE each entry is < p of the total — the top token's before-
    # mass is 0, so at least one entry always survives
    if top_p is None:
        pth = -jnp.inf
    else:
        keep = (csum - e) < jnp.asarray(top_p,
                                        jnp.float32) * csum[..., -1:]
        pth = jnp.min(jnp.where(keep, srt, jnp.inf), axis=-1,
                      keepdims=True)
    thr = jnp.maximum(kth, pth)
    ek = jnp.where(srt >= thr, e, 0.0)
    ck = jnp.cumsum(ek, axis=-1)
    u = jax.random.uniform(rng, logits.shape[:-1],
                           jnp.float32)[..., None] * ck[..., -1:]
    pick = jnp.sum((ck <= u).astype(jnp.int32), axis=-1)
    # u can round up to exactly ck[-1] (uniform near 1 x the total),
    # making every cumsum entry <= u — clamp to the KEPT prefix so a
    # truncation-excluded token can never be drawn
    kept = jnp.sum((ek > 0.0).astype(jnp.int32), axis=-1)
    pick = jnp.clip(pick, 0, jnp.maximum(kept - 1, 0))
    sampled = jnp.take_along_axis(src, pick[..., None],
                                  axis=-1)[..., 0]
    if static_t:
        return sampled
    return jnp.where(jnp.asarray(temperature) > 0.0, sampled, greedy)


def build_generate_fn(hyper, s_p: int, max_new: int, temperature: float,
                      top_k: Optional[int], top_p: Optional[float] = None,
                      ragged: bool = False):
    """Compile one generation plan: (params, prompt, rng) -> (b, max_new)
    sampled token ids — or, with ``ragged``, (params, prompt, lengths,
    rng) where right-padded rows decode from their own (b,) prompt
    lengths (per-row positions and cache slots).  Static: prompt width,
    step count, sampling config.  The scan carries the caches, so the
    whole decode is one XLA while-loop — no per-token host dispatch."""
    cache_len = s_p + max_new

    def run(params, prompt, lengths, rng):
        x, caches = _prefill(params, hyper, prompt, cache_len)
        if lengths is None:
            last_hidden = x[:, -1, :]
        else:
            # ragged (right-padded) prompts: each row's last REAL token
            last_hidden = x[jnp.arange(x.shape[0]), lengths - 1]
        logits0 = _head_logits(params, last_hidden)
        rng0, rng_loop = jax.random.split(rng)
        tok0 = _sample(logits0, rng0, temperature, top_k, top_p)

        def step(carry, i):
            tok, caches, r = carry
            r, r_step = jax.random.split(r)
            pos = (s_p + i) if lengths is None else (lengths + i)
            emb = _embed_token(params, tok, pos)
            logits, caches = _decode_step(params, hyper, caches, emb, pos)
            nxt = _sample(logits, r_step, temperature, top_k, top_p)
            return (nxt, caches, r), tok

        (_, _, _), toks = lax.scan(
            step, (tok0, caches, rng_loop), jnp.arange(max_new))
        return jnp.swapaxes(toks, 0, 1)  # (steps, b) -> (b, steps)

    if ragged:
        return jax.jit(run)
    # jit the 3-arg closure (not a bare lambda over a jitted fn) so the
    # returned callable keeps .lower() — bench.py AOT-checks the plan
    return jax.jit(lambda params, prompt, rng: run(params, prompt, None,
                                                   rng))


def build_beam_fn(hyper, s_p: int, max_new: int, beam_width: int):
    """Compile one beam-search plan: (params, prompt) -> (tok0, toks,
    parents, scores) for post-scan backtracking.  Deterministic (no
    rng); beams ride the batch dimension (row b·W + w), so every decode
    step stays one batched MXU computation, and each step's surviving
    beams gather their parents' KV caches."""
    cache_len = s_p + max_new
    W = beam_width

    @jax.jit
    def run(params, prompt):
        b = prompt.shape[0]
        x, caches = _prefill(params, hyper, prompt, cache_len)
        logits0 = _head_logits(params, x[:, -1, :])
        logp0 = jax.nn.log_softmax(logits0.astype(jnp.float32), axis=-1)
        cum, tok0 = lax.top_k(logp0, W)  # (b, W)
        # broadcast each cache row to its W beams (b-major: row b·W + w)
        caches = jax.tree_util.tree_map(
            lambda c: jnp.repeat(c, W, axis=0), caches)

        def step(carry, i):
            tok, cum_lp, caches = carry  # (b, W), (b, W), (b·W, ...)
            pos = s_p + i
            emb = _embed_token(params, tok.reshape(b * W), pos)
            logits, caches = _decode_step(params, hyper, caches, emb,
                                          pos)
            logp = jax.nn.log_softmax(logits.astype(jnp.float32),
                                      axis=-1)
            V = logp.shape[-1]
            total = cum_lp[:, :, None] + logp.reshape(b, W, V)
            cum2, idx = lax.top_k(total.reshape(b, W * V), W)
            parent = idx // V  # (b, W) surviving beams' ancestors
            tok2 = idx % V
            brow = jnp.arange(b)[:, None]
            caches = jax.tree_util.tree_map(
                lambda c: c.reshape(b, W, *c.shape[1:])[brow, parent]
                .reshape(b * W, *c.shape[1:]), caches)
            return (tok2, cum2, caches), (tok2, parent)

        (_, cum, _), (toks, parents) = lax.scan(
            step, (tok0, cum, caches), jnp.arange(max_new - 1))
        return tok0, toks, parents, cum

    return run


def _backtrack_beams(tok0, toks, parents, scores):
    """Reassemble (b, W, max_new) sequences from per-step (token,
    parent) records — walk each final beam's ancestry backwards."""
    tok0, toks, parents, scores = (np.asarray(jax.device_get(a))
                                   for a in (tok0, toks, parents,
                                             scores))
    steps, b, W = toks.shape
    seqs = np.zeros((b, W, steps + 1), np.int32)
    rows = np.arange(b)[:, None]
    beam = np.tile(np.arange(W), (b, 1))  # final beams, in score order
    for t in range(steps - 1, -1, -1):
        seqs[:, :, t + 1] = toks[t][rows, beam]
        beam = parents[t][rows, beam]
    seqs[:, :, 0] = tok0[rows, beam]
    return seqs, scores


def _plan_cache(model, key, build):
    """LRU-bounded compiled-plan cache: every distinct (prompt_len,
    max_new, sampling/beam) tuple is its own XLA executable —
    chat-style callers should pad prompts to a few bucket lengths, and
    the bound keeps a long-lived server from accumulating executables
    forever."""
    cache = getattr(model, "_generate_fns", None)
    if cache is None:
        import collections
        cache = model._generate_fns = collections.OrderedDict()
    fn = cache.get(key)
    if fn is None:
        fn = cache[key] = build()
        while len(cache) > 8:
            cache.popitem(last=False)
    else:
        cache.move_to_end(key)
    return fn


def generate(model, prompt_ids, max_new_tokens: int,
             temperature: float = 0.0, top_k: Optional[int] = None,
             top_p: Optional[float] = None,
             seed: int = 0, num_beams: int = 1,
             prompt_lengths=None) -> np.ndarray:
    """Generate continuations for a batch of equal-length prompts.

    Args:
        model: a (trained or loaded) :class:`TransformerLM`.
        prompt_ids: (batch, prompt_len) int token ids; prompt_len +
            max_new_tokens must fit ``max_len``.
        max_new_tokens: number of tokens to decode.
        temperature: 0.0 = greedy argmax; > 0 samples from the
            temperature-scaled distribution.
        top_k: optional truncation to the k most likely tokens before
            sampling (ignored when greedy).
        top_p: optional nucleus truncation — sample from the smallest
            descending-probability set reaching mass ``top_p``
            (ignored when greedy; composable with top_k, which is
            applied first).
        num_beams: > 1 runs deterministic beam search over that many
            beams (temperature/top_k must be unset) and returns each
            batch row's highest-log-prob sequence.
        prompt_lengths: optional (batch,) true lengths of RIGHT-padded
            ragged prompts.  Each row decodes from its own last real
            token with per-row positions; its continuation lands at
            ``[lengths[b], lengths[b] + max_new_tokens)`` in the
            returned array (positions past that keep value 0).  Not
            combinable with beam search.
    Returns:
        (batch, prompt_len + max_new_tokens) int32 ids — prompt
        followed by the generated continuation (right-aligned per row
        when ``prompt_lengths`` is given, see above).
    """
    prompt = np.asarray(prompt_ids)
    if prompt.ndim != 2:
        raise ValueError(f"prompt_ids must be (batch, prompt_len), got "
                         f"shape {prompt.shape}")
    h = model.hyper
    s_p = int(prompt.shape[1])
    total = s_p + int(max_new_tokens)
    if total > h["max_len"]:
        raise ValueError(
            f"prompt ({s_p}) + max_new_tokens ({max_new_tokens}) = "
            f"{total} exceeds max_len ({h['max_len']})")
    # the decode path is implementation-agnostic: it reads params by
    # layer name and computes its own cached attention, so a model
    # TRAINED with ring (sequence-parallel) attention decodes here
    # unchanged — the KV cache for one sequence fits one device, which
    # is why there is no ring decode.  (Params under any strategy are
    # replicated or resharded by the jit on first call.)
    trainer = model.ensure_inference_ready()
    if prompt_lengths is not None:
        lengths = np.asarray(prompt_lengths)
        if lengths.shape != (prompt.shape[0],):
            raise ValueError(
                f"prompt_lengths must be ({prompt.shape[0]},), got "
                f"shape {lengths.shape}")
        if (lengths < 1).any() or (lengths > s_p).any():
            raise ValueError(
                f"prompt_lengths must lie in [1, {s_p}]")
        if num_beams > 1:
            raise ValueError(
                "prompt_lengths is not supported with beam search — "
                "pad prompts to equal length for num_beams > 1")
    if num_beams <= 1 and int(max_new_tokens) == 0:
        # nothing to decode — same (b, s_p) result on both sampling
        # paths without building a plan (beam keeps its >= 1 raise)
        return prompt.astype(np.int32)
    if num_beams > 1:
        if temperature != 0.0 or top_k is not None or top_p is not None:
            raise ValueError(
                "beam search (num_beams > 1) is deterministic — "
                "temperature/top_k/top_p do not apply")
        if max_new_tokens < 1:
            # the beam plan always scores at least the first token, so
            # a 0-token request cannot keep the output-shape contract
            raise ValueError("beam search needs max_new_tokens >= 1")
        if num_beams > h["vocab_size"]:
            raise ValueError(f"num_beams ({num_beams}) exceeds "
                             f"vocab_size ({h['vocab_size']})")
        fn = _plan_cache(model, ("beam", s_p, int(max_new_tokens),
                                 int(num_beams)),
                         lambda: build_beam_fn(h, s_p,
                                               int(max_new_tokens),
                                               int(num_beams)))
        seqs, _ = _backtrack_beams(
            *fn(trainer.state.params, jnp.asarray(prompt)))
        # beams come out in descending cumulative log-prob order; all
        # beams share one length, so raw log-prob IS the ranking
        return np.concatenate([prompt.astype(np.int32), seqs[:, 0]],
                              axis=1)
    ragged = prompt_lengths is not None
    key = (s_p, int(max_new_tokens), float(temperature),
           None if top_k is None else int(top_k),
           None if top_p is None else float(top_p), ragged)
    fn = _plan_cache(model, key,
                     lambda: build_generate_fn(
                         h, s_p, int(max_new_tokens), float(temperature),
                         None if top_k is None else int(top_k),
                         None if top_p is None else float(top_p),
                         ragged=ragged))
    if ragged:
        toks = fn(trainer.state.params, jnp.asarray(prompt),
                  jnp.asarray(lengths, jnp.int32),
                  jax.random.PRNGKey(seed))
        toks = np.asarray(jax.device_get(toks), np.int32)
        out = np.zeros((prompt.shape[0], s_p + int(max_new_tokens)),
                       np.int32)
        out[:, :s_p] = prompt
        rows = np.arange(prompt.shape[0])[:, None]
        cols = lengths[:, None] + np.arange(int(max_new_tokens))[None]
        out[rows, cols] = toks
        # anything past each row's continuation is not real content
        mask = np.arange(out.shape[1])[None] >= cols[:, -1:] + 1
        out[mask] = 0
        return out
    toks = fn(trainer.state.params, jnp.asarray(prompt),
              jax.random.PRNGKey(seed))
    return np.concatenate([prompt.astype(np.int32),
                           np.asarray(jax.device_get(toks),
                                      np.int32)], axis=1)
