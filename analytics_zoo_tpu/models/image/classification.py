"""ImageClassifier model zoo: ResNet-50, VGG-16/19, MobileNet v1/v2,
SqueezeNet, Inception-v1, DenseNet-161.

Parity surface: reference zoo/.../models/image/imageclassification/
{ImageClassifier.scala, ImageClassificationConfig.scala:34-50} — a named
registry of architectures with pre/postprocessing configs (the reference
ships pretrained BigDL weights per name; here the architectures are built
natively and weights train or load from checkpoints).

TPU-first notes: all nets are NHWC; ResNet uses fused conv+BN blocks that
XLA folds into single MXU convolutions; bottleneck widths are multiples of
128 so tiles fill the 128x128 systolic array.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from ...core.graph import Input
from ...pipeline.api.keras.engine import Model
from ...pipeline.api.keras.layers import (
    Activation, AveragePooling2D, BatchNormalization, Convolution2D, Dense,
    Dropout, Flatten, GlobalAveragePooling2D, MaxPooling2D, Merge,
    SeparableConvolution2D, SpaceToDepth2D, ZeroPadding2D)
from ..common import (QuantizedVariantMixin, ZooModel, parse_quantize_name,
                      register_zoo_model)


def _conv_bn(x, filters, kernel, stride=1, padding="same", activation="relu",
             name=None, bias=False):
    x = Convolution2D(filters, kernel, kernel, subsample=(stride, stride),
                      border_mode=padding, bias=bias, name=name)(x)
    x = BatchNormalization(name=None if name is None else name + "_bn")(x)
    if activation:
        x = Activation(activation)(x)
    return x


# ---------------------------------------------------------------- ResNet-50

def _bottleneck(x, filters, stride=1, downsample=False, prefix=""):
    shortcut = x
    if downsample:
        shortcut = _conv_bn(x, filters * 4, 1, stride=stride,
                            activation=None, name=f"{prefix}_proj")
    y = _conv_bn(x, filters, 1, stride=stride, name=f"{prefix}_1")
    y = _conv_bn(y, filters, 3, name=f"{prefix}_2")
    y = _conv_bn(y, filters * 4, 1, activation=None, name=f"{prefix}_3")
    out = Merge(mode="sum")([y, shortcut])
    return Activation("relu")(out)


def resnet50(input_shape=(224, 224, 3), num_classes=1000,
             space_to_depth=False) -> Model:
    """ResNet-50 v1 (the reference registry's 'resnet-50',
    ImageClassificationConfig.scala:40).

    ``space_to_depth=True`` swaps the 7x7/s2 C=3 stem for the MLPerf-TPU
    formulation: pack 2x2 pixel blocks into channels, then a 4x4/s1 C=12
    conv (asymmetric pad (2,1)) — numerically equivalent to the standard
    stem under ``space_to_depth_stem_kernel``, but the contraction dim
    rises 147→192 and the filter-gradient conv stops being the MXU's
    worst case.  Everything after the stem is identical.
    """
    inp = Input(input_shape, name="image")
    if space_to_depth:
        x = SpaceToDepth2D(block_size=2)(inp)
        x = ZeroPadding2D(padding=(2, 1, 2, 1))(x)
        x = _conv_bn(x, 64, 4, padding="valid", name="conv1")
    else:
        x = ZeroPadding2D(padding=(3, 3))(inp)
        x = _conv_bn(x, 64, 7, stride=2, padding="valid", name="conv1")
    x = ZeroPadding2D(padding=(1, 1))(x)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    stages = [(64, 3, 1), (128, 4, 2), (256, 6, 2), (512, 3, 2)]
    for s, (filters, blocks, stride) in enumerate(stages):
        x = _bottleneck(x, filters, stride=stride, downsample=True,
                        prefix=f"res{s}b0")
        for b in range(1, blocks):
            x = _bottleneck(x, filters, prefix=f"res{s}b{b}")
    x = GlobalAveragePooling2D()(x)
    x = Dense(num_classes, activation="softmax", name="fc1000")(x)
    return Model(input=inp, output=x, name="resnet50")


def space_to_depth_stem_kernel(w, block_size=2):
    """Convert a standard stem conv kernel (kh, kw, C, O in HWIO) into
    the equivalent packed kernel for the ``space_to_depth=True`` stem.

    Zero-pads the kernel at the top-left to a multiple of the block,
    then folds each block's taps into the packed channel dim using the
    same (r * b + s) * C + c ordering as ``SpaceToDepth2D``.  With this
    kernel the packed stem is numerically identical to the standard
    7x7/s2 stem (see test_space_to_depth_stem_equivalence).
    """
    import jax.numpy as jnp
    kh, kw, c, o = w.shape
    b = block_size
    ph, pw = (-kh) % b, (-kw) % b
    w_pad = jnp.pad(jnp.asarray(w), ((ph, 0), (pw, 0), (0, 0), (0, 0)))
    w_pack = w_pad.reshape((kh + ph) // b, b, (kw + pw) // b, b, c, o)
    w_pack = jnp.transpose(w_pack, (0, 2, 1, 3, 4, 5))
    return w_pack.reshape((kh + ph) // b, (kw + pw) // b, b * b * c, o)


# ---------------------------------------------------------------- VGG

def _vgg(cfg: List, input_shape, num_classes) -> Model:
    inp = Input(input_shape, name="image")
    x = inp
    for i, block in enumerate(cfg):
        for j in range(block[0]):
            x = Convolution2D(block[1], 3, 3, activation="relu",
                              border_mode="same",
                              name=f"block{i + 1}_conv{j + 1}")(x)
        x = MaxPooling2D()(x)
    x = Flatten()(x)
    x = Dense(4096, activation="relu")(x)
    x = Dropout(0.5)(x)
    x = Dense(4096, activation="relu")(x)
    x = Dropout(0.5)(x)
    x = Dense(num_classes, activation="softmax")(x)
    return Model(input=inp, output=x, name="vgg")


def vgg16(input_shape=(224, 224, 3), num_classes=1000):
    return _vgg([(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)],
                input_shape, num_classes)


def vgg19(input_shape=(224, 224, 3), num_classes=1000):
    return _vgg([(2, 64), (2, 128), (4, 256), (4, 512), (4, 512)],
                input_shape, num_classes)


# ---------------------------------------------------------------- MobileNet

def mobilenet(input_shape=(224, 224, 3), num_classes=1000, alpha=1.0):
    inp = Input(input_shape, name="image")
    x = _conv_bn(inp, int(32 * alpha), 3, stride=2)
    cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
           (512, 1), (512, 1), (512, 1), (512, 1), (512, 1), (1024, 2),
           (1024, 1)]
    for filters, stride in cfg:
        x = SeparableConvolution2D(int(filters * alpha), 3, 3,
                                   border_mode="same",
                                   subsample=(stride, stride))(x)
        x = BatchNormalization()(x)
        x = Activation("relu6")(x)
    x = GlobalAveragePooling2D()(x)
    x = Dense(num_classes, activation="softmax")(x)
    return Model(input=inp, output=x, name="mobilenet")


def _inverted_residual(x, in_ch, filters, stride, expansion, prefix):
    hidden = in_ch * expansion
    y = _conv_bn(x, hidden, 1, activation="relu6",
                 name=f"{prefix}_expand") if expansion != 1 else x
    y = SeparableConvolution2D(filters, 3, 3, border_mode="same",
                               subsample=(stride, stride),
                               depth_multiplier=1,
                               name=f"{prefix}_dw")(y)
    y = BatchNormalization()(y)
    # no activation after the linear bottleneck projection (v2 design)
    if stride == 1 and in_ch == filters:
        return Merge(mode="sum")([x, y])
    return y


def mobilenet_v2(input_shape=(224, 224, 3), num_classes=1000):
    inp = Input(input_shape, name="image")
    x = _conv_bn(inp, 32, 3, stride=2, activation="relu6")
    in_ch = 32
    cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
           (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
    for bi, (t, c, n, s) in enumerate(cfg):
        for i in range(n):
            x = _inverted_residual(x, in_ch, c, s if i == 0 else 1, t,
                                   prefix=f"ir{bi}_{i}")
            in_ch = c
    x = _conv_bn(x, 1280, 1, activation="relu6")
    x = GlobalAveragePooling2D()(x)
    x = Dense(num_classes, activation="softmax")(x)
    return Model(input=inp, output=x, name="mobilenet_v2")


# ---------------------------------------------------------------- SqueezeNet

def _fire(x, squeeze, expand, prefix):
    s = Convolution2D(squeeze, 1, 1, activation="relu",
                      name=f"{prefix}_s1")(x)
    e1 = Convolution2D(expand, 1, 1, activation="relu",
                       name=f"{prefix}_e1")(s)
    e3 = Convolution2D(expand, 3, 3, activation="relu", border_mode="same",
                       name=f"{prefix}_e3")(s)
    return Merge(mode="concat", concat_axis=-1)([e1, e3])


def squeezenet(input_shape=(224, 224, 3), num_classes=1000):
    inp = Input(input_shape, name="image")
    x = Convolution2D(64, 3, 3, subsample=(2, 2), activation="relu")(inp)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    x = _fire(x, 16, 64, "fire2")
    x = _fire(x, 16, 64, "fire3")
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    x = _fire(x, 32, 128, "fire4")
    x = _fire(x, 32, 128, "fire5")
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    x = _fire(x, 48, 192, "fire6")
    x = _fire(x, 48, 192, "fire7")
    x = _fire(x, 64, 256, "fire8")
    x = _fire(x, 64, 256, "fire9")
    x = Dropout(0.5)(x)
    x = Convolution2D(num_classes, 1, 1, activation="relu")(x)
    x = GlobalAveragePooling2D()(x)
    x = Activation("softmax")(x)
    return Model(input=inp, output=x, name="squeezenet")


# ---------------------------------------------------------------- Inception

def _inception_block(x, b1, b3r, b3, b5r, b5, pp, prefix):
    branch1 = Convolution2D(b1, 1, 1, activation="relu",
                            name=f"{prefix}_1x1")(x)
    branch3 = Convolution2D(b3r, 1, 1, activation="relu",
                            name=f"{prefix}_3x3r")(x)
    branch3 = Convolution2D(b3, 3, 3, activation="relu", border_mode="same",
                            name=f"{prefix}_3x3")(branch3)
    branch5 = Convolution2D(b5r, 1, 1, activation="relu",
                            name=f"{prefix}_5x5r")(x)
    branch5 = Convolution2D(b5, 5, 5, activation="relu", border_mode="same",
                            name=f"{prefix}_5x5")(branch5)
    pool = MaxPooling2D(pool_size=(3, 3), strides=(1, 1),
                        border_mode="same")(x)
    pool = Convolution2D(pp, 1, 1, activation="relu",
                         name=f"{prefix}_pool")(pool)
    return Merge(mode="concat", concat_axis=-1)(
        [branch1, branch3, branch5, pool])


def inception_v1(input_shape=(224, 224, 3), num_classes=1000):
    """GoogLeNet (the reference registry's 'inception-v1')."""
    inp = Input(input_shape, name="image")
    x = Convolution2D(64, 7, 7, subsample=(2, 2), activation="relu",
                      border_mode="same")(inp)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                     border_mode="same")(x)
    x = Convolution2D(64, 1, 1, activation="relu")(x)
    x = Convolution2D(192, 3, 3, activation="relu", border_mode="same")(x)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                     border_mode="same")(x)
    x = _inception_block(x, 64, 96, 128, 16, 32, 32, "i3a")
    x = _inception_block(x, 128, 128, 192, 32, 96, 64, "i3b")
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                     border_mode="same")(x)
    x = _inception_block(x, 192, 96, 208, 16, 48, 64, "i4a")
    x = _inception_block(x, 160, 112, 224, 24, 64, 64, "i4b")
    x = _inception_block(x, 128, 128, 256, 24, 64, 64, "i4c")
    x = _inception_block(x, 112, 144, 288, 32, 64, 64, "i4d")
    x = _inception_block(x, 256, 160, 320, 32, 128, 128, "i4e")
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2),
                     border_mode="same")(x)
    x = _inception_block(x, 256, 160, 320, 32, 128, 128, "i5a")
    x = _inception_block(x, 384, 192, 384, 48, 128, 128, "i5b")
    x = GlobalAveragePooling2D()(x)
    x = Dropout(0.4)(x)
    x = Dense(num_classes, activation="softmax")(x)
    return Model(input=inp, output=x, name="inception_v1")


def _conv_bn_v3(x, filters, nr, nc, strides=(1, 1), padding="same",
                name=None):
    """conv + BN(scale-free in tf.keras; our gamma stays 1 on weight
    import) + relu — the conv2d_bn unit of keras.applications
    inception_v3, which inception_v3 below mirrors block-for-block so
    tf.keras InceptionV3 checkpoints transfer by op order
    (models/weight_loading.py)."""
    x = Convolution2D(filters, nr, nc, subsample=strides,
                      border_mode=padding, bias=False, name=name)(x)
    x = BatchNormalization()(x)
    return Activation("relu")(x)


def inception_v3(input_shape=(299, 299, 3), num_classes=1000,
                 include_top=True):
    """Inception-v3 (the reference registry's 'inception-v3',
    ImageClassificationConfig.scala:34-50).  With ``include_top=False``
    the output is the 2048-d global-average-pooled feature (matching
    tf.keras ``include_top=False, pooling='avg'`` for oracle testing and
    transfer learning)."""
    cb = _conv_bn_v3
    inp = Input(input_shape, name="image")
    x = cb(inp, 32, 3, 3, strides=(2, 2), padding="valid")
    x = cb(x, 32, 3, 3, padding="valid")
    x = cb(x, 64, 3, 3)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    x = cb(x, 80, 1, 1, padding="valid")
    x = cb(x, 192, 3, 3, padding="valid")
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)

    cat = lambda parts: Merge(mode="concat", concat_axis=-1)(parts)
    # mixed 0-2
    for pool_ch in (32, 64, 64):
        b1 = cb(x, 64, 1, 1)
        b5 = cb(cb(x, 48, 1, 1), 64, 5, 5)
        b3 = cb(cb(cb(x, 64, 1, 1), 96, 3, 3), 96, 3, 3)
        bp = AveragePooling2D(pool_size=(3, 3), strides=(1, 1),
                              border_mode="same")(x)
        bp = cb(bp, pool_ch, 1, 1)
        x = cat([b1, b5, b3, bp])
    # mixed 3
    b3 = cb(x, 384, 3, 3, strides=(2, 2), padding="valid")
    bd = cb(cb(x, 64, 1, 1), 96, 3, 3)
    bd = cb(bd, 96, 3, 3, strides=(2, 2), padding="valid")
    bp = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    x = cat([b3, bd, bp])
    # mixed 4-7
    for mid in (128, 160, 160, 192):
        b1 = cb(x, 192, 1, 1)
        b7 = cb(cb(cb(x, mid, 1, 1), mid, 1, 7), 192, 7, 1)
        bd = cb(x, mid, 1, 1)
        bd = cb(cb(bd, mid, 7, 1), mid, 1, 7)
        bd = cb(cb(bd, mid, 7, 1), 192, 1, 7)
        bp = AveragePooling2D(pool_size=(3, 3), strides=(1, 1),
                              border_mode="same")(x)
        bp = cb(bp, 192, 1, 1)
        x = cat([b1, b7, bd, bp])
    # mixed 8
    b3 = cb(cb(x, 192, 1, 1), 320, 3, 3, strides=(2, 2), padding="valid")
    b7 = cb(cb(cb(x, 192, 1, 1), 192, 1, 7), 192, 7, 1)
    b7 = cb(b7, 192, 3, 3, strides=(2, 2), padding="valid")
    bp = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    x = cat([b3, b7, bp])
    # mixed 9-10
    for _ in range(2):
        b1 = cb(x, 320, 1, 1)
        b3 = cb(x, 384, 1, 1)
        b3 = cat([cb(b3, 384, 1, 3), cb(b3, 384, 3, 1)])
        bd = cb(cb(x, 448, 1, 1), 384, 3, 3)
        bd = cat([cb(bd, 384, 1, 3), cb(bd, 384, 3, 1)])
        bp = AveragePooling2D(pool_size=(3, 3), strides=(1, 1),
                              border_mode="same")(x)
        bp = cb(bp, 192, 1, 1)
        x = cat([b1, b3, bd, bp])
    x = GlobalAveragePooling2D()(x)
    if include_top:
        x = Dense(num_classes, activation="softmax",
                  name="predictions")(x)
    return Model(input=inp, output=x, name="inception_v3")


# ---------------------------------------------------------------- DenseNet

def _dense_block(x, layers, growth, prefix):
    for i in range(layers):
        y = BatchNormalization()(x)
        y = Activation("relu")(y)
        y = Convolution2D(4 * growth, 1, 1, bias=False)(y)
        y = BatchNormalization()(y)
        y = Activation("relu")(y)
        y = Convolution2D(growth, 3, 3, border_mode="same", bias=False,
                          name=f"{prefix}_l{i}")(y)
        x = Merge(mode="concat", concat_axis=-1)([x, y])
    return x


def _transition(x, out_ch):
    x = BatchNormalization()(x)
    x = Activation("relu")(x)
    x = Convolution2D(out_ch, 1, 1, bias=False)(x)
    return AveragePooling2D(pool_size=(2, 2))(x)


def densenet161(input_shape=(224, 224, 3), num_classes=1000):
    growth, init_ch = 48, 96
    inp = Input(input_shape, name="image")
    x = ZeroPadding2D(padding=(3, 3))(inp)
    x = Convolution2D(init_ch, 7, 7, subsample=(2, 2), bias=False)(x)
    x = BatchNormalization()(x)
    x = Activation("relu")(x)
    x = ZeroPadding2D(padding=(1, 1))(x)
    x = MaxPooling2D(pool_size=(3, 3), strides=(2, 2))(x)
    ch = init_ch
    for bi, layers in enumerate([6, 12, 36, 24]):
        x = _dense_block(x, layers, growth, f"db{bi}")
        ch += layers * growth
        if bi < 3:
            ch //= 2
            x = _transition(x, ch)
    x = BatchNormalization()(x)
    x = Activation("relu")(x)
    x = GlobalAveragePooling2D()(x)
    x = Dense(num_classes, activation="softmax")(x)
    return Model(input=inp, output=x, name="densenet161")


# ---------------------------------------------------------------- registry

# '<arch>[-quantize]' -> (arch, wants_int8); canonical implementation
# lives in models.common so every registry parses the suffix identically
_parse_model_name = parse_quantize_name


_ARCHITECTURES: Dict[str, Callable] = {
    "resnet-50": resnet50,
    "vgg-16": vgg16,
    "vgg-19": vgg19,
    "mobilenet": mobilenet,
    "mobilenet-v2": mobilenet_v2,
    "squeezenet": squeezenet,
    "inception-v1": inception_v1,
    "inception-v3": inception_v3,
    "densenet-161": densenet161,
}


@register_zoo_model
class ImageClassifier(QuantizedVariantMixin, ZooModel):
    """Named-architecture image classifier
    (reference ImageClassifier.scala + config registry)."""

    def __init__(self, model_name="resnet-50", input_shape=(224, 224, 3),
                 num_classes=1000, name=None, **kw):
        # reference registry carries '<arch>-quantize' variants
        # (ImageClassificationConfig.scala:34-50): same architecture, int8
        # inference path (dispatch + cache in QuantizedVariantMixin)
        base, _ = _parse_model_name(model_name)
        if base not in _ARCHITECTURES:
            raise ValueError(
                f"Unknown model {model_name!r}; known: "
                f"{sorted(_ARCHITECTURES)} (+ '-quantize' suffixes)")
        super().__init__(name=name, model_name=model_name,
                         input_shape=tuple(input_shape),
                         num_classes=num_classes, **kw)

    def build_model(self) -> Model:
        h = self.hyper
        base, _ = _parse_model_name(h["model_name"])
        return _ARCHITECTURES[base](
            input_shape=h["input_shape"], num_classes=h["num_classes"])

    def predict_image_set(self, image_set, configure=None):
        """predictImageSet parity (ImageModel.scala:45-69): preprocess →
        predict → postprocess → attach results.  ``configure`` defaults
        to the model name's registry entry (ImageConfigure.parse).

        .. warning:: When ``configure`` is omitted, images whose shape
           already equals the model input are assumed *model-ready* and
           skip registry preprocessing entirely — a raw, unnormalized
           image that happens to be exactly ``input_shape`` (e.g.
           224x224x3) would be fed in un-mean-subtracted and predict
           garbage.  The shape test is a heuristic, not a proof of
           preprocessing.  To force the canonical pipeline regardless of
           shape, pass it explicitly::

               configure=ImageConfigure.parse(model_name)

           which bypasses the shape shortcut unconditionally.
        """
        from .config import ImageConfigure
        model_shape = tuple(self.hyper["input_shape"])
        if configure is None:
            shapes = {tuple(f["image"].shape) for f in image_set.features}
            if shapes == {model_shape}:
                # images are already model-ready (the pre-registry API
                # contract): do NOT force registry preprocessing onto
                # them — resize/normalize on preprocessed tensors would
                # silently corrupt the predictions
                configure = ImageConfigure()
            else:
                try:
                    configure = ImageConfigure.parse(
                        self.hyper["model_name"])
                except ValueError:
                    configure = ImageConfigure()
                if configure.input_size is not None and (
                        model_shape[0] != configure.input_size
                        or model_shape[1] != configure.input_size):
                    # model built at a non-registry (or non-square) input
                    # size: the canonical preprocessing would emit the
                    # wrong shape — skip it rather than crash
                    configure = ImageConfigure(
                        label_map=configure.label_map,
                        batch_per_partition=configure.batch_per_partition)
        work = image_set
        if configure.pre_processor is not None:
            # preprocess a COPY: the caller's images must survive (they
            # are what visualization / other models consume afterwards)
            work = image_set.copy().transform(configure.pre_processor)
        x = work.to_array()
        probs = self.predict(
            x, batch_size=max(configure.batch_per_partition, 1) * 8)
        if configure.post_processor is not None:
            probs = configure.post_processor(probs)
        elif configure.label_map:
            probs = label_output(
                probs, [configure.label_map.get(i, str(i))
                        for i in range(int(np.shape(probs)[-1]))])
        image_set.set_predictions(probs)
        return image_set


def label_output(probs, labels: Optional[List[str]] = None, top_k: int = 5):
    """LabelOutput parity (reference LabelOutput.scala): top-k (label,
    confidence) per image."""
    import numpy as np
    probs = np.asarray(probs)
    idx = np.argsort(-probs, axis=-1)[:, :top_k]
    out = []
    for row, ids in zip(probs, idx):
        out.append([
            (labels[i] if labels else int(i), float(row[i])) for i in ids])
    return out
