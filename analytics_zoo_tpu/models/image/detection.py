"""ObjectDetector: SSD detection models + postprocessing.

Parity surface: reference zoo/.../models/image/objectdetection/
{ObjectDetector.scala:29-37, ObjectDetectionConfig.scala:32-108 (registry:
ssd-vgg16-300/512, ssd-mobilenet-300, frcnn variants), Postprocessor.scala:
30-75 (ScaleDetection, DecodeOutput), Visualizer.scala}.

TPU-first design: the reference's postprocessing is imperative JVM code over
variable-length detection lists; under jit everything is fixed-shape — conf
softmax → per-class top-k → iterative NMS via ``lax.fori_loop`` over a
padded candidate set → a fixed (max_detections, 6) output
[label, score, x1, y1, x2, y2] with -1-label padding (SURVEY §7 flags this
padded formulation as the hard part).  Boxes are normalized [0,1];
ScaleDetection maps them to pixel coordinates.

Faster-RCNN variants are out of scope for round 1 (two-stage region
proposal; the reference itself can't ship those weights — SURVEY §7 stage 9
marks them optional).
"""

from __future__ import annotations

import itertools
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...core.graph import Input, Variable
from ...pipeline.api.keras.engine import Model
from ...pipeline.api.keras.layers import (
    Activation, BatchNormalization, Convolution2D, Dense,
    GlobalAveragePooling2D, MaxPooling2D, Merge, Reshape, ZeroPadding2D)
from ..common import (QuantizedVariantMixin, ZooModel, parse_quantize_name,
                      register_zoo_model)


# ------------------------------------------------------------ prior boxes

def ssd_priors(image_size: int = 300,
               feature_sizes: Sequence[int] = (38, 19, 10, 5, 3, 1),
               min_ratio: float = 0.2, max_ratio: float = 0.9,
               aspect_ratios: Sequence[Sequence[float]] = (
                   (2,), (2, 3), (2, 3), (2, 3), (2,), (2,)),
               ) -> np.ndarray:
    """Generate SSD prior (anchor) boxes (cx, cy, w, h), normalized.

    Matches the standard SSD-300 recipe the reference's pretrained configs
    assume: per-scale min/max sizes interpolated between ratios, priors
    {1, 1', ar, 1/ar} per cell.
    """
    n_maps = len(feature_sizes)
    scales = np.linspace(min_ratio, max_ratio, n_maps)
    scales = np.concatenate([[0.1], scales])  # conv4_3 uses a small scale
    priors = []
    for m, fsize in enumerate(feature_sizes):
        s_k = scales[m]
        s_k1 = scales[m + 1] if m + 1 < len(scales) else 1.0
        for i, j in itertools.product(range(fsize), repeat=2):
            cx = (j + 0.5) / fsize
            cy = (i + 0.5) / fsize
            priors.append([cx, cy, s_k, s_k])
            s_prime = math.sqrt(s_k * s_k1)
            priors.append([cx, cy, s_prime, s_prime])
            for ar in aspect_ratios[m]:
                r = math.sqrt(ar)
                priors.append([cx, cy, s_k * r, s_k / r])
                priors.append([cx, cy, s_k / r, s_k * r])
    return np.clip(np.asarray(priors, dtype=np.float32), 0.0, 1.0)


def priors_per_cell(aspect_ratios: Sequence[float]) -> int:
    return 2 + 2 * len(aspect_ratios)


# ------------------------------------------------------------ networks

def _vgg_base(x):
    """VGG-16 through conv5_3 with ceil-mode pool3 (SSD variant), plus
    fc6/fc7 as dilated convs."""
    cfg = [(2, 64), (2, 128), (3, 256), (3, 512), (3, 512)]
    feats = {}
    for bi, (reps, ch) in enumerate(cfg):
        for r in range(reps):
            x = Convolution2D(ch, 3, 3, activation="relu",
                              border_mode="same",
                              name=f"ssd_b{bi + 1}c{r + 1}")(x)
        if bi == 3:
            feats["conv4_3"] = x
        if bi < 4:
            x = MaxPooling2D(pool_size=(2, 2), strides=(2, 2),
                             border_mode="same")(x)
        else:
            x = MaxPooling2D(pool_size=(3, 3), strides=(1, 1),
                             border_mode="same")(x)
    x = Convolution2D(1024, 3, 3, activation="relu", border_mode="same",
                      dilation=(6, 6), name="ssd_fc6")(x)
    x = Convolution2D(1024, 1, 1, activation="relu", name="ssd_fc7")(x)
    feats["fc7"] = x
    return feats


def _extra_layers(x, n_extras: int = 4):
    """SSD extra feature maps: 19->10->5->3->1 for input 300."""
    outs = []
    specs = [(256, 512, 2), (128, 256, 2), (128, 256, 2),
             (128, 256, 2)][:n_extras]
    for i, (mid, out, stride) in enumerate(specs):
        x = Convolution2D(mid, 1, 1, activation="relu",
                          name=f"ssd_extra{i}_1")(x)
        if stride == 2 and i < 2:
            x = ZeroPadding2D(padding=(1, 1))(x)
            x = Convolution2D(out, 3, 3, subsample=(2, 2),
                              activation="relu",
                              name=f"ssd_extra{i}_2")(x)
        else:
            x = Convolution2D(out, 3, 3,
                              subsample=(stride, stride) if i < 2 else (1, 1),
                              activation="relu", border_mode="valid",
                              name=f"ssd_extra{i}_2")(x)
        outs.append(x)
    return outs


def ssd_vgg16(num_classes: int = 21, image_size: int = 300) -> Model:
    """SSD-VGG16-300 (the reference registry's 'ssd-vgg16-300').

    Output: concat of per-scale multibox heads —
    (batch, n_priors, 4 + num_classes), loc deltas then class scores.
    """
    aspect_ratios = ((2,), (2, 3), (2, 3), (2, 3), (2,), (2,))
    inp = Input((image_size, image_size, 3), name="image")
    feats = _vgg_base(inp)
    sources = [feats["conv4_3"], feats["fc7"]] + _extra_layers(feats["fc7"])
    head_outs = []
    feature_sizes = []
    for i, (src, ars) in enumerate(zip(sources, aspect_ratios)):
        k = priors_per_cell(ars)
        loc = Convolution2D(k * 4, 3, 3, border_mode="same",
                            name=f"ssd_loc{i}")(src)
        conf = Convolution2D(k * num_classes, 3, 3, border_mode="same",
                             name=f"ssd_conf{i}")(src)
        h, w = src.shape[1], src.shape[2]
        feature_sizes.append(h)
        loc = Reshape((h * w * k, 4))(loc)
        conf = Reshape((h * w * k, num_classes))(conf)
        head_outs.append(Merge(mode="concat", concat_axis=-1)([loc, conf]))
    out = Merge(mode="concat", concat_axis=1)(head_outs)
    model = Model(input=inp, output=out, name="ssd_vgg16")
    model._ssd_feature_sizes = feature_sizes
    model._ssd_aspect_ratios = aspect_ratios
    return model


def ssd_mobilenet(num_classes: int = 21, image_size: int = 300) -> Model:
    """SSD-MobileNet-300 (the reference registry's 'ssd-mobilenet-300'):
    lighter base, same multibox head structure."""
    from .classification import _conv_bn
    from ...pipeline.api.keras.layers import SeparableConvolution2D
    # 5 scales: 19, 10, 5, 3, 1 (for input 300 the base reaches /16=19
    # after six stride-2 stages counting the stem)
    aspect_ratios = ((2,), (2, 3), (2, 3), (2, 3), (2,))
    inp = Input((image_size, image_size, 3), name="image")
    x = _conv_bn(inp, 32, 3, stride=2)
    cfg = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2)]
    for filters, stride in cfg:
        x = SeparableConvolution2D(filters, 3, 3, border_mode="same",
                                   subsample=(stride, stride))(x)
        x = BatchNormalization()(x)
        x = Activation("relu6")(x)
    src_a = x  # 19×19 for input 300
    for filters, stride in [(512, 1)] * 3:
        x = SeparableConvolution2D(filters, 3, 3, border_mode="same")(x)
        x = BatchNormalization()(x)
        x = Activation("relu6")(x)
    x = SeparableConvolution2D(1024, 3, 3, border_mode="same",
                               subsample=(2, 2))(x)
    x = BatchNormalization()(x)
    x = Activation("relu6")(x)
    src_b = x  # 10×10
    extras = _extra_layers(src_b, n_extras=3)  # 5, 3, 1
    sources = [src_a, src_b] + extras
    head_outs = []
    feature_sizes = []
    for i, (src, ars) in enumerate(zip(sources, aspect_ratios)):
        k = priors_per_cell(ars)
        loc = Convolution2D(k * 4, 3, 3, border_mode="same",
                            name=f"ssdm_loc{i}")(src)
        conf = Convolution2D(k * num_classes, 3, 3, border_mode="same",
                             name=f"ssdm_conf{i}")(src)
        h, w = src.shape[1], src.shape[2]
        feature_sizes.append(h)
        loc = Reshape((h * w * k, 4))(loc)
        conf = Reshape((h * w * k, num_classes))(conf)
        head_outs.append(Merge(mode="concat", concat_axis=-1)([loc, conf]))
    out = Merge(mode="concat", concat_axis=1)(head_outs)
    model = Model(input=inp, output=out, name="ssd_mobilenet")
    model._ssd_feature_sizes = feature_sizes
    model._ssd_aspect_ratios = aspect_ratios
    return model


def model_priors(model: Model, num_classes: int,
                 image_size: int = 300) -> np.ndarray:
    """Priors matching a built model's actual per-scale head shapes
    (recorded on the model at build time)."""
    sizes = model._ssd_feature_sizes
    ars = model._ssd_aspect_ratios
    return ssd_priors(image_size, feature_sizes=sizes,
                      aspect_ratios=ars[:len(sizes)])


# ------------------------------------------------------------ decoding

def decode_boxes(loc: jnp.ndarray, priors: jnp.ndarray,
                 variances=(0.1, 0.1, 0.2, 0.2)) -> jnp.ndarray:
    """SSD box decoding: loc deltas + priors(cx,cy,w,h) -> (x1,y1,x2,y2)
    normalized (reference DecodeOutput semantics)."""
    cxcy = priors[:, :2] + loc[..., :2] * variances[0] * priors[:, 2:]
    wh = priors[:, 2:] * jnp.exp(loc[..., 2:] * variances[2])
    x1y1 = cxcy - wh / 2.0
    x2y2 = cxcy + wh / 2.0
    return jnp.clip(jnp.concatenate([x1y1, x2y2], axis=-1), 0.0, 1.0)


def _iou(box: jnp.ndarray, boxes: jnp.ndarray) -> jnp.ndarray:
    inter_lt = jnp.maximum(box[:2], boxes[:, :2])
    inter_rb = jnp.minimum(box[2:], boxes[:, 2:])
    inter_wh = jnp.maximum(inter_rb - inter_lt, 0.0)
    inter = inter_wh[:, 0] * inter_wh[:, 1]
    area1 = jnp.maximum(box[2] - box[0], 0) * jnp.maximum(box[3] - box[1], 0)
    area2 = (jnp.maximum(boxes[:, 2] - boxes[:, 0], 0)
             * jnp.maximum(boxes[:, 3] - boxes[:, 1], 0))
    return inter / jnp.maximum(area1 + area2 - inter, 1e-9)


def nms_padded(boxes: jnp.ndarray, scores: jnp.ndarray, iou_threshold: float,
               max_out: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Fixed-shape iterative NMS: select max_out boxes via fori_loop,
    suppressing overlaps — the jit-friendly formulation of the
    reference's imperative NMS (Postprocessor.scala)."""

    def body(i, carry):
        live_scores, keep_idx, keep_score = carry
        best = jnp.argmax(live_scores)
        best_score = live_scores[best]
        keep_idx = keep_idx.at[i].set(best)
        keep_score = keep_score.at[i].set(best_score)
        ious = _iou(boxes[best], boxes)
        suppress = (ious > iou_threshold) | \
            (jnp.arange(len(live_scores)) == best)
        live_scores = jnp.where(suppress, -1.0, live_scores)
        return live_scores, keep_idx, keep_score

    keep_idx = jnp.zeros((max_out,), jnp.int32)
    keep_score = jnp.full((max_out,), -1.0)
    _, keep_idx, keep_score = lax.fori_loop(
        0, max_out, body, (scores, keep_idx, keep_score))
    return keep_idx, keep_score


def decode_output(output: jnp.ndarray, priors: jnp.ndarray,
                  num_classes: int, conf_threshold: float = 0.01,
                  nms_threshold: float = 0.45, top_k: int = 200,
                  max_detections: int = 100) -> jnp.ndarray:
    """Full SSD postprocessing under jit (reference DecodeOutput,
    Postprocessor.scala:30-68).

    output: (batch, n_priors, 4 + num_classes).
    Returns (batch, max_detections, 6): [label, score, x1, y1, x2, y2]
    normalized coords, label -1 on padding rows.  Class 0 is background
    (reference convention).
    """

    def per_image(out):
        loc, conf = out[:, :4], out[:, 4:]
        probs = jax.nn.softmax(conf, axis=-1)
        boxes = decode_boxes(loc, priors)

        def per_class(c, acc):
            dets, cursor = acc
            scores = jnp.where(probs[:, c] >= conf_threshold,
                               probs[:, c], -1.0)
            cand_scores, cand_idx = lax.top_k(scores, top_k)
            cand_boxes = boxes[cand_idx]
            keep_rel, keep_scores = nms_padded(
                cand_boxes, cand_scores, nms_threshold, max_detections)
            keep_boxes = cand_boxes[keep_rel]
            rows = jnp.concatenate([
                jnp.full((max_detections, 1), c, jnp.float32),
                keep_scores[:, None], keep_boxes], axis=-1)
            rows = jnp.where(keep_scores[:, None] > 0, rows, -1.0)
            dets = lax.dynamic_update_slice(
                dets, rows, (cursor, 0))
            return dets, cursor + max_detections

        n_fg = num_classes - 1
        all_dets = jnp.full((n_fg * max_detections, 6), -1.0)
        all_dets, _ = lax.fori_loop(
            1, num_classes,
            lambda c, acc: per_class(c, acc), (all_dets, 0))
        # keep global top max_detections by score
        order = jnp.argsort(-all_dets[:, 1])[:max_detections]
        return all_dets[order]

    return jax.vmap(per_image)(output)


class ScaleDetection:
    """Scale normalized detections to original image pixels
    (reference ScaleDetection, Postprocessor.scala:30)."""

    def __call__(self, detections: np.ndarray,
                 heights: Sequence[int], widths: Sequence[int]
                 ) -> np.ndarray:
        dets = np.array(detections, copy=True)
        for i, (h, w) in enumerate(zip(heights, widths)):
            valid = dets[i, :, 0] >= 0
            dets[i, valid, 2] *= w
            dets[i, valid, 4] *= w
            dets[i, valid, 3] *= h
            dets[i, valid, 5] *= h
        return dets


# ------------------------------------------------------------ ObjectDetector

_DETECTORS = {
    "ssd-vgg16-300": lambda classes: (ssd_vgg16(classes, 300), 300),
    "ssd-vgg16-300x300": lambda classes: (ssd_vgg16(classes, 300), 300),
    "ssd-mobilenet-300": lambda classes: (ssd_mobilenet(classes, 300), 300),
    "ssd-vgg16-512": lambda classes: (ssd_vgg16(classes, 512), 512),
}


@register_zoo_model
class ObjectDetector(QuantizedVariantMixin, ZooModel):
    """Named SSD detector with jit postprocessing
    (reference ObjectDetector.scala + ObjectDetectionConfig registry)."""

    def __init__(self, model_name="ssd-vgg16-300", num_classes=21,
                 conf_threshold=0.01, nms_threshold=0.45,
                 max_detections=100, name=None, **kw):
        # '<name>-quantize' = same architecture, int8 inference path
        # (reference registry ObjectDetectionConfig.scala:33-44 carries
        # ssd-vgg16-300-quantize etc.; dispatch + cache in
        # QuantizedVariantMixin)
        base, _ = parse_quantize_name(model_name)
        if base not in _DETECTORS:
            raise ValueError(
                f"Unknown detector {model_name!r}; known: "
                f"{sorted(_DETECTORS)} (+ '-quantize' suffixes; frcnn "
                "variants are out of scope in the TPU build)")
        super().__init__(name=name, model_name=model_name,
                         num_classes=num_classes,
                         conf_threshold=conf_threshold,
                         nms_threshold=nms_threshold,
                         max_detections=max_detections, **kw)
        # build_model (called by super) recorded self._image_size
        self.priors = model_priors(self.model, num_classes,
                                   self._image_size)

    def build_model(self) -> Model:
        h = self.hyper
        base, _ = parse_quantize_name(h["model_name"])
        model, self._image_size = _DETECTORS[base](h["num_classes"])
        return model

    def predict_image_set(self, image_set, batch_size: int = 8,
                          configure=None):
        """preprocess → forward → decode → scale, parity with
        ImageModel.predictImageSet (ImageModel.scala:45-69).  Pass an
        ``ImageConfigure`` (e.g. ``ImageConfigure.parse("ssd-vgg16-300")``)
        to run its pre_processor on raw-sized images first; detections
        are scaled back to the ORIGINAL image coordinates."""
        h = self.hyper
        # original sizes before any preprocessing — detections come back
        # in these coordinates (reference ScaleDetection semantics)
        heights = [f["image"].shape[0] for f in image_set.features]
        widths = [f["image"].shape[1] for f in image_set.features]
        work = image_set
        if configure is not None and configure.pre_processor is not None:
            # preprocess a COPY — detections return in ORIGINAL
            # coordinates, so the original pixels must survive for
            # Visualizer to draw on
            work = image_set.copy().transform(configure.pre_processor)
        x = work.to_array()
        raw = self.predict(x, batch_size=batch_size)
        dets = decode_output(
            jnp.asarray(raw), jnp.asarray(self.priors), h["num_classes"],
            h["conf_threshold"], h["nms_threshold"],
            max_detections=h["max_detections"])
        scaled = ScaleDetection()(np.asarray(dets), heights, widths)
        image_set.set_predictions(scaled)
        return image_set


def visualize(image: np.ndarray, detections: np.ndarray,
              label_map: Optional[Dict[int, str]] = None,
              threshold: float = 0.3) -> np.ndarray:
    """Draw detection boxes (reference Visualizer.scala) with PIL."""
    from PIL import Image, ImageDraw
    img = Image.fromarray(np.clip(image, 0, 255).astype(np.uint8))
    draw = ImageDraw.Draw(img)
    for det in detections:
        label, score = int(det[0]), float(det[1])
        if label < 0 or score < threshold:
            continue
        x1, y1, x2, y2 = det[2], det[3], det[4], det[5]
        draw.rectangle([x1, y1, x2, y2], outline=(255, 0, 0), width=2)
        text = (label_map.get(label, str(label)) if label_map
                else str(label))
        draw.text((x1 + 2, y1 + 2), f"{text}:{score:.2f}",
                  fill=(255, 0, 0))
    return np.asarray(img)


class Visualizer:
    """Configured box-drawer over an ImageSet (reference
    Visualizer.scala): holds label map + threshold, applies
    ``visualize`` to every (image, detections) pair."""

    def __init__(self, label_map: Optional[Dict[int, str]] = None,
                 threshold: float = 0.3):
        self.label_map = label_map
        self.threshold = threshold

    def __call__(self, image: np.ndarray,
                 detections: np.ndarray) -> np.ndarray:
        return visualize(image, detections, label_map=self.label_map,
                         threshold=self.threshold)

    def visualize_image_set(self, image_set):
        """Return annotated copies of every image in a predicted set."""
        return [self(f["image"], f["predict"])
                for f in image_set.features]
