"""Predictor configuration for the image model zoo.

Parity surface: reference zoo/models/image/common/image_config.py
(ImageConfigure :28, PaddingParam) and ImageConfigure.parse — the
per-model-name registry of default pre/post-processing
(ImageClassificationConfig.scala:34-50, ObjectDetectionConfig.scala:32-108)
— plus the label-map readers (LabelReader for ImageNet,
read_pascal_label_map / read_coco_label_map in object_detector.py).
"""

import dataclasses
from typing import Callable, Dict, Optional

from ...feature.common import Preprocessing
from ...feature.image.transforms import (ImageCenterCrop,
                                         ImageChannelNormalize, ImageResize)


@dataclasses.dataclass
class PaddingParam:
    """Feature padding for variant-sized inputs (reference
    PaddingParam): pad every image of a batch up to the batch max."""

    pad_value: float = 0.0


@dataclasses.dataclass
class ImageConfigure:
    """Bundle of pre/post-processing around a zoo image model
    (reference image_config.py:28-60)."""

    pre_processor: Optional[Preprocessing] = None
    post_processor: Optional[Callable] = None
    batch_per_partition: int = 4
    label_map: Optional[Dict[int, str]] = None
    feature_padding_param: Optional[PaddingParam] = None
    input_size: Optional[int] = None  # spatial size pre_processor emits

    @classmethod
    def parse(cls, model_name: str) -> "ImageConfigure":
        """Default configure for a registry model name
        (ImageConfigure.parse / ImageClassificationConfig.scala:52-77)."""
        from ..common import parse_quantize_name
        base, _ = parse_quantize_name(model_name)
        if base not in _CONFIGURES:
            raise ValueError(
                f"No default configure for {model_name!r}; known: "
                f"{sorted(_CONFIGURES)}")
        return _CONFIGURES[base]()


# imagenet preprocessing constants (the reference's per-model configs)
_IMAGENET_MEAN = (123.68, 116.779, 103.939)
_IMAGENET_STD = (1.0, 1.0, 1.0)


def _imagenet_configure(size: int):
    def build():
        pre = (ImageResize(size + 32, size + 32)
               >> ImageCenterCrop(size, size)
               >> ImageChannelNormalize(*_IMAGENET_MEAN, *_IMAGENET_STD))
        return ImageConfigure(pre_processor=pre, batch_per_partition=4,
                              input_size=size)
    return build


def _inception_v3_configure():
    # inception-v3: 299x299, inputs scaled to [-1, 1]
    pre = (ImageResize(320, 320) >> ImageCenterCrop(299, 299)
           >> ImageChannelNormalize(127.5, 127.5, 127.5,
                                    127.5, 127.5, 127.5))
    return ImageConfigure(pre_processor=pre, batch_per_partition=4,
                          input_size=299)


def _ssd_configure(size: int):
    def build():
        pre = (ImageResize(size, size)
               >> ImageChannelNormalize(*_IMAGENET_MEAN, *_IMAGENET_STD))
        return ImageConfigure(pre_processor=pre, batch_per_partition=2,
                              input_size=size)
    return build


_CONFIGURES = {
    "resnet-50": _imagenet_configure(224),
    "vgg-16": _imagenet_configure(224),
    "vgg-19": _imagenet_configure(224),
    "mobilenet": _imagenet_configure(224),
    "mobilenet-v2": _imagenet_configure(224),
    "squeezenet": _imagenet_configure(224),
    "densenet-161": _imagenet_configure(224),
    "inception-v1": _imagenet_configure(224),
    "inception-v3": _inception_v3_configure,
    "ssd-vgg16-300": _ssd_configure(300),
    "ssd-vgg16-512": _ssd_configure(512),
    "ssd-mobilenet-300": _ssd_configure(300),
}


# ------------------------------------------------------------- label maps

PASCAL_CLASSES = (
    "__background__", "aeroplane", "bicycle", "bird", "boat", "bottle",
    "bus", "car", "cat", "chair", "cow", "diningtable", "dog", "horse",
    "motorbike", "person", "pottedplant", "sheep", "sofa", "train",
    "tvmonitor")

COCO_CLASSES = (
    "__background__", "person", "bicycle", "car", "motorcycle",
    "airplane", "bus", "train", "truck", "boat", "traffic light",
    "fire hydrant", "stop sign", "parking meter", "bench", "bird", "cat",
    "dog", "horse", "sheep", "cow", "elephant", "bear", "zebra",
    "giraffe", "backpack", "umbrella", "handbag", "tie", "suitcase",
    "frisbee", "skis", "snowboard", "sports ball", "kite",
    "baseball bat", "baseball glove", "skateboard", "surfboard",
    "tennis racket", "bottle", "wine glass", "cup", "fork", "knife",
    "spoon", "bowl", "banana", "apple", "sandwich", "orange", "broccoli",
    "carrot", "hot dog", "pizza", "donut", "cake", "chair", "couch",
    "potted plant", "bed", "dining table", "toilet", "tv", "laptop",
    "mouse", "remote", "keyboard", "cell phone", "microwave", "oven",
    "toaster", "sink", "refrigerator", "book", "clock", "vase",
    "scissors", "teddy bear", "hair drier", "toothbrush")


def read_pascal_label_map() -> Dict[int, str]:
    """PASCAL VOC label map (reference read_pascal_label_map)."""
    return dict(enumerate(PASCAL_CLASSES))


def read_coco_label_map() -> Dict[int, str]:
    """COCO label map (reference read_coco_label_map)."""
    return dict(enumerate(COCO_CLASSES))


def read_label_map(path: str, start: int = 0) -> Dict[int, str]:
    """Read a label map from a text file: either one label per line
    (index = line number + start) or ``<index><sep><label>`` lines."""
    out: Dict[int, str] = {}
    with open(path) as f:
        for lineno, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            head, _, tail = line.partition("\t") if "\t" in line \
                else line.partition(" ")
            if tail and head.lstrip("-").isdigit():
                out[int(head)] = tail.strip()
            else:
                out[lineno + start] = line
    return out


def read_imagenet_label_map(path: str) -> Dict[int, str]:
    """ImageNet-1k label map from a user-supplied synset/words file (the
    reference bundles this data in its jar; redistribute-free here)."""
    return read_label_map(path)
