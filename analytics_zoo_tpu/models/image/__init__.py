from .classification import (ImageClassifier, resnet50, vgg16, vgg19,
                             mobilenet, mobilenet_v2, squeezenet,
                             inception_v1, densenet161, label_output)
from .detection import (ObjectDetector, ssd_vgg16, ssd_mobilenet,
                        decode_output, ScaleDetection, visualize,
                        Visualizer)
from .config import (ImageConfigure, PaddingParam, read_label_map,
                     read_imagenet_label_map, read_pascal_label_map,
                     read_coco_label_map, PASCAL_CLASSES, COCO_CLASSES)
