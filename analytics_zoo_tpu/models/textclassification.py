"""TextClassifier model.

Parity surface: reference zoo/.../models/textclassification/
TextClassifier.scala:31-60 — embedding (optional WordEmbedding) →
{CNN(Conv1D 256,k=5 + GlobalMaxPooling1D) | LSTM | GRU} encoder →
Dense(128) → Dropout(0.2) → ReLU → Dense(classNum, softmax);
sequenceLength default 500.
"""

from __future__ import annotations

from typing import Optional

from ..pipeline.api.keras.engine import Sequential
from ..pipeline.api.keras.layers import (
    Activation, Convolution1D, Dense, Dropout, GlobalMaxPooling1D, GRU,
    LSTM, WordEmbedding)
from .common import ZooModel, register_zoo_model


@register_zoo_model
class TextClassifier(ZooModel):
    def __init__(self, class_num=None, token_length=None,
                 sequence_length=500, encoder="cnn", encoder_output_dim=256,
                 embedding_file=None, word_index=None, name=None, **kw):
        super().__init__(name=name, class_num=class_num,
                         token_length=token_length,
                         sequence_length=sequence_length, encoder=encoder,
                         encoder_output_dim=encoder_output_dim,
                         embedding_file=embedding_file,
                         word_index=word_index, **kw)

    def build_model(self) -> Sequential:
        h = self.hyper
        model = Sequential(name="net")
        if h.get("embedding_file"):
            model.add(WordEmbedding(
                h["embedding_file"], word_index=h.get("word_index"),
                input_length=h["sequence_length"]))
            first_shape = None  # embedding provides the input
        else:
            # pre-embedded input (sequence_length, token_length), matching
            # the reference's InputLayer branch
            first_shape = (h["sequence_length"], h["token_length"])

        enc = h["encoder"].lower()
        dim = h["encoder_output_dim"]
        if enc == "cnn":
            model.add(Convolution1D(dim, 5, activation="relu",
                                    input_shape=first_shape))
            model.add(GlobalMaxPooling1D())
        elif enc == "lstm":
            model.add(LSTM(dim, input_shape=first_shape))
        elif enc == "gru":
            model.add(GRU(dim, input_shape=first_shape))
        else:
            raise ValueError(
                f"Unsupported encoder for TextClassifier: {h['encoder']}")
        model.add(Dense(128))
        model.add(Dropout(0.2))
        model.add(Activation("relu"))
        model.add(Dense(h["class_num"], activation="softmax"))
        return model
