"""Pretrained-weight loading: layout converters from public checkpoint
formats into registry models.

Parity surface: the reference's model zoo serves *pretrained* models
(ImageClassificationConfig.scala:34-50 downloads published weights); its
test suites encode the layout traps with per-layer ``weightConverter``
functions (reference DenseSpec.scala:29).  Here the same role is played
by two whole-model converters:

* ``load_tf_keras_weights`` — from a live ``tf.keras`` model (or its
  ``get_weights`` layer list).  tf.keras convs are already HWIO (our
  layout); the work is pairing by op order, splitting BN gamma/beta
  (params) from moving stats (state), and handling scale-free BNs.
* ``load_torch_state_dict`` — from a PyTorch ``state_dict``.  Torch
  convs are OIHW and linears are (out, in): both transpose.

Both match OUR graph's layer order against the source's layer order per
kind (conv/bn/dense) — which is construction order on both sides — so a
registry model written to mirror its public counterpart block-for-block
(e.g. ``inception_v3``) loads that counterpart's checkpoints directly.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np
import jax


def _name_counter(name: str) -> int:
    """Trailing auto-name counter ('conv2d_9' -> 9, 'conv2d' -> -1) —
    creation order within a kind on BOTH sides (graph traversals order
    branchy models differently from code order, so topological order
    cannot pair them; creation counters can)."""
    tail = name.rpartition("_")[2]
    return int(tail) if tail.isdigit() else -1


def _our_layers_by_kind(net) -> Dict[str, List[object]]:
    """kind -> weight-bearing layers of ``net``'s graph in CREATION
    order; kind in {conv, bn, dense}."""
    from ..pipeline.api.keras.layers.convolutional import _ConvND
    from ..pipeline.api.keras.layers.core import Dense
    from ..pipeline.api.keras.layers.normalization import (
        BatchNormalization)

    graph = net.to_graph()
    seen = set()
    out: Dict[str, List[object]] = {"conv": [], "bn": [], "dense": []}
    for v in graph.nodes:
        layer = v.layer
        if layer is None or id(layer) in seen:
            continue
        seen.add(id(layer))
        if isinstance(layer, _ConvND):
            out["conv"].append(layer)
        elif isinstance(layer, BatchNormalization):
            out["bn"].append(layer)
        elif isinstance(layer, Dense):
            out["dense"].append(layer)
    for kind in out:
        out[kind].sort(key=lambda l: _name_counter(l.name))
    return out


def _pair_by_kind(ours: Dict[str, List], theirs: Dict[str, List],
                  source: str):
    """Zip per-kind creation-order sequences; count mismatches raise."""
    n_ours = sum(len(v) for v in ours.values())
    n_theirs = sum(len(v) for v in theirs.values())
    if n_ours != n_theirs or any(
            len(ours[k]) != len(theirs.get(k, [])) for k in ours):
        detail = {k: (len(ours[k]), len(theirs.get(k, []))) for k in ours}
        raise ValueError(
            f"op-count mismatch: ours vs {source} per kind "
            f"(ours, theirs) = {detail}")
    for kind in ("conv", "bn", "dense"):
        for ol, tl in zip(ours[kind], theirs.get(kind, [])):
            yield kind, ol, tl


def _apply(net, params: Dict, state: Dict):
    """Merge converted entries into the net's current weights/state."""
    trainer = net.ensure_inference_ready()
    new_params = dict(jax.device_get(trainer.state.params))
    for k, v in params.items():
        cur = new_params.get(k, {})
        merged = dict(cur)
        merged.update(v)
        new_params[k] = merged
    net.set_weights(new_params)
    if state:
        new_state = dict(jax.device_get(trainer.state.model_state))
        for k, v in state.items():
            cur = dict(new_state.get(k, {}))
            cur.update(v)
            new_state[k] = cur
        # place under the trainer's replicated mesh sharding — a bare
        # device_put would commit the stats to one device and conflict
        # with mesh-sharded params inside jit
        trainer.state.model_state = jax.device_put(
            new_state, trainer._repl_sharding)
    return net


def load_tf_keras_weights(net, keras_model) -> object:
    """Transfer a tf.keras model's weights into ``net`` by op order.

    Supports Conv2D (with/without bias), BatchNormalization (with/without
    scale/center), and Dense.  Raises when the op sequences disagree in
    kind or shape — a structural mismatch, not a silent skip."""
    ours = _our_layers_by_kind(net)
    kind_of = {"Conv2D": "conv", "BatchNormalization": "bn",
               "Dense": "dense"}
    theirs: Dict[str, List[object]] = {"conv": [], "bn": [], "dense": []}
    for kl in keras_model.layers:
        kind = kind_of.get(type(kl).__name__)
        if kind:
            theirs[kind].append(kl)
    for kind in theirs:
        theirs[kind].sort(key=lambda l: _name_counter(l.name))
    params: Dict = {}
    state: Dict = {}
    for ok, ol, tl in _pair_by_kind(ours, theirs, "keras model"):
        w = [np.asarray(a) for a in tl.get_weights()]
        if ok == "conv":
            entry = {"W": w[0]}  # HWIO on both sides
            if getattr(ol, "bias", False):
                # source without a bias: zero ours — forward-equivalent
                # to the bias-free source (never keep random init)
                entry["b"] = (w[1] if len(w) > 1
                              else np.zeros((w[0].shape[-1],), np.float32))
            params[ol.name] = entry
        elif ok == "dense":
            entry = {"W": w[0]}
            if getattr(ol, "bias", True):
                entry["b"] = (w[1] if len(w) > 1
                              else np.zeros((w[0].shape[-1],), np.float32))
            params[ol.name] = entry
        else:  # bn — keras order: [gamma][beta] mean var
            i = 0
            n = w[-1].shape[0]
            if getattr(tl, "scale", True):
                gamma = w[i]
                i += 1
            else:
                gamma = np.ones((n,), np.float32)
            if getattr(tl, "center", True):
                beta = w[i]
                i += 1
            else:
                beta = np.zeros((n,), np.float32)
            mean, var = w[i], w[i + 1]
            params[ol.name] = {"gamma": gamma, "beta": beta}
            state[ol.name] = {"moving_mean": mean, "moving_var": var,
                              "count": np.float32(np.inf)}
    return _apply(net, params, state)


def _dense_flatten_reorders(net) -> Dict[str, tuple]:
    """dense-layer-name -> (H, W, C) when the dense input IS a Flatten
    of a 4-D NHWC feature map.  Torch flattens NCHW (row index
    c·H·W + h·W + w) while this framework flattens NHWC, so the first
    linear after a conv→flatten boundary needs its input rows
    permuted — the classic layout trap of every torch importer."""
    from ..pipeline.api.keras.layers.core import Dense, Flatten
    out: Dict[str, tuple] = {}
    for v in net.to_graph().nodes:
        if not isinstance(v.layer, Dense) or not v.inputs:
            continue
        # walk back through shape-preserving pass-throughs (Dropout,
        # Activation, ...) — torch heads are commonly
        # Flatten -> Dropout -> Linear
        src = v.inputs[0]
        hops = 0
        while (not isinstance(getattr(src, "layer", None), Flatten)
               and len(src.inputs) == 1
               and src.shape == src.inputs[0].shape and hops < 8):
            src = src.inputs[0]
            hops += 1
        if isinstance(getattr(src, "layer", None), Flatten) \
                and src.inputs and len(src.inputs[0].shape) == 4:
            _, h, w, c = src.inputs[0].shape
            out[v.layer.name] = (h, w, c)
    return out


def load_torch_state_dict(net, state_dict) -> object:
    """Transfer a PyTorch ``state_dict`` into ``net`` by op order.

    Layout conversion (the reference's weightConverter traps):
    conv OIHW → HWIO (transpose 2,3,1,0); linear (out,in) → (in,out),
    with the first linear after a conv→Flatten boundary additionally
    re-indexed from torch's CHW flatten order to NHWC's HWC order.
    BN weight/bias → gamma/beta, running stats → moving stats."""
    ours = _our_layers_by_kind(net)
    reorders = _dense_flatten_reorders(net)
    # group torch entries by module prefix, preserving insertion order
    # (state_dict insertion order IS construction order in torch)
    groups: Dict[str, Dict[str, np.ndarray]] = {}
    for key, val in state_dict.items():
        if key.endswith("num_batches_tracked"):
            continue
        prefix, _, leaf = key.rpartition(".")
        groups.setdefault(prefix, {})[leaf] = np.asarray(
            val.detach().cpu().numpy() if hasattr(val, "detach") else val)
    theirs: Dict[str, List] = {"conv": [], "bn": [], "dense": []}
    for prefix, g in groups.items():
        if "running_mean" in g:
            theirs["bn"].append(g)
        elif "weight" in g and g["weight"].ndim == 4:
            theirs["conv"].append(g)
        elif "weight" in g and g["weight"].ndim == 2:
            theirs["dense"].append(g)
    params: Dict = {}
    state: Dict = {}
    for ok, ol, g in _pair_by_kind(ours, theirs, "state_dict"):
        if ok == "conv":
            w = g["weight"].transpose(2, 3, 1, 0)  # OIHW→HWIO
            entry = {"W": w}
            if getattr(ol, "bias", False):
                # bias-free torch conv: zero ours (forward-equivalent)
                entry["b"] = g.get("bias",
                                   np.zeros((w.shape[-1],), np.float32))
            params[ol.name] = entry
        elif ok == "dense":
            w = g["weight"].T  # (out,in) → (in,out)
            hwc = reorders.get(ol.name)
            if hwc is not None and w.shape[0] == int(np.prod(hwc)):
                h, ww, c = hwc
                # torch rows are (C, H, W)-ordered; ours are (H, W, C)
                w = (w.reshape(c, h, ww, -1).transpose(1, 2, 0, 3)
                     .reshape(h * ww * c, -1))
            entry = {"W": w}
            if getattr(ol, "bias", True):
                entry["b"] = g.get("bias",
                                   np.zeros((w.shape[-1],), np.float32))
            params[ol.name] = entry
        else:
            n = g["running_mean"].shape[0]
            params[ol.name] = {
                "gamma": g.get("weight", np.ones((n,), np.float32)),
                "beta": g.get("bias", np.zeros((n,), np.float32))}
            state[ol.name] = {"moving_mean": g["running_mean"],
                              "moving_var": g["running_var"],
                              # imported running stats are converged
                              # averages: inf => debias denom 1
                              "count": np.float32(np.inf)}
    return _apply(net, params, state)
