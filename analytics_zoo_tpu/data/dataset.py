"""Input pipeline: host-side batching feeding the device mesh.

Parity surface: the reference's data path — ``TFDataset.from_rdd`` with its
"batch_size % total cores == 0" contract (reference:
pyzoo/zoo/pipeline/api/net.py:432-509,461-465) and BigDL
DataSet/Sample/SampleToMiniBatch chains (Topology.scala:235-246).

TPU-first shape: a Dataset yields fixed-shape numpy batches; ``shard()``
device_puts each batch with the mesh's data sharding so per-device shards
land directly on their chips (the role Spark partition→core mapping played).
The global batch must divide evenly over the data axis — the same invariant
the reference enforces per core — checked eagerly with a clear error.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import jax


def _stack_tree(samples: List[Any]):
    """Stack a list of samples (arrays or tuples/lists of arrays)."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(
            _stack_tree([s[i] for s in samples]) for i in range(len(first)))
    return np.stack(samples)


class Dataset:
    """A finite, re-iterable dataset of (x, y) pairs (y may be None)."""

    def __init__(self, x, y=None, size: Optional[int] = None, valid=None):
        self.x = x
        self.y = y
        self._size = size
        # optional per-row validity (False rows are wrap-around fillers
        # from shard_by_process) — evaluate() masks them out of metrics
        self.valid = valid

    # ---- constructors (parity with TFDataset.from_* family) ----
    @classmethod
    def from_ndarray(cls, x, y=None) -> "Dataset":
        """From numpy arrays (or tuple/list of arrays for multi-input)."""
        xs = x if isinstance(x, (tuple, list)) else [x]
        n = len(np.asarray(xs[0]))
        for a in xs:
            if len(np.asarray(a)) != n:
                raise ValueError("All input arrays must share length")
        if y is not None:
            ys = y if isinstance(y, (tuple, list)) else [y]
            for a in ys:
                if len(np.asarray(a)) != n:
                    raise ValueError("x and y must share length")
        return cls(x, y, size=n)

    @classmethod
    def from_iterable(cls, samples: Iterable, size: Optional[int] = None
                      ) -> "Dataset":
        """From an iterable of (x, y) sample pairs (the RDD-like path:
        anything partition-shaped collapses to an iterable per host)."""
        samples = list(samples)
        xs = [s[0] for s in samples]
        ys = [s[1] for s in samples] if isinstance(
            samples[0], (tuple, list)) and len(samples[0]) > 1 else None
        x = _stack_tree(xs)
        y = _stack_tree(ys) if ys is not None else None
        return cls(x, y, size=len(samples))

    # alias for API parity with TFDataset.from_rdd: an "rdd" here is any
    # iterable of samples already local to this host
    from_rdd = from_iterable

    @property
    def size(self) -> int:
        if self._size is None:
            first = self.x[0] if isinstance(self.x, (tuple, list)) else self.x
            self._size = len(np.asarray(first))
        return self._size

    def _index(self, arrs, idx):
        if arrs is None:
            return None
        if isinstance(arrs, (tuple, list)):
            return tuple(np.asarray(a)[idx] for a in arrs)
        return np.asarray(arrs)[idx]

    def batches(self, batch_size: int, shuffle: bool = False,
                seed: int = 0, epoch: int = 0, drop_remainder: bool = True,
                ) -> Iterator[Tuple[Any, Any]]:
        """Yield (x, y) numpy batches.

        With ``drop_remainder`` (the default, matching the reference's
        strict divisibility) the trailing partial batch is dropped so every
        step has identical shapes — one XLA compilation, no recompiles.
        """
        n = self.size
        idx = np.arange(n)
        if shuffle:
            rng = np.random.default_rng(seed + epoch)
            rng.shuffle(idx)
        steps = n // batch_size if drop_remainder else math.ceil(
            n / batch_size)
        for s in range(steps):
            sel = idx[s * batch_size:(s + 1) * batch_size]
            yield self._index(self.x, sel), self._index(self.y, sel)

    def steps_per_epoch(self, batch_size: int,
                        drop_remainder: bool = True) -> int:
        if drop_remainder:
            return self.size // batch_size
        return math.ceil(self.size / batch_size)

    def shard_by_process(self, process_index: Optional[int] = None,
                         process_count: Optional[int] = None) -> "Dataset":
        """This host's shard for multi-host training — the TPU-native
        analog of the reference's RDD-partition→executor assignment
        (net.py:458-468).  Rows are taken strided (``x[pid::nproc]``) and
        the trailing ragged edge is wrapped around so every process holds
        exactly ``ceil(n / nproc)`` rows — equal per-host step counts keep
        the pod-wide SPMD program in lockstep (at most ``nproc - 1``
        duplicated samples per epoch).  Wrapped filler rows are flagged in
        ``.valid`` so ``evaluate`` excludes them from metrics."""
        pid = (process_index if process_index is not None
               else jax.process_index())
        pc = (process_count if process_count is not None
              else jax.process_count())
        n = self.size
        per = math.ceil(n / pc)
        raw = np.arange(pid, pid + per * pc, pc)
        idx = raw % n
        valid = raw < n
        return Dataset(self._index(self.x, idx), self._index(self.y, idx),
                       size=per, valid=None if valid.all() else valid)

    def map(self, fn: Callable) -> "Dataset":
        """Apply fn to every (x, y) pair eagerly (Preprocessing chains from
        feature/common.py slot in here)."""
        n = self.size
        xs, ys = [], []
        for i in range(n):
            x_i = self._index(self.x, i)
            y_i = self._index(self.y, i)
            out = fn((x_i, y_i))
            xs.append(out[0])
            ys.append(out[1])
        x = _stack_tree(xs)
        y = _stack_tree(ys) if ys[0] is not None else None
        return Dataset(x, y, size=n, valid=self.valid)


def check_batch_divisibility(batch_size: int, dp: int, n_processes: int = 1):
    """The reference's hard contract (net.py:461-465), lifted to the mesh:
    the global batch must divide the data-parallel degree and (multi-host)
    the process count, so every host feeds an equal per-host shard."""
    if batch_size % max(dp, 1) != 0:
        raise ValueError(
            f"batch_size ({batch_size}) must be divisible by the data-"
            f"parallel degree ({dp}) — same invariant as the reference's "
            "batch_size % total_core_num == 0")
    if batch_size % max(n_processes, 1) != 0:
        raise ValueError(
            f"global batch_size ({batch_size}) must be divisible by the "
            f"number of host processes ({n_processes}) for per-host "
            "feeding")


def prefetch_iterator(iterator: Iterator, put_fn: Callable, depth: int = 2):
    """Keep ``depth`` device-put batches in flight ahead of the consumer.

    ``jax.device_put`` is asynchronous, so enqueueing the next batches while
    the current step computes overlaps host→device transfer with the device
    step — the role the reference's Spark-partition prefetch played.  This
    replaces the synchronous put-then-step pattern (one of the "2 Spark jobs
    per step" overheads the rebuild removes, wp-bigdl.md:113-160)."""
    import collections
    q = collections.deque()
    for item in iterator:
        q.append(put_fn(item))
        if len(q) > depth:
            yield q.popleft()
    while q:
        yield q.popleft()


def shard_batch(batch, sharding):
    """Place a host batch onto the mesh with the given NamedSharding."""
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sharding) if a is not None else None,
        batch, is_leaf=lambda a: a is None or not isinstance(a, (tuple, list,
                                                                 dict)))
