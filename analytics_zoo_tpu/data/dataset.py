"""Input pipeline: host-side batching feeding the device mesh.

Parity surface: the reference's data path — ``TFDataset.from_rdd`` with its
"batch_size % total cores == 0" contract (reference:
pyzoo/zoo/pipeline/api/net.py:432-509,461-465) and BigDL
DataSet/Sample/SampleToMiniBatch chains (Topology.scala:235-246).

TPU-first shape: a Dataset yields fixed-shape numpy batches; ``shard()``
device_puts each batch with the mesh's data sharding so per-device shards
land directly on their chips (the role Spark partition→core mapping played).
The global batch must divide evenly over the data axis — the same invariant
the reference enforces per core — checked eagerly with a clear error.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np
import jax


def _stack_tree(samples: List[Any]):
    """Stack a list of samples (arrays or tuples/lists of arrays)."""
    first = samples[0]
    if isinstance(first, (tuple, list)):
        return type(first)(
            _stack_tree([s[i] for s in samples]) for i in range(len(first)))
    return np.stack(samples)


class Dataset:
    """A finite, re-iterable dataset of (x, y) pairs (y may be None)."""

    def __init__(self, x, y=None, size: Optional[int] = None, valid=None):
        self.x = x
        self.y = y
        self._size = size
        # optional per-row validity (False rows are wrap-around fillers
        # from shard_by_process) — evaluate() masks them out of metrics
        self.valid = valid

    # ---- constructors (parity with TFDataset.from_* family) ----
    @classmethod
    def from_ndarray(cls, x, y=None) -> "Dataset":
        """From numpy arrays (or tuple/list of arrays for multi-input)."""
        xs = x if isinstance(x, (tuple, list)) else [x]
        n = len(np.asarray(xs[0]))
        for a in xs:
            if len(np.asarray(a)) != n:
                raise ValueError("All input arrays must share length")
        if y is not None:
            ys = y if isinstance(y, (tuple, list)) else [y]
            for a in ys:
                if len(np.asarray(a)) != n:
                    raise ValueError("x and y must share length")
        return cls(x, y, size=n)

    @classmethod
    def from_iterable(cls, samples: Iterable, size: Optional[int] = None
                      ) -> "Dataset":
        """From an iterable of (x, y) sample pairs (the RDD-like path:
        anything partition-shaped collapses to an iterable per host)."""
        samples = list(samples)
        xs = [s[0] for s in samples]
        ys = [s[1] for s in samples] if isinstance(
            samples[0], (tuple, list)) and len(samples[0]) > 1 else None
        x = _stack_tree(xs)
        y = _stack_tree(ys) if ys is not None else None
        return cls(x, y, size=len(samples))

    # alias for API parity with TFDataset.from_rdd: an "rdd" here is any
    # iterable of samples already local to this host
    from_rdd = from_iterable

    @classmethod
    def from_loader(cls, loader) -> "StreamingDataset":
        """Stream batches from an ``ImageLoader`` (or any object with
        ``files``-like length that re-iterates (x, y) batches) WITHOUT
        materializing — training over a folder larger than host RAM
        (reference streams via sc.binaryFiles, ImageSet.scala:80)."""
        n = len(getattr(loader, "files", []) or []) or None

        def factory(shuffle, seed, epoch):
            if hasattr(loader, "shuffle"):
                loader.shuffle = shuffle
            if hasattr(loader, "seed") and hasattr(loader, "_epoch"):
                # deterministic per-epoch order under the loader's own
                # seed+epoch scheme
                loader.seed = seed
                loader._epoch = epoch
            return iter(loader)

        ds = StreamingDataset(factory, size=n)
        ds._can_shuffle = hasattr(loader, "shuffle")
        return ds

    @classmethod
    def from_batch_iterable(cls, make_iter: Callable[[], Iterable],
                            size: Optional[int] = None,
                            steps_per_epoch: Optional[int] = None,
                            shuffle_buffer: Optional[int] = 8192,
                            ) -> "StreamingDataset":
        """Stream from any zero-arg factory returning an iterator of
        (x, y) numpy batches (arbitrary chunk sizes — they are re-batched
        to the requested batch size).

        The factory itself cannot re-order the source, so fit's
        ``shuffle=True`` shuffles through a **windowed buffer**:
        ``shuffle_buffer`` rows (default 8192) are collected, permuted
        with the per-epoch seed, and emitted; the sub-batch tail carries
        into the next window.  Memory stays bounded at ~one window.  Set
        ``shuffle_buffer=None`` to restore the old behavior (source
        order replayed, one warning logged).  Rows move at most ~one
        window from their source position — shuffle at the source too if
        the stream is strongly ordered (e.g. sorted by label)."""
        ds = StreamingDataset(lambda shuffle, seed, epoch: make_iter(),
                              size=size, steps_hint=steps_per_epoch)
        ds._can_shuffle = False
        ds._shuffle_buffer = shuffle_buffer
        return ds

    @property
    def size(self) -> int:
        if self._size is None:
            first = self.x[0] if isinstance(self.x, (tuple, list)) else self.x
            self._size = len(np.asarray(first))
        return self._size

    def _index(self, arrs, idx):
        if arrs is None:
            return None
        if isinstance(arrs, (tuple, list)):
            return tuple(np.asarray(a)[idx] for a in arrs)
        return np.asarray(arrs)[idx]

    def batches(self, batch_size: int, shuffle: bool = False,
                seed: int = 0, epoch: int = 0, drop_remainder: bool = True,
                ) -> Iterator[Tuple[Any, Any]]:
        """Yield (x, y) numpy batches.

        With ``drop_remainder`` (the default, matching the reference's
        strict divisibility) the trailing partial batch is dropped so every
        step has identical shapes — one XLA compilation, no recompiles.
        """
        n = self.size
        idx = np.arange(n)
        if shuffle:
            rng = np.random.default_rng(seed + epoch)
            rng.shuffle(idx)
        steps = n // batch_size if drop_remainder else math.ceil(
            n / batch_size)
        for s in range(steps):
            sel = idx[s * batch_size:(s + 1) * batch_size]
            yield self._index(self.x, sel), self._index(self.y, sel)

    def steps_per_epoch(self, batch_size: int,
                        drop_remainder: bool = True) -> int:
        if drop_remainder:
            return self.size // batch_size
        return math.ceil(self.size / batch_size)

    def shard_by_process(self, process_index: Optional[int] = None,
                         process_count: Optional[int] = None) -> "Dataset":
        """This host's shard for multi-host training — the TPU-native
        analog of the reference's RDD-partition→executor assignment
        (net.py:458-468).  Rows are taken strided (``x[pid::nproc]``) and
        the trailing ragged edge is wrapped around so every process holds
        exactly ``ceil(n / nproc)`` rows — equal per-host step counts keep
        the pod-wide SPMD program in lockstep (at most ``nproc - 1``
        duplicated samples per epoch).  Wrapped filler rows are flagged in
        ``.valid`` so ``evaluate`` excludes them from metrics."""
        pid = (process_index if process_index is not None
               else jax.process_index())
        pc = (process_count if process_count is not None
              else jax.process_count())
        n = self.size
        per = math.ceil(n / pc)
        raw = np.arange(pid, pid + per * pc, pc)
        idx = raw % n
        valid = raw < n
        return Dataset(self._index(self.x, idx), self._index(self.y, idx),
                       size=per, valid=None if valid.all() else valid)

    def map(self, fn: Callable, batched: bool = False,
            batch_size: int = 4096) -> "Dataset":
        """Apply fn eagerly (Preprocessing chains from feature/common.py
        slot in here).

        ``batched=False``: fn maps one (x, y) SAMPLE pair (the reference's
        per-record Preprocessing contract).  ``batched=True``: fn maps a
        whole (x_batch, y_batch) pair and is applied in ``batch_size``
        chunks — one python call per chunk instead of per sample, the
        right shape for numpy-vectorized transforms at ImageNet scale."""
        n = self.size
        if batched:
            xs, ys = [], []
            for s in range(0, n, batch_size):
                sel = np.arange(s, min(s + batch_size, n))
                out = fn((self._index(self.x, sel), self._index(self.y,
                                                                sel)))
                xs.append(out[0])
                ys.append(out[1])
            cat = lambda parts: (
                tuple(np.concatenate([p[i] for p in parts])
                      for i in range(len(parts[0])))
                if isinstance(parts[0], (tuple, list))
                else np.concatenate(parts))
            x = cat(xs)
            y = cat(ys) if ys[0] is not None else None
            return Dataset(x, y, size=n, valid=self.valid)
        xs, ys = [], []
        for i in range(n):
            x_i = self._index(self.x, i)
            y_i = self._index(self.y, i)
            out = fn((x_i, y_i))
            xs.append(out[0])
            ys.append(out[1])
        x = _stack_tree(xs)
        y = _stack_tree(ys) if ys[0] is not None else None
        return Dataset(x, y, size=n, valid=self.valid)


def _batch_rows(batch) -> int:
    x = batch[0] if isinstance(batch, tuple) and len(batch) == 2 else batch
    first = x[0] if isinstance(x, (tuple, list)) else x
    return len(first)


def _batch_concat_all(batches):
    """Concatenate a list of (x, y) batches tree-wise (y may be None)."""
    def cat(parts):
        if parts[0] is None:
            return None
        if isinstance(parts[0], (tuple, list)):
            return tuple(np.concatenate([p[i] for p in parts])
                         for i in range(len(parts[0])))
        return np.concatenate(parts)
    return cat([b[0] for b in batches]), cat([b[1] for b in batches])


def _batch_slice(batch, start, stop):
    def sl(u):
        if u is None:
            return None
        if isinstance(u, (tuple, list)):
            return tuple(ui[start:stop] for ui in u)
        return u[start:stop]
    return sl(batch[0]), sl(batch[1])


def _batch_take(batch, idx):
    """Row-permute an (x, y) batch tree by index array."""
    def tk(u):
        if u is None:
            return None
        if isinstance(u, (tuple, list)):
            return tuple(np.asarray(ui)[idx] for ui in u)
        return np.asarray(u)[idx]
    return tk(batch[0]), tk(batch[1])


class StreamingDataset(Dataset):
    """Batches stream from a re-iterable source — NOTHING is materialized
    beyond the current working window, so a folder larger than host RAM
    trains in bounded memory (the role sc.binaryFiles streaming plays in
    the reference, ImageSet.scala:80).

    ``factory(shuffle, seed, epoch)`` returns a fresh iterator of (x, y)
    numpy batches of ARBITRARY chunk sizes; ``batches()`` re-chunks them
    to the requested batch size with a small concat buffer.
    """

    def __init__(self, factory: Callable, size: Optional[int] = None,
                 steps_hint: Optional[int] = None):
        super().__init__(None, None, size=size)
        self._factory = factory
        self._steps_hint = steps_hint
        self._maps: List[Callable] = []

    @property
    def size(self) -> Optional[int]:
        return self._size  # may be None (unknown until one full pass)

    def map(self, fn: Callable, batched: bool = False
            ) -> "StreamingDataset":
        """LAZY map: fn is applied to each sample (``batched=False``, the
        same contract as ``Dataset.map``) or to each streamed (x, y)
        batch (``batched=True`` — one python call per chunk) — either way
        nothing materializes."""
        if batched:
            wrapped = fn
        else:
            def wrapped(batch, _fn=fn):
                x, y = batch
                n = _batch_rows(batch)

                def at(u, i):
                    if u is None:
                        return None
                    if isinstance(u, (tuple, list)):
                        return tuple(ui[i] for ui in u)
                    return u[i]

                outs = [_fn((at(x, i), at(y, i))) for i in range(n)]
                xs = _stack_tree([o[0] for o in outs])
                ys = (_stack_tree([o[1] for o in outs])
                      if outs and outs[0][1] is not None else None)
                return xs, ys
        child = StreamingDataset(self._factory, size=self._size,
                                 steps_hint=self._steps_hint)
        child._maps = self._maps + [wrapped]
        child._can_shuffle = self._can_shuffle
        child._shuffle_buffer = self._shuffle_buffer
        return child

    _can_shuffle = True
    _shuffle_buffer: Optional[int] = None
    _warned_no_shuffle = False

    def batches(self, batch_size: int, shuffle: bool = False,
                seed: int = 0, epoch: int = 0, drop_remainder: bool = True,
                ) -> Iterator[Tuple[Any, Any]]:
        if shuffle and not self._can_shuffle:
            if self._shuffle_buffer:
                yield from self._windowed_shuffle_batches(
                    batch_size, seed, epoch, drop_remainder)
                return
            if not StreamingDataset._warned_no_shuffle:
                StreamingDataset._warned_no_shuffle = True
                from ..observability.log import get_logger
                get_logger("analytics_zoo_tpu.data").warning(
                    "this stream source cannot shuffle and has "
                    "shuffle_buffer=None — every epoch replays the "
                    "source order. Shuffle at the source or pass a "
                    "shuffle_buffer to from_batch_iterable.")
        src = self._ingest(self._factory(shuffle, seed, epoch))
        # pending chunks + running row count: one concatenate per EMITTED
        # batch (a grow-the-buffer concat per source chunk would copy the
        # whole window once per chunk — ~batch/chunk× write amplification
        # on the thread that keeps the TPU fed)
        pending: List[Tuple[Any, Any]] = []
        rows = 0
        count = 0
        for chunk in src:
            pending.append(chunk)
            rows += _batch_rows(chunk)
            while rows >= batch_size:
                window = pending[0] if len(pending) == 1 else \
                    _batch_concat_all(pending)
                pending = []
                n = _batch_rows(window)
                start = 0
                while n - start >= batch_size:
                    yield _batch_slice(window, start, start + batch_size)
                    start += batch_size
                    count += batch_size
                if start < n:
                    pending = [_batch_slice(window, start, n)]
                rows = n - start
        if rows:
            count += rows
            if not drop_remainder:
                yield (pending[0] if len(pending) == 1
                       else _batch_concat_all(pending))
        if self._size is None:
            self._size = count  # learned after one full pass

    def _ingest(self, src) -> Iterator[Tuple[Any, Any]]:
        """Normalize source chunks to (x, y) tuples and apply the lazy
        map chain — the single ingest path shared by the ordered and
        windowed-shuffle batch iterators."""
        for chunk in src:
            if not (isinstance(chunk, tuple) and len(chunk) == 2):
                chunk = (chunk, None)
            for fn in self._maps:
                chunk = fn(chunk)
            yield chunk

    def _windowed_shuffle_batches(self, batch_size: int, seed: int,
                                  epoch: int, drop_remainder: bool
                                  ) -> Iterator[Tuple[Any, Any]]:
        """Windowed-buffer shuffle for sources that cannot re-order
        themselves: collect ``_shuffle_buffer`` rows, permute, emit full
        batches, carry the sub-batch tail into the next window.  Bounded
        memory (~one window); per-epoch determinism via seed+epoch."""
        rng = np.random.default_rng(seed + epoch)
        window_rows = max(int(self._shuffle_buffer), batch_size)
        src = self._ingest(self._factory(False, seed, epoch))
        pending: List[Tuple[Any, Any]] = []
        rows = 0
        count = 0

        def drain(final):
            nonlocal pending, rows, count
            window = (pending[0] if len(pending) == 1
                      else _batch_concat_all(pending))
            n = _batch_rows(window)
            perm = rng.permutation(n)
            window = _batch_take(window, perm)
            start = 0
            while n - start >= batch_size:
                yield _batch_slice(window, start, start + batch_size)
                start += batch_size
                count += batch_size
            if start < n:
                if final:
                    count += n - start
                    if not drop_remainder:
                        yield _batch_slice(window, start, n)
                    pending, rows = [], 0
                else:
                    pending = [_batch_slice(window, start, n)]
                    rows = n - start
            else:
                pending, rows = [], 0

        for chunk in src:
            pending.append(chunk)
            rows += _batch_rows(chunk)
            if rows >= window_rows:
                yield from drain(final=False)
        if rows:
            yield from drain(final=True)
        if self._size is None:
            self._size = count

    def steps_per_epoch(self, batch_size: int,
                        drop_remainder: bool = True) -> int:
        if self._size is not None:
            return super().steps_per_epoch(batch_size, drop_remainder)
        if self._steps_hint is not None:
            return self._steps_hint
        raise ValueError("unknown stream length — pass steps_per_epoch to "
                         "from_batch_iterable or iterate one epoch first")

    def shard_by_process(self, process_index=None, process_count=None):
        raise NotImplementedError(
            "shard a stream at the source (give each host its own file "
            "list / loader) rather than wrapping shard_by_process around "
            "it")


def check_batch_divisibility(batch_size: int, dp: int, n_processes: int = 1):
    """The reference's hard contract (net.py:461-465), lifted to the mesh:
    the global batch must divide the data-parallel degree and (multi-host)
    the process count, so every host feeds an equal per-host shard."""
    if batch_size % max(dp, 1) != 0:
        raise ValueError(
            f"batch_size ({batch_size}) must be divisible by the data-"
            f"parallel degree ({dp}) — same invariant as the reference's "
            "batch_size % total_core_num == 0")
    if batch_size % max(n_processes, 1) != 0:
        raise ValueError(
            f"global batch_size ({batch_size}) must be divisible by the "
            f"number of host processes ({n_processes}) for per-host "
            "feeding")


def prefetch_iterator(iterator: Iterator, put_fn: Callable, depth: int = 2):
    """Keep ``depth`` device-put batches in flight ahead of the consumer.

    ``jax.device_put`` is asynchronous, but the HOST work feeding it
    (decode, shuffle-gather, ``np.stack``, padding) is not — so this now
    delegates to ``common.prefetch``: ``put_fn`` runs on a background
    thread, overlapping batch *k+1*'s host materialization AND transfer
    with batch *k*'s device compute (the role the reference's
    Spark-partition prefetch played, wp-bigdl.md:113-160)."""
    from ..common.prefetch import prefetch
    return prefetch(iterator, transform=put_fn, depth=depth)


def shard_batch(batch, sharding):
    """Place a host batch onto the mesh with the given NamedSharding."""
    return jax.tree_util.tree_map(
        lambda a: jax.device_put(a, sharding) if a is not None else None,
        batch, is_leaf=lambda a: a is None or not isinstance(a, (tuple, list,
                                                                 dict)))
