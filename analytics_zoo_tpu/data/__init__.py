from .dataset import Dataset, check_batch_divisibility, shard_batch
