"""Batched host-side image loader: files → device-ready float batches.

The reference's input pipeline decodes images inside Spark tasks through
OpenCV JNI (ImageSet.read + ImageBytesToMat + ImageResize +
ImageChannelNormalize chained per-image).  On TPU the host must hand the
device ready NHWC float batches at HBM-fill rate, so this loader does
decode + resize + normalize for a whole batch in one native C++ call
(analytics_zoo_tpu/native: libjpeg/libpng + std::thread pool) and overlaps
the next batch's decode with device compute via a background prefetch
thread.  Falls back to PIL per-image when the native library is
unavailable.
"""

from __future__ import annotations

import os
import queue
import threading
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .. import native
from .dataset import Dataset

_IMG_EXTS = (".jpg", ".jpeg", ".png")


def list_image_files(path: str, with_label: bool = False):
    """Recursively list image files; with_label uses the immediate
    subdirectory name as the class label (same layout ImageSet.read
    consumes)."""
    files: List[str] = []
    labels: List[int] = []
    label_names: List[str] = []
    if with_label:
        label_names = sorted(
            d for d in os.listdir(path)
            if os.path.isdir(os.path.join(path, d)))
        index = {name: i for i, name in enumerate(label_names)}
        for name in label_names:
            sub = os.path.join(path, name)
            for root, _, fnames in os.walk(sub):
                for f in sorted(fnames):
                    if f.lower().endswith(_IMG_EXTS):
                        files.append(os.path.join(root, f))
                        labels.append(index[name])
    else:
        for root, _, fnames in os.walk(path):
            for f in sorted(fnames):
                if f.lower().endswith(_IMG_EXTS):
                    files.append(os.path.join(root, f))
    return files, (np.asarray(labels, np.int32) if with_label else None), \
        label_names


def _decode_batch_pil(blobs: Sequence[bytes], size, mean, std, scale):
    import io
    from PIL import Image
    h, w = size
    out = np.empty((len(blobs), h, w, 3), np.float32)
    for i, raw in enumerate(blobs):
        img = Image.open(io.BytesIO(raw)).convert("RGB")
        if img.size != (w, h):
            img = img.resize((w, h), Image.BILINEAR)
        out[i] = np.asarray(img, np.float32)
    out *= scale
    if mean is not None:
        out -= np.asarray(mean, np.float32)
    if std is not None:
        out /= np.asarray(std, np.float32)
    return out


class ImageLoader:
    """Iterate (images, labels) batches decoded natively off the main
    thread.

    images: float32 (B, H, W, 3) RGB, normalized
    ``(pixel * scale - mean) / std``.
    """

    def __init__(self, files: Sequence[str],
                 labels: Optional[np.ndarray] = None,
                 batch_size: int = 32, size=(224, 224),
                 mean: Optional[Sequence[float]] = None,
                 std: Optional[Sequence[float]] = None,
                 scale: float = 1.0, shuffle: bool = False, seed: int = 0,
                 num_threads: int = 0, drop_remainder: bool = False,
                 prefetch: int = 2, out_dtype: str = "float32"):
        self.files = list(files)
        self.labels = labels if labels is None else np.asarray(labels)
        if self.labels is not None and len(self.labels) != len(self.files):
            raise ValueError("labels/files length mismatch")
        self.batch_size = int(batch_size)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.mean, self.std, self.scale = mean, std, float(scale)
        self.shuffle, self.seed = shuffle, seed
        self.num_threads = num_threads
        self.drop_remainder = drop_remainder
        self.prefetch = max(int(prefetch), 1)
        self._epoch = 0
        # out_dtype="uint8": emit raw resized pixels and DEFER
        # normalization to the device — a 4x smaller host→device transfer
        # (the normalize belongs in the jit'd step; see bench.py)
        if out_dtype not in ("float32", "uint8"):
            raise ValueError(f"unsupported out_dtype {out_dtype!r}")
        if out_dtype == "uint8" and (mean is not None or std is not None
                                     or scale != 1.0):
            raise ValueError(
                "out_dtype='uint8' emits RAW pixels — normalization "
                "(mean/std/scale) must be applied on-device by the "
                "consumer; passing it here would be silently dropped")
        self.out_dtype = out_dtype

    @classmethod
    def from_folder(cls, path: str, with_label: bool = True, **kw
                    ) -> "ImageLoader":
        files, labels, names = list_image_files(path, with_label)
        loader = cls(files, labels=labels, **kw)
        loader.label_names = names
        return loader

    def steps_per_epoch(self) -> int:
        n = len(self.files)
        if self.drop_remainder:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size

    def _decode(self, blobs: List[bytes]) -> np.ndarray:
        if self.out_dtype == "uint8":
            if native.available():
                # the native decoder emits float32; the cast-down costs a
                # host pass (~4 bytes/px) — only the host→device transfer
                # shrinks.  A native uint8 output mode would remove it.
                raw = native.decode_resize_normalize_batch(
                    blobs, self.size, mean=None, std=None, scale=1.0,
                    num_threads=self.num_threads)
                return raw.astype(np.uint8)
            import io
            from PIL import Image
            h, w = self.size
            out = np.empty((len(blobs), h, w, 3), np.uint8)
            for i, raw in enumerate(blobs):
                img = Image.open(io.BytesIO(raw)).convert("RGB")
                if img.size != (w, h):
                    img = img.resize((w, h), Image.BILINEAR)
                out[i] = np.asarray(img, np.uint8)
            return out
        if native.available():
            return native.decode_resize_normalize_batch(
                blobs, self.size, mean=self.mean, std=self.std,
                scale=self.scale, num_threads=self.num_threads)
        return _decode_batch_pil(blobs, self.size, self.mean, self.std,
                                 self.scale)

    def __iter__(self) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray]]]:
        order = np.arange(len(self.files))
        if self.shuffle:
            rng = np.random.RandomState(self.seed + self._epoch)
            rng.shuffle(order)
        self._epoch += 1
        n = len(order)
        stop = n - n % self.batch_size if self.drop_remainder else n
        starts = list(range(0, stop, self.batch_size))
        if not starts:
            return

        q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        _END = object()
        stop = threading.Event()

        def _put(item) -> bool:
            # bounded put that gives up when the consumer abandoned the
            # iterator — an unconditional q.put would block this thread
            # forever holding decoded batches
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def producer():
            try:
                for s in starts:
                    idx = order[s:s + self.batch_size]
                    blobs = []
                    for i in idx:
                        with open(self.files[i], "rb") as f:
                            blobs.append(f.read())
                    imgs = self._decode(blobs)
                    y = (self.labels[idx]
                         if self.labels is not None else None)
                    if not _put((imgs, y)):
                        return
                _put(_END)
            except BaseException as e:  # surface errors on the consumer
                _put(e)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is _END:
                    break
                if isinstance(item, BaseException):
                    raise item
                yield item
        finally:
            stop.set()
            # drain so a blocked producer sees the stop promptly
            try:
                while True:
                    q.get_nowait()
            except queue.Empty:
                pass

    def as_dataset(self) -> Dataset:
        """Materialize the whole loader into an in-memory Dataset."""
        xs, ys = [], []
        for imgs, y in self:
            xs.append(imgs)
            if y is not None:
                ys.append(y)
        x = np.concatenate(xs) if xs else np.empty((0,) + self.size + (3,),
                                                   np.float32)
        if ys:
            return Dataset(x, np.concatenate(ys))
        return Dataset(x)
