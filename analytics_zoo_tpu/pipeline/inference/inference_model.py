"""InferenceModel: the thread-safe serving handle.

Parity surface: reference zoo/.../pipeline/inference/
{AbstractInferenceModel.java:30-148, FloatInferenceModel.scala:29-83,
InferenceModelFactory.scala, JTensor.java}.

The reference clones the model N times behind a LinkedBlockingQueue because
BigDL modules carry mutable forward state.  A jitted JAX function is pure
and thread-safe over immutable device arrays, so ONE compiled executable
serves all threads; ``supported_concurrent_num`` is honored with a
semaphore purely to bound concurrent device work (queueing semantics match
the reference's blocking take/offer).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Sequence

import numpy as np
import jax


class JTensor:
    """Plain data+shape carrier (reference JTensor.java) — accepted and
    returned for POJO-style callers; numpy works everywhere too."""

    def __init__(self, data, shape=None):
        arr = np.asarray(data, dtype=np.float32)
        self.data = arr.ravel()
        self.shape = tuple(shape) if shape is not None else arr.shape

    def to_ndarray(self) -> np.ndarray:
        return self.data.reshape(self.shape)

    @classmethod
    def from_ndarray(cls, arr) -> "JTensor":
        return cls(arr)


def _to_ndarray(x):
    if isinstance(x, JTensor):
        return x.to_ndarray()
    a = np.asarray(x)
    # keep integer dtypes (embedding/gather ids must stay int); float64
    # narrows to the framework's working f32
    if np.issubdtype(a.dtype, np.integer):
        return a
    return a.astype(np.float32, copy=False)


class InferenceModel:
    """load / predict with bounded concurrency
    (reference AbstractInferenceModel API)."""

    def __init__(self, supported_concurrent_num: int = 1):
        self.concurrent_num = int(supported_concurrent_num)
        self._semaphore = threading.Semaphore(self.concurrent_num)
        self._predict_fn = None
        self._params = None
        self._state = None
        self._graph = None

    # ---- loading (reference load/loadCaffe/loadTF surface) ----
    def load(self, model_path: str, weight_path: Optional[str] = None,
             quantize: Optional[bool] = None):
        """Load a model saved with save_model (the framework's own
        format; reference ``load`` reads BigDL format).  ``quantize=True``
        serves the int8 inference variant (reference loads ``*-quantize``
        models)."""
        from ..api.keras.engine import KerasNet
        net = KerasNet.load_model(model_path)
        trainer = net.ensure_inference_ready()
        if weight_path is not None:
            trainer.load_weights(weight_path)
        return self.load_keras_net(net, quantize=quantize)

    def load_keras_net(self, net, quantize: Optional[bool] = None):
        """Serve an in-memory KerasNet/ZooModel."""
        if quantize is None:
            # reload() must not silently flip a quantized handle back to
            # float: default to however this handle was last loaded
            quantize = getattr(self, "_quantize_flag", None)
        if quantize is None:
            # honor the registry's '<arch>-quantize' naming convention
            # (a saved ImageClassifier('resnet-50-quantize') must serve
            # int8 without an explicit flag)
            name = getattr(net, "hyper", {}).get("model_name", "")
            quantize = isinstance(name, str) and name.endswith("-quantize")
        self._quantize_flag = bool(quantize)
        if quantize:
            net = net.quantize()
        trainer = net.ensure_inference_ready()
        self._attach(net.to_graph(), trainer.state.params,
                     trainer.state.model_state)
        return self

    def load_tf(self, path: Optional[str] = None, net=None,
                input_names=None, output_names=None):
        """Serve a frozen TF graph or imported keras model (reference
        AbstractInferenceModel.loadTF): ``path`` loads an export folder /
        .pb via TFNet, or pass an existing TFNet (e.g. from
        Net.load_keras / Net.from_tf_keras) as ``net``."""
        from ..api.tfgraph.net import TFNet
        if net is None:
            if path is None:
                raise ValueError("load_tf: pass path= (export folder / "
                                 ".pb) or net= (an existing TFNet)")
            net = TFNet(path=path, input_names=input_names,
                        output_names=output_names)
        params = net.init_params(jax.random.PRNGKey(0), None)

        def run(p, x):
            xs = x if isinstance(x, (tuple, list)) else (x,)
            # frozen graphs may retain dropout nodes; pin the key (same
            # policy as TFNet.predict)
            out = net.fn(p, *xs, rng=jax.random.PRNGKey(0))
            if isinstance(out, (tuple, list)) and len(out) == 1:
                return out[0]  # single-output graphs return the array
            return out

        return self.load_jax(run, params)

    def load_jax(self, fn, params):
        """Serve a raw jax function fn(params, x) (the TFNet-equivalent
        import path for externally-defined computations)."""
        self._graph = None
        self._params = jax.device_put(params)
        self._state = None
        jitted = jax.jit(fn)

        def predict_fn(x):
            return jitted(self._params, x)

        self._predict_fn = predict_fn
        return self

    def _attach(self, graph, params, state):
        self._graph = graph
        self._params = params
        self._state = state

        @jax.jit
        def forward(params, state, x):
            out, _ = graph.apply(params, state, x, training=False)
            return out

        def predict_fn(x):
            return forward(self._params, self._state, x)

        self._predict_fn = predict_fn

    def reload(self, model_path: str, weight_path: Optional[str] = None,
               quantize: Optional[bool] = None):
        """Hot-swap the served model; keeps the previous quantize mode
        unless overridden."""
        return self.load(model_path, weight_path, quantize=quantize)

    # ---- prediction (AbstractInferenceModel.predict:112-126) ----
    def predict(self, inputs) -> Any:
        """Accepts one batch array, a JTensor, a list of per-sample inputs,
        or a list of input-lists for multi-input models; returns
        predictions in the matching container type."""
        if self._predict_fn is None:
            raise RuntimeError("InferenceModel: no model loaded")
        batched, single, jtensor = self._normalize(inputs)
        with self._semaphore:
            out = self._predict_fn(batched)
        out = np.asarray(jax.device_get(out))
        if jtensor:
            tensors = [JTensor.from_ndarray(o) for o in out]
            return tensors[0] if single else tensors
        return out[0] if single else out

    def _normalize(self, inputs):
        jtensor = False
        single = False
        if isinstance(inputs, JTensor):
            inputs, jtensor, single = [inputs], True, True
        if isinstance(inputs, np.ndarray):
            return inputs, False, False
        if isinstance(inputs, tuple):
            # tuple = multi-input batch (one array per model input);
            # _to_ndarray keeps integer dtypes — embedding/gather inputs
            # must stay int
            return tuple(
                a if isinstance(a, np.ndarray) else _to_ndarray(a)
                for a in inputs), False, False
        if isinstance(inputs, list):
            if inputs and isinstance(inputs[0], JTensor):
                jtensor = True
                arrs = [_to_ndarray(t) for t in inputs]
                return np.stack(arrs), single, jtensor
            if inputs and isinstance(inputs[0], (list, tuple)):
                # list of per-sample input-lists (multi-input models):
                # stack column-wise into one batch array per input
                n_inputs = len(inputs[0])
                return tuple(
                    np.stack([_to_ndarray(sample[i]) for sample in inputs])
                    for i in range(n_inputs)), single, jtensor
            arrs = [_to_ndarray(t) for t in inputs]
            return np.stack(arrs), single, jtensor
        return _to_ndarray(inputs), False, False

    def __repr__(self):
        loaded = self._predict_fn is not None
        return (f"InferenceModel(concurrent={self.concurrent_num}, "
                f"loaded={loaded})")


class AbstractInferenceModel(InferenceModel):
    """Name-parity alias for the POJO-style entry class."""
