"""InferenceModel: the thread-safe serving handle.

Parity surface: reference zoo/.../pipeline/inference/
{AbstractInferenceModel.java:30-148, FloatInferenceModel.scala:29-83,
InferenceModelFactory.scala, JTensor.java}.

The reference clones the model N times behind a LinkedBlockingQueue because
BigDL modules carry mutable forward state.  A jitted JAX function is pure
and thread-safe over immutable device arrays, so ONE compiled executable
serves all threads; ``supported_concurrent_num`` is honored with a
semaphore purely to bound concurrent device work (queueing semantics match
the reference's blocking take/offer).
"""

from __future__ import annotations

import threading
from typing import Any, List, Optional, Sequence

import numpy as np
import jax

from ...observability import profile as _profile
from ...observability import trace as _trace
from .decode import DecodeEngine
from .serving import (BucketedExecutableCache, CoalescerClosedError,
                      ReplicaSet, RequestCoalescer, _execstore, _rows)


class JTensor:
    """Plain data+shape carrier (reference JTensor.java) — accepted and
    returned for POJO-style callers; numpy works everywhere too."""

    def __init__(self, data, shape=None):
        arr = np.asarray(data, dtype=np.float32)
        self.data = arr.ravel()
        self.shape = tuple(shape) if shape is not None else arr.shape

    def to_ndarray(self) -> np.ndarray:
        return self.data.reshape(self.shape)

    @classmethod
    def from_ndarray(cls, arr) -> "JTensor":
        return cls(arr)


def _to_ndarray(x):
    if isinstance(x, JTensor):
        return x.to_ndarray()
    a = np.asarray(x)
    # keep integer dtypes (embedding/gather ids must stay int); float64
    # narrows to the framework's working f32
    if np.issubdtype(a.dtype, np.integer):
        return a
    return a.astype(np.float32, copy=False)


class InferenceModel:
    """load / predict with bounded concurrency
    (reference AbstractInferenceModel API)."""

    def __init__(self, supported_concurrent_num: int = 1,
                 max_batch_size: int = 32,
                 buckets: Optional[Sequence[int]] = None,
                 bucket_growth: float = 2.0,
                 bucketing: bool = True,
                 coalescing: bool = False,
                 max_wait_ms: float = 2.0,
                 replicas=1,
                 hedging: bool = False,
                 hedge_quantile: float = 0.99,
                 hedge_min_ms: float = 0.5,
                 decode_capacity: Optional[int] = None,
                 decode_max_len: Optional[int] = None,
                 decode_prompt_buckets: Optional[Sequence[int]] = None,
                 decode_eos_id: Optional[int] = None,
                 decode_prefix_pool: int = 0,
                 decode_draft=None,
                 decode_spec_tokens: int = 4,
                 mesh: Optional[dict] = None,
                 store_tag: Optional[str] = None):
        """``supported_concurrent_num`` bounds concurrent device work
        (reference semantics; PER REPLICA when replicated — the
        effective bound scales with the replica count).  The serving
        fast path adds:

        * ``bucketing`` — pad each batch up to a geometric ladder of
          batch sizes (1, 2, … ``max_batch_size`` scaled by
          ``bucket_growth``, or an explicit ``buckets`` list) so a
          ragged request stream hits a handful of compiled executables
          instead of compiling per shape.  Disabled automatically for
          int8-quantized handles (their dynamic activation scales are
          batch-global, so padding would perturb real rows).
        * ``coalescing`` — concurrent ``predict()`` callers are packed
          by a dispatcher thread into ONE padded device batch per
          dispatch (amortizing the ~4-8 ms dispatch floor), waiting at
          most ``max_wait_ms`` to fill ``max_batch_size`` rows; results
          fan back out bit-identical to solo runs.
        * ``replicas`` — ``"all"`` or an int N: place each bucket
          executable on that many local devices (compiled ONCE,
          serialized, loaded per device — see
          :class:`~.serving.ReplicaSet`), params copied per device, and
          route dispatches across the replicas.  Clamped to the local
          device count; 1 (the default) keeps the single-device path.
          Quantized handles stay single-device (their exact-shape path
          has no bucket executables to replicate).
        * ``hedging`` — p99 straggler mitigation (coalesced,
          multi-replica only): a dispatched group whose in-flight time
          exceeds the ``hedge_quantile`` of observed group latencies
          (floored at ``hedge_min_ms``) is re-dispatched to a second
          healthy replica and the first result wins — bit-exact either
          way (same serialized executable on every replica).  No-ops
          with fewer than 2 eligible replicas.
        * ``decode_capacity`` — attach a continuous-batching
          :class:`~.decode.DecodeEngine` with that many slots when a
          language model (a net with ``generate`` + a transformer
          ``hyper``) is loaded, enabling :meth:`generate` /
          :meth:`generate_stream` with iteration-level scheduling.
          ``decode_max_len`` / ``decode_prompt_buckets`` /
          ``decode_eos_id`` configure it (see the engine's docstring).
          The engine is warmed at load — every (bucket, capacity)
          plan compiles before the handle serves, never under a live
          stream.
        * ``decode_prefix_pool`` — > 0 enables the engine's on-device
          prefix-KV LRU pool with that many entries (shared-prefix
          admissions skip the prefix prefill; decode.py module doc).
        * ``decode_draft`` — a small generation-capable draft net (or
          a ``(params, hyper)`` pair) enables speculative decoding of
          up to ``decode_spec_tokens`` tokens per dispatch.
        * ``mesh`` — a sharded-serving spec dict (see
          :func:`analytics_zoo_tpu.serving.shardgroup.normalize_mesh_spec`):
          replicas become replica GROUPS, each a sharded executable
          over a sub-mesh of that shape with the weight tree
          partitioned by the spec's rule table — how a model bigger
          than one chip serves.  ``replicas`` is ignored (the spec's
          ``groups`` controls the group count), and the decode engine,
          when configured, shards its slot arrays over the same mesh.
        """
        # per-model accounting tag for the persistent executable store
        # (``stat --by-model``): metadata on every entry this handle
        # persists, never part of a fingerprint
        self.store_tag = store_tag
        self.concurrent_num = int(supported_concurrent_num)
        self._semaphore = threading.Semaphore(self.concurrent_num)
        self._sem_capacity = self.concurrent_num
        self._replicas_req = replicas
        self._predict_fn = None
        self._params = None
        self._state = None
        self._graph = None
        self.max_batch_size = int(max_batch_size)
        self._buckets = buckets
        self._bucket_growth = float(bucket_growth)
        self._bucketing = bool(bucketing)
        self._coalescing = bool(coalescing)
        self.max_wait_ms = float(max_wait_ms)
        self._hedging = bool(hedging)
        self._hedge_quantile = float(hedge_quantile)
        self._hedge_min_ms = float(hedge_min_ms)
        self._decode_capacity = (None if decode_capacity is None
                                 else int(decode_capacity))
        self._decode_max_len = decode_max_len
        self._decode_prompt_buckets = decode_prompt_buckets
        self._decode_eos_id = decode_eos_id
        self._decode_prefix_pool = int(decode_prefix_pool)
        self._decode_draft = decode_draft
        self._decode_spec_tokens = int(decode_spec_tokens)
        # sharded serving: normalized once here so a malformed spec
        # fails the CONSTRUCTOR (deploy-time), not the first install
        if mesh is not None:
            from ...serving.shardgroup import normalize_mesh_spec
            mesh = normalize_mesh_spec(mesh)
        self._mesh = mesh
        self._decode_engine: Optional[DecodeEngine] = None
        self._cache: Optional[BucketedExecutableCache] = None
        self._coalescer: Optional[RequestCoalescer] = None
        # (predict_fn, cache, coalescer) published as ONE tuple: a
        # predict() racing reload() snapshots a consistent path — never
        # the new forward with the old bucket cache or vice versa
        self._fastpath = None

    # ---- loading (reference load/loadCaffe/loadTF surface) ----
    def load(self, model_path: str, weight_path: Optional[str] = None,
             quantize: Optional[bool] = None):
        """Load a model saved with save_model (the framework's own
        format; reference ``load`` reads BigDL format).  ``quantize=True``
        serves the int8 inference variant (reference loads ``*-quantize``
        models)."""
        from ..api.keras.engine import KerasNet
        net = KerasNet.load_model(model_path)
        trainer = net.ensure_inference_ready()
        if weight_path is not None:
            trainer.load_weights(weight_path)
        return self.load_keras_net(net, quantize=quantize)

    def load_keras_net(self, net, quantize: Optional[bool] = None):
        """Serve an in-memory KerasNet/ZooModel."""
        if quantize is None:
            # reload() must not silently flip a quantized handle back to
            # float: default to however this handle was last loaded
            quantize = getattr(self, "_quantize_flag", None)
        if quantize is None:
            # honor the registry's '<arch>-quantize' naming convention
            # (a saved ImageClassifier('resnet-50-quantize') must serve
            # int8 without an explicit flag)
            name = getattr(net, "hyper", {}).get("model_name", "")
            quantize = isinstance(name, str) and name.endswith("-quantize")
        self._quantize_flag = bool(quantize)
        if quantize:
            net = net.quantize()
        trainer = net.ensure_inference_ready()
        # build + warm the decode engine BEFORE publishing the predict
        # plane: a reload whose engine build fails (non-LM path, warmup
        # crash) must leave the handle fully on the OLD version — a
        # half-swapped handle (new predict, stale generate) is the one
        # state no caller can reason about
        engine = self._build_decode_engine(net, trainer)
        self._attach(net.to_graph(), trainer.state.params,
                     trainer.state.model_state)
        if self._decode_capacity is not None:
            old, self._decode_engine = self._decode_engine, engine
            if old is not None:
                # close AFTER the swap (the reload discipline of
                # ``_install``): the old engine's active streams drain
                # on the old plans while new submits hit the new ones
                old.close()
        return self

    def _build_decode_engine(self, net, trainer):
        """Validate, build, and warm the continuous-batching decode
        engine when ``decode_capacity`` is configured and the loaded
        net is a generation-capable LM.  Pure — publishes nothing;
        any failure here leaves the handle untouched."""
        if self._decode_capacity is None:
            return None
        hyper = getattr(net, "hyper", None)
        if (not callable(getattr(net, "generate", None))
                or not isinstance(hyper, dict)
                or "n_layers" not in hyper):
            raise ValueError(
                "decode_capacity needs a generation-capable language "
                f"model (TransformerLM-like), got {type(net).__name__}")
        if getattr(self, "_quantize_flag", False):
            raise ValueError(
                "decode_capacity is not supported for quantized "
                "handles (the decode math reads float params by name)")
        draft_params = draft_hyper = None
        draft = self._decode_draft
        if draft is not None:
            if isinstance(draft, tuple):
                draft_params, draft_hyper = draft
            else:
                dtrainer = draft.ensure_inference_ready()
                draft_params = dtrainer.state.params
                draft_hyper = draft.hyper
        engine = DecodeEngine(
            trainer.state.params, hyper,
            capacity=self._decode_capacity,
            max_len=self._decode_max_len,
            prompt_buckets=self._decode_prompt_buckets,
            eos_id=self._decode_eos_id,
            prefix_pool=self._decode_prefix_pool,
            draft_params=draft_params, draft_hyper=draft_hyper,
            spec_tokens=self._decode_spec_tokens,
            mesh=self._mesh,
            store_tag=self.store_tag)
        engine.warmup()
        return engine

    def load_tf(self, path: Optional[str] = None, net=None,
                input_names=None, output_names=None):
        """Serve a frozen TF graph or imported keras model (reference
        AbstractInferenceModel.loadTF): ``path`` loads an export folder /
        .pb via TFNet, or pass an existing TFNet (e.g. from
        Net.load_keras / Net.from_tf_keras) as ``net``."""
        from ..api.tfgraph.net import TFNet
        if net is None:
            if path is None:
                raise ValueError("load_tf: pass path= (export folder / "
                                 ".pb) or net= (an existing TFNet)")
            net = TFNet(path=path, input_names=input_names,
                        output_names=output_names)
        params = net.init_params(jax.random.PRNGKey(0), None)

        def run(p, x):
            xs = x if isinstance(x, (tuple, list)) else (x,)
            # frozen graphs may retain dropout nodes; pin the key (same
            # policy as TFNet.predict)
            out = net.fn(p, *xs, rng=jax.random.PRNGKey(0))
            if isinstance(out, (tuple, list)) and len(out) == 1:
                return out[0]  # single-output graphs return the array
            return out

        return self.load_jax(run, params)

    def load_graph(self, graph, params, state=None):
        """Serve a prebuilt pure graph (``graph.apply(params, state,
        x, training=False)``) with an explicit param/state tree — the
        weight pager's keras-side fault-in path: a cold deployment
        keeps the graph plus HOST numpy weights, and this call places
        them exactly once (the replica set's ``device_put``; the
        placed-tree discipline of :meth:`load_jax`)."""
        self._quantize_flag = False
        self._attach(graph, params, state)
        return self

    def load_jax(self, fn, params):
        """Serve a raw jax function fn(params, x) (the TFNet-equivalent
        import path for externally-defined computations)."""
        self._graph = None
        self._params = jax.device_put(params)
        self._state = None
        # a raw jax fn is not a quantized registry handle — a stale flag
        # from a previous quantized load must not disable the fast path
        self._quantize_flag = False
        # close over the placed params instead of passing the tree per
        # call: weights are fixed for the lifetime of a load (reload
        # re-installs), and flattening a many-leaf tree on every call is
        # measurable against the per-dispatch floor
        params_dev = self._params
        predict_fn = jax.jit(lambda x: fn(params_dev, x))
        # hand the PLACED tree to the replica path: device_put of an
        # array already committed to the target device is a no-op, so
        # replica 0 shares the closure's buffers instead of pinning a
        # second copy of the weights in device-0 memory
        self._install(predict_fn, replica_fn=fn,
                      replica_params=self._params)
        return self

    def _attach(self, graph, params, state):
        self._graph = graph
        self._params = params
        self._state = state

        # params/state are captured as jit closure constants — per-call
        # python arg processing shrinks to the batch alone (weights are
        # fixed until the next load, which re-installs)
        @jax.jit
        def predict_fn(x):
            out, _ = graph.apply(params, state, x, training=False)
            return out

        def replica_fn(bundle, x):
            # the replica path needs the weights as an ARGUMENT (placed
            # per device by the ReplicaSet), not a closure constant
            out, _ = graph.apply(bundle["params"], bundle["state"], x,
                                 training=False)
            return out

        self._install(predict_fn, replica_fn=replica_fn,
                      replica_params={"params": params, "state": state})

    def _resolve_replicas(self) -> int:
        """The effective replica count: the request ("all" or an int),
        clamped to the local device count."""
        req = self._replicas_req
        avail = len(jax.local_devices())
        if isinstance(req, str):
            if req.lower() != "all":
                raise ValueError(
                    f'replicas must be "all" or an int, got {req!r}')
            return avail
        n = int(req)
        if n < 1:
            raise ValueError(f"replicas must be >= 1, got {n}")
        return min(n, avail)

    def _install(self, predict_fn, replica_fn=None, replica_params=None):
        """Install the forward and (re)build the serving fast path for
        it: bucketed executable cache (optionally replicated across
        local devices) + optional coalescer.  Quantized handles stay on
        the exact-shape path — their dynamic activation scales are
        batch-global, so padded filler rows would change real-row
        outputs.

        Reload ordering (the zero-downtime contract): the NEW path is
        fully built and published first, THEN the old coalescer is
        closed — its already-queued requests drain through the OLD
        executables while new traffic flows to the new ones.  No request
        is ever abandoned or served by a half-swapped path."""
        old_coalescer = self._coalescer
        cache = None
        coalescer = None
        replica_set = None
        if self._bucketing and not getattr(self, "_quantize_flag", False):
            n_rep = self._resolve_replicas()
            # the raw-dispatch ReplicaSet path engages for N > 1
            # devices, and ALSO single-device whenever the persistent
            # executable store is enabled: the store serves serialized
            # raw executables, and only the replica path dispatches
            # them — this is what makes a warm-store deploy()
            # zero-compile even on one device.  Store off, one device:
            # the closure-jit path of PR 1, bit-for-bit unchanged.
            store_on = _execstore().current() is not None
            if self._mesh is not None and replica_fn is not None:
                # sharded serving: the mesh spec (not ``replicas``)
                # decides how many groups the local device set carves
                # into; one sharded compile, every further group is a
                # device-assignment rewrite
                from ...serving.shardgroup import ShardGroupSet
                replica_set = ShardGroupSet(
                    replica_fn, replica_params, self._mesh,
                    devices=jax.local_devices(), tag=self.store_tag)
            elif (n_rep > 1 or store_on) and replica_fn is not None:
                replica_set = ReplicaSet(
                    replica_fn, replica_params,
                    devices=jax.local_devices()[:n_rep],
                    tag=self.store_tag)
            cache = BucketedExecutableCache(
                predict_fn, max_batch=self.max_batch_size,
                buckets=self._buckets, growth=self._bucket_growth,
                replica_set=replica_set)
        # the concurrency budget is per replica: N devices can carry N
        # times the concurrent device work of one.  The semaphore is
        # REUSED when the capacity is unchanged: a reload under traffic
        # must keep old-path drains and new-path traffic on one shared
        # budget (a fresh semaphore would let them stack to 2x during
        # the drain window).  Only a genuine capacity change — the
        # replica count moved — warrants a new budget.
        n_active = replica_set.n if replica_set is not None else 1
        cap = self.concurrent_num * n_active
        if cap != self._sem_capacity:
            self._semaphore = threading.Semaphore(cap)
            self._sem_capacity = cap
        if cache is not None and self._coalescing:
            # pipeline two dispatches when the concurrency budget
            # allows — the device computes group k while group k+1
            # is gathered and dispatched behind it.  (The coalescer
            # widens this to one slot per replica when replicated.)
            coalescer = RequestCoalescer(
                cache, max_wait_ms=self.max_wait_ms,
                semaphore=self._semaphore,
                pipeline_depth=min(2, self.concurrent_num),
                hedging=self._hedging,
                hedge_quantile=self._hedge_quantile,
                hedge_min_ms=self._hedge_min_ms)
        # one assignment publishes the whole new path (GIL-atomic)
        self._fastpath = (predict_fn, cache, coalescer)
        self._predict_fn = predict_fn
        self._cache = cache
        self._coalescer = coalescer
        if old_coalescer is not None:
            # graceful drain: queued requests complete on the old
            # executables; anything racing the shutdown gets
            # CoalescerClosedError and the caller falls back
            old_coalescer.close()

    @property
    def n_replicas(self) -> int:
        """Total replica count (1 on the single-device path)."""
        fastpath = self._fastpath
        if fastpath is None:
            return 1
        _, cache, _ = fastpath
        if cache is None or cache.replica_set is None:
            return 1
        return cache.replica_set.n

    @property
    def active_replicas(self) -> int:
        """Replicas currently in the scheduled (elastic) set."""
        fastpath = self._fastpath
        if fastpath is None:
            return 1
        _, cache, _ = fastpath
        if cache is None or cache.replica_set is None:
            return 1
        return cache.replica_set.n_active

    def placement_complete(self) -> bool:
        """True when every replica (group) of the installed set holds
        every placed executable — the pager's group-atomic install
        guard.  Handles without a replica set are trivially complete
        (one device, one executable)."""
        fastpath = self._fastpath
        if fastpath is None:
            return False
        _, cache, _ = fastpath
        if cache is None or cache.replica_set is None:
            return True
        return cache.replica_set.placement_complete()

    def set_active_replicas(self, n: int) -> int:
        """Resize the scheduled replica set (the autoscaler's lever) —
        joining replicas are primed on every placed signature BEFORE
        they take traffic, so a scale-up never serves cold and never
        compiles.  Returns the resulting active count; no-ops (returns
        1) on the single-device path."""
        fastpath = self._fastpath
        if fastpath is None:
            raise RuntimeError("InferenceModel: no model loaded")
        _, cache, _ = fastpath
        if cache is None or cache.replica_set is None:
            return 1
        return cache.replica_set.set_active(n)

    # ---- serving fast path surface ----
    def warmup(self, sample_shapes, dtypes=None) -> float:
        """AOT-compile every ladder bucket for the given per-sample
        input shape(s) (no batch axis; list of shapes for multi-input
        models, ``dtypes`` element-wise).  Returns compile seconds —
        call once at deploy time so live traffic never pays a trace."""
        if self._predict_fn is None:
            raise RuntimeError("InferenceModel: no model loaded")
        if self._cache is None:
            raise RuntimeError(
                "warmup needs the bucketed path (bucketing=True and a "
                "non-quantized handle)")
        return self._cache.warmup(sample_shapes, dtypes)

    def serving_stats(self) -> dict:
        """Per-bucket hit/miss/compile-time counters plus coalescer
        dispatch stats (consumed directly and re-exported per model by
        the serving control plane's metrics snapshot)."""
        out = {"buckets": (), "hits": {}, "misses": {},
               "compile_time_s": {}, "dispatches": 0,
               "coalesced_requests": 0, "coalescer_pending": 0,
               "replicas": 1}
        # snapshot the triple so a metrics read during reload() never
        # pairs the new cache's counters with the old coalescer's
        fastpath = self._fastpath
        if fastpath is None:
            return out
        _, cache, coalescer = fastpath
        if cache is not None:
            out["buckets"] = cache.buckets
            out.update(cache.stats.snapshot())
            if cache.replica_set is not None:
                out.update(cache.replica_set.stats())
        if coalescer is not None:
            out["dispatches"] = coalescer.dispatches
            out["coalesced_requests"] = coalescer.coalesced_requests
            out["coalescer_pending"] = coalescer.pending
            if coalescer.hedging:
                out["hedges"] = coalescer.hedge_stats()
        engine = self._decode_engine
        if engine is not None:
            out["decode"] = engine.stats()
        return out

    # ---- continuous-batching generation ----
    @property
    def decode_engine(self) -> Optional[DecodeEngine]:
        """The attached continuous-batching engine (None unless the
        handle was built with ``decode_capacity`` and loaded an LM)."""
        return self._decode_engine

    def _require_engine(self) -> DecodeEngine:
        engine = self._decode_engine
        if engine is None:
            raise RuntimeError(
                "no decode engine: construct the InferenceModel with "
                "decode_capacity= and load a generation-capable LM")
        return engine

    def generate(self, prompt_ids, max_new_tokens,
                 eos_id: Optional[int] = None,
                 timeout: Optional[float] = None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None, seed=0):
        """Continuous-batching decode: each prompt (a (B, L) array or
        a list of ragged 1-D id rows) is bucketed, prefilled, and
        slot-scheduled per decode step alongside every other live
        request — a short request never pays a long neighbor's latency.
        Returns each row's generated continuation (list of 1-D int32
        arrays; EOS included when hit).  ``max_new_tokens`` (and
        ``seed``) may be per-row.  Greedy (``temperature == 0``,
        default) is token-identical to ``TransformerLM.generate``'s
        compiled scan for the same prompt; ``temperature > 0`` samples
        (top-k/top-p truncated) from the per-request ``(seed, token
        index)`` fold_in stream — same request, same stream, at any
        engine occupancy."""
        return self._require_engine().generate(
            prompt_ids, max_new_tokens, eos_id=eos_id, timeout=timeout,
            span=_trace.current_span(), temperature=temperature,
            top_k=top_k, top_p=top_p, seed=seed)

    def generate_stream(self, prompt_ids, max_new_tokens: int,
                        eos_id: Optional[int] = None,
                        temperature: float = 0.0,
                        top_k: Optional[int] = None,
                        top_p: Optional[float] = None, seed: int = 0):
        """Streaming single-prompt decode: returns a
        :class:`~.decode.TokenStream` immediately — iterate it for
        per-token delivery, or ``.result()`` for the full
        continuation."""
        span = _trace.current_span()
        return self._require_engine().submit(
            prompt_ids, max_new_tokens, eos_id=eos_id, span=span,
            temperature=temperature, top_k=top_k, top_p=top_p,
            seed=seed)

    def close(self):
        """Stop the coalescer and decode dispatcher threads (no-op
        without them)."""
        if self._coalescer is not None:
            self._coalescer.close()
        if self._decode_engine is not None:
            self._decode_engine.close()

    def reload(self, model_path: str, weight_path: Optional[str] = None,
               quantize: Optional[bool] = None):
        """Hot-swap the served model; keeps the previous quantize mode
        unless overridden."""
        return self.load(model_path, weight_path, quantize=quantize)

    # ---- prediction (AbstractInferenceModel.predict:112-126) ----
    def predict(self, inputs) -> Any:
        """Accepts one batch array, a JTensor, a list of per-sample inputs,
        or a list of input-lists for multi-input models; returns
        predictions in the matching container type."""
        fastpath = self._fastpath  # ONE read: consistent under reload()
        if fastpath is None:
            raise RuntimeError("InferenceModel: no model loaded")
        predict_fn, cache, coalescer = fastpath
        # the whole tracing cost when disabled is this one branch
        # (current_span checks a module flag before touching the
        # contextvar); every phase call below guards on span is None
        span = _trace.current_span()
        batched, single, jtensor = self._normalize(inputs)
        if cache is None:
            # exact-shape path (bucketing off, or quantized handle whose
            # batch-global activation scales forbid padding).  Explicit
            # device_put for the same reason as the bucketed dispatch:
            # the upload must be visible to transfer guards.
            with self._semaphore:
                if span is not None:
                    span.phase_start("device_put")
                xb = jax.device_put(batched)
                _profile.note_transfer("h2d")
                if span is not None:
                    span.phase_start("execute")
                out = predict_fn(xb)
            out = np.asarray(jax.device_get(out))
            _profile.note_transfer("d2h")
            if span is not None:
                span.phase_end()
        else:
            out = None
            if (coalescer is not None and not coalescer.closed
                    and _rows(batched) <= cache.max_batch):
                try:
                    out = np.asarray(
                        coalescer.submit(batched, span=span).result())
                except CoalescerClosedError:
                    out = None  # closed between check and submit
            if out is None:
                # the snapshotted cache — a racing reload() may have
                # already nulled self._cache
                out = np.asarray(cache.run(batched, sem=self._semaphore,
                                           span=span))
        if jtensor:
            tensors = [JTensor.from_ndarray(o) for o in out]
            return tensors[0] if single else tensors
        return out[0] if single else out

    def _normalize(self, inputs):
        jtensor = False
        single = False
        if isinstance(inputs, JTensor):
            inputs, jtensor, single = [inputs], True, True
        if isinstance(inputs, np.ndarray):
            return inputs, False, False
        if isinstance(inputs, tuple):
            # tuple = multi-input batch (one array per model input);
            # _to_ndarray keeps integer dtypes — embedding/gather inputs
            # must stay int
            return tuple(
                a if isinstance(a, np.ndarray) else _to_ndarray(a)
                for a in inputs), False, False
        if isinstance(inputs, list):
            if inputs and isinstance(inputs[0], JTensor):
                jtensor = True
                arrs = [_to_ndarray(t) for t in inputs]
                return np.stack(arrs), single, jtensor
            if inputs and isinstance(inputs[0], (list, tuple)):
                # list of per-sample input-lists (multi-input models):
                # stack column-wise into one batch array per input
                n_inputs = len(inputs[0])
                return tuple(
                    np.stack([_to_ndarray(sample[i]) for sample in inputs])
                    for i in range(n_inputs)), single, jtensor
            arrs = [_to_ndarray(t) for t in inputs]
            return np.stack(arrs), single, jtensor
        return _to_ndarray(inputs), False, False

    def __repr__(self):
        loaded = self._predict_fn is not None
        return (f"InferenceModel(concurrent={self.concurrent_num}, "
                f"loaded={loaded})")


class AbstractInferenceModel(InferenceModel):
    """Name-parity alias for the POJO-style entry class."""
