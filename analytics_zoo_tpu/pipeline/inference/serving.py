"""Serving fast path: shape-bucketed executables + request coalescing.

Two measured walls motivate this module (PERF_NOTES):

* **Compile-per-shape.** A jitted forward re-traces for every distinct
  batch size, so a live request stream with ragged batch sizes compiles
  continuously.  ``BucketedExecutableCache`` pads every batch up to a
  small geometric ladder of batch sizes (1, 2, 4, … max_batch by
  default) so the whole stream is served by a handful of pre-compilable
  executables, with per-bucket hit/miss/compile-time counters and an
  AOT ``warmup``.
* **Per-dispatch floor.** A dispatched computation has a ~4-8 ms floor
  (PERF_NOTES §"Per-dispatch floor"), so one device call per request
  caps throughput regardless of model size.  ``RequestCoalescer`` packs
  concurrent ``predict()`` callers into ONE padded device batch per
  dispatch and fans the rows back out — amortizing the floor across
  every rider.

Padding safety: rows are independent under inference-mode forward
passes (BatchNorm uses running stats, softmax is row-wise), so padded
filler rows cannot perturb real rows and un-padded results are
bit-identical to a solo run.  Computations with BATCH-GLOBAL terms —
int8 dynamic activation scales — are NOT row-independent; callers must
keep those on the exact-shape path (``InferenceModel`` does).

A third wall falls with ``ReplicaSet`` (multi-replica serving): the
per-request path above is structurally single-device — one executable,
one device, N-1 chips idle.  A ``ReplicaSet`` places the SAME compiled
executable on every local device (compile once, ``serialize`` the
executable, ``deserialize`` it per device — milliseconds against a
multi-hundred-ms compile) with a per-device copy of the params, and the
coalescer's dispatcher routes each group to the replica with the fewest
undelivered groups — cross-replica pipelining that generalizes the
one-deep dispatch pipeline to depth N.
"""

from __future__ import annotations

import collections
import contextlib
import queue
import threading
import time
from concurrent.futures import (FIRST_COMPLETED, Future,
                                ThreadPoolExecutor)
from concurrent.futures import wait as _futures_wait
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.lib import xla_client as _xla_client

from ...common.utils import pad_leading as _pad_rows
from ...observability import profile as _profile
from ...observability import trace as _trace
from ...observability.log import get_logger as _get_logger
from ...observability.metrics import LatencyWindow as _LatencyWindow

_slog = _get_logger("zoo.serving")


def _execstore():
    """The persistent-executable-store module, imported lazily: the
    data plane must stay importable on its own, and the store is
    consulted only at compile/warmup time anyway."""
    from ...serving import execstore
    return execstore


def bucket_ladder(max_batch: int, growth: float = 2.0,
                  min_batch: int = 1) -> Tuple[int, ...]:
    """The geometric ladder of padded batch sizes: ``min_batch`` scaled
    by ``growth`` until ``max_batch`` (always included)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if growth <= 1.0:
        raise ValueError(f"bucket growth must be > 1, got {growth}")
    out: List[int] = []
    b = float(max(1, min_batch))
    while int(b) < max_batch:
        if not out or int(b) != out[-1]:
            out.append(int(b))
        b *= growth
    out.append(int(max_batch))
    return tuple(out)


def _rows(batched) -> int:
    first = batched[0] if isinstance(batched, (tuple, list)) else batched
    return int(np.asarray(first).shape[0])


def _slice_rows(tree, start: int, stop: int):
    return jax.tree_util.tree_map(lambda a: a[start:stop], tree)


def _concat_trees(trees: Sequence):
    """Concatenate result trees (arrays or tuples of arrays) row-wise."""
    if len(trees) == 1:
        return trees[0]
    first = trees[0]
    if isinstance(first, (tuple, list)):
        return type(first)(
            np.concatenate([t[i] for t in trees])
            for i in range(len(first)))
    return np.concatenate(trees)


def batch_signature(batched) -> Tuple:
    """Everything but the batch row count: per-input trailing shape +
    dtype.  Two batches coalesce / share a bucket executable iff their
    signatures match."""
    def one(a):
        a = np.asarray(a)
        return (tuple(a.shape[1:]), str(a.dtype))

    if isinstance(batched, (tuple, list)):
        return tuple(one(a) for a in batched)
    return (one(batched),)


class BucketStats:
    """Per-bucket serving counters (thread-safe snapshots via dict copy)."""

    def __init__(self):
        self.hits: Dict[int, int] = {}
        self.misses: Dict[int, int] = {}
        self.compile_time_s: Dict[int, float] = {}

    def snapshot(self) -> Dict[str, Dict[int, Any]]:
        return {"hits": dict(self.hits), "misses": dict(self.misses),
                "compile_time_s": dict(self.compile_time_s)}


class Replica:
    """One device's share of a :class:`ReplicaSet`: the device, its own
    copy of the params (flattened, pre-placed), and per-replica serving
    counters.  Counter writes happen under the owning cache's lock (the
    same lock as the bucket counters); ``healthy``, ``active`` and the
    probe-backoff fields flip under the replica set's lock.

    ``healthy`` tracks fault state (a dispatch raised; restored by a
    successful health re-probe).  ``active`` tracks the ELASTIC set: a
    deactivated replica keeps its placed executables and params — warm,
    idle, off the scheduler — so re-activation is a prime, never a
    compile."""

    __slots__ = ("index", "device", "params_flat", "healthy", "active",
                 "probe_at", "probe_backoff",
                 "dispatches", "bucket_dispatches")

    def __init__(self, index: int, device, params_flat: List):
        self.index = index
        self.device = device
        self.params_flat = params_flat
        self.healthy = True
        self.active = True
        self.probe_at = 0.0        # perf_counter time of the next probe
        self.probe_backoff = 0.0   # current backoff step (seconds)
        self.dispatches = 0
        self.bucket_dispatches: Dict[int, int] = {}

    def __repr__(self):
        return (f"Replica({self.index}, {self.device}, "
                f"healthy={self.healthy}, active={self.active})")


class ReplicaSet:
    """Compile-once / place-everywhere: one executable per padded input
    signature, loaded onto EVERY local device, each device holding its
    own copy of the params.

    The mechanism (and why it is one compile, counter-verified): a
    jitted forward re-COMPILES per device placement — jax's executable
    cache keys on input shardings, so serving N devices through N jits
    pays N identical XLA compiles per bucket.  Here the forward is
    traced and lowered ONCE (``jax.jit(fn).lower(...).compile()`` — the
    single monitored ``backend_compile``), then the compiled executable
    is ``serialize``d and ``deserialize``d onto each remaining device
    with only its device assignment rewritten.  Deserialization is a
    load, not a compile (~3-10 ms against a multi-hundred-ms compile)
    and fires no compile event — which is exactly the accounting the
    sanitizer and the bench's one-compile-per-bucket gate enforce.

    Dispatch bypasses the jit wrapper entirely: inputs are uploaded to
    the replica's device via explicit ``device_put`` (transfer-guard
    visible, like the single-device path) and handed straight to the
    replica's loaded executable.  Unused inputs pruned by XLA
    (``kept_var_idx``) are dropped to match the executable's parameter
    list.

    Persistence: with the executable store enabled
    (:mod:`analytics_zoo_tpu.serving.execstore`), ``ensure_compiled``
    is read-through/write-behind against it — a process whose store
    already holds this (graph, weights, signature, jax version,
    device kind) fingerprint LOADS the executable in milliseconds and
    fires no compile event at all, which is what makes a second
    process's ``deploy()`` zero-compile.

    Fault handling: a replica whose dispatch raises is marked unhealthy
    and the failed dispatch is retried once on another healthy replica
    by the owning cache.  Recovery is structured, not luck: an
    unhealthy replica is RE-PROBED with a cheap warmed no-op execute on
    an exponential backoff (``maybe_reprobe``, driven from the
    coalescer loop and the solo scheduler), and a probe that returns
    flips it healthy again — so ``zoo_replica_unhealthy`` goes back to
    0 without waiting for a hot-swap or a lucky retry.  When EVERY
    replica is unhealthy the set still falls back to serving through
    all of them — availability over purity, the gauge shows red until
    a probe succeeds.

    Elasticity: ``set_active(n)`` shrinks or grows the SCHEDULED set
    (the autoscaler's lever).  Deactivated replicas keep executables
    and params placed; re-activation primes every placed signature on
    the joining replica BEFORE it takes traffic (the registry's
    warm-before-activate discipline at runtime), so a scale-up never
    serves a cold replica and never compiles.
    """

    def __init__(self, fn: Callable, params, devices=None,
                 probe_backoff_s: float = 0.5,
                 probe_backoff_max_s: float = 30.0,
                 store="auto", tag: Optional[str] = None):
        self._fn = fn
        # per-model accounting tag for the executable store (stat
        # --by-model): rides every entry's header meta, never the key
        self._tag = tag
        # the set's placement units: one device per replica here, one
        # device GROUP (sub-mesh) per replica in ShardGroupSet — every
        # hook below keys off the unit, so the compile-once/
        # place-everywhere machinery is shared verbatim
        units = self._carve_units(devices)
        self._backend = self._unit_devices(units[0])[0].client
        # one jit wrapper for the whole set: every bucket's lowering
        # comes from it (a per-compile jax.jit would re-trace per call)
        self._jit = self._make_jit(units)
        # params are placed per unit ONCE at construction — the
        # per-dispatch upload is the padded batch alone
        placed0 = self._place_params(params, units[0])
        self._params_r0 = placed0
        # persistent executable store (read-through under
        # ensure_compiled, write-behind after each compile): "auto"
        # resolves the process store — None when none is configured,
        # which keeps every store branch below inert
        if store == "auto":
            store = _execstore().current()
        self._store = store
        # the weights are runtime ARGUMENTS of the replica executable,
        # so the compiled code is weight-agnostic — but the store key
        # must rotate on a weight change anyway: a redeploy with new
        # weights must never be answered by an entry recorded against
        # old ones.  Hashed once per set, at construction.
        self._wdigest = (_execstore().params_digest(placed0)
                         if store is not None else None)
        replicas = [self._make_replica(0, units[0], placed0)]
        for i, u in enumerate(units[1:], start=1):
            replicas.append(self._make_replica(
                i, u, self._place_params(params, u)))
        self.replicas: Tuple[Replica, ...] = tuple(replicas)
        self._n_param_leaves = len(self.replicas[0].params_flat)
        # per-signature executables: key -> (exe per replica, kept
        # indices or None, out treedef); published under _lock AFTER the
        # compile so readers never see a half-built entry
        self._exes: Dict[Tuple, Tuple] = {}
        self._kept: Dict[Tuple, Optional[Tuple[int, ...]]] = {}
        self._out_tree: Dict[Tuple, Any] = {}
        self._out_avals: Dict[Tuple, List] = {}
        self._lock = threading.Lock()
        self._compile_locks: Dict[Tuple, threading.Lock] = {}
        self._rr = 0
        self.probe_backoff_s = float(probe_backoff_s)
        self.probe_backoff_max_s = float(probe_backoff_max_s)
        # fast-path gate for maybe_reprobe: scanning the replica tuple
        # per dispatch is cheap, but one int compare is cheaper
        self._unhealthy_count = 0
        # serializes probes (dispatcher + solo threads may both ask)
        self._probe_guard = threading.Lock()

    # ---- placement-unit hooks (overridden by ShardGroupSet) ----
    # A "unit" is whatever one replica executes on: a single device
    # here, a (devices, mesh) sub-mesh in serving/shardgroup.py.  The
    # base class stays the single-device fast path — no mesh objects,
    # no sharding branches on its dispatch.

    def _carve_units(self, devices) -> List:
        devs = list(devices) if devices else list(jax.local_devices())
        if not devs:
            raise ValueError("ReplicaSet needs at least one device")
        return devs

    @staticmethod
    def _unit_devices(unit) -> Tuple:
        """The concrete devices behind one unit (backend access)."""
        return (unit,)

    def _make_jit(self, units):
        return jax.jit(self._fn)

    def _place_params(self, params, unit):
        return jax.device_put(params, unit)

    def _make_replica(self, index: int, unit, placed) -> "Replica":
        return Replica(index, unit, jax.tree_util.tree_leaves(placed))

    def _input_sharding(self):
        """The sharding batch inputs carry on replica 0 — the AOT
        lowering's input placement (and, in ShardGroupSet, the
        per-dispatch upload target)."""
        return jax.sharding.SingleDeviceSharding(self.replicas[0].device)

    def _fp_parts(self) -> Tuple:
        """Leading fingerprint components: the entry kind plus any
        layout extras that must rotate the store key.  ShardGroupSet
        appends the canonical mesh spec here so two deploys differing
        only in mesh shape / partition rules never share an entry."""
        return ("replica-forward",)

    def _store_meta(self) -> Dict[str, Any]:
        """Header metadata every store entry of this set carries
        (beyond kept/n_in/model, added by ensure_compiled)."""
        return {"kind": "replica-forward"}

    def span_labels(self, replica: "Replica") -> Dict[str, Any]:
        """Labels the dispatch path stamps on request spans for this
        unit.  ShardGroupSet adds ``group`` so a trace distinguishes
        which replica group served the request."""
        return {"replica": replica.index}

    def _place_serialized(self, ser: bytes, replica: "Replica"):
        """Rehydrate serialized-executable bytes onto one replica's
        unit.  The base maps a replica to its single device; the
        sharded set rewrites the assignment to span the whole group."""
        return self._load_serialized(ser, replica.device)

    @property
    def n(self) -> int:
        return len(self.replicas)

    @property
    def n_active(self) -> int:
        return sum(1 for r in self.replicas if r.active)

    @staticmethod
    def _key(batched) -> Tuple:
        leaves = jax.tree_util.tree_leaves(batched)
        return tuple((tuple(np.asarray(a).shape), str(np.asarray(a).dtype))
                     for a in leaves)

    @staticmethod
    def key_from(bucket: int, signature: Tuple) -> Tuple:
        """The placement key, derived from a cache-level
        ``(bucket, batch_signature)`` pair the dispatch path has
        already computed — equivalent to ``_key`` on the padded batch
        (every leaf's leading axis IS the bucket) without walking the
        input tree a second time."""
        return tuple(((bucket,) + tuple(shape), dtype)
                     for shape, dtype in signature)

    def compiled_keys(self) -> int:
        """How many distinct signatures hold a placed executable."""
        return len(self._exes)

    def placement_complete(self, key: Optional[Tuple] = None) -> bool:
        """True when every replica holds an executable for ``key`` (or
        for every placed key when None).  ensure_compiled publishes
        full tuples under the lock, so this holds by construction on
        any healthy set — it is the PAGER's install guard: a faulted-in
        model whose replica (group) placement is incomplete must never
        be published as resident, because for a sharded group partial
        residency means wrong answers, not degraded capacity."""
        with self._lock:
            keys = [key] if key is not None else list(self._exes)
            return all(len(self._exes[k]) == len(self.replicas)
                       for k in keys if k in self._exes)

    def _load_serialized(self, ser: bytes, device):
        """Load serialized-executable bytes onto ``device``: fresh
        single-device CompileOptions with only the device assignment
        set — the PR 5 round trip, now also how a store entry
        rehydrates (it works with no original executable in hand).  A
        load, not a compile: no ``backend_compile`` event fires."""
        opts = _xla_client.CompileOptions()
        opts.device_assignment = _xla_client.DeviceAssignment.create(
            np.array([[device.id]], dtype=np.int32))
        return self._backend.deserialize_executable(ser, opts)

    def ensure_compiled(self, batched, key: Optional[Tuple] = None
                        ) -> float:
        """Make the executable for ``batched``'s signature available
        on every replica — compiled once, or LOADED from the
        persistent executable store when a prior process (or deploy)
        already compiled the identical computation.  Returns the wall
        seconds spent (0.0 when the signature was already placed).
        Safe to call from several threads — concurrent DIFFERENT
        signatures compile in parallel (warmup's thread pool relies on
        this), the same signature compiles exactly once.  Callers on
        the dispatch path call this UNCONDITIONALLY (warm cost: one
        dict membership check): placement here is the authority, not
        any caller-side seen-bit — a concurrent cold dispatch may
        still be mid-compile, and a compile that failed once must be
        retryable.

        Store protocol (read-through / write-behind): the fingerprint
        covers the lowered HLO (graph + padded signature), the weights
        digest, and the runtime environment, so a hit is the SAME
        computation by construction; the entry carries
        ``_kept_var_idx`` so the raw dispatch path rehydrates without
        touching the compiled object's jax wrapper.  Any lookup or
        load failure falls back to the compile below — the store can
        cost a recompile, never serve a wrong executable.  Lookups
        happen only HERE, on the placement miss path — never on a
        per-dispatch hot path."""
        if key is None:
            key = self._key(batched)
        if key in self._exes:
            return 0.0
        with self._lock:
            klock = self._compile_locks.setdefault(key, threading.Lock())
        with klock:
            if key in self._exes:
                return 0.0
            t0 = time.perf_counter()
            dev0 = self.replicas[0].device
            s0 = self._input_sharding()
            specs = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(
                    np.asarray(a).shape, np.asarray(a).dtype, sharding=s0),
                batched)
            # tracing + lowering runs on BOTH paths (it fires no
            # backend_compile event): on a store hit it only feeds the
            # fingerprint, on a miss it is the compile's input
            lowered = self._jit.lower(self._params_r0, specs)
            n_in = self._n_param_leaves \
                + len(jax.tree_util.tree_leaves(specs))
            store = self._store
            fp = None
            exe0 = None
            kept_t: Optional[Tuple[int, ...]] = None
            ser: Optional[bytes] = None
            if store is not None:
                fp = store.fingerprint(
                    *self._fp_parts(), _execstore().hlo_digest(lowered),
                    self._wdigest, key, device=dev0)
                ent = store.lookup(fp)
                if ent is not None:
                    try:
                        kept_t = ent.meta.get("kept")
                        if kept_t is not None:
                            # () is legitimate — an executable whose
                            # inputs all constant-folded away keeps
                            # zero of them; only out-of-RANGE indices
                            # indict the entry
                            kept_t = tuple(int(i) for i in kept_t)
                            if any(i < 0 or i >= n_in
                                   for i in kept_t):
                                raise ValueError(
                                    f"kept indices {kept_t} out of "
                                    f"range for {n_in} inputs")
                        ser = ent.payload
                        exe0 = self._place_serialized(
                            ser, self.replicas[0])
                    except Exception as e:  # noqa: BLE001 — ANY load
                        # failure (truncated bytes, foreign artifact,
                        # bad metadata) must fall back to a fresh
                        # compile: the store may cost a recompile,
                        # never a wrong executable
                        store.note_invalid(fp, e)
                        exe0, kept_t, ser = None, None, None
            if exe0 is None:
                # the ONE traced lowering + XLA compile for this
                # signature (this is the call the backend_compile
                # counter sees)
                compiled = lowered.compile()
                mexe = compiled._executable
                exe0 = mexe.xla_executable
                kept = getattr(mexe, "_kept_var_idx", None)
                kept_t = (None if kept is None or len(kept) == n_in
                          else tuple(sorted(kept)))
                if len(self.replicas) > 1:
                    # multi-replica placement REQUIRES the bytes: a
                    # serialize failure here fails the deploy exactly
                    # as it did pre-store
                    ser = self._backend.serialize_executable(exe0)
                elif store is not None:
                    # store-only serialization is best-effort: a
                    # backend that cannot serialize must not fail a
                    # deploy that just compiled successfully
                    try:
                        ser = self._backend.serialize_executable(exe0)
                    except Exception as e:  # noqa: BLE001
                        ser = None
                        _slog.error("execstore_serialize_failed",
                                    error=f"{type(e).__name__}: {e}")
                if store is not None and ser is not None:
                    # write-behind: the device-0 serialization the
                    # multi-replica path produces anyway, plus the
                    # metadata the raw dispatch path needs back
                    meta = dict(self._store_meta())
                    meta.update({"kept": kept_t, "n_in": n_in})
                    if self._tag is not None:
                        meta["model"] = self._tag
                    store.put(fp, ser, meta=meta)
            exes = [exe0]
            # place everywhere: one serialization (from the compile or
            # from the store entry), loaded per unit with only the
            # device assignment rewritten — a load, not a compile
            for rep in self.replicas[1:]:
                exes.append(self._place_serialized(ser, rep))
            out_shapes = jax.eval_shape(self._fn, self._params_r0, specs)
            out_tree = jax.tree_util.tree_structure(out_shapes)
            out_avals = jax.tree_util.tree_leaves(out_shapes)
            with self._lock:
                self._kept[key] = kept_t
                self._out_tree[key] = out_tree
                self._out_avals[key] = out_avals
                self._exes[key] = tuple(exes)  # publish last
            return time.perf_counter() - t0

    def dispatch(self, replica: Replica, batched, spans: Sequence = (),
                 key: Optional[Tuple] = None):
        """Upload one exactly-bucket-sized host batch to ``replica``'s
        device and run its executable; returns the DEVICE result tree
        (fetch via :func:`fetch_rows`).  The signature must already be
        placed (``ensure_compiled``) — dispatch itself never compiles.
        ``spans`` get the ``device_put`` -> ``execute`` transitions
        (``execute`` stays open until the fetch, like the single-device
        path).  ``key`` skips re-deriving the signature when the caller
        already holds it (the per-dispatch hot path does)."""
        if key is None:
            key = self._key(batched)
        exe = self._exes[key][replica.index]
        for s in spans:
            s.phase_start("device_put")
        dev = replica.device
        dev_x = [jax.device_put(a, dev)
                 for a in jax.tree_util.tree_leaves(batched)]
        _profile.note_transfer("h2d")
        args = replica.params_flat + dev_x
        kept = self._kept[key]
        if kept is not None:
            args = [args[i] for i in kept]
        for s in spans:
            s.phase_start("execute")
        outs = exe.execute(args)
        return jax.tree_util.tree_unflatten(self._out_tree[key], outs)

    # ---- elasticity ----
    def _zeros_for(self, key: Tuple) -> List[np.ndarray]:
        """A host batch matching a placed signature — the key IS the
        full per-leaf (shape, dtype) list, so a warmed no-op input
        needs no remembered sample."""
        return [np.zeros(shape, dtype) for shape, dtype in key]

    def _prime(self, replica: Replica) -> None:
        """Execute every placed signature once on ``replica`` —
        warm-before-activate (and the probe body).  Never compiles:
        the executables were placed at ensure_compiled time (placement
        covers INACTIVE replicas too, exactly so this stays a load).
        Fetches via explicit device_get — priming must not leave work
        in flight behind the activation flip."""
        for key in list(self._exes):
            jax.device_get(self.dispatch(replica, self._zeros_for(key),
                                         key=key))

    def set_active(self, n: int) -> int:
        """Resize the scheduled replica set to ``n`` replicas (clamped
        to [1, total]); returns the active count.  Selection is
        HEALTH-AWARE, lowest index first: a dead replica must not hold
        a seat — or fail the whole resize from inside its prime —
        while healthy spares sit deactivated, so when healthy replicas
        run short the remainder fills with unhealthy ones, unprimed
        (the scheduler routes around them until their probe heals;
        placement already covered them, so healing never compiles).
        Healthy joiners are primed BEFORE the flag flips, so the
        scheduler never routes to a replica whose first request would
        pay lazy init; a joiner whose prime raises is marked unhealthy
        and the resize carries on with the rest.  Deactivation only
        unschedules: in-flight groups resolve normally and the replica
        keeps its warm state."""
        n = max(1, min(int(n), len(self.replicas)))
        chosen = {r.index for r in
                  sorted(self.replicas,
                         key=lambda r: (not r.healthy, r.index))[:n]}
        joining = [r for r in self.replicas
                   if r.index in chosen and not r.active]
        leaving = [r for r in self.replicas
                   if r.active and r.index not in chosen]
        for r in joining:
            if not r.healthy:
                continue  # never dispatch a prime to a red device
            try:
                self._prime(r)
            except RuntimeError as e:
                self.mark_unhealthy(r, e)
        with self._lock:
            for r in self.replicas:
                r.active = r.index in chosen
        if joining or leaving:
            _slog.info("replica_set_active", active=n,
                       total=len(self.replicas),
                       joined=[r.index for r in joining],
                       left=[r.index for r in leaving])
        return n

    # ---- health / scheduling ----
    def healthy_indices(self) -> List[int]:
        """Replica indices eligible for dispatch: active AND healthy.
        Falls back to the active set when every active replica is
        marked unhealthy (a fully-red set keeps serving — and keeps
        showing red — rather than bricking), then to ALL replicas."""
        out = [r.index for r in self.replicas if r.healthy and r.active]
        if out:
            return out
        out = [r.index for r in self.replicas if r.active]
        return out if out else [r.index for r in self.replicas]

    def mark_unhealthy(self, replica: Replica, exc: BaseException):
        now = time.perf_counter()
        with self._lock:
            if replica.healthy:
                replica.healthy = False
                self._unhealthy_count += 1
            replica.probe_backoff = max(replica.probe_backoff,
                                        self.probe_backoff_s)
            replica.probe_at = now + replica.probe_backoff
        _slog.error("replica_unhealthy", replica=replica.index,
                    device=str(replica.device),
                    probe_in_s=round(replica.probe_backoff, 3),
                    error=f"{type(exc).__name__}: {exc}")

    def maybe_reprobe(self) -> None:
        """Time-gated health re-probe of unhealthy replicas: a cheap
        warmed no-op execute per due replica, on exponential backoff
        (``probe_backoff_s`` doubling to ``probe_backoff_max_s``).  A
        probe that returns flips the replica healthy — recovery no
        longer depends on live-traffic retry luck.  Cost when all
        replicas are healthy: one int compare.

        The probe itself runs on a DETACHED daemon thread: this method
        is driven from the coalescer dispatcher and solo request
        threads, and a device that fails SLOWLY (wedged rather than
        raising) must stall the probe thread, not live traffic on the
        healthy replicas.  The non-blocking guard (held by the probe
        thread until it finishes) keeps concurrent dispatch paths from
        stacking probes."""
        if not self._unhealthy_count:
            return
        now = time.perf_counter()
        due = [r for r in self.replicas
               if not r.healthy and r.probe_at <= now]
        if not due:
            return
        if not self._probe_guard.acquire(blocking=False):
            return
        threading.Thread(target=self._probe_due, args=(due,),
                         name="zoo-replica-probe", daemon=True).start()

    def _probe_due(self, due: List[Replica]) -> None:
        """Probe-thread body: probe each due replica, then release the
        guard (the guard is acquired by maybe_reprobe and handed to
        this thread)."""
        try:
            for r in due:
                self._probe(r)
        finally:
            self._probe_guard.release()

    def _probe(self, replica: Replica) -> bool:
        """One health probe: execute the smallest placed signature on
        ``replica`` and fetch the result.  Success restores health
        (and resets the backoff); device-side failure doubles it."""
        with self._lock:
            keys = list(self._exes)
        if not keys:
            return False  # nothing placed yet — nothing warm to probe
        key = min(keys, key=lambda k: k[0][0][0] if k and k[0][0] else 0)
        try:
            jax.device_get(self.dispatch(replica, self._zeros_for(key),
                                         key=key))
        except RuntimeError as e:
            with self._lock:
                replica.probe_backoff = min(replica.probe_backoff * 2.0
                                            or self.probe_backoff_s,
                                            self.probe_backoff_max_s)
                replica.probe_at = (time.perf_counter()
                                    + replica.probe_backoff)
            _slog.info("replica_probe_failed", replica=replica.index,
                       next_probe_in_s=round(replica.probe_backoff, 3),
                       error=f"{type(e).__name__}: {e}")
            return False
        with self._lock:
            if not replica.healthy:
                replica.healthy = True
                self._unhealthy_count -= 1
            replica.probe_backoff = self.probe_backoff_s
        _slog.info("replica_recovered", replica=replica.index,
                   device=str(replica.device))
        return True

    def retry_target(self, failed: Replica) -> Optional[Replica]:
        """A healthy replica other than ``failed`` (round-robin), or
        None when there is nowhere left to retry.  Inactive-but-healthy
        replicas are eligible — they are warm and idle, the best
        possible place for a one-off retry."""
        with self._lock:
            cands = [r for r in self.replicas
                     if r.healthy and r is not failed]
            if not cands:
                return None
            self._rr += 1
            return cands[self._rr % len(cands)]

    def pick(self) -> Replica:
        """Round-robin over active healthy replicas — the solo
        (non-coalesced) path's scheduler.  The coalescer's dispatcher
        uses least-outstanding-work instead (it owns the in-flight
        counts).  Also the solo path's probe driver: each pick gives
        due unhealthy replicas their time-gated recovery probe."""
        self.maybe_reprobe()
        with self._lock:
            idxs = [r.index for r in self.replicas
                    if r.healthy and r.active]
            if not idxs:
                idxs = [r.index for r in self.replicas if r.active] \
                    or [r.index for r in self.replicas]
            self._rr += 1
            return self.replicas[idxs[self._rr % len(idxs)]]

    def stats(self) -> Dict[str, Any]:
        return {
            "replicas": len(self.replicas),
            "replicas_active": self.n_active,
            "replica_dispatches": {r.index: r.dispatches
                                   for r in self.replicas},
            "replica_unhealthy": {r.index: (not r.healthy)
                                  for r in self.replicas},
            "replica_active": {r.index: r.active
                               for r in self.replicas},
            "replica_bucket_dispatches": {
                r.index: dict(r.bucket_dispatches)
                for r in self.replicas},
        }


class BucketedExecutableCache:
    """Pad batches to a bucket ladder so a ragged request stream hits a
    handful of compiled executables.

    ``fn`` is the (jitted underneath) forward over one host batch; the
    jit's own shape cache holds the executables — this layer guarantees
    only ladder shapes ever reach it, tracks hit/miss/compile-time per
    bucket, and un-pads results.  Batches larger than the top bucket are
    served in top-bucket chunks (the tail padded), so arbitrarily large
    inputs still hit only ladder shapes.
    """

    def __init__(self, fn: Callable, max_batch: int = 32,
                 buckets: Optional[Sequence[int]] = None,
                 growth: float = 2.0,
                 replica_set: Optional[ReplicaSet] = None):
        self._fn = fn
        self.buckets = (tuple(sorted(set(int(b) for b in buckets)))
                        if buckets else bucket_ladder(max_batch, growth))
        if self.buckets[0] < 1:
            raise ValueError(f"buckets must be >= 1, got {self.buckets}")
        self.max_batch = self.buckets[-1]
        self.stats = BucketStats()
        # device-parallel backend: when set, dispatches route to one of
        # its replicas (compile-once/place-everywhere) instead of the
        # single jitted ``fn``
        self.replica_set = replica_set
        self._seen: set = set()
        self._lock = threading.Lock()

    def bucket_for(self, n: int) -> int:
        """Smallest ladder bucket >= n (top bucket for oversized n)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_batch

    def _note_lookup(self, bucket: int, signature: Tuple) -> bool:
        """Hit/miss bookkeeping for one bucket lookup — the ONE counter
        protocol shared by the dispatch path and warmup.  Returns True
        when this (bucket, signature) is new to the cache."""
        sig = (bucket, signature)
        with self._lock:
            fresh = sig not in self._seen
            if fresh:
                self._seen.add(sig)
                self.stats.misses[bucket] = \
                    self.stats.misses.get(bucket, 0) + 1
            else:
                self.stats.hits[bucket] = self.stats.hits.get(bucket, 0) + 1
        return fresh

    def _note_compile(self, bucket: int, secs: float):
        with self._lock:
            self.stats.compile_time_s[bucket] = \
                self.stats.compile_time_s.get(bucket, 0.0) + secs

    def _dispatch(self, batched, bucket: int, spans: Sequence = (),
                  replica: Optional[Replica] = None):
        """Run one exactly-bucket-sized padded batch, with counters.
        ``spans`` are the riders' trace spans: each gets the
        ``device_put`` -> ``execute`` phase transitions and its padded
        bucket as a label (``execute`` stays open — it ends when the
        owner starts ``depad`` after the fetch).  With a replica set the
        batch routes to ``replica`` (or the round-robin pick), retried
        once on another replica if the dispatch raises."""
        signature = batch_signature(batched)
        fresh = self._note_lookup(bucket, signature)
        for s in spans:
            s.set_label("bucket", bucket)
        if self.replica_set is not None:
            return self._dispatch_replica(self.replica_set, batched,
                                          bucket, signature, fresh,
                                          spans, replica)
        for s in spans:
            s.phase_start("device_put")
        # explicit upload: handing numpy straight to the jit is an
        # IMPLICIT host->device transfer per dispatch — same bytes
        # moved, but invisible to jax's transfer guards.  device_put
        # keeps the hot loop clean under zoolint.sanitize() (and on a
        # real TPU makes the per-dispatch upload an auditable event).
        batched = jax.device_put(batched)
        _profile.note_transfer("h2d")
        for s in spans:
            s.phase_start("execute")
        if fresh:
            t0 = time.perf_counter()
            # the dispatcher thread has no contextvar span, so the XLA
            # profile hook would drop this compile's span event;
            # activating the group's lead span here (cold path only)
            # keeps the docstring promise that an unwarmed shape shows
            # up IN the request's trace
            with _trace.activate(spans[0] if spans else None):
                out = jax.block_until_ready(self._fn(batched))
            self._note_compile(bucket, time.perf_counter() - t0)
            return out
        return self._fn(batched)

    def _dispatch_replica(self, rs: ReplicaSet, batched, bucket: int,
                          signature: Tuple, fresh: bool,
                          spans: Sequence,
                          replica: Optional[Replica]):
        """Replica-path half of ``_dispatch``: ensure the signature is
        compiled-and-placed, route to a replica, and retry ONCE on
        another healthy replica when the dispatch raises a runtime
        error (the failed one is marked unhealthy).

        ``ensure_compiled`` runs UNCONDITIONALLY — the ``fresh``
        hit/miss bit only attributes the compile's span event.  Gating
        placement on it would race: a second request can see
        fresh=False while the first is still mid-compile, and a compile
        that raised once would leave the signature poisoned forever.
        The warm-path cost is one dict membership check."""
        key = ReplicaSet.key_from(bucket, signature)
        with _trace.activate(spans[0] if (fresh and spans) else None):
            # on the cold path the lead span is active so the compile's
            # backend_compile event attributes to the request paying it
            secs = rs.ensure_compiled(batched, key=key)
        if secs:
            self._note_compile(bucket, secs)
        if replica is None:
            replica = rs.pick()
        for s in spans:
            for lk, lv in rs.span_labels(replica).items():
                s.set_label(lk, lv)
        try:
            out = rs.dispatch(replica, batched, spans, key=key)
        except RuntimeError as e:
            # RuntimeError covers device-side failures (XlaRuntimeError
            # subclasses it) — those indict the REPLICA.  Host-side
            # errors (TypeError/ValueError on a malformed input, or
            # KeyboardInterrupt) propagate untouched: one bad request
            # must not flip healthy hardware red.
            rs.mark_unhealthy(replica, e)
            alt = rs.retry_target(replica)
            if alt is None:
                raise
            for s in spans:
                for lk, lv in rs.span_labels(alt).items():
                    s.set_label(lk, lv)
                s.event("replica_retry", failed=replica.index,
                        error=type(e).__name__)
            try:
                out = rs.dispatch(alt, batched, spans, key=key)
            except RuntimeError as e2:
                # the retry replica is just as dead — say so in the
                # gauge before surfacing the error (no second retry:
                # a model-level fault would loop over every replica)
                rs.mark_unhealthy(alt, e2)
                raise
            replica = alt
        with self._lock:
            replica.dispatches += 1
            replica.bucket_dispatches[bucket] = \
                replica.bucket_dispatches.get(bucket, 0) + 1
        return out

    def run(self, batched, sem: Optional[threading.Semaphore] = None,
            span=None):
        """Serve one host batch of any row count; returns HOST numpy
        results with padding rows removed.  ``sem`` (the owner's
        device-concurrency bound) is held around the DISPATCH only —
        the blocking host fetch happens outside it, so concurrent
        callers' dispatches overlap each other's result transfers.
        ``span`` (the request's trace span, if tracing) records the
        pad/device_put/execute/depad phases — once per chunk for
        oversized batches."""
        guard = sem if sem is not None else contextlib.nullcontext()
        spans = (span,) if span is not None else ()
        n = _rows(batched)
        if n == 0:
            # run the smallest bucket and keep zero rows — the output
            # structure/shape contract stays intact for empty inputs
            with guard:
                out = self._dispatch(_pad_rows(batched, self.buckets[0]),
                                     self.buckets[0], spans)
            return fetch_rows(out, 0, span=span)
        outs = []
        start = 0
        while start < n:
            take = min(self.max_batch, n - start)
            chunk = _slice_rows(batched, start, start + take) \
                if (start or take < n) else batched
            bucket = self.bucket_for(take)
            if span is not None:
                span.phase_start("pad")
            padded = _pad_rows(chunk, bucket - take)
            with guard:
                out = self._dispatch(padded, bucket, spans)
            outs.append(fetch_rows(out, take, span=span))
            start += take
        return _concat_trees(outs)

    def dispatch_padded(self, batched, spans: Sequence = (),
                        replica: Optional[Replica] = None):
        """Async single dispatch: pad to the bucket and return the
        DEVICE result tree without fetching.  jax dispatch is
        asynchronous, so the caller can overlap host work (gathering
        the next batch) with this compute and fetch later via
        ``fetch_rows``.  One bucket only — rows must fit ``max_batch``.
        ``replica`` pins the dispatch to one replica of the replica set
        (the coalescer's least-outstanding-work scheduler passes it)."""
        n = _rows(batched)
        if n > self.max_batch:
            raise ValueError(
                f"dispatch_padded: {n} rows exceed the top bucket "
                f"{self.max_batch}; use run() for chunked serving")
        bucket = self.bucket_for(max(n, 1))
        for s in spans:
            s.phase_start("pad")
        return self._dispatch(_pad_rows(batched, bucket - n), bucket,
                              spans, replica=replica)

    def warmup(self, sample_shapes, dtypes=None,
               buckets: Optional[Sequence[int]] = None) -> float:
        """AOT-compile the ladder for one input signature — and, with a
        replica set, place + prime every replica's executable.

        ``sample_shapes``: per-sample shape (no batch axis) for a
        single-input model, or a list of them for multi-input;
        ``dtypes`` matches element-wise (default float32).  Returns the
        total compile wall seconds spent (wall, not CPU: bucket
        compiles overlap in a small thread pool — XLA compiles release
        the GIL, so the ladder compiles concurrently and the hot-swap
        blip a deploy pays shrinks accordingly).  Per-bucket compile
        milliseconds go through the structured logger."""
        multi = (sample_shapes and
                 isinstance(sample_shapes[0], (tuple, list)))
        shapes = list(sample_shapes) if multi else [sample_shapes]
        if dtypes is None:
            dts = [np.float32] * len(shapes)
        elif isinstance(dtypes, (tuple, list)):
            dts = list(dtypes)
        else:
            dts = [dtypes] * len(shapes)
        rs = self.replica_set
        ladder = list(buckets or self.buckets)

        def warm_one(b: int) -> float:
            arrs = tuple(np.zeros((b,) + tuple(s), dt)
                         for s, dt in zip(shapes, dts))
            batched = arrs if multi else arrs[0]
            if rs is None:
                tb = time.perf_counter()
                self._dispatch(batched, b)
                ms = (time.perf_counter() - tb) * 1e3
            else:
                # replica path: compile + place via ensure_compiled
                # (same counter protocol as the dispatch path, via
                # _note_lookup), then prime EVERY replica's executable
                # so no replica's first live request pays lazy init.
                # Priming bypasses the dispatch counters — warmup must
                # not skew the scheduler-balance metrics — and the
                # logged compile_ms is the COMPILE alone, not the N
                # priming executions.
                self._note_lookup(b, batch_signature(batched))
                secs = rs.ensure_compiled(batched)
                if secs:
                    self._note_compile(b, secs)
                for rep in rs.replicas:
                    jax.block_until_ready(rs.dispatch(rep, batched))
                ms = secs * 1e3
            _slog.info("warmup_bucket", bucket=b,
                       compile_ms=round(ms, 3),
                       replicas=(rs.n if rs is not None else 1))
            return ms

        t0 = time.perf_counter()
        if len(ladder) > 1:
            with ThreadPoolExecutor(
                    max_workers=min(len(ladder), 4),
                    thread_name_prefix="zoo-warmup") as pool:
                list(pool.map(warm_one, ladder))
        else:
            for b in ladder:
                warm_one(b)
        return time.perf_counter() - t0


def fetch_rows(device_tree, n: int, span=None):
    """Block on a ``dispatch_padded`` result and strip the padding.
    With a ``span`` the blocking fetch closes the open ``execute``
    phase (``depad`` starts once the bytes are on the host)."""
    host = jax.tree_util.tree_map(
        lambda a: np.asarray(jax.device_get(a)), device_tree)
    _profile.note_transfer("d2h")
    if span is not None:
        span.phase_start("depad")
    out = _slice_rows(host, 0, n)
    if span is not None:
        span.phase_end()
    return out


class _StagingArena:
    """Zero-alloc staging for the dispatcher thread: reusable host
    buffers, one ring per (slot, bucket, signature), that coalesced
    riders are gathered into directly — eliminating the per-group
    ``np.concatenate`` + pad allocations on the hot path.

    OWNERSHIP RULE: single-owner, dispatcher thread only — no locks by
    design.  Reuse safety: ``device_put`` of a host buffer may be
    ZERO-COPY (the device array aliases the buffer until the execution
    consumes it), so a buffer must not be rewritten while its dispatch
    is still in flight.  Each slot's ring holds ``depth`` buffers,
    rotated per dispatch, and the coalescer (a) caps per-slot in-flight
    groups at ``depth`` and (b) resolves FIFO — so by the time a buffer
    rotates back around, the dispatch that used it has been fetched.
    """

    __slots__ = ("depth", "_bufs", "_turn", "_pending")

    def __init__(self, depth: int):
        self.depth = max(1, int(depth))
        self._bufs: Dict[Tuple, List] = {}
        self._turn: Dict[Tuple, int] = {}
        self._pending: Optional[Tuple] = None

    def buffers_allocated(self) -> int:
        """Total staging buffers currently held (introspection)."""
        return sum(1 for ring in self._bufs.values()
                   for b in ring if b is not None)

    def commit(self):
        """Advance the ring of the last ``pack``ed key — called by the
        dispatcher ONLY after its dispatch succeeded.  A failed
        dispatch leaves the turn in place (that buffer is free to
        rewrite), keeping rotation in lock-step with the in-flight cap:
        advancing on failure would desync them and let a later pack
        land on a buffer whose dispatch is still in flight."""
        key = self._pending
        if key is not None:
            self._pending = None
            self._turn[key] = (self._turn[key] + 1) % self.depth

    def pack(self, group: Sequence["_Request"], bucket: int, slot: int):
        """Gather ``group``'s rows into the current staging buffer for
        (slot, bucket), zero the padding tail, and return the padded
        batch tree (exactly ``bucket`` rows) — same structure as the
        riders' batches, backed by arena memory.  The ring only
        advances on ``commit()``."""
        head = group[0]
        key = (slot, bucket, head.sig)
        ring = self._bufs.get(key)
        if ring is None:
            ring = self._bufs[key] = [None] * self.depth
            self._turn[key] = 0
        turn = self._turn[key]
        self._pending = key
        leaves0, treedef = jax.tree_util.tree_flatten(head.batched)
        bufs = ring[turn]
        if bufs is None:
            bufs = ring[turn] = [
                np.zeros((bucket,) + tuple(np.asarray(l).shape[1:]),
                         np.asarray(l).dtype)
                for l in leaves0]
        off = 0
        for r in group:
            leaves = (leaves0 if r is head
                      else jax.tree_util.tree_leaves(r.batched))
            for buf, leaf in zip(bufs, leaves):
                buf[off:off + r.n] = leaf
            off += r.n
        if off < bucket:
            for buf in bufs:
                buf[off:bucket] = 0
        return jax.tree_util.tree_unflatten(treedef, bufs)


class _Request:
    # ``span`` is the EXPLICIT cross-thread trace handoff: contextvars
    # do not propagate into the dispatcher thread (started long before
    # this request existed), so the pending request carries its span
    # and the dispatcher records phases on it directly.
    __slots__ = ("batched", "n", "sig", "future", "span")

    def __init__(self, batched, n, sig, span=None):
        self.batched = batched
        self.n = n
        self.sig = sig
        self.span = span
        self.future: Future = Future()


_SHUTDOWN = object()


class CoalescerClosedError(RuntimeError):
    """The dispatcher is gone — this request was (or would be) never
    served.  Distinct type so callers can fall back to the solo path
    without masking genuine model-execution errors (XlaRuntimeError is
    a RuntimeError subclass)."""


class RequestCoalescer:
    """Pack concurrent predict() calls into one device dispatch, with
    the NEXT batch gathered while the current one computes.

    Callers ``submit()`` into a bounded queue; a single dispatcher
    thread takes the head request, gathers same-signature riders until
    ``max_batch`` rows are packed, ``max_wait_ms`` elapses, or the
    queue momentarily drains, concatenates them into one padded batch,
    and dispatches it through the bucketed ``cache`` WITHOUT fetching —
    jax dispatch is asynchronous, so the dispatcher goes straight back
    to gathering the next group while the device computes, then fetches
    and fans rows back onto each caller's Future (one-deep pipeline:
    the serving-side analog of the data path's double-buffered
    prefetch).  A signature mismatch ends a group — the odd request
    leads the next one, so mixed streams stay correct, just un-packed
    across shapes.

    With a multi-replica cache the pipeline generalizes from depth
    ``pipeline_depth`` on one device to depth N across devices: every
    replica owns ONE in-flight slot, and each group routes to the
    healthy replica with the fewest undelivered groups
    (least-outstanding-work), so group k+1 executes on replica B while
    group k's fetch from replica A is still in flight.

    Groups are staged through a :class:`_StagingArena` (reusable
    dispatcher-owned buffers) instead of a fresh concatenate+pad per
    dispatch — the steady-state hot path allocates nothing on the host
    side.

    ``semaphore`` (the owner's ``supported_concurrent_num`` bound) is
    held from dispatch to fetch so coalesced work counts against the
    same device-concurrency budget as solo calls.
    """

    # forced loser-drain budget: a pending hedge loser still in flight
    # past this is treated as WEDGED (its replica marked unhealthy)
    # instead of blocking the dispatcher indefinitely.  Class-level so
    # tests can shrink it per instance.
    _WEDGE_TIMEOUT_S = 30.0
    # the hedge threshold quantile is recomputed every N group
    # resolves, not per group (see _hedge_threshold_s)
    _HEDGE_THR_REFRESH = 32

    def __init__(self, cache: BucketedExecutableCache,
                 max_batch: Optional[int] = None,
                 max_wait_ms: float = 2.0,
                 semaphore: Optional[threading.Semaphore] = None,
                 pipeline_depth: int = 2,
                 queue_size: int = 1024,
                 hedging: bool = False,
                 hedge_quantile: float = 0.99,
                 hedge_min_ms: float = 0.5,
                 hedge_min_samples: int = 20):
        self._cache = cache
        self.max_batch = int(max_batch or cache.max_batch)
        self.max_wait_ms = float(max_wait_ms)
        self._sem = semaphore
        self.pipeline_depth = max(1, int(pipeline_depth))
        rs = cache.replica_set
        # one slot per replica (cap 1 each) when device-parallel; one
        # slot with the legacy pipeline depth as its cap otherwise
        self._rs = rs if (rs is not None and rs.n > 1) else None
        self._n_slots = self._rs.n if self._rs is not None else 1
        self._slot_cap = 1 if self._rs is not None else self.pipeline_depth
        self._slot_inflight = [0] * self._n_slots
        self._slot_rr = 0
        self._arena = _StagingArena(self._slot_cap)
        # ---- p99 hedging (device-parallel only: a hedge needs a
        # second replica to win on).  The threshold derives from the
        # observed group resolve-latency quantile, so "straggler"
        # means straggler RELATIVE to this model's own distribution.
        self.hedging = bool(hedging) and self._rs is not None
        self.hedge_quantile = float(hedge_quantile)
        self.hedge_min_ms = float(hedge_min_ms)
        self.hedge_min_samples = int(hedge_min_samples)
        self._group_lat = _LatencyWindow(maxlen=512)
        # dispatcher-thread-owned threshold cache (see
        # _hedge_threshold_s): (value, window count at compute)
        self._hedge_thr: Optional[float] = None
        self._hedge_thr_at = -1
        # dispatcher-thread-owned counters (read via dict copy)
        self._hedges = {"fired": 0, "primary_won": 0, "hedge_won": 0,
                        "skipped_no_replica": 0}
        self._hedge_pool: Optional[ThreadPoolExecutor] = None
        # loser futures already reported as wedged (bounded: entries
        # leave when their loser retires)
        self._wedged_reported: set = set()
        # hedge losers still aliasing a staging buffer: (primary_slot,
        # future, hedge_replica_index|None).  The primary slot's
        # in-flight count is held until the losing fetch returns (the
        # PR 5 retry-window ownership rule — see _drain_losers); the
        # third element releases the hedge replica's own in-flight
        # count when the pending loser IS the hedge
        self._pending_losers: List[Tuple[int, Future,
                                         Optional[int]]] = []
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._carry: Optional[_Request] = None
        self.dispatches = 0
        self.coalesced_requests = 0
        # live-request accounting: _outstanding counts submitted-but-
        # unresolved requests; _inflight_n the subset already dispatched.
        # Their difference is every rider that could still arrive — once
        # a group holds them all, waiting any longer is pure latency.
        self._outstanding = 0
        self._out_lock = threading.Lock()
        self._inflight_n = 0
        self._closed = False
        # makes (closed-check + enqueue) atomic against close()'s
        # (set-closed + sentinel + drain): a submit can never slip into
        # the queue after the drain.  Separate from _out_lock — a put
        # blocking on a full queue must not deadlock the dispatcher's
        # _done() accounting.
        self._submit_lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._crashed = False
        self._inflight: "collections.deque" = collections.deque()
        self._thread = threading.Thread(
            target=self._loop, name="zoo-serving-dispatch", daemon=True)
        self._thread.start()

    @property
    def closed(self) -> bool:
        """True once close() ran or the dispatcher died — submits would
        never be served."""
        return self._closed or not self._thread.is_alive()

    @property
    def pending(self) -> int:
        """Submitted-but-unresolved request count (queued + in flight)."""
        with self._out_lock:
            return self._outstanding

    def hedge_stats(self) -> Dict[str, int]:
        """Copy of the hedge outcome counters (dispatcher-owned ints;
        the copy is GIL-atomic enough for a metrics scrape)."""
        return dict(self._hedges)

    def submit(self, batched, span=None) -> Future:
        n = _rows(batched)
        if n > self.max_batch:
            raise ValueError(
                f"coalesced request of {n} rows exceeds max_batch "
                f"{self.max_batch} — send it through the solo path")
        if span is not None:
            # open here, on the caller's thread: coalesce_wait covers
            # queue time + group gathering, ending when the dispatcher
            # starts the group's pad phase
            span.phase_start("coalesce_wait")
        req = _Request(batched, n, batch_signature(batched), span)
        with self._submit_lock:
            if self.closed:
                raise CoalescerClosedError(
                    "RequestCoalescer is closed — no dispatcher is "
                    "serving this queue")
            with self._out_lock:
                self._outstanding += 1
            self._q.put(req)
        if self._crashed or not self._thread.is_alive():
            # the dispatcher died between the aliveness check and the
            # enqueue — its crash-net drain may already have run, so
            # nobody would ever serve (or fail) this request.  Flush it
            # (and anything else stranded) ourselves.  ``_crashed`` is
            # set BEFORE the crash net's flush, so even a put that was
            # blocked on a full queue (and only completed because that
            # flush freed a slot, while the crashing thread still reads
            # as alive) observes it here.
            self._flush_queue(CoalescerClosedError(
                "RequestCoalescer dispatcher died"))
        return req.future

    def _done(self, k: int):
        with self._out_lock:
            self._outstanding -= k

    def _flush_queue(self, exc: BaseException):
        """Fail every queued (never-dispatched) request with ``exc``.
        Only safe once no dispatcher owns the queue: closed-and-joined,
        crashed, or from the crash net itself.  ``_flush_lock``
        serializes the crash net against a concurrent submit-side flush
        (both may race to fail the same carry)."""
        with self._flush_lock:
            leftovers, self._carry = (
                [self._carry] if self._carry is not None else []), None
            try:
                while True:
                    r = self._q.get_nowait()
                    if r is not _SHUTDOWN:
                        leftovers.append(r)
            except queue.Empty:
                pass
            # flushed requests leave the live count too — ``pending``
            # must not report phantom requests on a dead coalescer
            self._done(len(leftovers))
            for r in leftovers:
                if not r.future.done():
                    r.future.set_exception(exc)

    def close(self, timeout: float = 5.0):
        """Stop the dispatcher: already-queued requests are SERVED (the
        shutdown sentinel sits behind them in the queue — this is the
        graceful drain reload()/the registry rely on), then anything
        racing the shutdown fails with CoalescerClosedError
        (idempotent)."""
        with self._submit_lock:
            already = self._closed
            self._closed = True
            if not already and self._thread.is_alive():
                self._q.put(_SHUTDOWN)
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            # the dispatcher is wedged mid-group (e.g. a long compile) —
            # it still owns _carry and the queue, so leave both alone;
            # it will drain to the sentinel and exit on its own
            return
        self._flush_queue(CoalescerClosedError("RequestCoalescer closed"))

    # ---- dispatcher ----
    def _gather(self, block: bool,
                pipeline_busy: bool = False) -> Tuple[List[_Request], bool]:
        """One group: head + same-signature riders until the batch is
        full, the wait budget lapses, or the queue momentarily drains.
        The drain condition is the important one: callers are blocked
        on their futures, so once the queue is empty, holding a partial
        batch for the rest of ``max_wait_ms`` cannot attract closed-loop
        riders — it only adds their wait to every row.  A short grace
        (max_wait/8) still absorbs staggered arrivals.  Returns
        (group, shutdown_seen); with ``block`` False the head wait is
        bounded by the grace too (a dispatch is in flight — the
        dispatcher must come back to fetch it promptly)."""
        grace = max(min(self.max_wait_ms / 8.0, 0.5), 0.05) / 1000.0
        head = self._carry
        self._carry = None
        if head is None:
            try:
                head = (self._q.get() if block
                        else self._q.get(timeout=grace))
            except queue.Empty:
                return [], False
            if head is _SHUTDOWN:
                return [], True
        group, count, rows = [head], 1, head.n
        deadline = time.perf_counter() + self.max_wait_ms / 1000.0
        while rows < self.max_batch:
            # every live request not yet dispatched is either in this
            # group or could still ride it; once the group holds them
            # all, no grace wait can attract another — dispatch now.
            # Only when the device is idle, though: with a dispatch in
            # flight there is no urgency, and the about-to-resolve
            # riders will want seats on THIS group
            if not pipeline_busy \
                    and count >= self._outstanding - self._inflight_n:
                break
            remaining = deadline - time.perf_counter()
            try:
                nxt = (self._q.get_nowait() if remaining <= 0
                       else self._q.get(timeout=min(remaining, grace)))
            except queue.Empty:
                break
            if nxt is _SHUTDOWN:
                return group, True
            if nxt.sig != head.sig or rows + nxt.n > self.max_batch:
                self._carry = nxt
                break
            group.append(nxt)
            count += 1
            rows += nxt.n
        return group, False

    def _acquire_slot(self, inflight):
        """Take one device-concurrency slot without deadlocking: the
        dispatcher itself may hold every slot via unfetched dispatches,
        so on contention it resolves its oldest in-flight group (which
        releases a slot) before blocking."""
        if self._sem is None:
            return
        while not self._sem.acquire(blocking=False):
            if inflight:
                self._resolve(inflight.popleft())
            else:
                self._sem.acquire()  # held by solo callers — just wait
                return

    def _pick_slot(self) -> int:
        """Least-outstanding-work: the healthy replica with the fewest
        undelivered groups (dispatcher thread only — the counts are
        single-owner state).  Ties rotate round-robin so a lightly
        loaded stream (every dispatch resolved before the next) still
        spreads across replicas instead of camping on index 0.  Slot 0
        when not device-parallel.

        ONLY below-cap slots are eligible — this is the arena-safety
        invariant, not a preference.  The healthy set can shrink
        between the caller's capacity check and this pick (a SOLO-path
        dispatch on another thread may mark a replica unhealthy at any
        time), so an at-cap "least loaded healthy" slot is possible
        here; picking it would rewrite a staging buffer whose
        zero-copy dispatch is still in flight.  The in-flight counts
        themselves only change on this thread, so a below-cap slot the
        caller saw is still below cap — falling back to ANY below-cap
        slot (even an unhealthy one: its buffer is free, and the
        cache's fault retry re-routes the execution) always succeeds."""
        if self._rs is None:
            return 0
        idxs = [i for i in self._rs.healthy_indices()
                if self._slot_inflight[i] < self._slot_cap]
        if not idxs:
            idxs = [i for i in range(self._n_slots)
                    if self._slot_inflight[i] < self._slot_cap]
        rr = self._slot_rr
        slot = min(idxs, key=lambda i: (self._slot_inflight[i],
                                        (i - rr) % self._n_slots))
        self._slot_rr = (slot + 1) % self._n_slots
        return slot

    def _has_free_capacity(self) -> bool:
        """True when some eligible slot is below its in-flight cap —
        i.e. a new group can be staged without rewriting an arena
        buffer that is still in flight."""
        if self._rs is None:
            return len(self._inflight) < self._slot_cap
        return any(self._slot_inflight[i] < self._slot_cap
                   for i in self._rs.healthy_indices())

    def _capacity(self) -> int:
        """Total undelivered-group capacity across eligible slots."""
        if self._rs is None:
            return self._slot_cap
        return len(self._rs.healthy_indices()) * self._slot_cap

    def _dispatch_group(self, group: List[_Request], inflight):
        """Stage into the arena + async dispatch; returns an in-flight
        entry (group, rows, device_out, slot, t_dispatch, padded_batch,
        placement_key) or None when the dispatch itself failed.  The
        caller guarantees a free slot (arena-reuse safety — see
        :class:`_StagingArena`).  The padded batch and placement key
        ride along so a later hedge can re-dispatch the SAME staged
        buffer to another replica without re-packing."""
        try:
            spans = tuple(r.span for r in group if r.span is not None)
            for s in spans:
                s.phase_start("pad")  # ends coalesce_wait; covers staging
            n = sum(r.n for r in group)
            slot = self._pick_slot()
            bucket = self._cache.bucket_for(max(n, 1))
            batched = self._arena.pack(group, bucket, slot)
            replica = (self._rs.replicas[slot]
                       if self._rs is not None else None)
            key = (ReplicaSet.key_from(bucket, group[0].sig)
                   if self._rs is not None else None)
            self._acquire_slot(inflight)
            try:
                dev = self._cache.dispatch_padded(batched, spans,
                                                  replica=replica)
            except BaseException:
                if self._sem is not None:
                    self._sem.release()
                raise
            self._arena.commit()  # dispatch succeeded: rotate the ring
            self.dispatches += 1
            self.coalesced_requests += len(group)
            self._inflight_n += len(group)
            # charged to the PICKED slot even if the cache's fault
            # retry actually executed on another replica: the slot
            # count is what guards this slot's staging buffer against
            # rewrite-while-in-flight, and the buffer belongs to the
            # picked slot regardless of where execution landed.  The
            # scheduling skew (retry replica briefly carries two
            # groups) is bounded to the rare fault window and
            # self-corrects at resolve.
            self._slot_inflight[slot] += 1
            return group, n, dev, slot, time.perf_counter(), batched, key
        except BaseException as e:
            self._done(len(group))
            for r in group:
                if not r.future.done():
                    r.future.set_exception(e)
            return None

    # ---- resolve (plain + hedged) ----
    def _fetch_slot(self, dev, n: int, slot: int):
        """Blocking host fetch of a dispatched group.  A method (not a
        bare ``fetch_rows`` call) so tests and the bench can patch a
        per-slot straggler delay in — the injection point for the
        hedging gates."""
        return fetch_rows(dev, n)

    def _fetch_hedge(self, dev, n: int, replica_index: int):
        """Blocking host fetch of a hedge re-dispatch (separate patch
        point: a test can delay the hedge to pin primary-wins)."""
        return fetch_rows(dev, n)

    def _hedge_threshold_s(self) -> Optional[float]:
        """The in-flight age past which a group is hedged: the
        ``hedge_quantile`` of observed group resolve latencies, floored
        by ``hedge_min_ms``.  None until ``hedge_min_samples`` groups
        have resolved — hedging from an unseeded distribution would
        fire on noise.  The quantile is recomputed only every
        ``_HEDGE_THR_REFRESH`` resolves: ``percentile`` sorts the whole
        window under its lock, and a quantile over a 512-sample window
        barely moves across 32 adds — per-group sorting on the
        dispatcher's hot path bought nothing."""
        c = self._group_lat.count
        if c < self.hedge_min_samples:
            return None
        if (self._hedge_thr is None
                or c - self._hedge_thr_at >= self._HEDGE_THR_REFRESH):
            q = self._group_lat.percentile(self.hedge_quantile * 100.0)
            if q is None:
                return None
            self._hedge_thr = max(q, self.hedge_min_ms / 1e3)
            self._hedge_thr_at = c
        return self._hedge_thr

    def _hedge_target(self, slot: int) -> Optional[Replica]:
        """A healthy, ACTIVE replica other than the primary's — the
        least-loaded one.  None when fewer than 2 replicas are
        eligible: hedging no-ops rather than re-dispatching onto the
        same straggler (or a red/retired replica)."""
        rs = self._rs
        cands = [r for r in rs.replicas
                 if r.healthy and r.active and r.index != slot]
        if not cands:
            return None
        return min(cands, key=lambda r: self._slot_inflight[r.index])

    def _hedge_executor(self) -> ThreadPoolExecutor:
        if self._hedge_pool is None:
            # sized so pending loser fetches can never starve the next
            # group's primary+hedge pair of workers: every in-flight
            # slot (n_slots * slot_cap) could be holding a straggling
            # loser, plus the pair itself
            self._hedge_pool = ThreadPoolExecutor(
                max_workers=self._n_slots * self._slot_cap + 2,
                thread_name_prefix="zoo-serving-hedge")
        return self._hedge_pool

    def _swallow_loser(self, fut: Future):
        """Consume a losing fetch's outcome.  Its result is moot (the
        winner already served the group) and its error must not
        propagate — the hedge existed precisely because that replica
        was misbehaving."""
        try:
            fut.result()
        except BaseException as e:  # noqa: BLE001 — deliberate sink
            _slog.info("hedge_loser_error",
                       error=f"{type(e).__name__}: {e}")

    def _drain_losers(self, block: bool = False) -> bool:
        """Retire finished hedge losers and release their slot
        ownership.  ARENA-OWNERSHIP RULE: a losing dispatch's zero-copy
        ``device_put`` aliases the SAME staging buffer as the primary
        (the hedge re-dispatched the staged batch), so the primary
        slot's in-flight count — which is what guards that buffer
        against rewrite — stays held until the losing execute+fetch
        returns, exactly like the PR 5 retry-window rule.  ``block``
        (used when every slot is pinned and nothing else can free one)
        waits for whichever pending loser finishes FIRST — never the
        oldest specifically, which could wedge behind a dead fetch
        while a newer done loser sat ready to free a slot — bounded by
        ``_WEDGE_TIMEOUT_S``: past it the still-pending losers'
        replicas are marked unhealthy instead of stalling the
        dispatcher forever.  Returns whether any loser was retired."""
        retired = False
        remaining: List[Tuple[int, Future, Optional[int]]] = []
        for slot, fut, alt_idx in self._pending_losers:
            if fut.done():
                self._swallow_loser(fut)
                self._wedged_reported.discard(id(fut))
                if 0 <= slot < len(self._slot_inflight):
                    self._slot_inflight[slot] -= 1
                if alt_idx is not None:
                    # the pending loser was the hedge: its replica's
                    # own in-flight count releases with it
                    self._slot_inflight[alt_idx] -= 1
                retired = True
            else:
                remaining.append((slot, fut, alt_idx))
        self._pending_losers = remaining
        if block and not retired and remaining:
            done, _ = _futures_wait([f for _, f, _ in remaining],
                                    timeout=self._WEDGE_TIMEOUT_S,
                                    return_when=FIRST_COMPLETED)
            if done:
                return self._drain_losers()
            self._mark_wedged_losers()
        return retired

    def _mark_wedged_losers(self):
        """Every pending loser outlived the wedge budget: mark each
        one's replica unhealthy (one-way, once per loser) so
        scheduling, hedging, and the recovery probe treat the device
        as red.  The slot counts stay held — the wedged dispatch still
        aliases its staging buffer (arena-ownership rule), so only its
        fetch returning can release the buffer for rewrite."""
        if self._rs is None:
            return
        for slot, fut, alt_idx in self._pending_losers:
            if id(fut) in self._wedged_reported:
                continue
            self._wedged_reported.add(id(fut))
            idx = alt_idx if alt_idx is not None else slot
            if 0 <= idx < len(self._rs.replicas):
                self._rs.mark_unhealthy(
                    self._rs.replicas[idx],
                    RuntimeError(
                        f"hedge loser fetch wedged for more than "
                        f"{self._WEDGE_TIMEOUT_S:g}s"))

    def _resolve(self, item):
        """Fetch a dispatched group's device result and fan rows out.
        ``item`` is a ``_dispatch_group`` in-flight entry."""
        group, n, dev, slot, t0, batched, key = item
        if self.hedging:
            thr = self._hedge_threshold_s()
            if thr is not None:
                self._resolve_hedged(group, n, dev, slot, t0, batched,
                                     key, thr)
                return
            # unseeded window: a hedge cannot fire, so don't pay the
            # pool submit + cross-thread wakeup — fetch inline below
        try:
            out = self._fetch_slot(dev, n, slot)
            err = None
        except BaseException as e:
            out, err = None, e
        self._group_lat.add(time.perf_counter() - t0)
        self._retire(group, slot)
        self._fan_out(group, out, err)

    def _resolve_hedged(self, group: List[_Request], n: int, dev,
                        slot: int, t0: float, batched, key,
                        thr: float):
        """First-wins resolve: wait for the primary fetch until the
        group's in-flight age crosses ``thr`` (the quantile-derived
        hedge threshold); past it, re-dispatch the SAME staged batch to
        a second healthy replica and take whichever result lands first.
        Results are bit-exact either way (same serialized executable on
        every replica — the PR 5 pin), so the race is free of output
        tearing by construction.  The loser's slot accounting is
        deferred to :meth:`_drain_losers` (arena-ownership rule)."""
        pool = self._hedge_executor()
        fut_p = pool.submit(self._fetch_slot, dev, n, slot)
        # the latency window learns the PRIMARY's true latency, win or
        # lose — recording the group's first-wins latency would feed
        # the threshold its own output (hedged groups resolve at the
        # fast replica's speed, the quantile sinks toward it, and a
        # persistent straggler ends up hedged on nearly every dispatch
        # instead of only at the tail)
        fut_p.add_done_callback(
            lambda _f, _t0=t0: self._group_lat.add(
                time.perf_counter() - _t0))
        fut_h = None
        alt = None
        remaining = (t0 + thr) - time.perf_counter()
        done, _ = _futures_wait([fut_p], timeout=max(remaining, 0.0))
        if not done:
            alt = self._hedge_target(slot)
            if alt is None:
                # <2 eligible replicas: hedging must no-op (there
                # is nowhere independent to win on)
                self._hedges["skipped_no_replica"] += 1
            else:
                try:
                    dev2 = self._rs.dispatch(alt, batched, key=key)
                except RuntimeError as e:
                    # a failed hedge never fails the group — the
                    # primary is still in flight and authoritative
                    self._rs.mark_unhealthy(alt, e)
                    alt = None
                else:
                    self._hedges["fired"] += 1
                    # hedge work is real load: the schedulers
                    # (least-outstanding-work + _hedge_target) must
                    # see it in flight, and operators must see it in
                    # the per-replica dispatch counters
                    self._slot_inflight[alt.index] += 1
                    bucket = _rows(batched)
                    with self._cache._lock:
                        alt.dispatches += 1
                        alt.bucket_dispatches[bucket] = \
                            alt.bucket_dispatches.get(bucket, 0) + 1
                    fut_h = pool.submit(self._fetch_hedge, dev2, n,
                                        alt.index)
        winner, loser = fut_p, None
        if fut_h is not None:
            done, _ = _futures_wait([fut_p, fut_h],
                                    return_when=FIRST_COMPLETED)
            winner = fut_p if fut_p in done else fut_h
            loser = fut_h if winner is fut_p else fut_p
        try:
            out = winner.result()
            err = None
        except BaseException as e:
            if loser is not None:
                # the winner crashed first — the other dispatch may
                # still deliver the group.  Bounded wait: a WEDGED
                # loser (the very failure hedging routes around) must
                # not stall the dispatcher forever on .result()
                _futures_wait([loser], timeout=self._WEDGE_TIMEOUT_S)
                if loser.done():
                    try:
                        out, err = loser.result(), None
                    except BaseException as e2:
                        out, err = None, e2
                    # the other future actually delivered (or crashed
                    # last): IT is the winner for outcome attribution,
                    # and nothing is left in flight to track
                    winner, loser = loser, None
                else:
                    # both dispatches failed the group: the crash is
                    # the answer.  The wedged fetch stays the tracked
                    # loser (pending-loser path below), holding its
                    # slot so the aliased buffer is never rewritten —
                    # and its replica goes red NOW (the budget already
                    # elapsed; don't wait for a forced drain to notice)
                    out, err = None, e
                    idx = alt.index if loser is fut_h else slot
                    self._wedged_reported.add(id(loser))
                    self._rs.mark_unhealthy(
                        self._rs.replicas[idx],
                        RuntimeError(
                            f"hedge fetch wedged for more than "
                            f"{self._WEDGE_TIMEOUT_S:g}s"))
            else:
                out, err = None, e
        if fut_h is not None and err is None:
            # outcome recorded AFTER the result was actually delivered
            # — a hedge that completed first by CRASHING must not count
            # as (or trace as) a win the primary then served
            outcome = ("primary_won" if winner is fut_p
                       else "hedge_won")
            self._hedges[outcome] += 1
            for r in group:
                if r.span is not None:
                    r.span.event("hedge", outcome=outcome,
                                 primary_slot=slot,
                                 hedge_replica=alt.index)
        self._inflight_n -= len(group)
        alt_released = fut_h is None  # no hedge → nothing to release
        if loser is not None and not loser.done():
            # slot stays owned until the losing execute returns — its
            # zero-copy upload still aliases this slot's buffer
            pend_alt = None
            if loser is fut_h:
                pend_alt = alt.index  # _drain_losers releases it
                alt_released = True
            self._pending_losers.append((slot, loser, pend_alt))
        else:
            if loser is not None:
                self._swallow_loser(loser)
            if 0 <= slot < len(self._slot_inflight):
                self._slot_inflight[slot] -= 1
        if not alt_released:
            # the hedge future has fully resolved (it won, or was
            # consumed): its replica's in-flight count releases now
            self._slot_inflight[alt.index] -= 1
        self._done(len(group))
        self._fan_out(group, out, err)

    def _retire(self, group: List[_Request], slot: int):
        """Un-count a resolved group (live count, slot, outstanding).
        Runs BEFORE waking callers, so their resubmissions aren't
        double-counted against the next gather's early-dispatch
        check."""
        self._inflight_n -= len(group)
        if 0 <= slot < len(self._slot_inflight):
            self._slot_inflight[slot] -= 1
        self._done(len(group))

    def _fan_out(self, group: List[_Request], out, err):
        """Fan a fetched group's rows (or its error) onto each caller's
        future and release the device-concurrency slot."""
        try:
            if err is None:
                off = 0
                for r in group:
                    if r.span is not None:
                        r.span.phase_start("depad")
                    rows = _slice_rows(out, off, off + r.n)
                    if r.span is not None:
                        # close depad BEFORE waking the caller so the
                        # future-wake slack reads as span tail, not as
                        # an inflated depad
                        r.span.phase_end()
                    if not r.future.done():  # close() may have raced us
                        r.future.set_result(rows)
                    off += r.n
            else:
                for r in group:
                    if r.span is not None:
                        r.span.phase_end()
                    if not r.future.done():
                        r.future.set_exception(err)
        finally:
            if self._sem is not None:
                self._sem.release()

    def _loop(self):
        try:
            self._loop_inner()
        except BaseException as e:  # crash net: never strand a caller
            # mark closed BEFORE draining so a submit racing this drain
            # either sees closed (and raises) or enqueues before the
            # drain starts (and is flushed here).  acquire with a
            # timeout: a submitter blocked on a full queue holds
            # _submit_lock and would never release it once we're dead —
            # submit()'s own post-put aliveness check covers that case.
            got = self._submit_lock.acquire(timeout=1.0)
            self._closed = True
            self._crashed = True  # before the flush — see submit()
            if got:
                self._submit_lock.release()
            self._flush_queue(e)
            # dispatched-but-unresolved groups die with us too: fail
            # their callers and return their device-concurrency slots
            # (a leaked slot would wedge the solo fallback path)
            while self._inflight:
                group = self._inflight.popleft()[0]
                self._done(len(group))
                for r in group:
                    if not r.future.done():
                        r.future.set_exception(e)
                if self._sem is not None:
                    self._sem.release()
            raise
        finally:
            # the dispatcher owns the hedge pool; once it exits no
            # buffer is ever staged again, so in-flight loser fetches
            # may finish unobserved (wait=False keeps a wedged fetch
            # from hanging shutdown)
            if self._hedge_pool is not None:
                self._hedge_pool.shutdown(wait=False)

    def _loop_inner(self):
        # instance-held so the crash net can fail dispatched groups
        inflight = self._inflight
        shutdown = False
        while True:
            if self._pending_losers:
                # retire finished hedge losers first: each one done
                # releases a slot (arena-ownership rule)
                self._drain_losers()
            if self._rs is not None:
                # due unhealthy replicas get their recovery probe (one
                # int compare when everything is green)
                self._rs.maybe_reprobe()
            group: List[_Request] = []
            if not shutdown:
                if inflight and self._carry is None and self._q.empty():
                    # nothing to gather and dispatches in flight: every
                    # closed-loop caller is blocked on a future — fetch
                    # and fan the oldest out NOW so they can resubmit,
                    # instead of grace-waiting on a queue that cannot fill
                    self._resolve(inflight.popleft())
                # gathering overlaps the in-flight groups' device
                # compute.  Single-device: any in-flight group means no
                # urgency; device-parallel: urgency ends only once every
                # replica's slot is occupied.
                busy = (bool(inflight) if self._rs is None
                        else len(inflight) >= self._capacity())
                group, shutdown = self._gather(
                    block=not inflight, pipeline_busy=busy)
            elif self._carry is not None:
                # a mismatched rider was pulled before the shutdown
                # sentinel — it still must be served
                group, _ = self._gather(block=False)
            if group:
                # arena-reuse safety: never stage while every eligible
                # slot is at its in-flight cap — resolve FIFO (or wait
                # out a hedge loser) until one frees (also how an
                # unhealthy replica's stragglers get delivered before
                # traffic re-routes around it)
                while not self._has_free_capacity():
                    if inflight:
                        self._resolve(inflight.popleft())
                    elif self._pending_losers:
                        self._drain_losers(block=True)
                    else:
                        break  # counts only come from the two above
                disp = self._dispatch_group(group, inflight)
                if disp is not None:
                    inflight.append(disp)
            # fetch the oldest group when the pipeline is full, or when
            # there was nothing to gather (its callers are waiting and
            # no new work arrived to overlap with)
            if inflight and (not group
                             or len(inflight) >= self._capacity()):
                self._resolve(inflight.popleft())
            if shutdown and not inflight and self._carry is None:
                while self._pending_losers:
                    if not self._drain_losers(block=True):
                        # the wedge budget elapsed with zero progress:
                        # abandoning the wedged fetches beats hanging
                        # shutdown forever — no buffer is ever staged
                        # again after return, and the hedge pool shuts
                        # down wait=False
                        _slog.info("shutdown_abandons_wedged_losers",
                                   n=len(self._pending_losers))
                        break
                return
