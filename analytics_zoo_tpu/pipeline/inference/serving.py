"""Serving fast path: shape-bucketed executables + request coalescing.

Two measured walls motivate this module (PERF_NOTES):

* **Compile-per-shape.** A jitted forward re-traces for every distinct
  batch size, so a live request stream with ragged batch sizes compiles
  continuously.  ``BucketedExecutableCache`` pads every batch up to a
  small geometric ladder of batch sizes (1, 2, 4, … max_batch by
  default) so the whole stream is served by a handful of pre-compilable
  executables, with per-bucket hit/miss/compile-time counters and an
  AOT ``warmup``.
* **Per-dispatch floor.** A dispatched computation has a ~4-8 ms floor
  (PERF_NOTES §"Per-dispatch floor"), so one device call per request
  caps throughput regardless of model size.  ``RequestCoalescer`` packs
  concurrent ``predict()`` callers into ONE padded device batch per
  dispatch and fans the rows back out — amortizing the floor across
  every rider.

Padding safety: rows are independent under inference-mode forward
passes (BatchNorm uses running stats, softmax is row-wise), so padded
filler rows cannot perturb real rows and un-padded results are
bit-identical to a solo run.  Computations with BATCH-GLOBAL terms —
int8 dynamic activation scales — are NOT row-independent; callers must
keep those on the exact-shape path (``InferenceModel`` does).
"""

from __future__ import annotations

import collections
import contextlib
import queue
import threading
import time
from concurrent.futures import Future
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax

from ...common.utils import pad_leading as _pad_rows
from ...observability import profile as _profile
from ...observability import trace as _trace


def bucket_ladder(max_batch: int, growth: float = 2.0,
                  min_batch: int = 1) -> Tuple[int, ...]:
    """The geometric ladder of padded batch sizes: ``min_batch`` scaled
    by ``growth`` until ``max_batch`` (always included)."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if growth <= 1.0:
        raise ValueError(f"bucket growth must be > 1, got {growth}")
    out: List[int] = []
    b = float(max(1, min_batch))
    while int(b) < max_batch:
        if not out or int(b) != out[-1]:
            out.append(int(b))
        b *= growth
    out.append(int(max_batch))
    return tuple(out)


def _rows(batched) -> int:
    first = batched[0] if isinstance(batched, (tuple, list)) else batched
    return int(np.asarray(first).shape[0])


def _slice_rows(tree, start: int, stop: int):
    return jax.tree_util.tree_map(lambda a: a[start:stop], tree)


def _concat_trees(trees: Sequence):
    """Concatenate result trees (arrays or tuples of arrays) row-wise."""
    if len(trees) == 1:
        return trees[0]
    first = trees[0]
    if isinstance(first, (tuple, list)):
        return type(first)(
            np.concatenate([t[i] for t in trees])
            for i in range(len(first)))
    return np.concatenate(trees)


def batch_signature(batched) -> Tuple:
    """Everything but the batch row count: per-input trailing shape +
    dtype.  Two batches coalesce / share a bucket executable iff their
    signatures match."""
    def one(a):
        a = np.asarray(a)
        return (tuple(a.shape[1:]), str(a.dtype))

    if isinstance(batched, (tuple, list)):
        return tuple(one(a) for a in batched)
    return (one(batched),)


class BucketStats:
    """Per-bucket serving counters (thread-safe snapshots via dict copy)."""

    def __init__(self):
        self.hits: Dict[int, int] = {}
        self.misses: Dict[int, int] = {}
        self.compile_time_s: Dict[int, float] = {}

    def snapshot(self) -> Dict[str, Dict[int, Any]]:
        return {"hits": dict(self.hits), "misses": dict(self.misses),
                "compile_time_s": dict(self.compile_time_s)}


class BucketedExecutableCache:
    """Pad batches to a bucket ladder so a ragged request stream hits a
    handful of compiled executables.

    ``fn`` is the (jitted underneath) forward over one host batch; the
    jit's own shape cache holds the executables — this layer guarantees
    only ladder shapes ever reach it, tracks hit/miss/compile-time per
    bucket, and un-pads results.  Batches larger than the top bucket are
    served in top-bucket chunks (the tail padded), so arbitrarily large
    inputs still hit only ladder shapes.
    """

    def __init__(self, fn: Callable, max_batch: int = 32,
                 buckets: Optional[Sequence[int]] = None,
                 growth: float = 2.0):
        self._fn = fn
        self.buckets = (tuple(sorted(set(int(b) for b in buckets)))
                        if buckets else bucket_ladder(max_batch, growth))
        if self.buckets[0] < 1:
            raise ValueError(f"buckets must be >= 1, got {self.buckets}")
        self.max_batch = self.buckets[-1]
        self.stats = BucketStats()
        self._seen: set = set()
        self._lock = threading.Lock()

    def bucket_for(self, n: int) -> int:
        """Smallest ladder bucket >= n (top bucket for oversized n)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.max_batch

    def _dispatch(self, batched, bucket: int, spans: Sequence = ()):
        """Run one exactly-bucket-sized padded batch, with counters.
        ``spans`` are the riders' trace spans: each gets the
        ``device_put`` -> ``execute`` phase transitions and its padded
        bucket as a label (``execute`` stays open — it ends when the
        owner starts ``depad`` after the fetch)."""
        sig = (bucket, batch_signature(batched))
        with self._lock:
            fresh = sig not in self._seen
            if fresh:
                self._seen.add(sig)
                self.stats.misses[bucket] = \
                    self.stats.misses.get(bucket, 0) + 1
            else:
                self.stats.hits[bucket] = self.stats.hits.get(bucket, 0) + 1
        for s in spans:
            s.set_label("bucket", bucket)
            s.phase_start("device_put")
        # explicit upload: handing numpy straight to the jit is an
        # IMPLICIT host->device transfer per dispatch — same bytes
        # moved, but invisible to jax's transfer guards.  device_put
        # keeps the hot loop clean under zoolint.sanitize() (and on a
        # real TPU makes the per-dispatch upload an auditable event).
        batched = jax.device_put(batched)
        _profile.note_transfer("h2d")
        for s in spans:
            s.phase_start("execute")
        if fresh:
            t0 = time.perf_counter()
            # the dispatcher thread has no contextvar span, so the XLA
            # profile hook would drop this compile's span event;
            # activating the group's lead span here (cold path only)
            # keeps the docstring promise that an unwarmed shape shows
            # up IN the request's trace
            with _trace.activate(spans[0] if spans else None):
                out = jax.block_until_ready(self._fn(batched))
            with self._lock:
                self.stats.compile_time_s[bucket] = \
                    self.stats.compile_time_s.get(bucket, 0.0) \
                    + (time.perf_counter() - t0)
            return out
        return self._fn(batched)

    def run(self, batched, sem: Optional[threading.Semaphore] = None,
            span=None):
        """Serve one host batch of any row count; returns HOST numpy
        results with padding rows removed.  ``sem`` (the owner's
        device-concurrency bound) is held around the DISPATCH only —
        the blocking host fetch happens outside it, so concurrent
        callers' dispatches overlap each other's result transfers.
        ``span`` (the request's trace span, if tracing) records the
        pad/device_put/execute/depad phases — once per chunk for
        oversized batches."""
        guard = sem if sem is not None else contextlib.nullcontext()
        spans = (span,) if span is not None else ()
        n = _rows(batched)
        if n == 0:
            # run the smallest bucket and keep zero rows — the output
            # structure/shape contract stays intact for empty inputs
            with guard:
                out = self._dispatch(_pad_rows(batched, self.buckets[0]),
                                     self.buckets[0], spans)
            return fetch_rows(out, 0, span=span)
        outs = []
        start = 0
        while start < n:
            take = min(self.max_batch, n - start)
            chunk = _slice_rows(batched, start, start + take) \
                if (start or take < n) else batched
            bucket = self.bucket_for(take)
            if span is not None:
                span.phase_start("pad")
            padded = _pad_rows(chunk, bucket - take)
            with guard:
                out = self._dispatch(padded, bucket, spans)
            outs.append(fetch_rows(out, take, span=span))
            start += take
        return _concat_trees(outs)

    def dispatch_padded(self, batched, spans: Sequence = ()):
        """Async single dispatch: pad to the bucket and return the
        DEVICE result tree without fetching.  jax dispatch is
        asynchronous, so the caller can overlap host work (gathering
        the next batch) with this compute and fetch later via
        ``fetch_rows``.  One bucket only — rows must fit ``max_batch``."""
        n = _rows(batched)
        if n > self.max_batch:
            raise ValueError(
                f"dispatch_padded: {n} rows exceed the top bucket "
                f"{self.max_batch}; use run() for chunked serving")
        bucket = self.bucket_for(max(n, 1))
        for s in spans:
            s.phase_start("pad")
        return self._dispatch(_pad_rows(batched, bucket - n), bucket,
                              spans)

    def warmup(self, sample_shapes, dtypes=None,
               buckets: Optional[Sequence[int]] = None) -> float:
        """AOT-compile the ladder for one input signature.

        ``sample_shapes``: per-sample shape (no batch axis) for a
        single-input model, or a list of them for multi-input;
        ``dtypes`` matches element-wise (default float32).  Returns the
        total compile wall seconds spent."""
        multi = (sample_shapes and
                 isinstance(sample_shapes[0], (tuple, list)))
        shapes = list(sample_shapes) if multi else [sample_shapes]
        if dtypes is None:
            dts = [np.float32] * len(shapes)
        elif isinstance(dtypes, (tuple, list)):
            dts = list(dtypes)
        else:
            dts = [dtypes] * len(shapes)
        t0 = time.perf_counter()
        for b in (buckets or self.buckets):
            arrs = tuple(np.zeros((b,) + tuple(s), dt)
                         for s, dt in zip(shapes, dts))
            self._dispatch(arrs if multi else arrs[0], b)
        return time.perf_counter() - t0


def fetch_rows(device_tree, n: int, span=None):
    """Block on a ``dispatch_padded`` result and strip the padding.
    With a ``span`` the blocking fetch closes the open ``execute``
    phase (``depad`` starts once the bytes are on the host)."""
    host = jax.tree_util.tree_map(
        lambda a: np.asarray(jax.device_get(a)), device_tree)
    _profile.note_transfer("d2h")
    if span is not None:
        span.phase_start("depad")
    out = _slice_rows(host, 0, n)
    if span is not None:
        span.phase_end()
    return out


class _Request:
    # ``span`` is the EXPLICIT cross-thread trace handoff: contextvars
    # do not propagate into the dispatcher thread (started long before
    # this request existed), so the pending request carries its span
    # and the dispatcher records phases on it directly.
    __slots__ = ("batched", "n", "sig", "future", "span")

    def __init__(self, batched, n, sig, span=None):
        self.batched = batched
        self.n = n
        self.sig = sig
        self.span = span
        self.future: Future = Future()


_SHUTDOWN = object()


class CoalescerClosedError(RuntimeError):
    """The dispatcher is gone — this request was (or would be) never
    served.  Distinct type so callers can fall back to the solo path
    without masking genuine model-execution errors (XlaRuntimeError is
    a RuntimeError subclass)."""


class RequestCoalescer:
    """Pack concurrent predict() calls into one device dispatch, with
    the NEXT batch gathered while the current one computes.

    Callers ``submit()`` into a bounded queue; a single dispatcher
    thread takes the head request, gathers same-signature riders until
    ``max_batch`` rows are packed, ``max_wait_ms`` elapses, or the
    queue momentarily drains, concatenates them into one padded batch,
    and dispatches it through the bucketed ``cache`` WITHOUT fetching —
    jax dispatch is asynchronous, so the dispatcher goes straight back
    to gathering the next group while the device computes, then fetches
    and fans rows back onto each caller's Future (one-deep pipeline:
    the serving-side analog of the data path's double-buffered
    prefetch).  A signature mismatch ends a group — the odd request
    leads the next one, so mixed streams stay correct, just un-packed
    across shapes.

    ``semaphore`` (the owner's ``supported_concurrent_num`` bound) is
    held from dispatch to fetch so coalesced work counts against the
    same device-concurrency budget as solo calls.
    """

    def __init__(self, cache: BucketedExecutableCache,
                 max_batch: Optional[int] = None,
                 max_wait_ms: float = 2.0,
                 semaphore: Optional[threading.Semaphore] = None,
                 pipeline_depth: int = 2,
                 queue_size: int = 1024):
        self._cache = cache
        self.max_batch = int(max_batch or cache.max_batch)
        self.max_wait_ms = float(max_wait_ms)
        self._sem = semaphore
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._q: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._carry: Optional[_Request] = None
        self.dispatches = 0
        self.coalesced_requests = 0
        # live-request accounting: _outstanding counts submitted-but-
        # unresolved requests; _inflight_n the subset already dispatched.
        # Their difference is every rider that could still arrive — once
        # a group holds them all, waiting any longer is pure latency.
        self._outstanding = 0
        self._out_lock = threading.Lock()
        self._inflight_n = 0
        self._closed = False
        # makes (closed-check + enqueue) atomic against close()'s
        # (set-closed + sentinel + drain): a submit can never slip into
        # the queue after the drain.  Separate from _out_lock — a put
        # blocking on a full queue must not deadlock the dispatcher's
        # _done() accounting.
        self._submit_lock = threading.Lock()
        self._flush_lock = threading.Lock()
        self._crashed = False
        self._inflight: "collections.deque" = collections.deque()
        self._thread = threading.Thread(
            target=self._loop, name="zoo-serving-dispatch", daemon=True)
        self._thread.start()

    @property
    def closed(self) -> bool:
        """True once close() ran or the dispatcher died — submits would
        never be served."""
        return self._closed or not self._thread.is_alive()

    @property
    def pending(self) -> int:
        """Submitted-but-unresolved request count (queued + in flight)."""
        with self._out_lock:
            return self._outstanding

    def submit(self, batched, span=None) -> Future:
        n = _rows(batched)
        if n > self.max_batch:
            raise ValueError(
                f"coalesced request of {n} rows exceeds max_batch "
                f"{self.max_batch} — send it through the solo path")
        if span is not None:
            # open here, on the caller's thread: coalesce_wait covers
            # queue time + group gathering, ending when the dispatcher
            # starts the group's pad phase
            span.phase_start("coalesce_wait")
        req = _Request(batched, n, batch_signature(batched), span)
        with self._submit_lock:
            if self.closed:
                raise CoalescerClosedError(
                    "RequestCoalescer is closed — no dispatcher is "
                    "serving this queue")
            with self._out_lock:
                self._outstanding += 1
            self._q.put(req)
        if self._crashed or not self._thread.is_alive():
            # the dispatcher died between the aliveness check and the
            # enqueue — its crash-net drain may already have run, so
            # nobody would ever serve (or fail) this request.  Flush it
            # (and anything else stranded) ourselves.  ``_crashed`` is
            # set BEFORE the crash net's flush, so even a put that was
            # blocked on a full queue (and only completed because that
            # flush freed a slot, while the crashing thread still reads
            # as alive) observes it here.
            self._flush_queue(CoalescerClosedError(
                "RequestCoalescer dispatcher died"))
        return req.future

    def _done(self, k: int):
        with self._out_lock:
            self._outstanding -= k

    def _flush_queue(self, exc: BaseException):
        """Fail every queued (never-dispatched) request with ``exc``.
        Only safe once no dispatcher owns the queue: closed-and-joined,
        crashed, or from the crash net itself.  ``_flush_lock``
        serializes the crash net against a concurrent submit-side flush
        (both may race to fail the same carry)."""
        with self._flush_lock:
            leftovers, self._carry = (
                [self._carry] if self._carry is not None else []), None
            try:
                while True:
                    r = self._q.get_nowait()
                    if r is not _SHUTDOWN:
                        leftovers.append(r)
            except queue.Empty:
                pass
            # flushed requests leave the live count too — ``pending``
            # must not report phantom requests on a dead coalescer
            self._done(len(leftovers))
            for r in leftovers:
                if not r.future.done():
                    r.future.set_exception(exc)

    def close(self, timeout: float = 5.0):
        """Stop the dispatcher: already-queued requests are SERVED (the
        shutdown sentinel sits behind them in the queue — this is the
        graceful drain reload()/the registry rely on), then anything
        racing the shutdown fails with CoalescerClosedError
        (idempotent)."""
        with self._submit_lock:
            already = self._closed
            self._closed = True
            if not already and self._thread.is_alive():
                self._q.put(_SHUTDOWN)
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
        if self._thread.is_alive():
            # the dispatcher is wedged mid-group (e.g. a long compile) —
            # it still owns _carry and the queue, so leave both alone;
            # it will drain to the sentinel and exit on its own
            return
        self._flush_queue(CoalescerClosedError("RequestCoalescer closed"))

    # ---- dispatcher ----
    def _gather(self, block: bool,
                pipeline_busy: bool = False) -> Tuple[List[_Request], bool]:
        """One group: head + same-signature riders until the batch is
        full, the wait budget lapses, or the queue momentarily drains.
        The drain condition is the important one: callers are blocked
        on their futures, so once the queue is empty, holding a partial
        batch for the rest of ``max_wait_ms`` cannot attract closed-loop
        riders — it only adds their wait to every row.  A short grace
        (max_wait/8) still absorbs staggered arrivals.  Returns
        (group, shutdown_seen); with ``block`` False the head wait is
        bounded by the grace too (a dispatch is in flight — the
        dispatcher must come back to fetch it promptly)."""
        grace = max(min(self.max_wait_ms / 8.0, 0.5), 0.05) / 1000.0
        head = self._carry
        self._carry = None
        if head is None:
            try:
                head = (self._q.get() if block
                        else self._q.get(timeout=grace))
            except queue.Empty:
                return [], False
            if head is _SHUTDOWN:
                return [], True
        group, count, rows = [head], 1, head.n
        deadline = time.perf_counter() + self.max_wait_ms / 1000.0
        while rows < self.max_batch:
            # every live request not yet dispatched is either in this
            # group or could still ride it; once the group holds them
            # all, no grace wait can attract another — dispatch now.
            # Only when the device is idle, though: with a dispatch in
            # flight there is no urgency, and the about-to-resolve
            # riders will want seats on THIS group
            if not pipeline_busy \
                    and count >= self._outstanding - self._inflight_n:
                break
            remaining = deadline - time.perf_counter()
            try:
                nxt = (self._q.get_nowait() if remaining <= 0
                       else self._q.get(timeout=min(remaining, grace)))
            except queue.Empty:
                break
            if nxt is _SHUTDOWN:
                return group, True
            if nxt.sig != head.sig or rows + nxt.n > self.max_batch:
                self._carry = nxt
                break
            group.append(nxt)
            count += 1
            rows += nxt.n
        return group, False

    def _acquire_slot(self, inflight):
        """Take one device-concurrency slot without deadlocking: the
        dispatcher itself may hold every slot via unfetched dispatches,
        so on contention it resolves its oldest in-flight group (which
        releases a slot) before blocking."""
        if self._sem is None:
            return
        while not self._sem.acquire(blocking=False):
            if inflight:
                self._resolve(*inflight.popleft())
            else:
                self._sem.acquire()  # held by solo callers — just wait
                return

    def _dispatch_group(self, group: List[_Request], inflight):
        """Concat + async dispatch; returns (group, rows, device_out)
        or None when the dispatch itself failed."""
        try:
            spans = tuple(r.span for r in group if r.span is not None)
            for s in spans:
                s.phase_start("pad")  # ends coalesce_wait; covers concat
            batched = _concat_trees([r.batched for r in group]) \
                if len(group) > 1 else group[0].batched
            n = sum(r.n for r in group)
            self._acquire_slot(inflight)
            try:
                dev = self._cache.dispatch_padded(batched, spans)
            except BaseException:
                if self._sem is not None:
                    self._sem.release()
                raise
            self.dispatches += 1
            self.coalesced_requests += len(group)
            self._inflight_n += len(group)
            return group, n, dev
        except BaseException as e:
            self._done(len(group))
            for r in group:
                if not r.future.done():
                    r.future.set_exception(e)
            return None

    def _resolve(self, group: List[_Request], n: int, dev):
        """Fetch a dispatched group's device result and fan rows out."""
        try:
            out = fetch_rows(dev, n)
            err = None
        except BaseException as e:
            out, err = None, e
        # retire the group from the live count BEFORE waking callers, so
        # their resubmissions aren't double-counted against the next
        # gather's early-dispatch check
        self._inflight_n -= len(group)
        self._done(len(group))
        try:
            if err is None:
                off = 0
                for r in group:
                    if r.span is not None:
                        r.span.phase_start("depad")
                    rows = _slice_rows(out, off, off + r.n)
                    if r.span is not None:
                        # close depad BEFORE waking the caller so the
                        # future-wake slack reads as span tail, not as
                        # an inflated depad
                        r.span.phase_end()
                    if not r.future.done():  # close() may have raced us
                        r.future.set_result(rows)
                    off += r.n
            else:
                for r in group:
                    if r.span is not None:
                        r.span.phase_end()
                    if not r.future.done():
                        r.future.set_exception(err)
        finally:
            if self._sem is not None:
                self._sem.release()

    def _loop(self):
        try:
            self._loop_inner()
        except BaseException as e:  # crash net: never strand a caller
            # mark closed BEFORE draining so a submit racing this drain
            # either sees closed (and raises) or enqueues before the
            # drain starts (and is flushed here).  acquire with a
            # timeout: a submitter blocked on a full queue holds
            # _submit_lock and would never release it once we're dead —
            # submit()'s own post-put aliveness check covers that case.
            got = self._submit_lock.acquire(timeout=1.0)
            self._closed = True
            self._crashed = True  # before the flush — see submit()
            if got:
                self._submit_lock.release()
            self._flush_queue(e)
            # dispatched-but-unresolved groups die with us too: fail
            # their callers and return their device-concurrency slots
            # (a leaked slot would wedge the solo fallback path)
            while self._inflight:
                group, _, _ = self._inflight.popleft()
                self._done(len(group))
                for r in group:
                    if not r.future.done():
                        r.future.set_exception(e)
                if self._sem is not None:
                    self._sem.release()
            raise

    def _loop_inner(self):
        # instance-held so the crash net can fail dispatched groups
        inflight = self._inflight
        shutdown = False
        while True:
            group: List[_Request] = []
            if not shutdown:
                if inflight and self._carry is None and self._q.empty():
                    # nothing to gather and dispatches in flight: every
                    # closed-loop caller is blocked on a future — fetch
                    # and fan the oldest out NOW so they can resubmit,
                    # instead of grace-waiting on a queue that cannot fill
                    self._resolve(*inflight.popleft())
                # gathering overlaps the in-flight groups' device compute
                group, shutdown = self._gather(
                    block=not inflight, pipeline_busy=bool(inflight))
            elif self._carry is not None:
                # a mismatched rider was pulled before the shutdown
                # sentinel — it still must be served
                group, _ = self._gather(block=False)
            if group:
                disp = self._dispatch_group(group, inflight)
                if disp is not None:
                    inflight.append(disp)
            # fetch the oldest group when the pipeline is full, or when
            # there was nothing to gather (its callers are waiting and
            # no new work arrived to overlap with)
            if inflight and (not group
                             or len(inflight) >= self.pipeline_depth):
                self._resolve(*inflight.popleft())
            if shutdown and not inflight and self._carry is None:
                return
