from .inference_model import InferenceModel, AbstractInferenceModel, JTensor
