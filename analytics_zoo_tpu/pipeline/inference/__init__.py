from .decode import DecodeEngine, DecodeEngineClosedError, TokenStream
from .inference_model import InferenceModel, AbstractInferenceModel, JTensor
from .serving import (BucketedExecutableCache, CoalescerClosedError,
                      Replica, ReplicaSet, RequestCoalescer, bucket_ladder)
