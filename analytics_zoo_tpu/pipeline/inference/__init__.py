from .inference_model import InferenceModel, AbstractInferenceModel, JTensor
from .serving import (BucketedExecutableCache, CoalescerClosedError,
                      RequestCoalescer, bucket_ladder)
