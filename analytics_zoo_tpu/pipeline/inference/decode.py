"""Continuous batching for autoregressive decode (ORCA-style).

The serving stack batches fixed-shape one-shot requests; the dominant
LLM workload is token streaming, where requests join and leave the
batch at EVERY decode step.  Naive batch-of-requests decoding makes
every rider pay the longest sequence's latency: a batch finishes when
its slowest member does, and short requests idle in finished rows.

``DecodeEngine`` is the iteration-level alternative:

* **Bucketed prefill.**  Each admitted prompt is right-padded to a
  small geometric ladder of prompt lengths and run through ONE batched
  causal forward (the training-shaped compute), writing its per-layer
  K/V into a free slot of the decode state — one ``admit`` executable
  per (prompt bucket, capacity), compiled once.
* **A single persistent slot-array decode executable.**  The decode
  state is a fixed-capacity slot array — per-layer K/V caches of shape
  ``(capacity, heads, max_len, d_head)`` plus per-slot current token
  and write position — stepped by ONE jitted function whose shapes
  never depend on occupancy.  Attention masks derive from per-slot
  positions, so occupied and free slots coexist in the same dispatch:
  admission and eviction are state writes, never recompiles.  Exactly
  one compile per (bucket, capacity) across a whole serving run — the
  zoolint sanitizer's compile counter pins this at every occupancy.
* **Per-step admission / eviction.**  A dispatcher thread loops:
  drain finished slots (EOS or max tokens), admit queued requests into
  free slots, step once, fan the step's tokens out to per-request
  :class:`TokenStream` futures.  A short request admitted next to a
  long one leaves as soon as ITS tokens are done; the freed slot is
  re-filled on the very next iteration.

Decode math: :mod:`analytics_zoo_tpu.models.generation`'s
``_prefill`` / ``_decode_step`` — the same per-row-position (ragged)
formulation ``TransformerLM.generate`` compiles into its scan, so a
slot stepped one token at a time is pinned token-identical to the
scan path (tests/test_serving_decode.py).  Greedy only: iteration-level
scheduling interleaves unrelated requests in one dispatch, and greedy
argmax is the one sampling mode whose per-slot stream provably cannot
depend on its neighbors.

Data movement is explicit (``device_put`` in, ``device_get`` out) so
the whole loop runs clean under ``zoolint.sanitize()`` transfer
guards; the decode state itself never leaves the device — the per-step
host traffic is one (capacity,) token fetch.
"""

from __future__ import annotations

import collections
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ...models.generation import (_decode_step, _embed_token,
                                  _head_logits, _prefill)
from ...observability import profile as _profile
from ...observability.log import get_logger as _get_logger
from .serving import _execstore, bucket_ladder

_slog = _get_logger("zoo.serving.decode")


class DecodeEngineClosedError(RuntimeError):
    """The decode dispatcher is gone — this request was (or would be)
    never served."""


class TokenStream:
    """Per-request streaming handle: tokens arrive one decode step at a
    time; iterate for streaming, or :meth:`result` for the full
    continuation.

    Thread contract: the engine's dispatcher is the only writer; any
    number of consumer threads may iterate / ``result()``.  The
    producer fast path is ONE list append (GIL-atomic) — the condition
    variable is only touched once a consumer actually iterates
    (``_live``), so blocking callers cost the dispatcher nothing per
    token.  This is hot-loop-relevant: at thousands of tokens/s a
    locked queue put per token was ~15% of the engine's wall.
    """

    def __init__(self, request_id: int):
        self.request_id = request_id
        self._tokens: List[int] = []
        self._error: Optional[BaseException] = None
        self._finished = threading.Event()
        self._live = False  # a consumer is iterating — notify pushes
        self._cond = threading.Condition()

    # ---- producer side (dispatcher thread only) ----
    def _push(self, tok: int):
        self._tokens.append(tok)
        if self._live:
            with self._cond:
                self._cond.notify_all()

    def _finish(self, error: Optional[BaseException] = None):
        self._error = error
        self._finished.set()
        if self._live:
            with self._cond:
                self._cond.notify_all()

    # ---- consumer side ----
    @property
    def done(self) -> bool:
        return self._finished.is_set()

    def __iter__(self):
        self._live = True
        i = 0
        while True:
            # catch up lock-free (append-only list, single writer)
            while i < len(self._tokens):
                yield int(self._tokens[i])
                i += 1
            if self._finished.is_set():
                if i < len(self._tokens):
                    continue  # tokens landed after the done flag
                if self._error is not None:
                    raise self._error
                return
            with self._cond:
                if i >= len(self._tokens) \
                        and not self._finished.is_set():
                    # bounded wait: _live may have been observed False
                    # by a push racing this first iteration
                    self._cond.wait(0.05)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request finishes; returns the generated
        continuation as a 1-D int32 array (EOS included when hit)."""
        if not self._finished.wait(timeout=timeout):
            raise TimeoutError(
                f"decode request {self.request_id} still streaming "
                f"after {timeout}s")
        if self._error is not None:
            raise self._error
        return np.asarray(self._tokens, np.int32)


class _DecodeRequest:
    # ``span`` is the explicit cross-thread trace handoff (same
    # convention as the coalescer's _Request): the dispatcher records
    # prefill/decode_step phases on it directly.
    # ``scheduled`` counts tokens covered by dispatched (possibly not
    # yet processed) steps — the pipelined loop plans fused windows
    # from it, since ``produced`` lags by the in-flight dispatch.
    __slots__ = ("prompt", "length", "bucket", "max_new", "eos_id",
                 "stream", "span", "produced", "scheduled", "slot")

    def __init__(self, prompt: np.ndarray, length: int, bucket: int,
                 max_new: int, eos_id: Optional[int], stream: TokenStream,
                 span=None):
        self.prompt = prompt
        self.length = length
        self.bucket = bucket
        self.max_new = max_new
        self.eos_id = eos_id
        self.stream = stream
        self.span = span
        self.produced = 0
        self.scheduled = 0
        self.slot = -1


_SHUTDOWN = object()


class DecodeEngine:
    """KV-cache-slotted continuous-batching decode engine (module doc).

    Args:
        params: the TransformerLM param tree (``trainer.state.params``)
            — placed on ``device`` once at construction.
        hyper: the model's hyper dict (``n_layers``/``n_heads``/
            ``d_model``/``max_len``/``moe_every``...).
        capacity: decode slots — the fixed batch width of the
            persistent step executable.
        max_len: per-slot cache length (default the model's
            ``max_len``); every request needs
            ``prompt_len + max_new_tokens <= max_len``.
        prompt_buckets: the prompt-length ladder (default: a geometric
            ladder up to ``max_len - 1``).  One admit executable
            compiles per bucket actually used.
        eos_id: default end-of-sequence token id (per-request
            override via ``submit``); ``None`` decodes to
            ``max_new_tokens`` always.
        max_queue: bound on submitted-but-unadmitted requests.
        step_fuse: fused-window size K — when no admission or
            eviction could land inside the next K steps, they
            dispatch as ONE compiled scan, amortizing per-dispatch
            overhead without giving up iteration-level scheduling
            (1 disables fusion; see ``_choose_fuse``).
        device: jax device for the decode state (default: the first
            local device).
    """

    def __init__(self, params, hyper: Dict[str, Any], capacity: int = 8,
                 max_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 eos_id: Optional[int] = None, max_queue: int = 256,
                 step_fuse: int = 4, device=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.step_fuse = max(1, int(step_fuse))
        self._hyper = dict(hyper)
        self.max_len = int(max_len or hyper["max_len"])
        if self.max_len > int(hyper["max_len"]):
            raise ValueError(
                f"max_len ({self.max_len}) exceeds the model's "
                f"positional table ({hyper['max_len']})")
        if prompt_buckets:
            self.prompt_buckets: Tuple[int, ...] = tuple(
                sorted(set(int(b) for b in prompt_buckets)))
        else:
            top = max(1, self.max_len - 1)
            self.prompt_buckets = bucket_ladder(
                top, growth=2.0, min_batch=min(8, top))
        if self.prompt_buckets[-1] >= self.max_len:
            raise ValueError(
                f"largest prompt bucket ({self.prompt_buckets[-1]}) "
                f"must leave room to decode (max_len {self.max_len})")
        self.eos_id = eos_id
        self._device = device or jax.local_devices()[0]
        self._params = jax.device_put(params, self._device)
        self._n_layers = int(hyper["n_layers"])

        # ---- device state: the persistent slot array.  jnp.zeros
        # builds ON the device (a fill, not a transfer); tok/pos for
        # free slots are don't-cares — their writes land in cache
        # positions a future occupant always overwrites before
        # attending (write-then-attend, see _build_step_fn).
        d_head = int(hyper["d_model"]) // int(hyper["n_heads"])
        shape = (self.capacity, int(hyper["n_heads"]), self.max_len,
                 d_head)
        with jax.default_device(self._device):
            caches = [(jnp.zeros(shape, jnp.float32),
                       jnp.zeros(shape, jnp.float32))
                      for _ in range(self._n_layers)]
            tok = jnp.zeros((self.capacity,), jnp.int32)
            pos = jnp.zeros((self.capacity,), jnp.int32)
        # COMMIT the initial state (device_put of an on-device array is
        # a no-op copy-wise but flips it committed): the live loop's
        # state is always committed — its producers take committed
        # device_put inputs — and the jit cache keys on committedness,
        # so an uncommitted first call would cost every admit plan a
        # SECOND compile the first time it sees steady-state inputs,
        # breaking the one-compile-per-(bucket, capacity) invariant
        self._caches = jax.device_put(caches, self._device)
        self._tok = jax.device_put(tok, self._device)
        self._pos = jax.device_put(pos, self._device)

        # one AOT-compiled single-step plan plus a halving ladder of
        # fused window plans (step_fuse, step_fuse/2, ... 2) per
        # engine; one admit plan per prompt bucket — built in
        # warmup() (or lazily at the first unwarmed dispatch), cached,
        # and NEVER rebuilt inside the dispatcher loop (zoolint
        # ZL101), so a serving run compiles exactly once per
        # (bucket, capacity) plan no matter how occupancy moves.
        # Plans are explicit lower()+compile() rather than lazy jit:
        # the AOT split is what lets the persistent executable store
        # answer the compile with a disk load (zero-compile warmup in
        # a process whose store is warm).
        self._fuse_sizes: Tuple[int, ...] = tuple(
            sorted({k for k in (self.step_fuse, self.step_fuse // 2)
                    if k > 1}, reverse=True))
        self._step_fn: Any = None
        self._stepk_fns: Dict[int, Any] = {}
        self._admit_fns: Dict[int, Any] = {}
        # persistent executable store: resolved once; None keeps every
        # store branch inert.  The plans close over the params, so the
        # weights digest rides every plan fingerprint — two engines
        # with different weights can never share a store entry.
        self._store = _execstore().current()
        self._wdigest = (_execstore().params_digest(self._params)
                         if self._store is not None else None)

        # host-side slot bookkeeping (dispatcher-thread-owned)
        self._slots: List[Optional[_DecodeRequest]] = \
            [None] * self.capacity
        self._free: collections.deque = collections.deque(
            range(self.capacity))

        # counters (dispatcher-owned ints; reads copy — GIL-atomic
        # enough for a metrics scrape, same convention as the
        # coalescer's hedge counters)
        self._counters = {"tokens": 0, "steps": 0, "prefills": 0,
                          "admitted": 0, "evicted": 0,
                          "fused_dispatches": 0}
        self._bucket_stats: Dict[str, Dict[int, Any]] = {
            "hits": {}, "misses": {}, "compile_time_s": {}}
        self._occupancy = 0

        self._q: "queue.Queue" = queue.Queue(maxsize=int(max_queue))
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._closed = False
        self._submit_lock = threading.Lock()
        self._crashed = False
        # the dispatcher starts LAZILY (first submit), not here:
        # warmup() runs on the caller thread and rebinds the shared
        # donated state, so a dispatcher stepping concurrently would
        # race it into use-after-donate — deferring the start makes
        # construct -> warmup -> serve safe by construction.  The
        # condition guards only the handshake FLAGS (the decode state
        # itself is single-owner by protocol: warmup's thread before
        # start, the dispatcher after)
        self._started = False
        self._warming = False
        self._start_cond = threading.Condition()
        self._thread = threading.Thread(
            target=self._decode_loop, name="zoo-decode-dispatch",
            daemon=True)

    def _ensure_started(self):
        with self._start_cond:
            while self._warming:  # let an in-flight warmup finish
                self._start_cond.wait()
            if not self._started:
                self._started = True
                self._thread.start()

    # ---- compiled plans -------------------------------------------------
    def _step_body(self, caches, tok, pos):
        """ONE slot-array decode step over ALL ``capacity`` slots —
        the body both step plans trace, so the fused plan is
        bit-identical to K consecutive single steps by construction.
        Free slots compute garbage that is never read: their (clamped)
        position's cache line is rewritten by the step itself before
        it is attended, and admission overwrites ``[0, bucket)``
        wholesale.  Shapes depend on (capacity, max_len) only — never
        occupancy."""
        params, hyper, max_len = self._params, self._hyper, self.max_len
        posc = jnp.minimum(pos, max_len - 1)
        emb = _embed_token(params, tok, posc)
        logits, caches = _decode_step(params, hyper, caches, emb, posc)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return caches, nxt, jnp.minimum(pos + 1, max_len)

    def _state_specs(self):
        """ShapeDtypeStructs matching the persistent decode state —
        the AOT lowering inputs for the step/admit plans (committed to
        the engine's device, exactly like the live state)."""
        s0 = jax.sharding.SingleDeviceSharding(self._device)
        d_head = (int(self._hyper["d_model"])
                  // int(self._hyper["n_heads"]))
        cspec = jax.ShapeDtypeStruct(
            (self.capacity, int(self._hyper["n_heads"]), self.max_len,
             d_head), jnp.float32, sharding=s0)
        ispec = jax.ShapeDtypeStruct((self.capacity,), jnp.int32,
                                     sharding=s0)
        caches = [(cspec, cspec) for _ in range(self._n_layers)]
        return caches, ispec, ispec

    def _plan(self, name: str, jitted, arg_specs):
        """AOT-build one decode plan: lower, consult the persistent
        executable store (read-through), compile + persist on a miss
        (write-behind).  Returns a callable jax-level ``Compiled`` —
        plan calls in the decode loop execute a fixed binary, never
        trace.  The fingerprint covers the lowered HLO text (graph +
        every shape; large closed-over constants may be elided from
        it, which is exactly why the weights digest rides alongside),
        the (capacity, max_len) tuple, and the runtime environment; a
        corrupt or unloadable entry counts ``invalid`` and falls back
        to the compile — never to a wrong executable."""
        lowered = jitted.lower(*arg_specs)
        store = self._store
        fp = None
        if store is not None:
            es = _execstore()
            fp = store.fingerprint(
                "decode-plan", name, es.hlo_digest(lowered),
                self._wdigest, (self.capacity, self.max_len),
                device=self._device)
            ent = store.lookup(fp)
            if ent is not None:
                try:
                    return es.rehydrate(ent.payload)
                except Exception as e:  # noqa: BLE001 — fall back to
                    # the compile below on any rehydration failure
                    store.note_invalid(fp, e)
        compiled = lowered.compile()
        if store is not None:
            try:
                store.put(fp, _execstore().serialize_compiled(compiled),
                          meta={"kind": "decode-plan", "name": name,
                                "capacity": self.capacity,
                                "max_len": self.max_len})
            except Exception as e:  # noqa: BLE001 — persisting is
                # best-effort: serving proceeds on the fresh compile
                _slog.error("decode_plan_store_failed", plan=name,
                            error=f"{type(e).__name__}: {e}")
        return compiled

    def _build_step_plan(self):
        """The persistent single-step plan: (caches, tok, pos) ->
        (caches', tok', pos')."""
        # the caches are DONATED: without donation every step copies
        # the whole (capacity, heads, max_len, d_head) cache array per
        # layer just to update one position — the in-place update the
        # scan path gets for free from its loop carry.  Measured ~40%
        # off the per-step wall on CPU; the loop always rebinds the
        # returned caches, so the invalidated buffers are never
        # touched again.  tok/pos are NOT donated: the pipelined loop
        # still holds the previous step's token vector for its
        # deferred fetch, and donating would invalidate that buffer
        # mid-flight (they are (capacity,) ints — the copy is free).
        return self._plan(
            "step1", jax.jit(self._step_body, donate_argnums=(0,)),
            self._state_specs())

    def _build_stepk_plan(self, k: int):
        """One fused window plan: ``k`` consecutive decode steps as
        ONE dispatch (a compiled ``lax.scan`` over
        :meth:`_step_body`), returning the (k, capacity) token matrix.
        Per-dispatch overhead — the python call, XLA's per-execution
        fixed cost, the host fetch — amortizes across k tokens, which
        is most of the single-step path's deficit against
        ``TransformerLM.generate``'s monolithic scan.  The dispatcher
        picks the window so scheduling NEVER changes inside it (see
        ``_choose_fuse``), so batching stays iteration-level exactly
        when iteration-level matters."""

        def stepk(caches, tok, pos):
            def body(carry, _):
                c, t, p = carry
                c, t, p = self._step_body(c, t, p)
                return (c, t, p), t

            (caches, tok, pos), toks = lax.scan(
                body, (caches, tok, pos), None, length=k)
            return caches, tok, pos, toks  # toks: (k, capacity)

        return self._plan(f"step{k}",
                          jax.jit(stepk, donate_argnums=(0,)),
                          self._state_specs())

    def _ensure_step_plans(self):
        """Build (or store-load) the step plan + the fused-window
        ladder — called from warmup(), or lazily at the first
        dispatch of an unwarmed engine (one ``is None`` check per
        step thereafter)."""
        if self._step_fn is not None:
            return
        for k in self._fuse_sizes:
            self._stepk_fns[k] = self._build_stepk_plan(k)
        self._step_fn = self._build_step_plan()  # set LAST: the flag

    def _build_admit_fn(self, s_b: int):
        """One prompt bucket's admission plan: batched prefill of the
        (1, s_b) padded prompt, first-token head + argmax, and the
        K/V insert into slot ``slot`` of the decode state — all one
        executable, so admitting is a single dispatch."""
        params, hyper = self._params, self._hyper

        def admit(caches, tok, pos, prompt, length, slot):
            x, pc = _prefill(params, hyper, prompt, s_b)
            last = lax.dynamic_index_in_dim(x[0], length - 1,
                                            keepdims=False)
            logits0 = _head_logits(params, last[None, :])[0]
            tok0 = jnp.argmax(logits0, axis=-1).astype(jnp.int32)
            new_caches = []
            for (ck, cv), (pk, pv) in zip(caches, pc):
                ck = lax.dynamic_update_slice(
                    ck, pk.astype(ck.dtype), (slot, 0, 0, 0))
                cv = lax.dynamic_update_slice(
                    cv, pv.astype(cv.dtype), (slot, 0, 0, 0))
                new_caches.append((ck, cv))
            tok = lax.dynamic_update_slice(tok, tok0[None], (slot,))
            pos = lax.dynamic_update_slice(
                pos, length[None].astype(pos.dtype), (slot,))
            return new_caches, tok, pos, tok0

        # caches donated for the same in-place-update reason as the
        # step plan; tok/pos excluded for the same pipeline-aliasing
        # reason (an admission can run while the previous step's token
        # vector still awaits its deferred fetch)
        return jax.jit(admit, donate_argnums=(0,))

    def _admit_fn_for(self, s_b: int):
        fn = self._admit_fns.get(s_b)
        if fn is None:
            caches, tok, pos = self._state_specs()
            s0 = jax.sharding.SingleDeviceSharding(self._device)
            pspec = jax.ShapeDtypeStruct((1, s_b), jnp.int32,
                                         sharding=s0)
            sspec = jax.ShapeDtypeStruct((), jnp.int32, sharding=s0)
            fn = self._admit_fns[s_b] = self._plan(
                f"admit{s_b}", self._build_admit_fn(s_b),
                (caches, tok, pos, pspec, sspec, sspec))
        return fn

    def warmup(self) -> float:
        """AOT-compile every prompt bucket's admit plan plus the step
        plan (deploy pays the compiles, live streams never do).
        Returns wall seconds.  The warmed admissions land in slot 0 of
        the REAL state — harmless: the host free-list is untouched, so
        slot 0 is re-admitted (and its cache overwritten) before any
        live request reads it.  Must run BEFORE the first submit: the
        warms rebind the shared donated state on THIS thread, so a
        live dispatcher would race them into use-after-donate —
        _start_lock makes a concurrent first submit wait here rather
        than start one."""
        t0 = time.perf_counter()
        with self._start_cond:
            if self._started:
                raise RuntimeError(
                    "DecodeEngine.warmup() must run before the first "
                    "submit — the dispatcher owns the decode state "
                    "once it is serving")
            self._warming = True
        try:
            zero = jax.device_put(np.int32(0), self._device)
            one = jax.device_put(np.int32(1), self._device)
            for b in self.prompt_buckets:
                prompt = jax.device_put(np.zeros((1, b), np.int32),
                                        self._device)
                # tb covers the plan BUILD (the AOT compile — or the
                # store load that replaces it) plus one verifying
                # execution; compile_time_s is honest either way
                tb = time.perf_counter()
                fn = self._admit_fn_for(b)
                self._caches, self._tok, self._pos, tok0 = fn(
                    self._caches, self._tok, self._pos, prompt, one,
                    zero)
                jax.device_get(tok0)
                secs = time.perf_counter() - tb
                self._bucket_stats["compile_time_s"][b] = \
                    self._bucket_stats["compile_time_s"].get(b, 0.0) \
                    + secs
                self._bucket_stats["misses"][b] = \
                    self._bucket_stats["misses"].get(b, 0) + 1
                _slog.info("decode_warmup_bucket", bucket=b,
                           compile_ms=round(secs * 1e3, 3))
            self._ensure_step_plans()
            self._caches, self._tok, self._pos = self._step_fn(
                self._caches, self._tok, self._pos)
            jax.device_get(self._tok)
            for fn in self._stepk_fns.values():
                self._caches, self._tok, self._pos, toks = fn(
                    self._caches, self._tok, self._pos)
                jax.device_get(toks)
        finally:
            with self._start_cond:
                self._warming = False
                self._start_cond.notify_all()
        return time.perf_counter() - t0

    # ---- submission -----------------------------------------------------
    @property
    def closed(self) -> bool:
        return (self._closed or self._crashed
                or (self._started and not self._thread.is_alive()))

    def bucket_for(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"prompt of {n} tokens exceeds the largest prompt bucket "
            f"({self.prompt_buckets[-1]})")

    def _validate(self, prompt_ids, max_new_tokens):
        """Shared request validation — raises ValueError, mutates
        nothing: (1-D prompt, length, bucket, max_new).  ``generate``
        pre-validates EVERY row through this before its first submit,
        so a bad late row cannot orphan earlier rows mid-decode."""
        prompt = np.asarray(prompt_ids)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError(
                f"prompt_ids must be a non-empty 1-D id sequence, got "
                f"shape {prompt.shape}")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new}")
        L = int(prompt.shape[0])
        if L + max_new > self.max_len:
            raise ValueError(
                f"prompt ({L}) + max_new_tokens ({max_new}) exceeds "
                f"max_len ({self.max_len})")
        return prompt, L, self.bucket_for(L), max_new

    def submit(self, prompt_ids, max_new_tokens: int,
               eos_id: Optional[int] = None, span=None) -> TokenStream:
        """Queue one prompt for continuous-batching decode; returns its
        :class:`TokenStream` immediately.  ``prompt_ids``: 1-D int ids
        (a (1, L) row is accepted too).  ``eos_id`` overrides the
        engine default; decoding stops at EOS (included in the stream)
        or after ``max_new_tokens``, whichever is first."""
        prompt, L, bucket, max_new = self._validate(prompt_ids,
                                                    max_new_tokens)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :L] = prompt
        with self._id_lock:
            self._next_id += 1
            rid = self._next_id
        stream = TokenStream(rid)
        if span is not None:
            # opened on the caller's thread: covers queue time until
            # the dispatcher starts this request's prefill
            span.phase_start("decode_wait")
        req = _DecodeRequest(padded, L, bucket, max_new,
                             self.eos_id if eos_id is None else eos_id,
                             stream, span)
        with self._submit_lock:
            if self.closed:
                raise DecodeEngineClosedError(
                    "DecodeEngine is closed — no dispatcher is "
                    "serving this queue")
            self._q.put(req)
            # waits out an in-flight warmup — the dispatcher only
            # begins once the warms are done
            self._ensure_started()
        if self._crashed or not self._thread.is_alive():
            # the dispatcher died between the closed check and the
            # enqueue — flush anything stranded (same crash-net race
            # the coalescer's submit covers)
            self._flush_queue(DecodeEngineClosedError(
                "DecodeEngine dispatcher died"))
        return stream

    def generate(self, prompts, max_new_tokens, eos_id=None,
                 timeout: Optional[float] = None,
                 span=None) -> List[np.ndarray]:
        """Blocking convenience over :meth:`submit`: decode a batch of
        prompts (a (B, L) array, or a list of 1-D ragged rows) and
        return each row's generated continuation (1-D int32).
        ``max_new_tokens`` may be per-row (a sequence) or shared.
        ``span`` rides the request when there is exactly one row (a
        span is single-owner; batch rows would interleave phases)."""
        rows = ([np.asarray(prompts[i]) for i in range(len(prompts))]
                if isinstance(prompts, (list, tuple))
                else [r for r in np.asarray(prompts)])
        if np.ndim(max_new_tokens) == 0:
            max_news = [int(max_new_tokens)] * len(rows)
        else:
            max_news = [int(m) for m in max_new_tokens]
            if len(max_news) != len(rows):
                raise ValueError(
                    f"max_new_tokens has {len(max_news)} entries for "
                    f"{len(rows)} prompts")
        # all-or-nothing: validate EVERY row before the first submit,
        # so a bad late row can't leave earlier rows decoding into
        # abandoned streams (burning slots the caller gave up on)
        for r, m in zip(rows, max_news):
            self._validate(r, m)
        streams = [self.submit(r, m, eos_id=eos_id,
                               span=span if len(rows) == 1 else None)
                   for r, m in zip(rows, max_news)]
        return [s.result(timeout=timeout) for s in streams]

    # ---- stats ----------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Point-in-time decode counters (re-exported per model by
        ``InferenceModel.serving_stats`` and the Prometheus bridge)."""
        out = dict(self._counters)
        out.update(capacity=self.capacity,
                   slots_active=self._occupancy,
                   queued=self._q.qsize(),
                   prompt_buckets=self.prompt_buckets,
                   prefill_hits=dict(self._bucket_stats["hits"]),
                   prefill_misses=dict(self._bucket_stats["misses"]),
                   prefill_compile_time_s=dict(
                       self._bucket_stats["compile_time_s"]))
        return out

    # ---- dispatcher -----------------------------------------------------
    def _flush_queue(self, exc: BaseException):
        try:
            while True:
                r = self._q.get_nowait()
                if r is not _SHUTDOWN:
                    if r.span is not None:
                        r.span.phase_end()
                    r.stream._finish(exc)
        except queue.Empty:
            pass

    def close(self, timeout: float = 5.0):
        """Stop the dispatcher: active slots finish their streams
        first (graceful drain), queued-but-unadmitted requests are
        admitted and served ahead of the shutdown sentinel; anything
        racing the shutdown fails with DecodeEngineClosedError."""
        with self._submit_lock:
            already = self._closed
            self._closed = True
            if not already and self._thread.is_alive():
                self._q.put(_SHUTDOWN)
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
        if not self._thread.is_alive():
            self._flush_queue(DecodeEngineClosedError(
                "DecodeEngine closed"))

    def _admit_slot(self, req: _DecodeRequest, slot: int):
        """Admit one queued request into ``slot``: run its bucket's
        prefill+insert plan, stream the first token, and activate the
        slot — or finish the request immediately when the first token
        already ends it (EOS / max_new == 1)."""
        span = req.span
        if span is not None:
            span.phase_start("prefill")
        fresh = req.bucket not in self._admit_fns
        stat = ("misses" if (fresh
                             and req.bucket
                             not in self._bucket_stats["misses"])
                else "hits")
        self._bucket_stats[stat][req.bucket] = \
            self._bucket_stats[stat].get(req.bucket, 0) + 1
        # the timer starts BEFORE the plan build: on an unwarmed
        # engine the AOT compile (or store load) happens inside
        # _admit_fn_for, and compile_time_s must cover it
        t0 = time.perf_counter()
        fn = self._admit_fn_for(req.bucket)
        # every host->device hop is explicit (device_put), so the loop
        # stays clean under zoolint.sanitize() transfer guards — the
        # scalars included (a bare python int into a jit is an
        # implicit transfer of its own)
        prompt_dev = jax.device_put(req.prompt, self._device)
        length_dev = jax.device_put(np.int32(req.length), self._device)
        slot_dev = jax.device_put(np.int32(slot), self._device)
        _profile.note_transfer("h2d")
        self._caches, self._tok, self._pos, tok0 = fn(
            self._caches, self._tok, self._pos, prompt_dev,
            length_dev, slot_dev)
        tok0 = int(jax.device_get(tok0))
        _profile.note_transfer("d2h")
        if fresh:
            self._bucket_stats["compile_time_s"][req.bucket] = \
                self._bucket_stats["compile_time_s"].get(
                    req.bucket, 0.0) + (time.perf_counter() - t0)
        self._counters["prefills"] += 1
        self._counters["admitted"] += 1
        self._counters["tokens"] += 1
        req.produced = 1
        req.scheduled = 1
        req.stream._push(tok0)
        if span is not None:
            span.set_label("decode_bucket", req.bucket)
            span.set_label("decode_slot", slot)
        done = (req.produced >= req.max_new
                or (req.eos_id is not None and tok0 == req.eos_id))
        if done:
            if span is not None:
                span.phase_end()
            self._counters["evicted"] += 1
            req.stream._finish()
            self._free.append(slot)
            return
        if span is not None:
            # one phase for the whole shared-step participation —
            # per-step phases would be ring-buffer noise at 128 steps
            span.phase_start("decode_step")
        req.slot = slot
        self._slots[slot] = req
        self._occupancy += 1

    def _choose_fuse(self) -> int:
        """Window size for the next dispatch.  The invariant: a fused
        window must not CROSS a scheduling event, so admissions and
        evictions land on exactly the same step indices as pure
        per-step dispatching — fusion changes overhead, never the
        schedule.  The window is therefore the minimum
        remaining-to-schedule over active slots (an EOS-capable
        request counts as 1 — it can end on any step), clamped to the
        compiled plan ladder.

        One deliberate exception: with an EMPTY queue, the full
        ``step_fuse`` window is taken even past a request's end —
        nobody is waiting for its slot, its surplus tokens are
        truncated at fan-out, and the only cost is up to K-1 extra
        slot-steps of garbage against K-fold fewer dispatches on the
        drain tail.  (A request submitted mid-window waits at most
        ~K step-times for admission — the same order as the
        coalescer's gather grace.)

        ``scheduled`` (not ``produced``) drives the remaining check:
        the pipeline may hold one dispatched-unprocessed window, and
        planning from ``produced`` would double-schedule it."""
        if not self._fuse_sizes:
            return 1
        if self._q.empty():
            return self.step_fuse
        rem = self.step_fuse
        for req in self._slots:
            if req is None:
                continue
            r = (1 if req.eos_id is not None
                 else req.max_new - req.scheduled)
            if r < rem:
                rem = r
                if rem <= 1:
                    return 1
        for k in self._fuse_sizes:
            if k <= rem:
                return k
        return 1

    def _dispatch_step(self):
        """Dispatch the next decode window WITHOUT fetching (jax
        dispatch is asynchronous) and snapshot the slot->request map as
        of this dispatch — the fetch side fans tokens out against the
        snapshot, so an eviction or admission that happens while the
        device computes cannot mis-route a token.  Returns
        (token vector or (k, capacity) matrix, snapshot, window)."""
        if self._step_fn is None:
            # unwarmed engine: build (or store-load) the step plans
            # inline, once — warmed engines pay one is-None check
            self._ensure_step_plans()
        k = self._choose_fuse()
        if k > 1:
            self._caches, self._tok, self._pos, toks = \
                self._stepk_fns[k](self._caches, self._tok, self._pos)
            self._counters["fused_dispatches"] += 1
        else:
            self._caches, self._tok, self._pos = self._step_fn(
                self._caches, self._tok, self._pos)
            toks = self._tok
        self._counters["steps"] += k
        for req in self._slots:
            if req is not None:
                req.scheduled += k
        return toks, list(self._slots), k

    def _process_step(self, pending):
        """Fetch a dispatched window's token vector ((capacity,) for a
        single step, (K, capacity) fused) and fan it out to the slots
        that were live AT DISPATCH TIME, evicting finished ones.  A
        request that finished in an EARLIER window's processing (the
        pipeline dispatches window k+1 before window k is processed,
        so its snapshot can still name it) is skipped — its stream is
        closed and the slot's extra computed tokens are garbage by
        construction, as are any tokens past a request's max_new/EOS
        inside a fused window."""
        tok_dev, snapshot, k = pending
        toks = jax.device_get(tok_dev)
        _profile.note_transfer("d2h")
        if k == 1:
            toks = toks.reshape(1, -1)
        for slot, req in enumerate(snapshot):
            if req is None or req.stream.done:
                continue
            for j in range(k):
                tok = int(toks[j, slot])
                req.produced += 1
                self._counters["tokens"] += 1
                req.stream._push(tok)
                if (req.produced >= req.max_new
                        or (req.eos_id is not None
                            and tok == req.eos_id)):
                    if req.span is not None:
                        req.span.phase_end()
                    self._counters["evicted"] += 1
                    self._occupancy -= 1
                    req.stream._finish()
                    self._slots[slot] = None
                    self._free.append(slot)
                    break

    def _decode_loop(self):
        try:
            self._loop_inner()
        except BaseException as e:  # crash net: never strand a caller
            # _crashed (this is its ONLY writer; the closed property
            # folds it in) flips BEFORE the lock barrier: a submit
            # already inside its critical section finishes the enqueue
            # and its own post-put check flushes, one entering after
            # sees closed and raises.  The acquire is a BARRIER, not a
            # guard — bounded because a submitter blocked on a full
            # queue holds the lock until our flush below frees a slot,
            # so we must not wait on it forever.
            self._crashed = True
            got = self._submit_lock.acquire(timeout=1.0)
            if got:
                self._submit_lock.release()
            self._flush_queue(e)
            for slot, req in enumerate(self._slots):
                if req is not None:
                    if req.span is not None:
                        req.span.phase_end()
                    req.stream._finish(e)
                    self._slots[slot] = None
            self._occupancy = 0
            raise

    def _loop_inner(self):
        # one-deep step pipeline: step k+1 is DISPATCHED before step
        # k's tokens are fetched, so the host side (token fan-out,
        # eviction, stream wake-ups, the next admission) overlaps the
        # device compute instead of serializing with it — the
        # serving-side analog of the coalescer's one-deep dispatch
        # pipeline.  Cost: an eviction is observed one step late, so a
        # freed slot re-admits one step later (bounded occupancy
        # slack, never a correctness issue — see _process_step).
        pending = None
        shutdown = False
        while True:
            # 1. admit queued requests into free slots — between
            # steps, which is what makes the batching iteration-level
            while self._free and not shutdown:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is _SHUTDOWN:
                    shutdown = True
                    break
                self._admit_slot(nxt, self._free.popleft())
            # 2. dispatch the next step, then fan out the previous one
            nxt_pending = (self._dispatch_step() if self._occupancy
                           else None)
            if pending is not None:
                self._process_step(pending)
            pending = nxt_pending
            # 3. idle: wait for work (or drain out on shutdown)
            if pending is None and not self._occupancy:
                if shutdown:
                    return
                try:
                    nxt = self._q.get(timeout=0.05)
                except queue.Empty:
                    continue
                if nxt is _SHUTDOWN:
                    shutdown = True
                    continue
                self._admit_slot(nxt, self._free.popleft())
