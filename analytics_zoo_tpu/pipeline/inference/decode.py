"""Continuous batching for autoregressive decode (ORCA-style).

The serving stack batches fixed-shape one-shot requests; the dominant
LLM workload is token streaming, where requests join and leave the
batch at EVERY decode step.  Naive batch-of-requests decoding makes
every rider pay the longest sequence's latency: a batch finishes when
its slowest member does, and short requests idle in finished rows.

``DecodeEngine`` is the iteration-level alternative:

* **Bucketed prefill.**  Each admitted prompt is right-padded to a
  small geometric ladder of prompt lengths and run through ONE batched
  causal forward (the training-shaped compute), writing its per-layer
  K/V into a free slot of the decode state — one ``admit`` executable
  per (prompt bucket, capacity), compiled once.
* **A single persistent slot-array decode executable.**  The decode
  state is a fixed-capacity slot array — per-layer K/V caches of shape
  ``(capacity, heads, max_len, d_head)`` plus per-slot current token
  and write position — stepped by ONE jitted function whose shapes
  never depend on occupancy.  Attention masks derive from per-slot
  positions, so occupied and free slots coexist in the same dispatch:
  admission and eviction are state writes, never recompiles.  Exactly
  one compile per (bucket, capacity) across a whole serving run — the
  zoolint sanitizer's compile counter pins this at every occupancy.
* **Per-step admission / eviction.**  A dispatcher thread loops:
  drain finished slots (EOS or max tokens), admit queued requests into
  free slots, step once, fan the step's tokens out to per-request
  :class:`TokenStream` futures.  A short request admitted next to a
  long one leaves as soon as ITS tokens are done; the freed slot is
  re-filled on the very next iteration.

Decode math: :mod:`analytics_zoo_tpu.models.generation`'s
``_prefill`` / ``_decode_step`` — the same per-row-position (ragged)
formulation ``TransformerLM.generate`` compiles into its scan, so a
slot stepped one token at a time is pinned token-identical to the
scan path (tests/test_serving_decode.py).

Decode engine v2 (ISSUE 14) extends the slot array with three
independently-gated stages, all preserving the
one-compile-per-(bucket, capacity, plan), sanitize-clean, and
bit-exact-replay invariants:

* **Per-slot sampling.**  temperature/top-k/top-p ride the slot array
  as DYNAMIC per-slot values (static configs would recompile the step
  per sampling mix), and each slot draws from its own
  ``fold_in(PRNGKey(request seed), absolute token index)`` key — the
  trainer's absolute-step fold_in discipline applied per stream.
  Because a slot's logits depend only on its own cache (masked
  attention) and its key only on (seed, index), streams are
  independent, bit-replayable, and occupancy-invariant; a
  ``temperature == 0`` slot selects the bare argmax, bit-identical to
  the pre-sampling greedy engine.
* **Prefix-KV pool.**  Prompts are split at the largest prompt-bucket
  boundary <= their length; the prefix block's per-layer K/V (and its
  last hidden state) is content-hash cached in a small on-device LRU
  pool, so a shared-system-prompt admission is a
  ``dynamic_update_slice`` memcpy plus a short tail prefill instead
  of a full-prompt recompute.  A pool hit copies bits a previous
  prefix-prefill produced and a miss recomputes them with the same
  plan, so hit and miss streams are bit-identical by construction;
  eviction (LRU beyond the pool bound) just recomputes — never a
  wrong prefix (the key is the prefix CONTENT hash).
* **Speculative decoding.**  A small draft model proposes
  ``spec_tokens - 1`` tokens per slot (a scan inside ONE dispatch);
  the target then takes one EXACT single-query step (the same traced
  body as the non-speculative plan — the bit-exact fallback token)
  and verifies the proposals with a k-query windowed forward
  (training-shaped matmuls).  Accepted proposals emit up to
  ``spec_tokens`` tokens per dispatch; a rejection falls back to the
  exact step's token, bit-identical to the non-speculative stream BY
  CONSTRUCTION (full rejection degrades to exactly the plain
  engine's computation).  Accepted window tokens are selected from
  the verify pass's own logits, which match the single-query step to
  ~1 ulp — identical selections on this backend (tests and the bench
  pin spec ≡ plain empirically); a near-tie flip under a backend
  whose window kernels round differently is the only theoretical
  divergence channel.  Sampled verification draws each window
  position from the same per-slot fold_in key the non-speculative
  path would use.

Data movement is explicit (``device_put`` in, ``device_get`` out) so
the whole loop runs clean under ``zoolint.sanitize()`` transfer
guards; the decode state itself never leaves the device — the per-step
host traffic is one (capacity,) token fetch (plus the (spec_tokens,
capacity) token matrix and acceptance vector per speculative window).
"""

from __future__ import annotations

import collections
import hashlib
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...models.generation import (_decode_step, _decode_window,
                                  _embed_token, _head_logits, _prefill,
                                  _prefill_ext, _sample)
from ...observability import profile as _profile
from ...observability.log import get_logger as _get_logger
from .serving import _execstore, bucket_ladder

_slog = _get_logger("zoo.serving.decode")


class DecodeEngineClosedError(RuntimeError):
    """The decode dispatcher is gone — this request was (or would be)
    never served."""


class TokenStream:
    """Per-request streaming handle: tokens arrive one decode step at a
    time; iterate for streaming, or :meth:`result` for the full
    continuation.

    Thread contract: the engine's dispatcher is the only writer; any
    number of consumer threads may iterate / ``result()``.  The
    producer fast path is ONE list append (GIL-atomic) — the condition
    variable is only touched once a consumer actually iterates
    (``_live``), so blocking callers cost the dispatcher nothing per
    token.  This is hot-loop-relevant: at thousands of tokens/s a
    locked queue put per token was ~15% of the engine's wall.
    """

    def __init__(self, request_id: int):
        self.request_id = request_id
        self._tokens: List[int] = []
        self._error: Optional[BaseException] = None
        self._finished = threading.Event()
        self._live = False  # a consumer is iterating — notify pushes
        self._cond = threading.Condition()

    # ---- producer side (dispatcher thread only) ----
    def _push(self, tok: int):
        self._tokens.append(tok)
        if self._live:
            with self._cond:
                self._cond.notify_all()

    def _finish(self, error: Optional[BaseException] = None):
        self._error = error
        self._finished.set()
        if self._live:
            with self._cond:
                self._cond.notify_all()

    # ---- consumer side ----
    @property
    def done(self) -> bool:
        return self._finished.is_set()

    def __iter__(self):
        self._live = True
        i = 0
        while True:
            # catch up lock-free (append-only list, single writer)
            while i < len(self._tokens):
                yield int(self._tokens[i])
                i += 1
            if self._finished.is_set():
                if i < len(self._tokens):
                    continue  # tokens landed after the done flag
                if self._error is not None:
                    raise self._error
                return
            with self._cond:
                if i >= len(self._tokens) \
                        and not self._finished.is_set():
                    # bounded wait: _live may have been observed False
                    # by a push racing this first iteration
                    self._cond.wait(0.05)

    def result(self, timeout: Optional[float] = None) -> np.ndarray:
        """Block until the request finishes; returns the generated
        continuation as a 1-D int32 array (EOS included when hit)."""
        if not self._finished.wait(timeout=timeout):
            raise TimeoutError(
                f"decode request {self.request_id} still streaming "
                f"after {timeout}s")
        if self._error is not None:
            raise self._error
        return np.asarray(self._tokens, np.int32)


class _DecodeRequest:
    # ``span`` is the explicit cross-thread trace handoff (same
    # convention as the coalescer's _Request): the dispatcher records
    # prefill/decode_step phases on it directly.
    # ``scheduled`` counts tokens covered by dispatched (possibly not
    # yet processed) steps — the pipelined loop plans fused windows
    # from it, since ``produced`` lags by the in-flight dispatch.
    __slots__ = ("prompt", "length", "bucket", "max_new", "eos_id",
                 "stream", "span", "produced", "scheduled", "slot",
                 "temperature", "top_k", "top_p", "seed")

    def __init__(self, prompt: np.ndarray, length: int, bucket: int,
                 max_new: int, eos_id: Optional[int], stream: TokenStream,
                 span=None, temperature: float = 0.0,
                 top_k: Optional[int] = None,
                 top_p: Optional[float] = None, seed: int = 0):
        self.prompt = prompt
        self.length = length
        self.bucket = bucket
        self.max_new = max_new
        self.eos_id = eos_id
        self.stream = stream
        self.span = span
        self.produced = 0
        self.scheduled = 0
        self.slot = -1
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.seed = seed


class _PrefixEntry:
    """One pooled prefix: the per-layer (k, v) device blocks of a
    prefix-prefill plus its last position's hidden state (the logits
    source for a prompt that IS exactly the prefix)."""

    __slots__ = ("kv", "h_last", "p_len")

    def __init__(self, kv, h_last, p_len: int):
        self.kv = kv
        self.h_last = h_last
        self.p_len = p_len


class _PrefixPool:
    """Dispatcher-owned LRU of prefix-KV blocks, keyed on the prefix
    CONTENT hash (sha256 over (prefix length, token bytes)) — a
    collision-free key means an entry can only ever serve the exact
    prefix it was computed from.  Eviction (beyond ``size`` entries)
    drops the device arrays; a later admission of that prefix simply
    recomputes (counted, never wrong).  Single-threaded by protocol
    (only the dispatcher touches it), like the slot bookkeeping."""

    def __init__(self, size: int):
        self.size = int(size)
        self.entries: "collections.OrderedDict[str, _PrefixEntry]" = \
            collections.OrderedDict()

    @staticmethod
    def key(prefix_ids: np.ndarray) -> str:
        ids = np.ascontiguousarray(prefix_ids, np.int32)
        h = hashlib.sha256()
        h.update(repr(ids.shape).encode())
        h.update(ids.tobytes())
        return h.hexdigest()

    def get(self, key: str) -> Optional[_PrefixEntry]:
        ent = self.entries.get(key)
        if ent is not None:
            self.entries.move_to_end(key)
        return ent

    def put(self, key: str, entry: _PrefixEntry) -> int:
        """Insert (most-recent) and trim to ``size``; returns how many
        entries the bound evicted (their device arrays are freed with
        the last reference — memory pressure resolves to a later
        recompute, never a wrong block)."""
        self.entries[key] = entry
        self.entries.move_to_end(key)
        evicted = 0
        while len(self.entries) > self.size:
            self.entries.popitem(last=False)
            evicted += 1
        return evicted


_SHUTDOWN = object()


class DecodeEngine:
    """KV-cache-slotted continuous-batching decode engine (module doc).

    Args:
        params: the TransformerLM param tree (``trainer.state.params``)
            — placed on ``device`` once at construction.
        hyper: the model's hyper dict (``n_layers``/``n_heads``/
            ``d_model``/``max_len``/``moe_every``...).
        capacity: decode slots — the fixed batch width of the
            persistent step executable.
        max_len: per-slot cache length (default the model's
            ``max_len``); every request needs
            ``prompt_len + max_new_tokens <= max_len``.
        prompt_buckets: the prompt-length ladder (default: a geometric
            ladder up to ``max_len - 1``).  One admit executable
            compiles per bucket actually used.
        eos_id: default end-of-sequence token id (per-request
            override via ``submit``); ``None`` decodes to
            ``max_new_tokens`` always.
        max_queue: bound on submitted-but-unadmitted requests.
        step_fuse: fused-window size K — when no admission or
            eviction could land inside the next K steps, they
            dispatch as ONE compiled scan, amortizing per-dispatch
            overhead without giving up iteration-level scheduling
            (1 disables fusion; see ``_choose_fuse``).
        prefix_pool: > 0 keeps that many prefix-KV blocks in an
            on-device LRU pool — admissions whose prompt shares a
            bucket-aligned prefix with a pooled block skip the
            prefix's prefill compute (module docstring §Prefix-KV
            pool).  0 (default) disables: admission is the monolithic
            single-plan prefill, bit-identical to the v1 engine.
        draft_params / draft_hyper: a small draft model (same vocab)
            enables speculative decoding — up to ``spec_tokens``
            tokens per dispatch (module docstring §Speculative).
            Mutually exclusive with ``prefix_pool`` for now.
        spec_tokens: tokens per speculative window (1 exact + up to
            ``spec_tokens - 1`` certified draft proposals).
        device: jax device for the decode state (default: the first
            local device).
    """

    def __init__(self, params, hyper: Dict[str, Any], capacity: int = 8,
                 max_len: Optional[int] = None,
                 prompt_buckets: Optional[Sequence[int]] = None,
                 eos_id: Optional[int] = None, max_queue: int = 256,
                 step_fuse: int = 4, prefix_pool: int = 0,
                 draft_params=None, draft_hyper: Optional[Dict] = None,
                 spec_tokens: int = 4, device=None,
                 mesh: Optional[dict] = None,
                 store_tag: Optional[str] = None):
        # per-model accounting tag for execstore entries (stat
        # --by-model); metadata only, never part of the fingerprint
        self._store_tag = store_tag
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if (draft_params is None) != (draft_hyper is None):
            raise ValueError(
                "speculative decoding needs BOTH draft_params and "
                "draft_hyper (or neither)")
        if int(prefix_pool) < 0:
            raise ValueError(
                f"prefix_pool must be >= 0, got {prefix_pool}")
        if draft_params is not None and prefix_pool:
            raise ValueError(
                "draft (speculative) and prefix_pool are mutually "
                "exclusive in this engine version — the pooled prefix "
                "blocks would need a draft-cache twin")
        if draft_params is not None and spec_tokens < 2:
            raise ValueError(
                f"spec_tokens must be >= 2 (1 exact + >=1 proposed), "
                f"got {spec_tokens}")
        if draft_hyper is not None \
                and int(draft_hyper["vocab_size"]) != int(
                    hyper["vocab_size"]):
            raise ValueError(
                "draft and target must share a vocabulary "
                f"({draft_hyper['vocab_size']} vs "
                f"{hyper['vocab_size']})")
        self.capacity = int(capacity)
        self.step_fuse = max(1, int(step_fuse))
        self._hyper = dict(hyper)
        self.max_len = int(max_len or hyper["max_len"])
        if self.max_len > int(hyper["max_len"]):
            raise ValueError(
                f"max_len ({self.max_len}) exceeds the model's "
                f"positional table ({hyper['max_len']})")
        if prompt_buckets:
            self.prompt_buckets: Tuple[int, ...] = tuple(
                sorted(set(int(b) for b in prompt_buckets)))
        else:
            top = max(1, self.max_len - 1)
            self.prompt_buckets = bucket_ladder(
                top, growth=2.0, min_batch=min(8, top))
        if self.prompt_buckets[-1] >= self.max_len:
            raise ValueError(
                f"largest prompt bucket ({self.prompt_buckets[-1]}) "
                f"must leave room to decode (max_len {self.max_len})")
        self.eos_id = eos_id
        self._device = device or jax.local_devices()[0]
        # ---- mesh-sharded slot state (big-LM continuous batching):
        # the CAPACITY axis shards over the group's mesh, so each
        # device steps its own contiguous slice of the slots while the
        # per-slot decode math — attention over that slot's own cache
        # line, sampling from that slot's own logits — stays entirely
        # on one device.  No cross-slot term exists in the step, so
        # the partitioned program is a pure per-device map: bit-exact
        # vs the unsharded engine BY CONSTRUCTION (bench.py sharded
        # gates it).  Params replicate across the group (the weights
        # ride the forward unsharded; rule-sharded decode weights
        # would put collectives inside the step — a later engine
        # version's trade).
        self._mesh_spec = None
        self._mesh = None
        self._mesh_cfg = None
        if mesh is not None:
            from ...serving.shardgroup import (carve_groups,
                                               mesh_spec_canonical,
                                               normalize_mesh_spec)
            if device is not None:
                raise ValueError(
                    "pass mesh= or device=, not both — the mesh spec "
                    "carves the engine's device group itself")
            if prefix_pool or draft_params is not None:
                raise ValueError(
                    "mesh-sharded decode does not support prefix_pool "
                    "or speculative drafts in this engine version — "
                    "their pool/draft caches would need the same slot "
                    "sharding twin")
            spec = normalize_mesh_spec(mesh)
            gdevs, gmesh = carve_groups(jax.local_devices(), spec)[0]
            if self.capacity % len(gdevs):
                raise ValueError(
                    f"capacity ({self.capacity}) must divide evenly "
                    f"over the mesh's {len(gdevs)} devices")
            self._mesh_spec = spec
            self._mesh_cfg = mesh_spec_canonical(spec)
            self._mesh = gmesh
            self._device = gdevs[0]
        # device_put target for replicated inputs (params, admission
        # scalars, prompts): the bare device unsharded, the group-
        # replicated NamedSharding under a mesh
        self._rep = (self._device if self._mesh is None
                     else NamedSharding(self._mesh, P()))
        self._params = jax.device_put(params, self._rep)
        self._n_layers = int(hyper["n_layers"])
        self.spec_tokens = int(spec_tokens)
        self._draft_hyper = (None if draft_hyper is None
                             else dict(draft_hyper))
        if self._draft_hyper is not None:
            if int(self._draft_hyper["max_len"]) < self.max_len:
                raise ValueError(
                    f"draft positional table "
                    f"({self._draft_hyper['max_len']}) is shorter than "
                    f"the engine's max_len ({self.max_len})")
            self._draft_params = jax.device_put(draft_params,
                                                self._rep)
        else:
            self._draft_params = None

        # ---- device state: the persistent slot array.  jnp.zeros
        # builds ON the device (a fill, not a transfer); tok/pos for
        # free slots are don't-cares — their writes land in cache
        # positions a future occupant always overwrites before
        # attending (write-then-attend, see _build_step_fn).
        d_head = int(hyper["d_model"]) // int(hyper["n_heads"])
        shape = (self.capacity, int(hyper["n_heads"]), self.max_len,
                 d_head)
        with jax.default_device(self._device):
            caches = [(jnp.zeros(shape, jnp.float32),
                       jnp.zeros(shape, jnp.float32))
                      for _ in range(self._n_layers)]
            dcaches = []
            if self._draft_hyper is not None:
                dh = self._draft_hyper
                dshape = (self.capacity, int(dh["n_heads"]),
                          self.max_len,
                          int(dh["d_model"]) // int(dh["n_heads"]))
                dcaches = [(jnp.zeros(dshape, jnp.float32),
                            jnp.zeros(dshape, jnp.float32))
                           for _ in range(int(dh["n_layers"]))]
            tok = jnp.zeros((self.capacity,), jnp.int32)
            pos = jnp.zeros((self.capacity,), jnp.int32)
            # per-slot sampling state: request seed, absolute token
            # index (the fold_in counter), and the dynamic sampling
            # knobs (temperature == 0 -> argmax, top_k == 0 / top_p
            # == 1 -> disabled) — slot writes at admission, never a
            # recompile
            samp = (jnp.zeros((self.capacity,), jnp.int32),
                    jnp.zeros((self.capacity,), jnp.int32),
                    jnp.zeros((self.capacity,), jnp.float32),
                    jnp.zeros((self.capacity,), jnp.int32),
                    jnp.ones((self.capacity,), jnp.float32))
        # COMMIT the initial state (device_put of an on-device array is
        # a no-op copy-wise but flips it committed): the live loop's
        # state is always committed — its producers take committed
        # device_put inputs — and the jit cache keys on committedness,
        # so an uncommitted first call would cost every admit plan a
        # SECOND compile the first time it sees steady-state inputs,
        # breaking the one-compile-per-(bucket, capacity) invariant
        self._caches = jax.device_put(caches, self._slot_sharding(4))
        self._dcaches = jax.device_put(dcaches, self._slot_sharding(4))
        self._tok = jax.device_put(tok, self._slot_sharding(1))
        self._pos = jax.device_put(pos, self._slot_sharding(1))
        self._samp = jax.device_put(samp, self._slot_sharding(1))

        # one AOT-compiled single-step plan plus a halving ladder of
        # fused window plans (step_fuse, step_fuse/2, ... 2) per
        # engine; one admit plan per prompt bucket — built in
        # warmup() (or lazily at the first unwarmed dispatch), cached,
        # and NEVER rebuilt inside the dispatcher loop (zoolint
        # ZL101), so a serving run compiles exactly once per
        # (bucket, capacity) plan no matter how occupancy moves.
        # Plans are explicit lower()+compile() rather than lazy jit:
        # the AOT split is what lets the persistent executable store
        # answer the compile with a disk load (zero-compile warmup in
        # a process whose store is warm).
        self._fuse_sizes: Tuple[int, ...] = tuple(
            sorted({k for k in (self.step_fuse, self.step_fuse // 2)
                    if k > 1}, reverse=True))
        self._step_fn: Any = None
        self._stepk_fns: Dict[int, Any] = {}
        self._admit_fns: Dict[int, Any] = {}
        self._spec_fn: Any = None
        self._pfxfill_fns: Dict[int, Any] = {}
        self._pfxadmit_fns: Dict[Tuple[int, int], Any] = {}
        self._prefix_pool = (_PrefixPool(prefix_pool) if prefix_pool
                             else None)
        # persistent executable store: resolved once; None keeps every
        # store branch inert.  The plans close over the params, so the
        # weights digest rides every plan fingerprint — two engines
        # with different weights can never share a store entry.  The
        # draft digest and the sampling-static config ride alongside
        # (large closed-over constants can elide from the HLO text, and
        # two spec engines differing only in draft weights must never
        # share a verify executable).
        self._store = _execstore().current()
        self._wdigest = (_execstore().params_digest(self._params)
                         if self._store is not None else None)
        self._ddigest = (_execstore().params_digest(self._draft_params)
                         if self._store is not None
                         and self._draft_params is not None else None)
        self._samp_cfg = ("samp-v2",
                          self.spec_tokens
                          if self._draft_hyper is not None else 0,
                          bool(self._prefix_pool))

        # host-side slot bookkeeping (dispatcher-thread-owned)
        self._slots: List[Optional[_DecodeRequest]] = \
            [None] * self.capacity
        self._free: collections.deque = collections.deque(
            range(self.capacity))

        # counters (dispatcher-owned ints; reads copy — GIL-atomic
        # enough for a metrics scrape, same convention as the
        # coalescer's hedge counters)
        self._counters = {"tokens": 0, "steps": 0, "prefills": 0,
                          "admitted": 0, "evicted": 0,
                          "fused_dispatches": 0, "sampled_tokens": 0,
                          "prefix_hits": 0, "prefix_misses": 0,
                          "prefix_evictions": 0, "spec_windows": 0,
                          "spec_proposed": 0, "spec_accepted": 0}
        self._bucket_stats: Dict[str, Dict[int, Any]] = {
            "hits": {}, "misses": {}, "compile_time_s": {}}
        self._occupancy = 0

        self._q: "queue.Queue" = queue.Queue(maxsize=int(max_queue))
        self._next_id = 0
        self._id_lock = threading.Lock()
        self._closed = False
        self._submit_lock = threading.Lock()
        self._crashed = False
        # the dispatcher starts LAZILY (first submit), not here:
        # warmup() runs on the caller thread and rebinds the shared
        # donated state, so a dispatcher stepping concurrently would
        # race it into use-after-donate — deferring the start makes
        # construct -> warmup -> serve safe by construction.  The
        # condition guards only the handshake FLAGS (the decode state
        # itself is single-owner by protocol: warmup's thread before
        # start, the dispatcher after)
        self._started = False
        self._warming = False
        self._start_cond = threading.Condition()
        self._thread = threading.Thread(
            target=self._decode_loop, name="zoo-decode-dispatch",
            daemon=True)

    # ---- placement shardings --------------------------------------------
    def _rep_sharding(self):
        """Spec sharding for group-replicated inputs (scalars,
        prompts, prefix blocks): the engine's single device unsharded,
        the whole group under a mesh."""
        if self._mesh is not None:
            return NamedSharding(self._mesh, P())
        return jax.sharding.SingleDeviceSharding(self._device)

    def _slot_sharding(self, rank: int):
        """Sharding for slot-state arrays (leading axis == capacity):
        under a mesh the slot axis shards over EVERY mesh axis (the
        sub-mesh exists to split the slots), remaining dims
        replicated."""
        if self._mesh is None:
            return jax.sharding.SingleDeviceSharding(self._device)
        axes = tuple(self._mesh.axis_names)
        return NamedSharding(self._mesh,
                             P(axes, *([None] * (rank - 1))))

    def _ensure_started(self):
        with self._start_cond:
            while self._warming:  # let an in-flight warmup finish
                self._start_cond.wait()
            if not self._started:
                self._started = True
                self._thread.start()

    # ---- compiled plans -------------------------------------------------
    def _select(self, logits, samp, offset: int = 0):
        """Per-slot token selection over (capacity, V) logits: each
        slot draws with ``fold_in(PRNGKey(seed), step + offset)`` —
        the absolute-token-index RNG that makes streams independent,
        replayable, and occupancy-invariant — through the SAME
        :func:`_sample` implementation the compiled-scan path uses.
        ``temperature == 0`` slots select the bare argmax
        (bit-identical to the v1 greedy step).

        Deliberate trade-off: greedy slots ride the same in-graph
        select, so a pure-greedy dispatch still computes the sampled
        branch it discards — that is what keeps sampling a STATE
        write (one step plan at every sampling mix, never a
        recompile), and the sampled path was engineered cheap (one
        top_k + one uniform, see ``_sample``) precisely so this dead
        work stays inside the bench's sampled-vs-greedy overhead
        bound.  A ``lax.cond`` fast path would shave the greedy step
        further at the cost of divergent step timing between modes —
        revisit if a production vocab makes the sort visible next to
        the transformer step."""
        seed, stepc, temp, topk, topp = samp

        def pick(lg, s, i, t, k, p):
            key = jax.random.fold_in(jax.random.PRNGKey(s), i + offset)
            return _sample(lg, key, t, k, p)

        return jax.vmap(pick)(logits, seed, stepc, temp, topk,
                              topp).astype(jnp.int32)

    def _step_core(self, caches, tok, pos, samp):
        """ONE slot-array decode step over ALL ``capacity`` slots —
        the body the step, fused, and speculative plans all trace, so
        every plan's per-token numerics are identical by construction.
        Free slots compute garbage that is never read: their (clamped)
        position's cache line is rewritten by the step itself before
        it is attended, and admission overwrites ``[0, bucket)``
        wholesale.  Shapes depend on (capacity, max_len) only — never
        occupancy."""
        params, hyper, max_len = self._params, self._hyper, self.max_len
        posc = jnp.minimum(pos, max_len - 1)
        emb = _embed_token(params, tok, posc)
        logits, caches = _decode_step(params, hyper, caches, emb, posc)
        nxt = self._select(logits, samp)
        seed, stepc, temp, topk, topp = samp
        return (caches, nxt, jnp.minimum(pos + 1, max_len),
                (seed, stepc + 1, temp, topk, topp))

    def _step_body(self, caches, tok, pos, samp):
        return self._step_core(caches, tok, pos, samp)

    def _samp_specs(self):
        s0 = self._slot_sharding(1)
        ispec = jax.ShapeDtypeStruct((self.capacity,), jnp.int32,
                                     sharding=s0)
        fspec = jax.ShapeDtypeStruct((self.capacity,), jnp.float32,
                                     sharding=s0)
        return (ispec, ispec, fspec, ispec, fspec)

    def _scalar_specs(self):
        """(seed, temperature, top_k, top_p) admission scalars."""
        s0 = self._rep_sharding()
        i0 = jax.ShapeDtypeStruct((), jnp.int32, sharding=s0)
        f0 = jax.ShapeDtypeStruct((), jnp.float32, sharding=s0)
        return (i0, f0, i0, f0)

    def _draft_specs(self):
        """Draft slot-cache ShapeDtypeStructs ([] without a draft —
        the plans carry the empty pytree so every engine flavor shares
        one plan signature)."""
        if self._draft_hyper is None:
            return []
        s0 = self._slot_sharding(4)
        dh = self._draft_hyper
        dspec = jax.ShapeDtypeStruct(
            (self.capacity, int(dh["n_heads"]), self.max_len,
             int(dh["d_model"]) // int(dh["n_heads"])), jnp.float32,
            sharding=s0)
        return [(dspec, dspec) for _ in range(int(dh["n_layers"]))]

    def _state_specs(self):
        """ShapeDtypeStructs matching the persistent decode state —
        the AOT lowering inputs for the step/admit plans (committed to
        the engine's device — or slot-sharded over its mesh — exactly
        like the live state)."""
        d_head = (int(self._hyper["d_model"])
                  // int(self._hyper["n_heads"]))
        cspec = jax.ShapeDtypeStruct(
            (self.capacity, int(self._hyper["n_heads"]), self.max_len,
             d_head), jnp.float32, sharding=self._slot_sharding(4))
        ispec = jax.ShapeDtypeStruct((self.capacity,), jnp.int32,
                                     sharding=self._slot_sharding(1))
        caches = [(cspec, cspec) for _ in range(self._n_layers)]
        return caches, ispec, ispec, self._samp_specs()

    def _plan(self, name: str, jitted, arg_specs):
        """AOT-build one decode plan: lower, consult the persistent
        executable store (read-through), compile + persist on a miss
        (write-behind).  Returns a callable jax-level ``Compiled`` —
        plan calls in the decode loop execute a fixed binary, never
        trace.  The fingerprint covers the lowered HLO text (graph +
        every shape; large closed-over constants may be elided from
        it, which is exactly why the weights digest rides alongside),
        the (capacity, max_len) tuple, and the runtime environment; a
        corrupt or unloadable entry counts ``invalid`` and falls back
        to the compile — never to a wrong executable."""
        lowered = jitted.lower(*arg_specs)
        store = self._store
        fp = None
        if store is not None:
            es = _execstore()
            fp = store.fingerprint(
                "decode-plan", name, es.hlo_digest(lowered),
                self._wdigest, self._ddigest, self._samp_cfg,
                self._mesh_cfg,
                (self.capacity, self.max_len),
                device=self._device)
            ent = store.lookup(fp)
            if ent is not None:
                try:
                    return es.rehydrate(ent.payload)
                except Exception as e:  # noqa: BLE001 — fall back to
                    # the compile below on any rehydration failure
                    store.note_invalid(fp, e)
        compiled = lowered.compile()
        if store is not None:
            try:
                meta = {"kind": "decode-plan", "name": name,
                        "capacity": self.capacity,
                        "max_len": self.max_len}
                if self._mesh_spec is not None:
                    meta["mesh"] = {
                        "axes": dict(self._mesh_spec["axes"]),
                        "strategy": self._mesh_spec["strategy"]}
                if self._store_tag is not None:
                    meta["model"] = self._store_tag
                store.put(fp, _execstore().serialize_compiled(compiled),
                          meta=meta)
            except Exception as e:  # noqa: BLE001 — persisting is
                # best-effort: serving proceeds on the fresh compile
                _slog.error("decode_plan_store_failed", plan=name,
                            error=f"{type(e).__name__}: {e}")
        return compiled

    def _build_step_plan(self):
        """The persistent single-step plan: (caches, tok, pos, samp)
        -> (caches', tok', pos', samp')."""
        # the caches are DONATED: without donation every step copies
        # the whole (capacity, heads, max_len, d_head) cache array per
        # layer just to update one position — the in-place update the
        # scan path gets for free from its loop carry.  Measured ~40%
        # off the per-step wall on CPU; the loop always rebinds the
        # returned caches, so the invalidated buffers are never
        # touched again.  tok/pos/samp are NOT donated: the pipelined
        # loop still holds the previous step's token vector for its
        # deferred fetch, and donating would invalidate that buffer
        # mid-flight (they are (capacity,) scalars — the copy is
        # free).
        return self._plan(
            "step1", jax.jit(self._step_body, donate_argnums=(0,)),
            self._state_specs())

    def _build_stepk_plan(self, k: int):
        """One fused window plan: ``k`` consecutive decode steps as
        ONE dispatch (a compiled ``lax.scan`` over
        :meth:`_step_body`), returning the (k, capacity) token matrix.
        Per-dispatch overhead — the python call, XLA's per-execution
        fixed cost, the host fetch — amortizes across k tokens, which
        is most of the single-step path's deficit against
        ``TransformerLM.generate``'s monolithic scan.  The dispatcher
        picks the window so scheduling NEVER changes inside it (see
        ``_choose_fuse``), so batching stays iteration-level exactly
        when iteration-level matters."""

        def stepk(caches, tok, pos, samp):
            def body(carry, _):
                c, t, p, sm = carry
                c, t, p, sm = self._step_body(c, t, p, sm)
                return (c, t, p, sm), t

            (caches, tok, pos, samp), toks = lax.scan(
                body, (caches, tok, pos, samp), None, length=k)
            return caches, tok, pos, samp, toks  # toks: (k, capacity)

        return self._plan(f"step{k}",
                          jax.jit(stepk, donate_argnums=(0,)),
                          self._state_specs())

    def _build_spec_plan(self):
        """The speculative window plan — draft proposal scan, ONE
        exact target step, windowed verify, and in-graph acceptance,
        all one dispatch:

            (caches, dcaches, tok, pos, samp) ->
            (caches', dcaches', tok', pos', samp',
             T (spec_tokens, capacity), accepted (capacity,))

        ``T[0]`` is the EXACT step's token (the same traced
        :meth:`_step_core` the non-speculative plan runs, so a full
        rejection falls back bit-identically); ``T[1:]`` are the
        window-verified target tokens for the draft's proposals, each
        selected with its absolute-index fold_in key.  ``accepted``
        in [1, spec_tokens] counts tokens valid to emit: proposal j is
        accepted while it equals the previous target token, the
        standard speculative prefix rule.  The draft scan runs
        ``spec_tokens - 1`` proposals plus one extra step so the LAST
        accepted token's draft K/V is written too (an all-accepted
        window leaves no cache gap).  Rolled-back state (tok', pos')
        re-derives from ``accepted``, so rejected positions are stale
        cache lines a later step overwrites before attending — the
        same write-then-attend invariant free slots rely on."""
        k = self.spec_tokens
        params, hyper, max_len = self._params, self._hyper, self.max_len
        dparams, dhyper = self._draft_params, self._draft_hyper

        def spec(caches, dcaches, tok, pos, samp):
            def dbody(carry, _):
                dc, t, p = carry
                posc = jnp.minimum(p, max_len - 1)
                emb = _embed_token(dparams, t, posc)
                lg, dc = _decode_step(dparams, dhyper, dc, emb, posc)
                nxt = jnp.argmax(lg, axis=-1).astype(jnp.int32)
                return (dc, nxt, jnp.minimum(p + 1, max_len)), nxt
            # k iterations: k-1 proposals + the cache-gap filler (its
            # proposal is never verified)
            (dcaches, _, _), dprops = lax.scan(
                dbody, (dcaches, tok, pos), None, length=k)
            dprops = dprops[:k - 1]  # (k-1, capacity)
            # the exact fallback token — bit-identical to the
            # non-speculative step plan by shared trace
            caches, t0, _, _ = self._step_core(caches, tok, pos, samp)
            # windowed verify of the proposals at pos+1 .. pos+k-1
            embs = [_embed_token(params, dprops[j],
                                 jnp.minimum(pos + 1 + j, max_len - 1))
                    for j in range(k - 1)]
            wlogits, caches = _decode_window(
                params, hyper, caches, jnp.stack(embs, axis=1),
                pos + 1)
            wtoks = [self._select(wlogits[:, j], samp, offset=1 + j)
                     for j in range(k - 1)]
            T = jnp.concatenate([t0[None], jnp.stack(wtoks, axis=0)],
                                axis=0)  # (k, capacity)
            match = (dprops == T[:k - 1]).astype(jnp.int32)
            acc = 1 + jnp.cumprod(match, axis=0).sum(axis=0)
            newtok = jnp.take_along_axis(T, (acc - 1)[None, :],
                                         axis=0)[0]
            newpos = jnp.minimum(pos + acc, max_len)
            seed, stepc, temp, topk, topp = samp
            samp = (seed, stepc + acc, temp, topk, topp)
            return caches, dcaches, newtok, newpos, samp, T, acc

        caches, ispec, _, samp = self._state_specs()
        return self._plan(
            f"spec{k}", jax.jit(spec, donate_argnums=(0, 1)),
            (caches, self._draft_specs(), ispec, ispec, samp))

    def _ensure_step_plans(self):
        """Build (or store-load) the decode-loop plans — the
        speculative window plan for a drafted engine, else the step
        plan + the fused-window ladder — called from warmup(), or
        lazily at the first dispatch of an unwarmed engine (one
        ``is None`` check per step thereafter)."""
        if self._step_fn is not None:
            return
        if self._draft_hyper is not None:
            self._spec_fn = self._build_spec_plan()
            # the built flag: a drafted engine's only step plan IS the
            # speculative window plan
            self._step_fn = self._spec_fn
            return
        for k in self._fuse_sizes:
            self._stepk_fns[k] = self._build_stepk_plan(k)
        self._step_fn = self._build_step_plan()  # set LAST: the flag

    def _slot_write(self, arrays, slot, tok0, length, seed0, temp0,
                    topk0, topp0):
        """Shared admission epilogue: write one slot's (tok, pos,
        sampling) state — step index starts at 1, the first token's
        index-0 key having just been consumed."""
        tok, pos, (seed, stepc, temp, topk, topp) = arrays
        tok = lax.dynamic_update_slice(tok, tok0[None], (slot,))
        pos = lax.dynamic_update_slice(
            pos, length[None].astype(pos.dtype), (slot,))
        seed = lax.dynamic_update_slice(seed, seed0[None], (slot,))
        stepc = lax.dynamic_update_slice(
            stepc, jnp.ones((1,), stepc.dtype), (slot,))
        temp = lax.dynamic_update_slice(temp, temp0[None], (slot,))
        topk = lax.dynamic_update_slice(topk, topk0[None], (slot,))
        topp = lax.dynamic_update_slice(topp, topp0[None], (slot,))
        return tok, pos, (seed, stepc, temp, topk, topp)

    def _sample_first(self, logits0, seed0, temp0, topk0, topp0):
        """First-token selection at absolute index 0 (the same
        :func:`_sample` + fold_in discipline every later index
        uses)."""
        key0 = jax.random.fold_in(jax.random.PRNGKey(seed0), 0)
        return _sample(logits0, key0, temp0, topk0,
                       topp0).astype(jnp.int32)

    def _build_admit_fn(self, s_b: int):
        """One prompt bucket's monolithic admission plan: batched
        prefill of the (1, s_b) padded prompt, first-token sampling,
        and the K/V insert into slot ``slot`` of the decode state —
        all one executable, so admitting is a single dispatch.  A
        drafted engine's plan also prefills the DRAFT's caches for the
        prompt (the draft must enter the window in lockstep)."""
        params, hyper = self._params, self._hyper
        dparams, dhyper = self._draft_params, self._draft_hyper

        def admit(caches, dcaches, tok, pos, samp, prompt, length,
                  slot, seed0, temp0, topk0, topp0):
            x, pc = _prefill(params, hyper, prompt, s_b)
            last = lax.dynamic_index_in_dim(x[0], length - 1,
                                            keepdims=False)
            logits0 = _head_logits(params, last[None, :])[0]
            tok0 = self._sample_first(logits0, seed0, temp0, topk0,
                                      topp0)
            new_caches = []
            for (ck, cv), (pk, pv) in zip(caches, pc):
                ck = lax.dynamic_update_slice(
                    ck, pk.astype(ck.dtype), (slot, 0, 0, 0))
                cv = lax.dynamic_update_slice(
                    cv, pv.astype(cv.dtype), (slot, 0, 0, 0))
                new_caches.append((ck, cv))
            new_dcaches = dcaches
            if dhyper is not None:
                _, dpc = _prefill(dparams, dhyper, prompt, s_b)
                new_dcaches = []
                for (ck, cv), (pk, pv) in zip(dcaches, dpc):
                    ck = lax.dynamic_update_slice(
                        ck, pk.astype(ck.dtype), (slot, 0, 0, 0))
                    cv = lax.dynamic_update_slice(
                        cv, pv.astype(cv.dtype), (slot, 0, 0, 0))
                    new_dcaches.append((ck, cv))
            tok, pos, samp = self._slot_write(
                (tok, pos, samp), slot, tok0, length, seed0, temp0,
                topk0, topp0)
            return new_caches, new_dcaches, tok, pos, samp, tok0

        # caches (target AND draft) donated for the same
        # in-place-update reason as the step plan; tok/pos/samp
        # excluded for the same pipeline-aliasing reason (an admission
        # can run while the previous step's token vector still awaits
        # its deferred fetch)
        return jax.jit(admit, donate_argnums=(0, 1))

    def _admit_fn_for(self, s_b: int):
        fn = self._admit_fns.get(s_b)
        if fn is None:
            caches, tok, pos, samp = self._state_specs()
            s0 = self._rep_sharding()
            pspec = jax.ShapeDtypeStruct((1, s_b), jnp.int32,
                                         sharding=s0)
            sspec = jax.ShapeDtypeStruct((), jnp.int32, sharding=s0)
            fn = self._admit_fns[s_b] = self._plan(
                f"admit{s_b}", self._build_admit_fn(s_b),
                (caches, self._draft_specs(), tok, pos, samp, pspec,
                 sspec, sspec) + self._scalar_specs())
        return fn

    # ---- prefix-KV pool plans -------------------------------------------
    def _prefix_bucket_for(self, n: int) -> int:
        """Largest prompt bucket <= n — the bucket-aligned prefix
        split point for a pool-eligible prompt."""
        p = self.prompt_buckets[0]
        for b in self.prompt_buckets:
            if b <= n:
                p = b
        return p

    def _build_pfxfill_fn(self, p_b: int):
        """The prefix-prefill plan: (1, p_b) prefix ids -> (per-layer
        (k, v) blocks (1, heads, p_b, d_head), last hidden (d,)).
        Runs ONCE per distinct prefix content (the pool miss); its
        outputs are exactly what a pool hit memcpys, which is why hit
        and miss admissions are bit-identical."""
        params, hyper = self._params, self._hyper

        def fill(prefix):
            x, pc = _prefill(params, hyper, prefix, p_b)
            return pc, x[0, p_b - 1]

        return jax.jit(fill)

    def _pfxfill_fn_for(self, p_b: int):
        fn = self._pfxfill_fns.get(p_b)
        if fn is None:
            s0 = self._rep_sharding()
            pspec = jax.ShapeDtypeStruct((1, p_b), jnp.int32,
                                         sharding=s0)
            fn = self._pfxfill_fns[p_b] = self._plan(
                f"pfxfill{p_b}", self._build_pfxfill_fn(p_b),
                (pspec,))
        return fn

    def _pfx_block_specs(self, p_b: int):
        s0 = self._rep_sharding()
        h = self._hyper
        d_head = int(h["d_model"]) // int(h["n_heads"])
        bspec = jax.ShapeDtypeStruct(
            (1, int(h["n_heads"]), p_b, d_head), jnp.float32,
            sharding=s0)
        hspec = jax.ShapeDtypeStruct((int(h["d_model"]),), jnp.float32,
                                     sharding=s0)
        return [(bspec, bspec) for _ in range(self._n_layers)], hspec

    def _build_pfxadmit_fn(self, p_b: int, s_b: int):
        """The pooled admission plan for (prefix bucket, prompt
        bucket): ``dynamic_update_slice`` the pooled prefix blocks
        into the slot (the memcpy), prefill only the TAIL (s_b - p_b
        padded positions, attending prefix + tail causally), sample
        the first token, and write the slot state — one executable per
        (p_b, s_b) pair actually used.  ``length == p_b`` (no tail)
        admissions reuse the pooled last-hidden for the first token's
        logits; the p_b == s_b variant compiles without any tail
        compute at all."""
        params, hyper = self._params, self._hyper
        tail_pad = s_b - p_b

        def padmit(caches, tok, pos, samp, pkv, h_pfx, tail, length,
                   slot, seed0, temp0, topk0, topp0):
            if tail_pad:
                xt, tc = _prefill_ext(params, hyper, tail, pkv, p_b)
            new_caches = []
            for i, (ck, cv) in enumerate(caches):
                pk, pv = pkv[i]
                ck = lax.dynamic_update_slice(
                    ck, pk.astype(ck.dtype), (slot, 0, 0, 0))
                cv = lax.dynamic_update_slice(
                    cv, pv.astype(cv.dtype), (slot, 0, 0, 0))
                if tail_pad:
                    tk, tv = tc[i]
                    ck = lax.dynamic_update_slice(
                        ck, tk.astype(ck.dtype), (slot, 0, p_b, 0))
                    cv = lax.dynamic_update_slice(
                        cv, tv.astype(cv.dtype), (slot, 0, p_b, 0))
                new_caches.append((ck, cv))
            if tail_pad:
                ti = jnp.clip(length - p_b - 1, 0, tail_pad - 1)
                lh = lax.dynamic_index_in_dim(xt[0], ti,
                                              keepdims=False)
                lh = jnp.where(length > p_b, lh, h_pfx)
            else:
                lh = h_pfx
            logits0 = _head_logits(params, lh[None, :])[0]
            tok0 = self._sample_first(logits0, seed0, temp0, topk0,
                                      topp0)
            tok, pos, samp = self._slot_write(
                (tok, pos, samp), slot, tok0, length, seed0, temp0,
                topk0, topp0)
            return new_caches, tok, pos, samp, tok0

        return jax.jit(padmit, donate_argnums=(0,))

    def _pfxadmit_fn_for(self, p_b: int, s_b: int):
        fn = self._pfxadmit_fns.get((p_b, s_b))
        if fn is None:
            caches, tok, pos, samp = self._state_specs()
            s0 = self._rep_sharding()
            blocks, hspec = self._pfx_block_specs(p_b)
            tspec = jax.ShapeDtypeStruct((1, s_b - p_b), jnp.int32,
                                         sharding=s0)
            sspec = jax.ShapeDtypeStruct((), jnp.int32, sharding=s0)
            fn = self._pfxadmit_fns[(p_b, s_b)] = self._plan(
                f"pfxadmit{p_b}_{s_b}",
                self._build_pfxadmit_fn(p_b, s_b),
                (caches, tok, pos, samp, blocks, hspec, tspec, sspec,
                 sspec) + self._scalar_specs())
        return fn

    def warmup(self) -> float:
        """AOT-compile every prompt bucket's admit plan plus the step
        plan (deploy pays the compiles, live streams never do).
        Returns wall seconds.  The warmed admissions land in slot 0 of
        the REAL state — harmless: the host free-list is untouched, so
        slot 0 is re-admitted (and its cache overwritten) before any
        live request reads it.  Must run BEFORE the first submit: the
        warms rebind the shared donated state on THIS thread, so a
        live dispatcher would race them into use-after-donate —
        _start_lock makes a concurrent first submit wait here rather
        than start one."""
        t0 = time.perf_counter()
        with self._start_cond:
            if self._started:
                raise RuntimeError(
                    "DecodeEngine.warmup() must run before the first "
                    "submit — the dispatcher owns the decode state "
                    "once it is serving")
            self._warming = True
        try:
            zero = jax.device_put(np.int32(0), self._rep)
            one = jax.device_put(np.int32(1), self._rep)
            fzero = jax.device_put(np.float32(0.0), self._rep)
            fone = jax.device_put(np.float32(1.0), self._rep)
            for b in self.prompt_buckets:
                prompt = jax.device_put(np.zeros((1, b), np.int32),
                                        self._rep)
                # tb covers the plan BUILD (the AOT compile — or the
                # store load that replaces it) plus one verifying
                # execution; compile_time_s is honest either way
                tb = time.perf_counter()
                fn = self._admit_fn_for(b)
                (self._caches, self._dcaches, self._tok, self._pos,
                 self._samp, tok0) = fn(
                    self._caches, self._dcaches, self._tok, self._pos,
                    self._samp, prompt, one, zero, zero, fzero, zero,
                    fone)
                jax.device_get(tok0)
                secs = time.perf_counter() - tb
                self._bucket_stats["compile_time_s"][b] = \
                    self._bucket_stats["compile_time_s"].get(b, 0.0) \
                    + secs
                self._bucket_stats["misses"][b] = \
                    self._bucket_stats["misses"].get(b, 0) + 1
                _slog.info("decode_warmup_bucket", bucket=b,
                           compile_ms=round(secs * 1e3, 3))
            if self._prefix_pool is not None:
                # every (prefix bucket, prompt bucket) pair a
                # pool-eligible prompt can land on: (b_i, b_i) for
                # exact-bucket prompts, (b_i, b_i+1) for in-between —
                # warmed here so the live loop never compiles one
                ladder = self.prompt_buckets
                for i, p_b in enumerate(ladder):
                    pfx = jax.device_put(np.zeros((1, p_b), np.int32),
                                         self._device)
                    pkv, h_last = self._pfxfill_fn_for(p_b)(pfx)
                    jax.device_get(h_last)
                    pairs = [(p_b, p_b)]
                    if i + 1 < len(ladder):
                        pairs.append((p_b, ladder[i + 1]))
                    plen = jax.device_put(np.int32(p_b), self._device)
                    for pb, sb in pairs:
                        tail = jax.device_put(
                            np.zeros((1, sb - pb), np.int32),
                            self._device)
                        fn = self._pfxadmit_fn_for(pb, sb)
                        (self._caches, self._tok, self._pos,
                         self._samp, tok0) = fn(
                            self._caches, self._tok, self._pos,
                            self._samp, pkv, h_last, tail, plen, zero,
                            zero, fzero, zero, fone)
                        jax.device_get(tok0)
            self._ensure_step_plans()
            if self._draft_hyper is not None:
                (self._caches, self._dcaches, self._tok, self._pos,
                 self._samp, toks, acc) = self._spec_fn(
                    self._caches, self._dcaches, self._tok, self._pos,
                    self._samp)
                jax.device_get(acc)
            else:
                (self._caches, self._tok, self._pos,
                 self._samp) = self._step_fn(
                    self._caches, self._tok, self._pos, self._samp)
                jax.device_get(self._tok)
                for fn in self._stepk_fns.values():
                    (self._caches, self._tok, self._pos, self._samp,
                     toks) = fn(self._caches, self._tok, self._pos,
                                self._samp)
                    jax.device_get(toks)
        finally:
            with self._start_cond:
                self._warming = False
                self._start_cond.notify_all()
        return time.perf_counter() - t0

    # ---- submission -----------------------------------------------------
    @property
    def closed(self) -> bool:
        return (self._closed or self._crashed
                or (self._started and not self._thread.is_alive()))

    def bucket_for(self, n: int) -> int:
        for b in self.prompt_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"prompt of {n} tokens exceeds the largest prompt bucket "
            f"({self.prompt_buckets[-1]})")

    @staticmethod
    def validate_sampling(temperature=0.0, top_k=None, top_p=None,
                          seed=0):
        """Sampling-parameter validation (raises ValueError) — shared
        by every envelope above the engine (the web sample's 400s, the
        fleet router, ``generate_ex``) so a bad request is rejected
        identically everywhere.  Returns the normalized
        (temperature, top_k, top_p, seed)."""
        t = float(temperature)
        if not np.isfinite(t) or t < 0.0:
            raise ValueError(
                f"temperature must be a finite value >= 0, got "
                f"{temperature!r}")
        if top_k is not None:
            top_k = int(top_k)
            if top_k < 1:
                raise ValueError(f"top_k must be >= 1, got {top_k}")
        if top_p is not None:
            top_p = float(top_p)
            if not (0.0 < top_p <= 1.0):
                raise ValueError(
                    f"top_p must lie in (0, 1], got {top_p}")
        seed = int(seed)
        if not (0 <= seed < 2 ** 31):
            raise ValueError(
                f"seed must lie in [0, 2**31), got {seed}")
        return t, top_k, top_p, seed

    def _validate(self, prompt_ids, max_new_tokens, temperature=0.0,
                  top_k=None, top_p=None, seed=0):
        """Shared request validation — raises ValueError, mutates
        nothing: (1-D prompt, length, bucket, max_new, sampling
        tuple).  ``generate`` pre-validates EVERY row through this
        before its first submit, so a bad late row cannot orphan
        earlier rows mid-decode."""
        prompt = np.asarray(prompt_ids)
        if prompt.ndim == 2 and prompt.shape[0] == 1:
            prompt = prompt[0]
        if prompt.ndim != 1 or prompt.size < 1:
            raise ValueError(
                f"prompt_ids must be a non-empty 1-D id sequence, got "
                f"shape {prompt.shape}")
        max_new = int(max_new_tokens)
        if max_new < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {max_new}")
        L = int(prompt.shape[0])
        if L + max_new > self.max_len:
            raise ValueError(
                f"prompt ({L}) + max_new_tokens ({max_new}) exceeds "
                f"max_len ({self.max_len})")
        samp = self.validate_sampling(temperature, top_k, top_p, seed)
        return prompt, L, self.bucket_for(L), max_new, samp

    def submit(self, prompt_ids, max_new_tokens: int,
               eos_id: Optional[int] = None, span=None,
               temperature: float = 0.0, top_k: Optional[int] = None,
               top_p: Optional[float] = None,
               seed: int = 0) -> TokenStream:
        """Queue one prompt for continuous-batching decode; returns its
        :class:`TokenStream` immediately.  ``prompt_ids``: 1-D int ids
        (a (1, L) row is accepted too).  ``eos_id`` overrides the
        engine default; decoding stops at EOS (included in the stream)
        or after ``max_new_tokens``, whichever is first.
        ``temperature`` > 0 samples (optionally top-k/top-p truncated)
        from the per-request ``(seed, token index)`` fold_in stream —
        resubmitting the same (prompt, sampling params, seed) replays
        the same tokens regardless of engine occupancy."""
        prompt, L, bucket, max_new, samp = self._validate(
            prompt_ids, max_new_tokens, temperature, top_k, top_p,
            seed)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :L] = prompt
        with self._id_lock:
            self._next_id += 1
            rid = self._next_id
        stream = TokenStream(rid)
        if span is not None:
            # opened on the caller's thread: covers queue time until
            # the dispatcher starts this request's prefill
            span.phase_start("decode_wait")
        req = _DecodeRequest(padded, L, bucket, max_new,
                             self.eos_id if eos_id is None else eos_id,
                             stream, span, temperature=samp[0],
                             top_k=samp[1], top_p=samp[2],
                             seed=samp[3])
        with self._submit_lock:
            if self.closed:
                raise DecodeEngineClosedError(
                    "DecodeEngine is closed — no dispatcher is "
                    "serving this queue")
            self._q.put(req)
            # waits out an in-flight warmup — the dispatcher only
            # begins once the warms are done
            self._ensure_started()
        if self._crashed or not self._thread.is_alive():
            # the dispatcher died between the closed check and the
            # enqueue — flush anything stranded (same crash-net race
            # the coalescer's submit covers)
            self._flush_queue(DecodeEngineClosedError(
                "DecodeEngine dispatcher died"))
        return stream

    def generate(self, prompts, max_new_tokens, eos_id=None,
                 timeout: Optional[float] = None, span=None,
                 temperature: float = 0.0, top_k: Optional[int] = None,
                 top_p: Optional[float] = None,
                 seed=0) -> List[np.ndarray]:
        """Blocking convenience over :meth:`submit`: decode a batch of
        prompts (a (B, L) array, or a list of 1-D ragged rows) and
        return each row's generated continuation (1-D int32).
        ``max_new_tokens`` and ``seed`` may be per-row (a sequence) or
        shared; ``temperature``/``top_k``/``top_p`` are shared.
        ``span`` rides the request when there is exactly one row (a
        span is single-owner; batch rows would interleave phases)."""
        rows = ([np.asarray(prompts[i]) for i in range(len(prompts))]
                if isinstance(prompts, (list, tuple))
                else [r for r in np.asarray(prompts)])
        if np.ndim(max_new_tokens) == 0:
            max_news = [int(max_new_tokens)] * len(rows)
        else:
            max_news = [int(m) for m in max_new_tokens]
            if len(max_news) != len(rows):
                raise ValueError(
                    f"max_new_tokens has {len(max_news)} entries for "
                    f"{len(rows)} prompts")
        if np.ndim(seed) == 0:
            seeds = [int(seed)] * len(rows)
        else:
            seeds = [int(s) for s in seed]
            if len(seeds) != len(rows):
                raise ValueError(
                    f"seed has {len(seeds)} entries for "
                    f"{len(rows)} prompts")
        # all-or-nothing: validate EVERY row before the first submit,
        # so a bad late row can't leave earlier rows decoding into
        # abandoned streams (burning slots the caller gave up on)
        for r, m, s in zip(rows, max_news, seeds):
            self._validate(r, m, temperature, top_k, top_p, s)
        streams = [self.submit(r, m, eos_id=eos_id,
                               span=span if len(rows) == 1 else None,
                               temperature=temperature, top_k=top_k,
                               top_p=top_p, seed=s)
                   for (r, m, s) in zip(rows, max_news, seeds)]
        return [s.result(timeout=timeout) for s in streams]

    # ---- stats ----------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """Point-in-time decode counters (re-exported per model by
        ``InferenceModel.serving_stats`` and the Prometheus bridge)."""
        out = dict(self._counters)
        out.update(capacity=self.capacity,
                   slots_active=self._occupancy,
                   queued=self._q.qsize(),
                   prompt_buckets=self.prompt_buckets,
                   prefill_hits=dict(self._bucket_stats["hits"]),
                   prefill_misses=dict(self._bucket_stats["misses"]),
                   prefill_compile_time_s=dict(
                       self._bucket_stats["compile_time_s"]))
        pool = self._prefix_pool
        out["prefix_pool_size"] = pool.size if pool is not None else 0
        out["prefix_pool_entries"] = (len(pool.entries)
                                      if pool is not None else 0)
        out["spec_enabled"] = self._draft_hyper is not None
        if self._mesh_spec is not None:
            out["mesh_axes"] = dict(self._mesh_spec["axes"])
            out["mesh_devices"] = int(np.prod(
                list(self._mesh_spec["axes"].values())))
        proposed = out.get("spec_proposed", 0)
        out["spec_acceptance"] = (
            round(out.get("spec_accepted", 0) / proposed, 4)
            if proposed else None)
        return out

    # ---- dispatcher -----------------------------------------------------
    def _flush_queue(self, exc: BaseException):
        try:
            while True:
                r = self._q.get_nowait()
                if r is not _SHUTDOWN:
                    if r.span is not None:
                        r.span.phase_end()
                    r.stream._finish(exc)
        except queue.Empty:
            pass

    def close(self, timeout: float = 5.0):
        """Stop the dispatcher: active slots finish their streams
        first (graceful drain), queued-but-unadmitted requests are
        admitted and served ahead of the shutdown sentinel; anything
        racing the shutdown fails with DecodeEngineClosedError."""
        with self._submit_lock:
            already = self._closed
            self._closed = True
            if not already and self._thread.is_alive():
                self._q.put(_SHUTDOWN)
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)
        if not self._thread.is_alive():
            self._flush_queue(DecodeEngineClosedError(
                "DecodeEngine closed"))

    def _samp_scalars(self, req: _DecodeRequest):
        """The request's sampling scalars as committed device values —
        explicit device_put like every other host->device hop in the
        loop (a bare python float into a jit is an implicit transfer
        of its own)."""
        return (jax.device_put(np.int32(req.seed), self._rep),
                jax.device_put(np.float32(req.temperature),
                               self._rep),
                jax.device_put(np.int32(req.top_k or 0), self._rep),
                jax.device_put(np.float32(1.0 if req.top_p is None
                                          else req.top_p),
                               self._rep))

    def _admit_monolithic(self, req: _DecodeRequest, slot: int) -> int:
        """The single-plan admission: one prefill+insert dispatch for
        the whole padded prompt (the v1 path — every engine without a
        prefix pool, and pool-ineligible short prompts)."""
        fresh = req.bucket not in self._admit_fns
        stat = ("misses" if (fresh
                             and req.bucket
                             not in self._bucket_stats["misses"])
                else "hits")
        self._bucket_stats[stat][req.bucket] = \
            self._bucket_stats[stat].get(req.bucket, 0) + 1
        # the timer starts BEFORE the plan build: on an unwarmed
        # engine the AOT compile (or store load) happens inside
        # _admit_fn_for, and compile_time_s must cover it
        t0 = time.perf_counter()
        fn = self._admit_fn_for(req.bucket)
        # every host->device hop is explicit (device_put), so the loop
        # stays clean under zoolint.sanitize() transfer guards
        prompt_dev = jax.device_put(req.prompt, self._rep)
        length_dev = jax.device_put(np.int32(req.length), self._rep)
        slot_dev = jax.device_put(np.int32(slot), self._rep)
        scalars = self._samp_scalars(req)
        _profile.note_transfer("h2d")
        (self._caches, self._dcaches, self._tok, self._pos,
         self._samp, tok0) = fn(
            self._caches, self._dcaches, self._tok, self._pos,
            self._samp, prompt_dev, length_dev, slot_dev, *scalars)
        tok0 = int(jax.device_get(tok0))
        _profile.note_transfer("d2h")
        if fresh:
            self._bucket_stats["compile_time_s"][req.bucket] = \
                self._bucket_stats["compile_time_s"].get(
                    req.bucket, 0.0) + (time.perf_counter() - t0)
        return tok0

    def _prefix_lookup(self, key: str) -> Optional[_PrefixEntry]:
        """Prefix-pool read — hot: once per pool-eligible admission;
        a miss is the signal to recompute (and re-pool) the block."""
        ent = self._prefix_pool.get(key)
        if ent is None:
            self._counters["prefix_misses"] += 1
        else:
            self._counters["prefix_hits"] += 1
        return ent

    def _admit_prefix(self, req: _DecodeRequest, slot: int) -> int:
        """Pool-eligible admission: split the prompt at its largest
        bucket boundary, serve the prefix block from the pool (or
        recompute + pool it), and run the (prefix, bucket) pair's
        memcpy+tail plan.  Hit or miss, the tail plan consumes
        bit-identical prefix blocks, so the streams cannot differ."""
        p_b = self._prefix_bucket_for(req.length)
        s_b = req.bucket
        # same fresh-compile accounting as the monolithic path: an
        # unwarmed engine's inline pfxfill/pfxadmit builds count as a
        # bucket MISS with their compile time recorded, never as a hit
        fresh = ((p_b, s_b) not in self._pfxadmit_fns
                 or p_b not in self._pfxfill_fns)
        stat = ("misses" if (fresh
                             and s_b
                             not in self._bucket_stats["misses"])
                else "hits")
        self._bucket_stats[stat][s_b] = \
            self._bucket_stats[stat].get(s_b, 0) + 1
        t0 = time.perf_counter()
        key = _PrefixPool.key(req.prompt[0, :p_b])
        ent = self._prefix_lookup(key)
        if ent is None:
            pfx_dev = jax.device_put(
                np.ascontiguousarray(req.prompt[:, :p_b]),
                self._device)
            _profile.note_transfer("h2d")
            pkv, h_last = self._pfxfill_fn_for(p_b)(pfx_dev)
            ent = _PrefixEntry(pkv, h_last, p_b)
            self._counters["prefix_evictions"] += \
                self._prefix_pool.put(key, ent)
        fn = self._pfxadmit_fn_for(p_b, s_b)
        tail = np.zeros((1, s_b - p_b), np.int32)
        tail[0, :req.length - p_b] = req.prompt[0, p_b:req.length]
        tail_dev = jax.device_put(tail, self._device)
        length_dev = jax.device_put(np.int32(req.length), self._device)
        slot_dev = jax.device_put(np.int32(slot), self._device)
        scalars = self._samp_scalars(req)
        _profile.note_transfer("h2d")
        (self._caches, self._tok, self._pos, self._samp, tok0) = fn(
            self._caches, self._tok, self._pos, self._samp, ent.kv,
            ent.h_last, tail_dev, length_dev, slot_dev, *scalars)
        tok0 = int(jax.device_get(tok0))
        _profile.note_transfer("d2h")
        if fresh:
            self._bucket_stats["compile_time_s"][s_b] = \
                self._bucket_stats["compile_time_s"].get(s_b, 0.0) \
                + (time.perf_counter() - t0)
        return tok0

    def _admit_slot(self, req: _DecodeRequest, slot: int):
        """Admit one queued request into ``slot``: run its admission
        plan (monolithic, or prefix-pooled when eligible), stream the
        first token, and activate the slot — or finish the request
        immediately when the first token already ends it (EOS /
        max_new == 1)."""
        span = req.span
        if span is not None:
            span.phase_start("prefill")
        if (self._prefix_pool is not None
                and req.length >= self.prompt_buckets[0]):
            tok0 = self._admit_prefix(req, slot)
        else:
            tok0 = self._admit_monolithic(req, slot)
        self._counters["prefills"] += 1
        self._counters["admitted"] += 1
        self._counters["tokens"] += 1
        if req.temperature > 0.0:
            self._counters["sampled_tokens"] += 1
        req.produced = 1
        req.scheduled = 1
        req.stream._push(tok0)
        if span is not None:
            span.set_label("decode_bucket", req.bucket)
            span.set_label("decode_slot", slot)
        done = (req.produced >= req.max_new
                or (req.eos_id is not None and tok0 == req.eos_id))
        if done:
            if span is not None:
                span.phase_end()
            self._counters["evicted"] += 1
            req.stream._finish()
            self._free.append(slot)
            return
        if span is not None:
            # one phase for the whole shared-step participation —
            # per-step phases would be ring-buffer noise at 128 steps
            span.phase_start("decode_step")
        req.slot = slot
        self._slots[slot] = req
        self._occupancy += 1

    def _choose_fuse(self) -> int:
        """Window size for the next dispatch.  The invariant: a fused
        window must not CROSS a scheduling event, so admissions and
        evictions land on exactly the same step indices as pure
        per-step dispatching — fusion changes overhead, never the
        schedule.  The window is therefore the minimum
        remaining-to-schedule over active slots (an EOS-capable
        request counts as 1 — it can end on any step), clamped to the
        compiled plan ladder.

        One deliberate exception: with an EMPTY queue, the full
        ``step_fuse`` window is taken even past a request's end —
        nobody is waiting for its slot, its surplus tokens are
        truncated at fan-out, and the only cost is up to K-1 extra
        slot-steps of garbage against K-fold fewer dispatches on the
        drain tail.  (A request submitted mid-window waits at most
        ~K step-times for admission — the same order as the
        coalescer's gather grace.)

        ``scheduled`` (not ``produced``) drives the remaining check:
        the pipeline may hold one dispatched-unprocessed window, and
        planning from ``produced`` would double-schedule it."""
        if not self._fuse_sizes:
            return 1
        if self._q.empty():
            return self.step_fuse
        rem = self.step_fuse
        for req in self._slots:
            if req is None:
                continue
            r = (1 if req.eos_id is not None
                 else req.max_new - req.scheduled)
            if r < rem:
                rem = r
                if rem <= 1:
                    return 1
        for k in self._fuse_sizes:
            if k <= rem:
                return k
        return 1

    def _dispatch_step(self):
        """Dispatch the next decode window WITHOUT fetching (jax
        dispatch is asynchronous) and snapshot the slot->request map as
        of this dispatch — the fetch side fans tokens out against the
        snapshot, so an eviction or admission that happens while the
        device computes cannot mis-route a token.  Returns
        (token vector or (k, capacity) matrix, acceptance vector or
        None, snapshot, window)."""
        if self._step_fn is None:
            # unwarmed engine: build (or store-load) the step plans
            # inline, once — warmed engines pay one is-None check
            self._ensure_step_plans()
        if self._draft_hyper is not None:
            return self._dispatch_spec()
        k = self._choose_fuse()
        if k > 1:
            (self._caches, self._tok, self._pos, self._samp,
             toks) = self._stepk_fns[k](self._caches, self._tok,
                                        self._pos, self._samp)
            self._counters["fused_dispatches"] += 1
        else:
            (self._caches, self._tok, self._pos,
             self._samp) = self._step_fn(self._caches, self._tok,
                                         self._pos, self._samp)
            toks = self._tok
        self._counters["steps"] += k
        for req in self._slots:
            if req is not None:
                req.scheduled += k
        return toks, None, list(self._slots), k

    def _dispatch_spec(self):
        """Dispatch one speculative window (draft scan + exact step +
        verify, ONE executable) — same snapshot discipline as
        :meth:`_dispatch_step`; the acceptance vector rides the
        pending tuple so the fetch side knows how many of each slot's
        ``spec_tokens`` candidates are valid."""
        k = self.spec_tokens
        (self._caches, self._dcaches, self._tok, self._pos,
         self._samp, toks, acc) = self._spec_fn(
            self._caches, self._dcaches, self._tok, self._pos,
            self._samp)
        self._counters["steps"] += k
        self._counters["spec_windows"] += 1
        for req in self._slots:
            if req is not None:
                req.scheduled += k
        return toks, acc, list(self._slots), k

    def _push_window(self, snapshot, toks, counts):
        """Fan one fetched window out to the slots live at dispatch
        time, evicting finished requests: ``toks`` is (k, capacity),
        ``counts[slot]`` how many of the k rows are valid for that
        slot.  A request that finished in an EARLIER window's
        processing (the pipeline dispatches window n+1 before window n
        is processed, so its snapshot can still name it) is skipped —
        its stream is closed and the slot's extra computed tokens are
        garbage by construction, as are any tokens past a request's
        max_new/EOS inside a window."""
        for slot, req in enumerate(snapshot):
            if req is None or req.stream.done:
                continue
            sampled = req.temperature > 0.0
            for j in range(counts[slot]):
                tok = int(toks[j, slot])
                req.produced += 1
                self._counters["tokens"] += 1
                if sampled:
                    self._counters["sampled_tokens"] += 1
                req.stream._push(tok)
                if (req.produced >= req.max_new
                        or (req.eos_id is not None
                            and tok == req.eos_id)):
                    if req.span is not None:
                        req.span.phase_end()
                    self._counters["evicted"] += 1
                    self._occupancy -= 1
                    req.stream._finish()
                    self._slots[slot] = None
                    self._free.append(slot)
                    break

    def _process_step(self, pending):
        """Fetch a dispatched window ((capacity,) single step,
        (K, capacity) fused) and fan it out against its snapshot."""
        tok_dev, acc_dev, snapshot, k = pending
        if acc_dev is not None:
            return self._process_spec(pending)
        toks = jax.device_get(tok_dev)
        _profile.note_transfer("d2h")
        if k == 1:
            toks = toks.reshape(1, -1)
        self._push_window(snapshot, toks, [k] * self.capacity)

    def _process_spec(self, pending):
        """Fetch a speculative window's (spec_tokens, capacity)
        candidate matrix + acceptance vector and fan out each slot's
        ACCEPTED tokens (at least the exact fallback token, at most
        the whole window) — the verify loop's host half, hot once per
        window."""
        tok_dev, acc_dev, snapshot, k = pending
        toks = jax.device_get(tok_dev)
        acc = jax.device_get(acc_dev)
        _profile.note_transfer("d2h")
        counts = [0] * self.capacity
        for slot, req in enumerate(snapshot):
            if req is None or req.stream.done:
                continue
            counts[slot] = int(acc[slot])
            # acceptance accounting covers live slots only — free
            # slots compute garbage windows that must not dilute the
            # reported acceptance rate
            self._counters["spec_proposed"] += k - 1
            self._counters["spec_accepted"] += int(acc[slot]) - 1
        self._push_window(snapshot, toks, counts)

    def _decode_loop(self):
        try:
            self._loop_inner()
        except BaseException as e:  # crash net: never strand a caller
            # _crashed (this is its ONLY writer; the closed property
            # folds it in) flips BEFORE the lock barrier: a submit
            # already inside its critical section finishes the enqueue
            # and its own post-put check flushes, one entering after
            # sees closed and raises.  The acquire is a BARRIER, not a
            # guard — bounded because a submitter blocked on a full
            # queue holds the lock until our flush below frees a slot,
            # so we must not wait on it forever.
            self._crashed = True
            got = self._submit_lock.acquire(timeout=1.0)
            if got:
                self._submit_lock.release()
            self._flush_queue(e)
            for slot, req in enumerate(self._slots):
                if req is not None:
                    if req.span is not None:
                        req.span.phase_end()
                    req.stream._finish(e)
                    self._slots[slot] = None
            self._occupancy = 0
            raise

    def _loop_inner(self):
        # one-deep step pipeline: step k+1 is DISPATCHED before step
        # k's tokens are fetched, so the host side (token fan-out,
        # eviction, stream wake-ups, the next admission) overlaps the
        # device compute instead of serializing with it — the
        # serving-side analog of the coalescer's one-deep dispatch
        # pipeline.  Cost: an eviction is observed one step late, so a
        # freed slot re-admits one step later (bounded occupancy
        # slack, never a correctness issue — see _process_step).
        pending = None
        shutdown = False
        while True:
            # 1. admit queued requests into free slots — between
            # steps, which is what makes the batching iteration-level
            while self._free and not shutdown:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is _SHUTDOWN:
                    shutdown = True
                    break
                self._admit_slot(nxt, self._free.popleft())
            # 2. dispatch the next step, then fan out the previous one
            nxt_pending = (self._dispatch_step() if self._occupancy
                           else None)
            if pending is not None:
                self._process_step(pending)
            pending = nxt_pending
            # 3. idle: wait for work (or drain out on shutdown)
            if pending is None and not self._occupancy:
                if shutdown:
                    return
                try:
                    nxt = self._q.get(timeout=0.05)
                except queue.Empty:
                    continue
                if nxt is _SHUTDOWN:
                    shutdown = True
                    continue
                self._admit_slot(nxt, self._free.popleft())
