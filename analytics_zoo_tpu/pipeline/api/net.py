"""Net loaders + GraphNet: model import and transfer-learning surgery.

Parity surface: reference zoo/.../pipeline/api/Net.scala:89-189 (load /
load_bigdl / load_caffe / load_torch / load_tf / load_keras) and GraphNet
(pyzoo/zoo/pipeline/api/net.py:43-108: new_graph, freeze_up_to, unfreeze,
to_keras; scala trait NetUtils.scala:216-277).

Import policy (SURVEY §7 + §2.9): the framework's own format loads
natively; Keras models and frozen TF graphs import through the GraphDef→
jax converter (TFNet) — no embedded TF runtime at inference time;
pytorch state_dicts transfer through the layout converter; only the dead
legacy formats (Caffe, Torch7 .t7 archives) raise with guidance.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ...core.graph import GraphModule, Variable
from .keras.engine import KerasNet, Model


class Net:
    """Static loaders (reference Net.scala:89-189)."""

    @staticmethod
    def load(path: str, weight_path: Optional[str] = None) -> KerasNet:
        """Load a model saved by this framework (reference Net.load reads
        the zoo/BigDL protobuf format)."""
        net = KerasNet.load_model(path)
        if weight_path is not None:
            net.load_weights(weight_path)
        return net

    load_bigdl = load  # the native format IS this framework's format here

    @staticmethod
    def load_keras(json_path: Optional[str] = None,
                   hdf5_path: Optional[str] = None,
                   input_shape: Optional[Sequence[int]] = None):
        """Import a Keras model (reference Net.load_keras): the model is
        loaded with tf.keras (.h5 / .keras / SavedModel dir, or a
        json+hdf5 pair), frozen to a GraphDef, and wrapped as a
        :class:`TFNet` layer running on the jax graph converter — no TF
        runtime at inference time."""
        import tensorflow as tf

        if json_path is not None:
            with open(json_path) as f:
                km = tf.keras.models.model_from_json(f.read())
            if hdf5_path is not None:
                km.load_weights(hdf5_path)
        elif hdf5_path is not None:
            km = tf.keras.models.load_model(hdf5_path, compile=False)
        else:
            raise ValueError("pass json_path and/or hdf5_path")
        return Net.from_tf_keras(km, input_shape=input_shape)

    @staticmethod
    def from_tf_keras(keras_model, input_shape: Optional[Sequence[int]]
                      = None):
        """Freeze a LIVE tf.keras model into a TFNet layer."""
        import tensorflow as tf
        from tensorflow.python.framework.convert_to_constants import (
            convert_variables_to_constants_v2)
        from .tfgraph.net import TFNet

        if input_shape is None:
            # respect each input's declared dtype (int inputs feeding an
            # Embedding must not trace as float placeholders)
            specs = [tf.TensorSpec([None] + list(t.shape[1:]), t.dtype)
                     for t in keras_model.inputs]
        else:
            specs = [tf.TensorSpec([None] + list(input_shape),
                                   keras_model.inputs[0].dtype
                                   if getattr(keras_model, "inputs", None)
                                   else tf.float32)]
        fn = tf.function(lambda *a: keras_model(a[0] if len(a) == 1
                                                else list(a)))
        cf = fn.get_concrete_function(*specs)
        frozen = convert_variables_to_constants_v2(cf)
        gd = frozen.graph.as_graph_def()
        return TFNet(graph_def=gd,
                     input_names=[t.name for t in frozen.inputs],
                     output_names=[t.name for t in frozen.outputs])

    @staticmethod
    def load_caffe(def_path: str, model_path: str):
        raise NotImplementedError(
            "Caffe model import is not supported in the TPU build "
            "(format retired; reference kept it only for legacy zoo "
            "weights)")

    @staticmethod
    def load_torch(path: str, net=None):
        """Torch interop: with ``net`` given, ``path`` is loaded with
        ``torch.load`` as a state_dict and transferred into ``net`` via
        the layout converter (models/weight_loading.py).  Legacy Torch7
        .t7 archives (the reference's actual format) stay unsupported —
        the module structure cannot be rebuilt from weights alone."""
        if net is None:
            raise NotImplementedError(
                "Torch7 .t7 import is not supported in the TPU build; "
                "pass net= (a structurally matching model) to load a "
                "pytorch state_dict into it via "
                "models.weight_loading.load_torch_state_dict")
        import torch
        try:
            sd = torch.load(path, map_location="cpu", weights_only=True)
        except Exception as e:
            raise ValueError(
                f"could not load {path!r} as a state_dict "
                f"(save with torch.save(model.state_dict(), path)): {e}")
        from ...models.weight_loading import load_torch_state_dict
        return load_torch_state_dict(net, sd)

    @staticmethod
    def load_onnx(path: str):
        """Load an ``.onnx`` model as an :class:`OnnxNet` layer (reference
        OnnxLoader, pyzoo/zoo/pipeline/api/onnx/onnx_loader.py:32-119).
        Uses the built-in protobuf codec — the ``onnx`` package is not
        required."""
        from .onnx import load_onnx
        return load_onnx(path)

    @staticmethod
    def load_tf(path: str, input_names: Optional[Sequence[str]] = None,
                output_names: Optional[Sequence[str]] = None):
        """Import a frozen TF graph (reference Net.load_tf / TFNet
        folder format): an export folder (pb + graph_meta.json) or a raw
        .pb with explicit input/output names, converted to jax ops — no
        embedded TF runtime."""
        from .tfgraph.net import TFNet
        return TFNet(path=path, input_names=input_names,
                     output_names=output_names)


class GraphNet(Model):
    """Model + transfer-learning surgery (reference GraphNet)."""

    @classmethod
    def from_model(cls, model: Model) -> "GraphNet":
        g = model.to_graph()
        net = cls.__new__(cls)
        KerasNet.__init__(net, name=model.name)
        net._graph = g
        net.inputs = g.input_vars
        net.outputs = g.output_vars
        return net

    def nodes(self, names: Sequence[str]) -> List[Variable]:
        by_name = {v.name: v for v in self._graph.nodes}
        return [by_name[n] for n in names]

    def freeze_up_to(self, names: Sequence[str]) -> "GraphNet":
        """Freeze every layer from the inputs up to (inclusive) the named
        nodes (reference freezeUpTo, NetUtils.scala:216-277): their
        weights stop receiving gradients."""
        targets = self.nodes(names)
        frozen_ids = set()
        for t in targets:
            for v in t.ancestors():
                frozen_ids.add(v.node_id)
        for v in self._graph.nodes:
            if v.node_id in frozen_ids and v.layer is not None:
                v.layer.trainable = False
        return self._sync_freeze()

    def unfreeze(self) -> "GraphNet":
        for layer in self._graph.layers:
            layer.trainable = True
        return self._sync_freeze()

    def frozen_layer_names(self) -> List[str]:
        return [l.name for l in self._graph.layers if not l.trainable]

    def to_keras(self) -> Model:
        """reference GraphNet.to_keras: it already IS a keras Model."""
        return self
