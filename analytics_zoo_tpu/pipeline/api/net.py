"""Net loaders + GraphNet: model import and transfer-learning surgery.

Parity surface: reference zoo/.../pipeline/api/Net.scala:89-189 (load /
load_bigdl / load_caffe / load_torch / load_tf / load_keras) and GraphNet
(pyzoo/zoo/pipeline/api/net.py:43-108: new_graph, freeze_up_to, unfreeze,
to_keras; scala trait NetUtils.scala:216-277).

Import policy (SURVEY §7 non-goals + §2.9): the framework's own format
loads natively; TF interop is replaced by jax-native functions served via
``InferenceModel.load_jax`` (there is no embedded TF runtime to port —
TFNet's JNI session was the thing being replaced); Caffe/Torch-legacy
formats are dead and raise with guidance.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from ...core.graph import GraphModule, Variable
from .keras.engine import KerasNet, Model


class Net:
    """Static loaders (reference Net.scala:89-189)."""

    @staticmethod
    def load(path: str, weight_path: Optional[str] = None) -> KerasNet:
        """Load a model saved by this framework (reference Net.load reads
        the zoo/BigDL protobuf format)."""
        net = KerasNet.load_model(path)
        if weight_path is not None:
            net.load_weights(weight_path)
        return net

    load_bigdl = load  # the native format IS this framework's format here

    @staticmethod
    def load_keras(json_path: Optional[str] = None,
                   hdf5_path: Optional[str] = None):
        raise NotImplementedError(
            "Keras-1 HDF5 import is not supported in the TPU build; "
            "define the model with analytics_zoo_tpu.pipeline.api.keras "
            "(same layer surface) and load weights via checkpoints")

    @staticmethod
    def load_caffe(def_path: str, model_path: str):
        raise NotImplementedError(
            "Caffe model import is not supported in the TPU build "
            "(format retired; reference kept it only for legacy zoo "
            "weights)")

    @staticmethod
    def load_torch(path: str):
        raise NotImplementedError(
            "Torch7 .t7 import is not supported in the TPU build; for "
            "pytorch interop convert weights to a checkpoint pytree")

    @staticmethod
    def load_onnx(path: str):
        """Load an ``.onnx`` model as an :class:`OnnxNet` layer (reference
        OnnxLoader, pyzoo/zoo/pipeline/api/onnx/onnx_loader.py:32-119).
        Uses the built-in protobuf codec — the ``onnx`` package is not
        required."""
        from .onnx import load_onnx
        return load_onnx(path)

    @staticmethod
    def load_tf(path: str):
        raise NotImplementedError(
            "Frozen-GraphDef import is replaced in the TPU build: wrap "
            "the computation as a jax function and serve it with "
            "InferenceModel.load_jax (the reference's TFNet existed to "
            "embed a TF runtime, which this framework replaces outright)")


class GraphNet(Model):
    """Model + transfer-learning surgery (reference GraphNet)."""

    @classmethod
    def from_model(cls, model: Model) -> "GraphNet":
        g = model.to_graph()
        net = cls.__new__(cls)
        KerasNet.__init__(net, name=model.name)
        net._graph = g
        net.inputs = g.input_vars
        net.outputs = g.output_vars
        return net

    def nodes(self, names: Sequence[str]) -> List[Variable]:
        by_name = {v.name: v for v in self._graph.nodes}
        return [by_name[n] for n in names]

    def freeze_up_to(self, names: Sequence[str]) -> "GraphNet":
        """Freeze every layer from the inputs up to (inclusive) the named
        nodes (reference freezeUpTo, NetUtils.scala:216-277): their
        weights stop receiving gradients."""
        targets = self.nodes(names)
        frozen_ids = set()
        for t in targets:
            for v in t.ancestors():
                frozen_ids.add(v.node_id)
        for v in self._graph.nodes:
            if v.node_id in frozen_ids and v.layer is not None:
                v.layer.trainable = False
        return self

    def unfreeze(self) -> "GraphNet":
        for layer in self._graph.layers:
            layer.trainable = True
        return self

    def frozen_layer_names(self) -> List[str]:
        return [l.name for l in self._graph.layers if not l.trainable]

    def to_keras(self) -> Model:
        """reference GraphNet.to_keras: it already IS a keras Model."""
        return self
