"""Shared static-vs-traced dispatch layer for the graph importers.

Both graph converters (tfgraph/converter.py and onnx/converter.py) keep
shape-math subgraphs host-side in numpy so traced shapes stay static under
``jit``.  This module is the single home of that dispatch logic so the two
importers cannot drift.
"""

from __future__ import annotations

from typing import List

import numpy as np
import jax


def is_static(v) -> bool:
    return isinstance(v, (np.ndarray, np.generic, int, float, bool))


def require_static(v, what: str):
    """Require a host-static value (shape math); fail with guidance."""
    if not is_static(v):
        raise ValueError(
            f"{what} must be statically known for XLA (got a traced "
            "value); keep shape-producing subgraphs free of graph inputs")
    return np.asarray(v)


def static_ints(v, what: str) -> List[int]:
    return [int(x) for x in np.atleast_1d(require_static(v, what))]


def np_or_jnp(np_fn, jnp_fn):
    """N-ary op that stays in numpy when all args are static."""
    def h(*args):
        if all(is_static(a) for a in args):
            return np_fn(*args)
        return jnp_fn(*args)
    return h


class ConvertCtx:
    """Per-call conversion context: params, threaded rng, training flag."""

    def __init__(self, params, rng, training):
        self.params = params
        self.rng = rng
        self.training = training
        self.node_seq = 0

    def next_rng(self):
        if self.rng is None:
            raise ValueError(
                "graph contains random ops (dropout?); pass rng= to the "
                "converted function")
        self.node_seq += 1
        return jax.random.fold_in(self.rng, self.node_seq)
