"""autograd DSL: Variable ops, Parameter, Lambda, CustomLoss.

Parity surface: reference zoo/.../pipeline/api/autograd (math.scala:32-567,
KerasParameter.scala:31-67, Lambda.scala:49, CustomLoss.scala:29-66) and the
python mirror pyzoo/zoo/pipeline/api/autograd.py:31-559.

The reference's "autograd" is graph-node composition whose backward is each
wrapped BigDL module's hand-written updateGradInput — NOT tape autodiff.
Here every op is a node in the same symbolic graph the functional API uses
(core/graph.py) and differentiation is real ``jax.grad`` through the traced
computation, so custom losses/layers need no per-op backward definitions.

Axis convention: axes index the full array (batch = axis 0), matching jnp.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ...core import shapes as shape_utils
from ...core.graph import GraphModule, Input, Variable
from ...core.module import Layer, register_layer
from ...ops import elementwise as _ops

# ---- module-level ops (reference autograd.py:31-246) ----
abs = _ops.abs  # noqa: A001
sum = _ops.sum  # noqa: A001
clip = _ops.clip
square = _ops.square
sqrt = _ops.sqrt
maximum = _ops.maximum
minimum = _ops.minimum
mean = _ops.mean
max = _ops.max  # noqa: A001
min = _ops.min  # noqa: A001
log = _ops.log
exp = _ops.exp
pow = _ops.pow  # noqa: A001
softsign = _ops.softsign
softplus = _ops.softplus
stack = _ops.stack
concat = _ops.concat
expand_dims = _ops.expand_dims
squeeze = _ops.squeeze
contiguous = _ops.contiguous
mm = _ops.mm
batch_dot = _ops.batch_dot
l2_normalize = _ops.l2_normalize
constant = _ops.constant
relu = _ops.relu
sigmoid = _ops.sigmoid
tanh = _ops.tanh
slice = _ops.slice  # noqa: A001
index_select = _ops.index_select


def epsilon() -> float:
    """Fuzz factor (reference AutoGrad.epsilon, math.scala:116)."""
    return _ops.epsilon()


@register_layer
class ParameterLayer(Layer):
    """Zero-input node holding a standalone trainable weight
    (reference KerasParameter.scala:31-67)."""

    is_source = True

    def __init__(self, shape=None, init_method="glorot_uniform",
                 init_weight=None, name=None, input_shape=None,
                 trainable=True):
        super().__init__(name=name, input_shape=input_shape,
                         trainable=trainable)
        self.shape = tuple(int(d) for d in shape)
        self.init_method = init_method
        self.init_weight = (np.asarray(init_weight, dtype=np.float32)
                            if init_weight is not None else None)

    def init_params(self, rng, input_shape):
        from ...core import initializers
        if self.init_weight is not None:
            w = jnp.asarray(self.init_weight)
        else:
            w = initializers.get(self.init_method)(rng, self.shape)
        return {"weight": w}

    def call(self, params, state, inputs, training=False, rng=None):
        return params["weight"]

    def compute_output_shape(self, input_shape):
        return self.shape

    def get_config(self):
        cfg = super().get_config()
        cfg.update(shape=list(self.shape), init_method=self.init_method,
                   init_weight=None if self.init_weight is None
                   else self.init_weight.tolist(),
                   trainable=self.trainable)
        return cfg


def Parameter(shape, init_method="glorot_uniform", init_weight=None,
              name=None) -> Variable:
    """Create a trainable weight Variable usable inside expressions
    (reference autograd.py:455 Parameter).  Shape has NO batch dim."""
    layer = ParameterLayer(shape=shape, init_method=init_method,
                           init_weight=init_weight, name=name)
    return Variable(layer, (), tuple(layer.shape), name=layer.name)


@register_layer
class Lambda(Layer):
    """User function as a layer (reference Lambda.scala:49,
    autograd.py:397).

    The function receives jnp arrays (single input) or a list of them and
    returns a jnp array; output shape is inferred by abstract tracing
    (``jax.eval_shape``) so the graph stays statically shaped.  Note:
    functions are not serializable — models containing Lambda layers
    save/load weights but need the code to rebuild (same restriction the
    reference has in practice: Lambda closures never round-trip the bridge).
    """

    stochastic = True

    def __init__(self, function: Callable = None, input_shape=None,
                 name=None):
        super().__init__(input_shape=input_shape, name=name)
        if function is None:
            raise ValueError("Lambda requires a function")
        self.function = function

    def call(self, params, state, inputs, training=False, rng=None):
        if isinstance(inputs, (list, tuple)):
            return self.function(*inputs)
        return self.function(inputs)

    def compute_output_shape(self, input_shape):
        multi = isinstance(input_shape[0], (tuple, list))
        shapes = input_shape if multi else [input_shape]
        dummies = [
            jax.ShapeDtypeStruct(
                tuple(2 if d is None else d for d in s), jnp.float32)
            for s in shapes]
        out = jax.eval_shape(lambda *xs: self.function(*xs), *dummies)
        batch_unknown = shapes[0][0] is None
        out_shape = tuple(out.shape)
        if batch_unknown and len(out_shape) > 0:
            return (None,) + out_shape[1:]
        return out_shape

    def get_config(self):
        cfg = super().get_config()
        cfg["function"] = None  # not serializable
        return cfg


class CustomLoss:
    """Build a loss from an expression over (y_true, y_pred)
    (reference CustomLoss.scala:29-66, autograd.py:501).

    ``loss_func(y_true, y_pred)`` receives jnp arrays (full batch) and
    returns per-sample losses or a scalar.  Instances are callable with the
    trainer's (y_true, y_pred) signature, so they slot directly into
    ``compile(loss=...)``.  ``from_variables`` supports the reference's
    Variable-expression form (CustomLossWithVariable).
    """

    def __init__(self, loss_func: Callable, y_pred_shape=None,
                 y_true_shape=None):
        self.loss_func = loss_func
        self.y_pred_shape = y_pred_shape
        self.y_true_shape = y_true_shape

    @classmethod
    def from_variables(cls, y_true: Variable, y_pred: Variable,
                       loss: Variable) -> "CustomLoss":
        graph = GraphModule([y_true, y_pred], loss, name="custom_loss")
        params, state = graph.init(jax.random.PRNGKey(0))

        def fn(yt, yp):
            out, _ = graph.apply(params, state, [yt, yp], training=False)
            return out

        return cls(fn)

    def __call__(self, y_true, y_pred):
        out = self.loss_func(y_true, y_pred)
        out = jnp.asarray(out)
        if out.ndim == 0:
            # scalar loss -> broadcast per-sample for the trainer's mean
            batch = (y_pred[0] if isinstance(y_pred, (list, tuple))
                     else y_pred).shape[0]
            return jnp.broadcast_to(out, (batch,))
        if out.ndim > 1:
            return jnp.mean(out, axis=tuple(range(1, out.ndim)))
        return out

    def forward(self, y_true, y_pred):
        """Reference CustomLoss.forward parity: mean scalar loss."""
        return float(jnp.mean(self(jnp.asarray(y_true),
                                   jnp.asarray(y_pred))))

    def backward(self, y_true, y_pred):
        """Reference CustomLoss.backward parity: d(mean loss)/d(y_pred) —
        real autodiff instead of the reference's module backward."""
        grad_fn = jax.grad(
            lambda yp: jnp.mean(self(jnp.asarray(y_true), yp)))
        return np.asarray(grad_fn(jnp.asarray(y_pred)))


__all__ = [
    "Variable", "Input", "Parameter", "ParameterLayer", "Lambda",
    "CustomLoss", "constant", "abs", "sum", "clip", "square", "sqrt",
    "maximum", "minimum", "mean", "max", "min", "log", "exp", "pow",
    "softsign", "softplus", "stack", "concat", "expand_dims", "squeeze",
    "contiguous", "mm", "batch_dot", "l2_normalize", "epsilon", "relu",
    "sigmoid", "tanh", "slice", "index_select",
]


# reference-name alias (autograd.py LambdaLayer wraps Lambda)
LambdaLayer = Lambda
