"""ONNX GraphProto → pure JAX function.

Parity surface: the reference ONNX importer
(pyzoo/zoo/pipeline/api/onnx/onnx_loader.py:32-119 + mapper/*.py, ~21 op
mappers) converts each node into a BigDL Keras layer.  Here each node maps
to a jnp/lax expression, so an imported model is ONE traceable function —
XLA fuses the whole graph and jax.grad differentiates it (the reference
could only fine-tune through layers its mappers produced).

Design notes (same stance as ..tfgraph.converter):
* ONNX convs/pools are NCHW; we keep that layout inside the imported
  function — XLA lays out for the MXU regardless of the logical order.
* Shape-feeding subgraphs (Shape → Concat → Reshape, Slice starts/ends,
  Pad pads, ...) are evaluated host-side in numpy so traced shapes stay
  static under jit.  Int64 initializers and Constant nodes start static;
  float initializers become params (trainable fine-tuning for free).
* Unsupported ops fail at conversion time with the op list, not mid-trace.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from .proto import GraphProto, NodeProto, attrs_dict, tensor_to_numpy
from .._convert_util import (ConvertCtx as _Ctx, is_static as _is_static,
                             np_or_jnp as _nb, require_static as _static,
                             static_ints as _ints)


# ---------------------------------------------------------------------------
# conv / pool helpers (ONNX: NCHW, weights OIHW, pads = [b..., e...])

def _spatial_rank(x) -> int:
    return x.ndim - 2


def _dim_numbers(rank: int):
    sp = "DHW"[-rank:] if rank <= 3 else None
    if sp is None:
        raise NotImplementedError(f"conv/pool spatial rank {rank}")
    return ("NC" + sp, "OI" + sp, "NC" + sp)


def _pad_pairs(attrs, rank) -> List[Tuple[int, int]]:
    pads = attrs.get("pads")
    if pads is None:
        return [(0, 0)] * rank
    return [(int(pads[i]), int(pads[i + rank])) for i in range(rank)]


def _auto_pad(attrs, rank, ks, strides):
    ap = attrs.get("auto_pad", "NOTSET")
    if ap in ("NOTSET", ""):
        return _pad_pairs(attrs, rank)
    if ap == "VALID":
        return [(0, 0)] * rank
    # SAME_UPPER / SAME_LOWER
    pairs = []
    for k, s in zip(ks, strides):
        total = max(k - s, 0) if s <= k else 0
        lo = total // 2
        hi = total - lo
        pairs.append((hi, lo) if ap == "SAME_LOWER" else (lo, hi))
    return pairs


def _conv(ctx, node, attrs, args):
    x, w = args[0], args[1]
    rank = _spatial_rank(x)
    ks = attrs.get("kernel_shape", list(w.shape[2:]))
    strides = attrs.get("strides", [1] * rank)
    dil = attrs.get("dilations", [1] * rank)
    group = attrs.get("group", 1)
    pads = _auto_pad(attrs, rank, ks, strides)
    out = lax.conv_general_dilated(
        x, w, tuple(strides), pads, rhs_dilation=tuple(dil),
        dimension_numbers=_dim_numbers(rank), feature_group_count=group)
    if len(args) > 2 and args[2] is not None:
        b = args[2]
        out = out + jnp.reshape(b, (1, -1) + (1,) * rank)
    return out


def _conv_transpose(ctx, node, attrs, args):
    x, w = args[0], args[1]
    rank = _spatial_rank(x)
    strides = attrs.get("strides", [1] * rank)
    dil = attrs.get("dilations", [1] * rank)
    group = attrs.get("group", 1)
    if group != 1:
        raise NotImplementedError("grouped ConvTranspose")
    pads = _pad_pairs(attrs, rank)
    out_pad = attrs.get("output_padding", [0] * rank)
    # ONNX ConvTranspose weight layout is (Cin, Cout/g, *k); lax wants IO
    dn = ("NC" + "DHW"[-rank:], "IO" + "DHW"[-rank:], "NC" + "DHW"[-rank:])
    # conv_transpose padding: ONNX pads shrink the output
    tpads = [(d * (k - 1) - p0, d * (k - 1) - p1 + op)
             for (p0, p1), k, d, op in zip(
                 pads, w.shape[2:], dil, out_pad)]
    out = lax.conv_general_dilated(
        x, w, (1,) * rank, tpads, lhs_dilation=tuple(strides),
        rhs_dilation=tuple(dil), dimension_numbers=dn,
        transpose_kernel=True)
    if len(args) > 2 and args[2] is not None:
        out = out + jnp.reshape(args[2], (1, -1) + (1,) * rank)
    return out


def _pool(reducer, init, is_avg=False):
    def h(ctx, node, attrs, args):
        (x,) = args
        rank = _spatial_rank(x)
        ks = attrs["kernel_shape"]
        strides = attrs.get("strides", [1] * rank)
        if attrs.get("ceil_mode", 0):
            raise NotImplementedError("pool ceil_mode=1")
        pads = _auto_pad(attrs, rank, ks, strides)
        window = (1, 1) + tuple(ks)
        wstrides = (1, 1) + tuple(strides)
        wpads = [(0, 0), (0, 0)] + pads
        summed = lax.reduce_window(x, jnp.asarray(init, x.dtype), reducer,
                                   window, wstrides, wpads)
        if not is_avg:
            return summed
        if attrs.get("count_include_pad", 0):
            return summed / np.prod(ks)
        ones = jnp.ones(x.shape, x.dtype)
        counts = lax.reduce_window(ones, jnp.zeros((), x.dtype), lax.add,
                                   window, wstrides, wpads)
        return summed / counts
    return h


def _global_pool(fn):
    def h(ctx, node, attrs, args):
        (x,) = args
        axes = tuple(range(2, x.ndim))
        return fn(x, axis=axes, keepdims=True)
    return h


def _gemm(ctx, node, attrs, args):
    a, b = args[0], args[1]
    if attrs.get("transA", 0):
        a = jnp.swapaxes(a, -1, -2)
    if attrs.get("transB", 0):
        b = jnp.swapaxes(b, -1, -2)
    out = attrs.get("alpha", 1.0) * jnp.matmul(a, b)
    if len(args) > 2 and args[2] is not None:
        out = out + attrs.get("beta", 1.0) * args[2]
    return out


def _batch_norm(ctx, node, attrs, args):
    x, scale, bias, mean, var = args[:5]
    eps = attrs.get("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    rs = lambda t: jnp.reshape(t, shape)
    return (x - rs(mean)) * rs(scale) * lax.rsqrt(rs(var) + eps) + rs(bias)


def _instance_norm(ctx, node, attrs, args):
    x, scale, bias = args
    eps = attrs.get("epsilon", 1e-5)
    red = tuple(range(2, x.ndim))
    m = jnp.mean(x, axis=red, keepdims=True)
    v = jnp.var(x, axis=red, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return (x - m) * lax.rsqrt(v + eps) * jnp.reshape(
        scale, shape) + jnp.reshape(bias, shape)


def _lrn(ctx, node, attrs, args):
    (x,) = args
    size = attrs["size"]
    alpha = attrs.get("alpha", 1e-4)
    beta = attrs.get("beta", 0.75)
    bias = attrs.get("bias", 1.0)
    sq = jnp.square(x)
    half = size // 2
    # sum over channel window via reduce_window on axis 1
    window = (1, size) + (1,) * (x.ndim - 2)
    pads = [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (x.ndim - 2)
    ssum = lax.reduce_window(sq, jnp.zeros((), x.dtype), lax.add,
                             window, (1,) * x.ndim, pads)
    return x / jnp.power(bias + (alpha / size) * ssum, beta)


def _dropout(ctx, node, attrs, args):
    x = args[0]
    ratio = attrs.get("ratio", 0.5)
    if len(args) > 1 and args[1] is not None:
        ratio = float(_static(args[1], "Dropout ratio").item())
    training = ctx.training
    if len(args) > 2 and args[2] is not None:
        training = bool(_static(args[2], "Dropout training_mode").item())
    n_out = len(node.output)
    if not training or ratio == 0.0:
        mask = jnp.ones(x.shape, bool)
        return (x, mask) if n_out > 1 else x
    keep = jax.random.bernoulli(ctx.next_rng(), 1.0 - ratio, x.shape)
    y = jnp.where(keep, x / (1.0 - ratio), 0.0).astype(x.dtype)
    return (y, keep) if n_out > 1 else y


def _reshape(ctx, node, attrs, args):
    x, shape = args[0], args[1] if len(args) > 1 else attrs.get("shape")
    tgt = _ints(shape, "Reshape shape")
    in_shape = np.asarray(x).shape if _is_static(x) else x.shape
    # ONNX: 0 = copy input dim (unless allowzero), -1 = infer
    tgt = [in_shape[i] if d == 0 and not attrs.get("allowzero", 0) else d
           for i, d in enumerate(tgt)]
    if _is_static(x):
        return np.reshape(np.asarray(x), tgt)
    return jnp.reshape(x, tgt)


def _flatten(ctx, node, attrs, args):
    (x,) = args
    ax = attrs.get("axis", 1)
    if ax < 0:  # ONNX: negative axis counts from the rank (axis += r)
        ax += x.ndim
    lead = int(np.prod(x.shape[:ax])) if ax else 1
    return jnp.reshape(x, (lead, -1))


def _squeeze(ctx, node, attrs, args):
    x = args[0]
    axes = attrs.get("axes")
    if len(args) > 1 and args[1] is not None:
        axes = _ints(args[1], "Squeeze axes")
    f = _nb(np.squeeze, jnp.squeeze)
    return f(x) if axes is None else f(x, tuple(int(a) for a in axes))


def _unsqueeze(ctx, node, attrs, args):
    x = args[0]
    axes = attrs.get("axes")
    if len(args) > 1 and args[1] is not None:
        axes = _ints(args[1], "Unsqueeze axes")
    out = x
    for ax in sorted(int(a) for a in axes):
        out = (np.expand_dims(out, ax) if _is_static(out)
               else jnp.expand_dims(out, ax))
    return out


def _slice(ctx, node, attrs, args):
    x = args[0]
    if len(args) > 1:  # opset >= 10: starts/ends/axes/steps are inputs
        starts = _ints(args[1], "Slice starts")
        ends = _ints(args[2], "Slice ends")
        axes = (_ints(args[3], "Slice axes") if len(args) > 3 and
                args[3] is not None else list(range(len(starts))))
        steps = (_ints(args[4], "Slice steps") if len(args) > 4 and
                 args[4] is not None else [1] * len(starts))
    else:  # opset < 10: attributes
        starts = attrs["starts"]
        ends = attrs["ends"]
        axes = attrs.get("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    ndim = np.asarray(x).ndim if _is_static(x) else x.ndim
    idx: List[Any] = [slice(None)] * ndim
    INT64_MAX = (1 << 63) - 1
    for s, e, a, st in zip(starts, ends, axes, steps):
        e = None if e >= INT64_MAX - 1 else e
        s_ = None if (st < 0 and s >= INT64_MAX - 1) else s
        e_ = None if (st < 0 and e is not None and e < -(1 << 62)) else e
        idx[a % ndim] = slice(s_, e_, st)
    return (np.asarray(x) if _is_static(x) else x)[tuple(idx)]


def _gather(ctx, node, attrs, args):
    data, indices = args
    axis = attrs.get("axis", 0)
    f = _nb(lambda d, i: np.take(d, np.asarray(i, np.int64), axis=axis),
            lambda d, i: jnp.take(d, i, axis=axis))
    return f(data, indices)


def _pad(ctx, node, attrs, args):
    x = args[0]
    mode = attrs.get("mode", "constant")
    if len(args) > 1 and args[1] is not None:
        pads = _ints(args[1], "Pad pads")
        cval = (float(np.asarray(_static(args[2], "Pad value")).item())
                if len(args) > 2 and args[2] is not None else 0.0)
    else:
        pads = attrs["pads"]
        cval = attrs.get("value", 0.0)
    n = len(pads) // 2
    pairs = [(pads[i], pads[i + n]) for i in range(n)]
    if mode == "constant":
        return jnp.pad(x, pairs, constant_values=cval)
    return jnp.pad(x, pairs,
                   mode="reflect" if mode == "reflect" else "edge")


def _concat(ctx, node, attrs, args):
    ax = attrs.get("axis", 0)
    if all(_is_static(a) for a in args):
        return np.concatenate([np.asarray(a) for a in args], axis=ax)
    return jnp.concatenate(args, axis=ax)


def _split(ctx, node, attrs, args):
    x = args[0]
    ax = attrs.get("axis", 0)
    sizes = attrs.get("split")
    if len(args) > 1 and args[1] is not None:
        sizes = _ints(args[1], "Split sizes")
    if sizes is None:
        return tuple(jnp.split(x, len(node.output), axis=ax))
    points = np.cumsum(sizes)[:-1].tolist()
    return tuple(jnp.split(x, points, axis=ax))


def _reduction(jnp_fn, np_fn):
    def h(ctx, node, attrs, args):
        x = args[0]
        axes = attrs.get("axes")
        if len(args) > 1 and args[1] is not None:
            axes = _ints(args[1], "reduction axes")
        keep = bool(attrs.get("keepdims", 1))
        if axes is not None and len(axes) == 0:
            # ONNX: empty axes reduces all dims unless noop_with_empty_axes
            if attrs.get("noop_with_empty_axes", 0):
                return x
            ax = None
        else:
            ax = tuple(int(a) for a in axes) if axes is not None else None
        if _is_static(x):
            return np_fn(np.asarray(x), axis=ax, keepdims=keep)
        return jnp_fn(x, axis=ax, keepdims=keep)
    return h


def _arg_reduce(fn):
    def h(ctx, node, attrs, args):
        (x,) = args
        ax = attrs.get("axis", 0)
        keep = bool(attrs.get("keepdims", 1))
        out = fn(x, axis=ax).astype(jnp.int64)
        return jnp.expand_dims(out, ax) if keep else out
    return h


def _clip(ctx, node, attrs, args):
    x = args[0]
    lo = attrs.get("min")
    hi = attrs.get("max")
    if len(args) > 1 and args[1] is not None:
        lo = args[1]
    if len(args) > 2 and args[2] is not None:
        hi = args[2]
    return jnp.clip(x, lo, hi)


def _cast(ctx, node, attrs, args):
    from .proto import np_dtype
    (x,) = args
    dt = np_dtype(attrs["to"])
    if _is_static(x):
        return np.asarray(x).astype(dt)
    return x.astype(dt)


def _softmax_like(fn):
    def h(ctx, node, attrs, args):
        (x,) = args
        ax = attrs.get("axis", -1)
        return fn(x, axis=ax)
    return h


def _constant(ctx, node, attrs, args):
    if "value" in attrs:
        return attrs["value"]
    for k in ("value_float", "value_int"):
        if k in attrs:
            return np.asarray(attrs[k])
    for k in ("value_floats", "value_ints"):
        if k in attrs:
            return np.asarray(attrs[k])
    raise NotImplementedError(f"Constant node {node.name} with no value")


def _constant_of_shape(ctx, node, attrs, args):
    shape = tuple(_ints(args[0], "ConstantOfShape shape"))
    val = attrs.get("value")
    if val is None:
        return np.zeros(shape, np.float32)
    return np.full(shape, np.asarray(val).reshape(-1)[0],
                   np.asarray(val).dtype)


def _expand(ctx, node, attrs, args):
    x, shape = args
    tgt = _ints(shape, "Expand shape")
    in_shape = np.asarray(x).shape if _is_static(x) else x.shape
    # ONNX Expand: numpy broadcast; 1s in target keep the input dim
    n = max(len(tgt), len(in_shape))
    in_p = (1,) * (n - len(in_shape)) + tuple(in_shape)
    tgt_p = [1] * (n - len(tgt)) + list(tgt)
    out = [max(a, b) for a, b in zip(in_p, tgt_p)]
    f = _nb(np.broadcast_to, jnp.broadcast_to)
    return f(x, tuple(out))


def _tile(ctx, node, attrs, args):
    x, reps = args
    f = _nb(np.tile, jnp.tile)
    return f(x, tuple(_ints(reps, "Tile repeats")))


def _onehot(ctx, node, attrs, args):
    indices, depth, values = args
    ax = attrs.get("axis", -1)
    d = _ints(depth, "OneHot depth")[0]
    off, on = np.asarray(_static(values, "OneHot values"))
    oh = jax.nn.one_hot(indices, d, axis=ax)
    return (oh * (on - off) + off)


def _topk(ctx, node, attrs, args):
    x = args[0]
    k = (_ints(args[1], "TopK k")[0] if len(args) > 1
         else attrs["k"])
    ax = attrs.get("axis", -1)
    if not attrs.get("largest", 1):
        vals, idxs = lax.top_k(-jnp.moveaxis(x, ax, -1), k)
        vals = -vals
    else:
        vals, idxs = lax.top_k(jnp.moveaxis(x, ax, -1), k)
    return (jnp.moveaxis(vals, -1, ax),
            jnp.moveaxis(idxs.astype(jnp.int64), -1, ax))


def _where(ctx, node, attrs, args):
    f = _nb(np.where, jnp.where)
    return f(*args)


def _ew(jnp_fn, np_fn=None):
    def h(ctx, node, attrs, args):
        (x,) = args
        if np_fn is not None and _is_static(x):
            return np_fn(x)
        return jnp_fn(x)
    return h


def _bin(jnp_fn, np_fn):
    f = _nb(np_fn, jnp_fn)
    return lambda ctx, node, attrs, args: f(*args)


def _variadic(jnp_fn):
    def h(ctx, node, attrs, args):
        out = args[0]
        for a in args[1:]:
            out = jnp_fn(out, a)
        return out
    return h


_H: Dict[str, Any] = {
    # plumbing
    "Identity": lambda ctx, node, attrs, args: args[0],
    "Constant": _constant,
    "ConstantOfShape": _constant_of_shape,
    "Cast": _cast,
    "Shape": lambda ctx, node, attrs, args: np.asarray(
        (np.asarray(args[0]).shape if _is_static(args[0])
         else args[0].shape), np.int64),
    "Size": lambda ctx, node, attrs, args: np.int64(int(np.prod(
        (np.asarray(args[0]) if _is_static(args[0]) else args[0]).shape))),
    "Dropout": _dropout,
    # shape ops
    "Reshape": _reshape,
    "Flatten": _flatten,
    "Transpose": lambda ctx, node, attrs, args: (
        np.transpose(np.asarray(args[0]), attrs.get("perm"))
        if _is_static(args[0])
        else jnp.transpose(args[0], attrs.get("perm"))),
    "Squeeze": _squeeze,
    "Unsqueeze": _unsqueeze,
    "Slice": _slice,
    "Gather": _gather,
    "Concat": _concat,
    "Split": _split,
    "Pad": _pad,
    "Expand": _expand,
    "Tile": _tile,
    "Range": lambda ctx, node, attrs, args: np.arange(
        *[np.asarray(_static(a, "Range")).item() for a in args]),
    "OneHot": _onehot,
    # math: binary (numpy-style broadcast)
    "Add": _bin(jnp.add, np.add),
    "Sub": _bin(jnp.subtract, np.subtract),
    "Mul": _bin(jnp.multiply, np.multiply),
    "Div": _bin(jnp.divide, np.divide),
    "Pow": _bin(jnp.power, np.power),
    "Mod": _bin(jnp.mod, np.mod),
    "Min": _variadic(jnp.minimum),
    "Max": _variadic(jnp.maximum),
    "Sum": _variadic(jnp.add),
    "Mean": lambda ctx, node, attrs, args: sum(args[1:], args[0]) / len(args),
    "MatMul": _bin(jnp.matmul, np.matmul),
    "Gemm": _gemm,
    "Einsum": lambda ctx, node, attrs, args: jnp.einsum(
        attrs["equation"], *args),
    # math: unary
    "Neg": _ew(jnp.negative, np.negative),
    "Abs": _ew(jnp.abs, np.abs),
    "Sqrt": _ew(jnp.sqrt),
    "Exp": _ew(jnp.exp),
    "Log": _ew(jnp.log),
    "Reciprocal": _ew(jnp.reciprocal),
    "Floor": _ew(jnp.floor, np.floor),
    "Ceil": _ew(jnp.ceil, np.ceil),
    "Round": _ew(jnp.round, np.round),
    "Sign": _ew(jnp.sign, np.sign),
    "Erf": _ew(lax.erf),
    "Sin": _ew(jnp.sin),
    "Cos": _ew(jnp.cos),
    "Clip": _clip,
    # activations
    "Relu": _ew(jax.nn.relu),
    "LeakyRelu": lambda ctx, node, attrs, args: jax.nn.leaky_relu(
        args[0], attrs.get("alpha", 0.01)),
    "PRelu": lambda ctx, node, attrs, args: jnp.where(
        args[0] >= 0, args[0], args[0] * args[1]),
    "Elu": lambda ctx, node, attrs, args: jax.nn.elu(
        args[0], attrs.get("alpha", 1.0)),
    "Selu": _ew(jax.nn.selu),
    "Celu": lambda ctx, node, attrs, args: jax.nn.celu(
        args[0], attrs.get("alpha", 1.0)),
    "Sigmoid": _ew(jax.nn.sigmoid),
    "HardSigmoid": lambda ctx, node, attrs, args: jnp.clip(
        attrs.get("alpha", 0.2) * args[0] + attrs.get("beta", 0.5), 0, 1),
    "Tanh": _ew(jnp.tanh),
    "Softplus": _ew(jax.nn.softplus),
    "Softsign": _ew(jax.nn.soft_sign),
    "Softmax": _softmax_like(jax.nn.softmax),
    "LogSoftmax": _softmax_like(jax.nn.log_softmax),
    "Gelu": _ew(jax.nn.gelu),
    # NN
    "Conv": _conv,
    "ConvTranspose": _conv_transpose,
    "MaxPool": _pool(lax.max, -np.inf),
    "AveragePool": _pool(lax.add, 0.0, is_avg=True),
    "GlobalAveragePool": _global_pool(jnp.mean),
    "GlobalMaxPool": _global_pool(jnp.max),
    "BatchNormalization": _batch_norm,
    "InstanceNormalization": _instance_norm,
    "LRN": _lrn,
    # reductions
    "ReduceMean": _reduction(jnp.mean, np.mean),
    "ReduceSum": _reduction(jnp.sum, np.sum),
    "ReduceMax": _reduction(jnp.max, np.max),
    "ReduceMin": _reduction(jnp.min, np.min),
    "ReduceProd": _reduction(jnp.prod, np.prod),
    "ReduceL2": _reduction(
        lambda x, axis, keepdims: jnp.sqrt(
            jnp.sum(jnp.square(x), axis=axis, keepdims=keepdims)),
        lambda x, axis, keepdims: np.sqrt(
            np.sum(np.square(x), axis=axis, keepdims=keepdims))),
    "ArgMax": _arg_reduce(jnp.argmax),
    "ArgMin": _arg_reduce(jnp.argmin),
    "TopK": _topk,
    # comparison / logic
    "Greater": _bin(jnp.greater, np.greater),
    "GreaterOrEqual": _bin(jnp.greater_equal, np.greater_equal),
    "Less": _bin(jnp.less, np.less),
    "LessOrEqual": _bin(jnp.less_equal, np.less_equal),
    "Equal": _bin(jnp.equal, np.equal),
    "Not": _ew(jnp.logical_not, np.logical_not),
    "And": _bin(jnp.logical_and, np.logical_and),
    "Or": _bin(jnp.logical_or, np.logical_or),
    "Xor": _bin(jnp.logical_xor, np.logical_xor),
    "Where": _where,
}


class OnnxGraph:
    """An ONNX GraphProto compiled to a callable JAX function.

    ``fn = OnnxGraph(graph)``; then
    ``fn(params, *inputs, rng=None, training=False) -> [outputs]``.

    Float initializers become entries of ``fn.initial_params`` (trainable);
    integer initializers stay host-static so shape-feeding subgraphs trace
    to static shapes.
    """

    def __init__(self, graph: GraphProto):
        self.graph = graph
        init_names = {t.name for t in graph.initializer}
        self.input_names: List[str] = [
            vi.name for vi in graph.input if vi.name not in init_names]
        self.output_names: List[str] = [vi.name for vi in graph.output]

        self.initial_params: Dict[str, np.ndarray] = {}
        self._static_consts: Dict[str, np.ndarray] = {}
        for t in graph.initializer:
            arr = tensor_to_numpy(t)
            if np.issubdtype(arr.dtype, np.floating):
                self.initial_params[t.name] = arr
            else:
                self._static_consts[t.name] = arr

        self._producer: Dict[str, Tuple[NodeProto, int]] = {}
        for node in graph.node:
            for i, out in enumerate(node.output):
                if out:
                    self._producer[out] = (node, i)
        missing_ops = sorted({n.op_type for n in graph.node
                              if n.op_type not in _H})
        if missing_ops:
            raise NotImplementedError(
                f"unsupported ONNX ops {missing_ops}; supported: "
                f"{sorted(_H)}")
        self._order = self._toposort()

    def _toposort(self) -> List[NodeProto]:
        """Iterative DFS (deep exported chains overflow Python's
        recursion limit — same stance as tfgraph converter)."""
        known = (set(self.input_names) | set(self.initial_params)
                 | set(self._static_consts))
        order: List[NodeProto] = []
        state: Dict[int, int] = {}  # id(node): 0 visiting, 1 done

        def deps(node):
            for ref in node.input:
                if ref and ref not in known:
                    if ref not in self._producer:
                        raise KeyError(
                            f"node {node.name or node.op_type} consumes "
                            f"unknown value {ref!r}")
                    yield self._producer[ref][0]

        stack = [(self._producer[out][0], False)
                 for out in reversed(self.output_names)
                 if out in self._producer]
        while stack:
            node, processed = stack.pop()
            if processed:
                state[id(node)] = 1
                order.append(node)
                continue
            s = state.get(id(node))
            if s == 1:
                continue
            if s == 0:
                # popped again while still unfinished: only a back-edge
                # (cycle) can reach a node on the current DFS path
                raise ValueError("ONNX graph has a cycle")
            state[id(node)] = 0
            stack.append((node, True))
            for d in deps(node):
                if state.get(id(d)) != 1:
                    stack.append((d, False))
        return order

    def __call__(self, params: Dict[str, Any], *input_values,
                 rng=None, training: bool = False):
        if len(input_values) != len(self.input_names):
            raise ValueError(
                f"expected {len(self.input_names)} inputs "
                f"({self.input_names}), got {len(input_values)}")
        env: Dict[str, Any] = dict(self._static_consts)
        env.update(params)
        env.update(zip(self.input_names, input_values))
        ctx = _Ctx(params, rng, training)
        for node in self._order:
            attrs = attrs_dict(node)
            args = [env[r] if r else None for r in node.input]
            out = _H[node.op_type](ctx, node, attrs, args)
            if isinstance(out, tuple):
                for name, v in zip(node.output, out):
                    if name:
                        env[name] = v
            else:
                env[node.output[0]] = out
        missing = [o for o in self.output_names if o not in env]
        if missing:
            raise KeyError(f"graph outputs never produced: {missing}")
        return [env[o] for o in self.output_names]

    @property
    def input_shapes(self) -> List[Optional[Tuple]]:
        """Declared shapes from graph.input value_info (None dims for
        symbolic/batch dims)."""
        shapes = []
        by_name = {vi.name: vi for vi in self.graph.input}
        for name in self.input_names:
            vi = by_name.get(name)
            if vi is None or vi.type is None or vi.type.tensor_type is None \
                    or vi.type.tensor_type.shape is None:
                shapes.append(None)
                continue
            dims = []
            for d in vi.type.tensor_type.shape.dim:
                dims.append(int(d.dim_value) if d.dim_value else None)
            shapes.append(tuple(dims))
        return shapes
