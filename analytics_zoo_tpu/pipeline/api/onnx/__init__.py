"""ONNX import (reference: pyzoo/zoo/pipeline/api/onnx/)."""

from .onnx_loader import OnnxLoader, OnnxNet, load_onnx
from .converter import OnnxGraph
from . import proto

__all__ = ["OnnxLoader", "OnnxNet", "load_onnx", "OnnxGraph", "proto"]
