"""Minimal ONNX protobuf codec — no dependency on the ``onnx`` package.

The reference's ONNX importer (pyzoo/zoo/pipeline/api/onnx/onnx_loader.py:32-119)
walks ``onnx.ModelProto`` objects produced by the installed onnx package.  This
environment does not ship ``onnx``, and an ONNX file is just a protobuf, so we
carry a ~300-line wire-format codec for exactly the message subset the loader
needs (ModelProto/GraphProto/NodeProto/TensorProto/AttributeProto/
ValueInfoProto).  Field numbers follow the public onnx.proto3 schema, which is
frozen for these core messages.

Both decode (load real ``.onnx`` files) and encode (build models
programmatically — the ``make_node``/``make_graph``/``make_model`` helpers
mirror ``onnx.helper``) are provided; tests round-trip through both.
"""

from __future__ import annotations

import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# generic tiny-proto framework

_REG: Dict[str, type] = {}

_VARINT_KINDS = {"int32", "int64", "uint64", "enum", "bool"}
_NUMERIC_KINDS = _VARINT_KINDS | {"float", "double"}


def _default(kind: str):
    if kind in _VARINT_KINDS:
        return 0
    if kind == "float" or kind == "double":
        return 0.0
    if kind == "string":
        return ""
    if kind == "bytes":
        return b""
    return None  # message


class Msg:
    """Base for schema-described protobuf messages."""

    FIELDS: Dict[int, Tuple[str, str, str]] = {}

    def __init_subclass__(cls):
        _REG[cls.__name__] = cls
        cls._BY_NAME = {name: (num, kind, label)
                        for num, (name, kind, label) in cls.FIELDS.items()}

    def __init__(self, **kw):
        for num, (name, kind, label) in self.FIELDS.items():
            setattr(self, name, [] if label == "rep" else _default(kind))
        for k, v in kw.items():
            if k not in self._BY_NAME:
                raise AttributeError(f"{type(self).__name__} has no field {k}")
            setattr(self, k, v)

    def __repr__(self):
        parts = []
        for num, (name, kind, label) in sorted(self.FIELDS.items()):
            v = getattr(self, name)
            if v not in ([], 0, 0.0, "", b"", None):
                parts.append(f"{name}={v!r}" if not isinstance(v, list)
                             else f"{name}=[{len(v)} items]")
        return f"{type(self).__name__}({', '.join(parts)})"


def _read_varint(buf: bytes, i: int) -> Tuple[int, int]:
    shift = 0
    val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not (b & 0x80):
            return val, i
        shift += 7
        if shift > 70:
            raise ValueError("varint too long")


def _signed(val: int) -> int:
    return val - (1 << 64) if val >= (1 << 63) else val


def _decode_scalar(kind: str, wire: int, buf: bytes, i: int):
    if wire == 0:
        val, i = _read_varint(buf, i)
        if kind in ("int32", "int64", "enum"):
            val = _signed(val)
        elif kind == "bool":
            val = bool(val)
        return val, i
    if wire == 5:
        (v,) = struct.unpack_from("<f", buf, i)
        return v, i + 4
    if wire == 1:
        if kind == "double":
            (v,) = struct.unpack_from("<d", buf, i)
        else:
            (v,) = struct.unpack_from("<Q", buf, i)
        return v, i + 8
    raise ValueError(f"bad wire type {wire} for scalar kind {kind}")


def decode(cls: type, buf: bytes) -> "Msg":
    """Decode ``buf`` into an instance of ``cls``."""
    msg = cls()
    i, n = 0, len(buf)
    while i < n:
        tag, i = _read_varint(buf, i)
        num, wire = tag >> 3, tag & 7
        spec = cls.FIELDS.get(num)
        if spec is None:  # unknown field: skip
            if wire == 0:
                _, i = _read_varint(buf, i)
            elif wire == 1:
                i += 8
            elif wire == 5:
                i += 4
            elif wire == 2:
                ln, i = _read_varint(buf, i)
                i += ln
            else:
                raise ValueError(f"cannot skip wire type {wire}")
            continue
        name, kind, label = spec
        if kind.startswith("msg:"):
            ln, i = _read_varint(buf, i)
            sub = decode(_REG[kind[4:]], buf[i:i + ln])
            i += ln
            if label == "rep":
                getattr(msg, name).append(sub)
            else:
                setattr(msg, name, sub)
        elif kind in ("string", "bytes"):
            ln, i = _read_varint(buf, i)
            raw = buf[i:i + ln]
            i += ln
            val = raw.decode("utf-8", "replace") if kind == "string" else raw
            if label == "rep":
                getattr(msg, name).append(val)
            else:
                setattr(msg, name, val)
        elif wire == 2 and kind in _NUMERIC_KINDS:  # packed repeated
            ln, i = _read_varint(buf, i)
            end = i + ln
            out = getattr(msg, name)
            while i < end:
                if kind == "float":
                    (v,) = struct.unpack_from("<f", buf, i)
                    i += 4
                elif kind == "double":
                    (v,) = struct.unpack_from("<d", buf, i)
                    i += 8
                else:
                    v, i = _read_varint(buf, i)
                    if kind in ("int32", "int64", "enum"):
                        v = _signed(v)
                out.append(v)
        else:
            val, i = _decode_scalar(kind, wire, buf, i)
            if label == "rep":
                getattr(msg, name).append(val)
            else:
                setattr(msg, name, val)
    return msg


def _write_varint(out: bytearray, val: int):
    if val < 0:
        val += 1 << 64
    while True:
        b = val & 0x7F
        val >>= 7
        if val:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _tag(out: bytearray, num: int, wire: int):
    _write_varint(out, (num << 3) | wire)


def encode(msg: Msg) -> bytes:
    """Serialize ``msg`` per its schema (packed repeated numerics,
    matching what protoc-generated code emits for proto3)."""
    out = bytearray()
    for num, (name, kind, label) in sorted(msg.FIELDS.items()):
        val = getattr(msg, name)
        if kind.startswith("msg:"):
            subs = val if label == "rep" else ([val] if val is not None else [])
            for sub in subs:
                raw = encode(sub)
                _tag(out, num, 2)
                _write_varint(out, len(raw))
                out += raw
        elif kind in ("string", "bytes"):
            vals = val if label == "rep" else ([val] if val else [])
            for v in vals:
                raw = v.encode("utf-8") if isinstance(v, str) else bytes(v)
                _tag(out, num, 2)
                _write_varint(out, len(raw))
                out += raw
        elif label == "rep":
            if not val:
                continue
            packed = bytearray()
            for v in val:
                if kind == "float":
                    packed += struct.pack("<f", v)
                elif kind == "double":
                    packed += struct.pack("<d", v)
                else:
                    _write_varint(packed, int(v))
            _tag(out, num, 2)
            _write_varint(out, len(packed))
            out += packed
        else:
            if kind == "float":
                if val:
                    _tag(out, num, 5)
                    out += struct.pack("<f", val)
            elif kind == "double":
                if val:
                    _tag(out, num, 1)
                    out += struct.pack("<d", val)
            else:
                if val:
                    _tag(out, num, 0)
                    _write_varint(out, int(val))
    return bytes(out)


# ---------------------------------------------------------------------------
# ONNX message subset (field numbers: public onnx.proto3)

class OperatorSetIdProto(Msg):
    FIELDS = {1: ("domain", "string", "opt"),
              2: ("version", "int64", "opt")}


class StringStringEntryProto(Msg):
    FIELDS = {1: ("key", "string", "opt"),
              2: ("value", "string", "opt")}


class TensorProto(Msg):
    FIELDS = {
        1: ("dims", "int64", "rep"),
        2: ("data_type", "int32", "opt"),
        4: ("float_data", "float", "rep"),
        5: ("int32_data", "int32", "rep"),
        6: ("string_data", "bytes", "rep"),
        7: ("int64_data", "int64", "rep"),
        8: ("name", "string", "opt"),
        9: ("raw_data", "bytes", "opt"),
        10: ("double_data", "double", "rep"),
        11: ("uint64_data", "uint64", "rep"),
    }


class Dimension(Msg):
    FIELDS = {1: ("dim_value", "int64", "opt"),
              2: ("dim_param", "string", "opt")}


class TensorShapeProto(Msg):
    FIELDS = {1: ("dim", "msg:Dimension", "rep")}


class TensorTypeProto(Msg):
    FIELDS = {1: ("elem_type", "int32", "opt"),
              2: ("shape", "msg:TensorShapeProto", "opt")}


class TypeProto(Msg):
    FIELDS = {1: ("tensor_type", "msg:TensorTypeProto", "opt")}


class ValueInfoProto(Msg):
    FIELDS = {1: ("name", "string", "opt"),
              2: ("type", "msg:TypeProto", "opt"),
              3: ("doc_string", "string", "opt")}


class AttributeProto(Msg):
    # type enum values
    FLOAT, INT, STRING, TENSOR, GRAPH = 1, 2, 3, 4, 5
    FLOATS, INTS, STRINGS, TENSORS, GRAPHS = 6, 7, 8, 9, 10

    FIELDS = {
        1: ("name", "string", "opt"),
        2: ("f", "float", "opt"),
        3: ("i", "int64", "opt"),
        4: ("s", "bytes", "opt"),
        5: ("t", "msg:TensorProto", "opt"),
        6: ("g", "msg:GraphProto", "opt"),
        7: ("floats", "float", "rep"),
        8: ("ints", "int64", "rep"),
        9: ("strings", "bytes", "rep"),
        10: ("tensors", "msg:TensorProto", "rep"),
        11: ("graphs", "msg:GraphProto", "rep"),
        13: ("doc_string", "string", "opt"),
        20: ("type", "enum", "opt"),
    }


class NodeProto(Msg):
    FIELDS = {
        1: ("input", "string", "rep"),
        2: ("output", "string", "rep"),
        3: ("name", "string", "opt"),
        4: ("op_type", "string", "opt"),
        5: ("attribute", "msg:AttributeProto", "rep"),
        6: ("doc_string", "string", "opt"),
        7: ("domain", "string", "opt"),
    }


class GraphProto(Msg):
    FIELDS = {
        1: ("node", "msg:NodeProto", "rep"),
        2: ("name", "string", "opt"),
        5: ("initializer", "msg:TensorProto", "rep"),
        10: ("doc_string", "string", "opt"),
        11: ("input", "msg:ValueInfoProto", "rep"),
        12: ("output", "msg:ValueInfoProto", "rep"),
        13: ("value_info", "msg:ValueInfoProto", "rep"),
    }


class ModelProto(Msg):
    FIELDS = {
        1: ("ir_version", "int64", "opt"),
        2: ("producer_name", "string", "opt"),
        3: ("producer_version", "string", "opt"),
        4: ("domain", "string", "opt"),
        5: ("model_version", "int64", "opt"),
        6: ("doc_string", "string", "opt"),
        7: ("graph", "msg:GraphProto", "opt"),
        8: ("opset_import", "msg:OperatorSetIdProto", "rep"),
        14: ("metadata_props", "msg:StringStringEntryProto", "rep"),
    }


# ---------------------------------------------------------------------------
# TensorProto <-> numpy

# onnx TensorProto.DataType enum -> numpy dtype
_DT_FLOAT, _DT_UINT8, _DT_INT8 = 1, 2, 3
_DT_UINT16, _DT_INT16, _DT_INT32, _DT_INT64 = 4, 5, 6, 7
_DT_STRING, _DT_BOOL, _DT_FLOAT16, _DT_DOUBLE = 8, 9, 10, 11
_DT_UINT32, _DT_UINT64, _DT_BFLOAT16 = 12, 13, 16

_DTYPE_OF = {
    _DT_FLOAT: np.dtype("float32"), _DT_UINT8: np.dtype("uint8"),
    _DT_INT8: np.dtype("int8"), _DT_UINT16: np.dtype("uint16"),
    _DT_INT16: np.dtype("int16"), _DT_INT32: np.dtype("int32"),
    _DT_INT64: np.dtype("int64"), _DT_BOOL: np.dtype("bool"),
    _DT_FLOAT16: np.dtype("float16"), _DT_DOUBLE: np.dtype("float64"),
    _DT_UINT32: np.dtype("uint32"), _DT_UINT64: np.dtype("uint64"),
}

_ENUM_OF = {v: k for k, v in _DTYPE_OF.items()}


def np_dtype(enum: int) -> np.dtype:
    if enum == _DT_BFLOAT16:
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16)
    if enum not in _DTYPE_OF:
        raise NotImplementedError(f"ONNX tensor data_type {enum} unsupported")
    return _DTYPE_OF[enum]


def tensor_to_numpy(tp: TensorProto) -> np.ndarray:
    dims = tuple(int(d) for d in tp.dims)
    dt = tp.data_type
    if tp.raw_data:
        return np.frombuffer(tp.raw_data, dtype=np_dtype(dt)).reshape(dims)
    if dt == _DT_FLOAT:
        return np.asarray(tp.float_data, np.float32).reshape(dims)
    if dt == _DT_DOUBLE:
        return np.asarray(tp.double_data, np.float64).reshape(dims)
    if dt == _DT_INT64:
        return np.asarray(tp.int64_data, np.int64).reshape(dims)
    if dt in (_DT_UINT32, _DT_UINT64):
        return np.asarray(tp.uint64_data, np_dtype(dt)).reshape(dims)
    if dt == _DT_FLOAT16:  # fp16 payload rides int32_data per onnx.proto
        return np.asarray(tp.int32_data, np.uint16).view(
            np.float16).reshape(dims)
    return np.asarray(tp.int32_data, np.int64).astype(
        np_dtype(dt)).reshape(dims)


def numpy_to_tensor(arr: np.ndarray, name: str = "") -> TensorProto:
    # NB: np.ascontiguousarray has ndmin=1 and would promote 0-d to 1-d
    arr = np.asarray(arr, order="C")
    if arr.dtype not in _ENUM_OF:
        raise NotImplementedError(f"dtype {arr.dtype} unsupported")
    return TensorProto(name=name, dims=[int(d) for d in arr.shape],
                       data_type=_ENUM_OF[arr.dtype],
                       raw_data=arr.tobytes())


# ---------------------------------------------------------------------------
# helper constructors (mirror onnx.helper for programmatic graph building)

def make_attribute(name: str, value: Any) -> AttributeProto:
    a = AttributeProto(name=name)
    if isinstance(value, bool) or isinstance(value, (int, np.integer)):
        a.type, a.i = AttributeProto.INT, int(value)
    elif isinstance(value, (float, np.floating)):
        a.type, a.f = AttributeProto.FLOAT, float(value)
    elif isinstance(value, str):
        a.type, a.s = AttributeProto.STRING, value.encode()
    elif isinstance(value, bytes):
        a.type, a.s = AttributeProto.STRING, value
    elif isinstance(value, np.ndarray):
        a.type, a.t = AttributeProto.TENSOR, numpy_to_tensor(value)
    elif isinstance(value, TensorProto):
        a.type, a.t = AttributeProto.TENSOR, value
    elif isinstance(value, (list, tuple)):
        vals = list(value)
        if all(isinstance(v, (int, np.integer)) for v in vals):
            a.type, a.ints = AttributeProto.INTS, [int(v) for v in vals]
        elif all(isinstance(v, (float, np.floating, int)) for v in vals):
            a.type, a.floats = AttributeProto.FLOATS, [float(v) for v in vals]
        elif all(isinstance(v, str) for v in vals):
            a.type = AttributeProto.STRINGS
            a.strings = [v.encode() for v in vals]
        else:
            raise TypeError(f"mixed attribute list for {name}: {vals}")
    else:
        raise TypeError(f"cannot make attribute from {type(value)}")
    return a


def make_node(op_type: str, inputs: List[str], outputs: List[str],
              name: str = "", **attrs) -> NodeProto:
    return NodeProto(op_type=op_type, input=list(inputs),
                     output=list(outputs), name=name,
                     attribute=[make_attribute(k, v)
                                for k, v in attrs.items()])


def make_value_info(name: str, shape=None, elem_type: int = _DT_FLOAT
                    ) -> ValueInfoProto:
    vi = ValueInfoProto(name=name)
    tt = TensorTypeProto(elem_type=elem_type)
    if shape is not None:
        tt.shape = TensorShapeProto(dim=[
            Dimension(dim_param=str(d)) if isinstance(d, str) or d is None
            else Dimension(dim_value=int(d)) for d in shape])
    vi.type = TypeProto(tensor_type=tt)
    return vi


def make_graph(nodes, name, inputs, outputs, initializer=None) -> GraphProto:
    return GraphProto(node=list(nodes), name=name, input=list(inputs),
                      output=list(outputs),
                      initializer=list(initializer or []))


def make_model(graph: GraphProto, opset_version: int = 13) -> ModelProto:
    return ModelProto(ir_version=8, producer_name="analytics_zoo_tpu",
                      graph=graph,
                      opset_import=[OperatorSetIdProto(
                          domain="", version=opset_version)])


def load_model(path_or_bytes) -> ModelProto:
    if isinstance(path_or_bytes, (bytes, bytearray)):
        return decode(ModelProto, bytes(path_or_bytes))
    with open(path_or_bytes, "rb") as f:
        return decode(ModelProto, f.read())


def attrs_dict(node: NodeProto) -> Dict[str, Any]:
    """AttributeProto list -> python values keyed by name."""
    out: Dict[str, Any] = {}
    for a in node.attribute:
        t = a.type
        if t == AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif t == AttributeProto.INT:
            out[a.name] = int(a.i)
        elif t == AttributeProto.STRING:
            out[a.name] = a.s.decode("utf-8", "replace")
        elif t == AttributeProto.TENSOR:
            out[a.name] = tensor_to_numpy(a.t)
        elif t == AttributeProto.FLOATS:
            out[a.name] = [float(v) for v in a.floats]
        elif t == AttributeProto.INTS:
            out[a.name] = [int(v) for v in a.ints]
        elif t == AttributeProto.STRINGS:
            out[a.name] = [v.decode("utf-8", "replace") for v in a.strings]
        elif t == AttributeProto.GRAPH:
            out[a.name] = a.g
        else:
            raise NotImplementedError(
                f"attribute {a.name} of type {t} unsupported")
    return out
