"""ONNX model → framework Layer (``OnnxNet``) / loader entry points.

Parity surface: reference ``OnnxLoader``
(pyzoo/zoo/pipeline/api/onnx/onnx_loader.py:32-119) turns an onnx GraphProto
into a BigDL KerasNet by mapping each node to a layer.  Here the whole graph
becomes one JAX function (:class:`.converter.OnnxGraph`) wrapped as a Layer,
so an imported model composes with native layers, jits into one XLA
computation, and fine-tunes through ``jax.grad`` (float initializers are the
layer's params).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ....core.module import Layer, register_layer
from .converter import OnnxGraph
from .proto import ModelProto, load_model


@register_layer
class OnnxNet(Layer):
    """An imported ONNX model as a layer of this framework."""

    stochastic = True  # imported graphs may contain Dropout

    def __init__(self, path: Optional[str] = None,
                 model: Optional[ModelProto] = None,
                 name: Optional[str] = None):
        super().__init__(name=name)
        if model is None:
            model = load_model(path)
        self._path = path
        if model.graph is None:
            raise ValueError("ONNX model has no graph")
        self.fn = OnnxGraph(model.graph)
        self.opset = max((o.version for o in model.opset_import
                          if o.domain in ("", "ai.onnx")), default=13)

    # ---- Layer contract ------------------------------------------------
    def init_params(self, rng, input_shape):
        return {k: jnp.asarray(v)
                for k, v in self.fn.initial_params.items()}

    def call(self, params, state, inputs, training=False, rng=None):
        xs = inputs if isinstance(inputs, (tuple, list)) else (inputs,)
        outs = self.fn(params, *xs, rng=rng, training=training)
        return outs[0] if len(outs) == 1 else tuple(outs)

    def compute_output_shape(self, input_shape):
        shapes = input_shape if isinstance(input_shape[0], (tuple, list)) \
            else [input_shape]
        dummies = [jax.ShapeDtypeStruct((2,) + tuple(s[1:]), jnp.float32)
                   for s in shapes]
        params = {k: jax.ShapeDtypeStruct(np.shape(v), jnp.float32)
                  for k, v in self.fn.initial_params.items()}
        out = jax.eval_shape(
            lambda p, *xs: self.fn(p, *xs, rng=jax.random.PRNGKey(0)),
            params, *dummies)
        outs = [(None,) + tuple(o.shape[1:]) for o in out]
        return outs[0] if len(outs) == 1 else outs

    # ---- convenience inference ----------------------------------------
    def predict(self, x, batch_per_thread: int = 32):
        # cache params + the jitted forward across calls — a fresh jit
        # closure per call would recompile the graph every predict()
        if getattr(self, "_predict_cache", None) is None:
            self._predict_cache = (
                self.init_params(jax.random.PRNGKey(0), None),
                jax.jit(lambda p, *a: self.fn(
                    p, *a, rng=jax.random.PRNGKey(0))))
        params, fwd = self._predict_cache
        xs = x if isinstance(x, (tuple, list)) else (x,)
        outs = []
        n = len(xs[0])
        for i in range(0, n, batch_per_thread):
            batch = [np.asarray(a[i:i + batch_per_thread]) for a in xs]
            outs.append([np.asarray(o) for o in fwd(params, *batch)])
    # concatenate per-output across batches
        cat = [np.concatenate([o[j] for o in outs])
               for j in range(len(outs[0]))]
        return cat[0] if len(cat) == 1 else cat


class OnnxLoader:
    """Reference-parity entry (onnx_loader.py:32): load an ONNX model."""

    @staticmethod
    def from_path(path: str) -> OnnxNet:
        return OnnxNet(path=path)

    @staticmethod
    def from_bytes(data: bytes) -> OnnxNet:
        return OnnxNet(model=load_model(data))


def load_onnx(path: str) -> OnnxNet:
    """Load an ``.onnx`` file as an :class:`OnnxNet` layer."""
    return OnnxNet(path=path)
