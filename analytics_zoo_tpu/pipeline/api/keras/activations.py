"""Activation functions resolvable by Keras-1 name strings.

Parity: the activation strings accepted across the reference layer set
(reference: zoo/.../pipeline/api/keras/layers/utils/KerasUtils.scala maps the
same strings to BigDL modules).  All are jnp elementwise ops that XLA fuses
into the producing matmul/conv — no standalone kernels needed.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def linear(x):
    return x


def relu(x):
    return jax.nn.relu(x)


def relu6(x):
    return jnp.minimum(jax.nn.relu(x), 6.0)


def tanh(x):
    return jnp.tanh(x)


def sigmoid(x):
    return jax.nn.sigmoid(x)


def hard_sigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


def softmax(x):
    return jax.nn.softmax(x, axis=-1)


def log_softmax(x):
    return jax.nn.log_softmax(x, axis=-1)


def softplus(x):
    return jax.nn.softplus(x)


def softsign(x):
    return jax.nn.soft_sign(x)


def elu(x):
    return jax.nn.elu(x)


def gelu(x):
    return jax.nn.gelu(x)


def silu(x):
    return jax.nn.silu(x)


_ACTIVATIONS = {
    "linear": linear,
    "relu": relu,
    "relu6": relu6,
    "tanh": tanh,
    "sigmoid": sigmoid,
    "hard_sigmoid": hard_sigmoid,
    "softmax": softmax,
    "log_softmax": log_softmax,
    "softplus": softplus,
    "softsign": softsign,
    "elu": elu,
    "gelu": gelu,
    "silu": silu,
    "swish": silu,
}


def get(name):
    if name is None:
        return None
    if callable(name):
        return name
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"Unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}")
