from .engine import Sequential, Model, KerasNet, load_model
from . import objectives, metrics, optimizers, activations
