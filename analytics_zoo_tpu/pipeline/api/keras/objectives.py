"""Loss functions (the 13 Keras objectives of the reference).

Parity surface: reference zoo/.../pipeline/api/keras/objectives/*.scala:
BinaryCrossEntropy, CategoricalCrossEntropy, SparseCategoricalCrossEntropy,
MeanSquaredError, MeanAbsoluteError, MeanAbsolutePercentageError,
MeanSquaredLogarithmicError, Hinge, SquaredHinge, Poisson,
KullbackLeiblerDivergence, CosineProximity (+ RankHinge used by examples).

Each is ``fn(y_true, y_pred) -> per-sample loss``; the trainer means over the
batch, so under a sharded batch axis the mean lowers to a psum over ICI —
this one reduction is the entire "parameter synchronization job" of the
reference's DistriOptimizer (wp-bigdl.md:150-158).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

EPS = 1e-7


def _batch_mean(x):
    """Mean over all non-batch axes -> per-sample scalar."""
    return jnp.mean(x, axis=tuple(range(1, x.ndim))) if x.ndim > 1 else x


def mean_squared_error(y_true, y_pred):
    return _batch_mean(jnp.square(y_pred - y_true))


def mean_absolute_error(y_true, y_pred):
    return _batch_mean(jnp.abs(y_pred - y_true))


def mean_absolute_percentage_error(y_true, y_pred):
    diff = jnp.abs((y_true - y_pred) /
                   jnp.maximum(jnp.abs(y_true), EPS))
    return 100.0 * _batch_mean(diff)


def mean_squared_logarithmic_error(y_true, y_pred):
    a = jnp.log(jnp.maximum(y_pred, EPS) + 1.0)
    b = jnp.log(jnp.maximum(y_true, EPS) + 1.0)
    return _batch_mean(jnp.square(a - b))


def binary_crossentropy(y_true, y_pred):
    p = jnp.clip(y_pred, EPS, 1.0 - EPS)
    return _batch_mean(-(y_true * jnp.log(p) +
                         (1.0 - y_true) * jnp.log(1.0 - p)))


def categorical_crossentropy(y_true, y_pred):
    """y_true one-hot, y_pred probabilities (post-softmax)."""
    p = jnp.clip(y_pred, EPS, 1.0)
    return -jnp.sum(y_true * jnp.log(p), axis=-1)


def _align_labels(y_true, y_pred):
    """Labels shaped ``y_pred.shape[:-1]``: squeeze ONLY a trailing
    singleton class axis — a full ``jnp.squeeze`` would collapse a
    batch_size=1 or seq_len=1 axis of sequence targets (b, S)."""
    labels = y_true.astype(jnp.int32)
    if labels.ndim == y_pred.ndim and labels.shape[-1] == 1:
        labels = labels.squeeze(-1)
    if labels.ndim == 0:
        labels = labels[None]
    return labels


def sparse_categorical_crossentropy(y_true, y_pred):
    """y_true int labels (zero-based), y_pred probabilities."""
    labels = _align_labels(y_true, y_pred)
    p = jnp.clip(y_pred, EPS, 1.0)
    logp = jnp.log(p)
    return _guarded_label_pick(logp, labels)


def _guarded_label_pick(logp, labels):
    """-logp[label] with a loud out-of-range guard.

    ``take_along_axis`` clamps out-of-range indices silently, which turns
    a label-base mistake (feeding 1-based ratings 1..5 to a zero-based
    loss) into quietly shifted training.  Instead, any label outside
    [0, n_classes) poisons that sample's loss with NaN, so the batch mean
    — and the first logged training loss — is NaN immediately.
    """
    n_classes = logp.shape[-1]
    valid = (labels >= 0) & (labels < n_classes)
    safe = jnp.clip(labels, 0, n_classes - 1)
    picked = -jnp.take_along_axis(
        logp, safe[..., None], axis=-1).squeeze(-1)
    return jnp.where(valid, picked, jnp.nan)


def class_nll(y_true, y_pred, zero_based_label=True):
    """y_true int labels, y_pred LOG-probabilities.

    Parity: BigDL ClassNLLCriterion paired with a LogSoftMax output —
    the reference's NeuralCF/WideAndDeep training criterion
    (apps/recommendation-ncf notebook, NeuralCF.scala log-softmax head).
    Use this, not sparse_categorical_crossentropy (which expects
    probabilities), for models whose final activation is log_softmax.

    The reference's ClassNLLCriterion consumes **1-based** labels
    (BigDL convention); this function defaults to zero-based (the JAX /
    tf.keras convention).  Pass ``zero_based_label=False`` — or
    construct ``ClassNLLCriterion(zero_based_label=False)`` — to feed
    1-based labels (e.g. ratings 1..5) directly, matching the reference
    metrics' parameter of the same name.  Out-of-range labels under
    either convention produce NaN loss rather than silently clamping.
    """
    labels = _align_labels(y_true, y_pred)
    if not zero_based_label:
        labels = labels - 1
    return _guarded_label_pick(y_pred, labels)


def hinge(y_true, y_pred):
    return _batch_mean(jnp.maximum(1.0 - y_true * y_pred, 0.0))


def squared_hinge(y_true, y_pred):
    return _batch_mean(jnp.square(jnp.maximum(1.0 - y_true * y_pred, 0.0)))


def poisson(y_true, y_pred):
    return _batch_mean(y_pred - y_true * jnp.log(y_pred + EPS))


def kullback_leibler_divergence(y_true, y_pred):
    p = jnp.clip(y_true, EPS, 1.0)
    q = jnp.clip(y_pred, EPS, 1.0)
    # Keras-1 semantics: SUM over the distribution axis (objectives.py
    # kullback_leibler_divergence), not a mean
    return jnp.sum(p * jnp.log(p / q), axis=-1)


def cosine_proximity(y_true, y_pred):
    a = y_true / jnp.maximum(
        jnp.linalg.norm(y_true, axis=-1, keepdims=True), EPS)
    b = y_pred / jnp.maximum(
        jnp.linalg.norm(y_pred, axis=-1, keepdims=True), EPS)
    return -jnp.sum(a * b, axis=-1)


def rank_hinge(y_true, y_pred, margin=1.0):
    """Pairwise rank hinge used by ranking examples; expects interleaved
    (positive, negative) pairs along the batch axis."""
    pos = y_pred[0::2]
    neg = y_pred[1::2]
    loss = jnp.maximum(0.0, margin - pos + neg)
    return jnp.repeat(loss, 2, axis=0)


_LOSSES = {
    "mse": mean_squared_error,
    "mean_squared_error": mean_squared_error,
    "mae": mean_absolute_error,
    "mean_absolute_error": mean_absolute_error,
    "mape": mean_absolute_percentage_error,
    "mean_absolute_percentage_error": mean_absolute_percentage_error,
    "msle": mean_squared_logarithmic_error,
    "mean_squared_logarithmic_error": mean_squared_logarithmic_error,
    "binary_crossentropy": binary_crossentropy,
    "categorical_crossentropy": categorical_crossentropy,
    "sparse_categorical_crossentropy": sparse_categorical_crossentropy,
    "class_nll": class_nll,
    "classnll": class_nll,
    "hinge": hinge,
    "squared_hinge": squared_hinge,
    "poisson": poisson,
    "kld": kullback_leibler_divergence,
    "kullback_leibler_divergence": kullback_leibler_divergence,
    "cosine_proximity": cosine_proximity,
    "rank_hinge": rank_hinge,
}


def get(name):
    if name is None or callable(name):
        return name
    try:
        return _LOSSES[name]
    except KeyError:
        raise ValueError(f"Unknown loss {name!r}; known: {sorted(_LOSSES)}")


# ---- class-style objectives (reference objectives.py:28-258 exposes
# each loss as a LossFunction subclass; an INSTANCE is the callable) ----

class LossFunction:
    """Base of the class-style objective surface: ``MeanSquaredError()``
    is interchangeable with ``"mse"`` / the bare function."""

    _fn = None

    def __call__(self, y_true, y_pred):
        return type(self)._fn(y_true, y_pred)

    def __repr__(self):
        return f"{type(self).__name__}()"


def _loss_class(fn, class_name):
    return type(class_name, (LossFunction,), {"_fn": staticmethod(fn)})


SparseCategoricalCrossEntropy = _loss_class(
    sparse_categorical_crossentropy, "SparseCategoricalCrossEntropy")
CategoricalCrossEntropy = _loss_class(categorical_crossentropy,
                                      "CategoricalCrossEntropy")
BinaryCrossEntropy = _loss_class(binary_crossentropy, "BinaryCrossEntropy")
MeanSquaredError = _loss_class(mean_squared_error, "MeanSquaredError")
MeanAbsoluteError = _loss_class(mean_absolute_error, "MeanAbsoluteError")
MeanAbsolutePercentageError = _loss_class(
    mean_absolute_percentage_error, "MeanAbsolutePercentageError")
MeanSquaredLogarithmicError = _loss_class(
    mean_squared_logarithmic_error, "MeanSquaredLogarithmicError")
Hinge = _loss_class(hinge, "Hinge")
SquaredHinge = _loss_class(squared_hinge, "SquaredHinge")
Poisson = _loss_class(poisson, "Poisson")
KullbackLeiblerDivergence = _loss_class(kullback_leibler_divergence,
                                        "KullbackLeiblerDivergence")
CosineProximity = _loss_class(cosine_proximity, "CosineProximity")
class ClassNLLCriterion(LossFunction):
    """Class-style ``class_nll``.  Unlike the other objectives this one
    carries state: ``zero_based_label=False`` replicates the reference
    ClassNLLCriterion's 1-based label convention exactly (BigDL
    ClassNLLCriterion.scala consumes labels 1..nClasses)."""

    _fn = staticmethod(class_nll)

    def __init__(self, zero_based_label=True):
        self.zero_based_label = zero_based_label

    def __call__(self, y_true, y_pred):
        return class_nll(y_true, y_pred,
                         zero_based_label=self.zero_based_label)

    def __repr__(self):
        return f"ClassNLLCriterion(zero_based_label={self.zero_based_label})"
RankHinge = _loss_class(rank_hinge, "RankHinge")
