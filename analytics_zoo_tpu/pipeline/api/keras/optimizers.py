"""Optimizer resolution: Keras-1 strings / objects -> optax transforms.

Parity surface: the reference maps strings to BigDL OptimMethods
(zoo/.../keras/layers/utils/KerasUtils.scala ``toBigDLOptimMethod``: sgd,
adam, adamax, adagrad, adadelta, rmsprop).  Here each resolves to an optax
gradient transformation; gradient clipping composes in front exactly where
the reference bolts clipping onto the Optimizer (Topology.scala:200-230).
"""

from __future__ import annotations

from typing import Optional

import optax


def get(optimizer, clip_norm: Optional[float] = None,
        clip_value: Optional[tuple] = None) -> optax.GradientTransformation:
    """Resolve an optimizer spec and compose clipping transforms.

    ``optimizer`` may be a string name, an optax transformation, or a dict
    {"name": ..., "lr"/"learning_rate": ..., extra kwargs}.
    """
    if isinstance(optimizer, optax.GradientTransformation):
        opt = optimizer
    else:
        if isinstance(optimizer, str):
            spec = {"name": optimizer}
        elif isinstance(optimizer, dict):
            spec = dict(optimizer)
        else:
            raise TypeError(f"Cannot resolve optimizer {optimizer!r}")
        name = spec.pop("name").lower()
        lr = spec.pop("lr", spec.pop("learning_rate", None))
        schedule = _schedule(lr, spec)
        if name == "sgd":
            momentum = spec.pop("momentum", 0.0) or None
            nesterov = spec.pop("nesterov", False)
            opt = optax.sgd(schedule if schedule is not None else 0.01,
                            momentum=momentum, nesterov=nesterov)
        elif name == "adam":
            opt = optax.adam(schedule if schedule is not None else 1e-3,
                             **spec)
        elif name == "adamax":
            opt = optax.adamax(schedule if schedule is not None else 2e-3,
                               **spec)
        elif name == "adagrad":
            opt = optax.adagrad(schedule if schedule is not None else 1e-2,
                                **spec)
        elif name == "adadelta":
            opt = optax.adadelta(schedule if schedule is not None else 1.0,
                                 **spec)
        elif name == "rmsprop":
            opt = optax.rmsprop(schedule if schedule is not None else 1e-3,
                                **spec)
        elif name in ("adamw", "lamb", "lars"):
            opt = getattr(optax, name)(
                schedule if schedule is not None else 1e-3, **spec)
        else:
            raise ValueError(f"Unknown optimizer {name!r}")

    chain = []
    if clip_value is not None:
        # reference setConstantGradientClipping (Topology.scala:207-213)
        chain.append(optax.clip(max(abs(clip_value[0]), abs(clip_value[1]))))
    if clip_norm is not None:
        # reference setGradientClippingByL2Norm (Topology.scala:219-224)
        chain.append(optax.clip_by_global_norm(clip_norm))
    chain.append(opt)
    return optax.chain(*chain) if len(chain) > 1 else opt


def _schedule(lr, spec):
    """Build an optax schedule from lr (+ optional decay, as in the
    reference's SGD learningRateDecay semantics)."""
    if lr is None:
        return None
    decay = spec.pop("decay", spec.pop("learning_rate_decay", 0.0))
    if decay:
        # BigDL-style hyperbolic decay: lr / (1 + decay * step)
        return lambda step: lr / (1.0 + decay * step)
    return lr
