"""Optimizer resolution: Keras-1 strings / objects -> optax transforms.

Parity surface: the reference maps strings to BigDL OptimMethods
(zoo/.../keras/layers/utils/KerasUtils.scala ``toBigDLOptimMethod``: sgd,
adam, adamax, adagrad, adadelta, rmsprop).  Here each resolves to an optax
gradient transformation; gradient clipping composes in front exactly where
the reference bolts clipping onto the Optimizer (Topology.scala:200-230).
"""

from __future__ import annotations

from typing import Callable, NamedTuple, Optional

import optax


class ZooOptimizer(NamedTuple):
    """An optax GradientTransformation plus the resolved learning-rate
    schedule, so the Trainer can emit the LearningRate TrainSummary scalar
    (reference wires Loss/LearningRate/Throughput, Topology.scala:157-175).
    Duck-types optax: only ``init``/``update`` are consumed downstream."""

    init: Callable
    update: Callable
    lr_fn: Optional[Callable] = None


def get(optimizer, clip_norm: Optional[float] = None,
        clip_value: Optional[tuple] = None) -> optax.GradientTransformation:
    """Resolve an optimizer spec and compose clipping transforms.

    ``optimizer`` may be a string name, an optax transformation, or a dict
    {"name": ..., "lr"/"learning_rate": ..., extra kwargs}.
    """
    lr_fn = None
    if isinstance(optimizer, (optax.GradientTransformation, ZooOptimizer)):
        opt = optimizer
        lr_fn = getattr(optimizer, "lr_fn", None)
    else:
        if isinstance(optimizer, str):
            spec = {"name": optimizer}
        elif isinstance(optimizer, dict):
            spec = dict(optimizer)
        else:
            raise TypeError(f"Cannot resolve optimizer {optimizer!r}")
        name = spec.pop("name").lower()
        lr = spec.pop("lr", spec.pop("learning_rate", None))
        schedule = _schedule(lr, spec)
        defaults = {"sgd": 0.01, "adam": 1e-3, "adamax": 2e-3,
                    "adagrad": 1e-2, "adadelta": 1.0, "rmsprop": 1e-3,
                    "adamw": 1e-3, "lamb": 1e-3, "lars": 1e-3}
        if name not in defaults:
            raise ValueError(f"Unknown optimizer {name!r}")
        resolved = schedule if schedule is not None else defaults[name]
        if name == "sgd":
            momentum = spec.pop("momentum", 0.0) or None
            nesterov = spec.pop("nesterov", False)
            opt = optax.sgd(resolved, momentum=momentum, nesterov=nesterov)
        else:
            opt = getattr(optax, name)(resolved, **spec)
        lr_fn = (resolved if callable(resolved)
                 else (lambda step, _lr=resolved: _lr))

    chain = []
    if clip_value is not None:
        # reference setConstantGradientClipping (Topology.scala:207-213)
        chain.append(optax.clip(max(abs(clip_value[0]), abs(clip_value[1]))))
    if clip_norm is not None:
        # reference setGradientClippingByL2Norm (Topology.scala:219-224)
        chain.append(optax.clip_by_global_norm(clip_norm))
    chain.append(opt)
    final = optax.chain(*chain) if len(chain) > 1 else opt
    return ZooOptimizer(final.init, final.update, lr_fn=lr_fn)


def _schedule(lr, spec):
    """Build an optax schedule from lr (+ optional decay, as in the
    reference's SGD learningRateDecay semantics)."""
    if lr is None:
        return None
    decay = spec.pop("decay", spec.pop("learning_rate_decay", 0.0))
    if decay:
        # BigDL-style hyperbolic decay: lr / (1 + decay * step)
        return lambda step: lr / (1.0 + decay * step)
    return lr
