"""Weight regularizers (reference: BigDL L1/L2/L1L2Regularizer, consumed
by Keras-1 layers' ``W_regularizer``/``b_regularizer`` args).

A regularizer maps a weight tensor to a scalar penalty.  Regularized
layers surface the summed penalty through their state under the
reserved ``aux_loss`` key, which ``build_train_step`` folds into the
training loss inside the gradient closure — the same machinery as
SwitchMoE's balancing loss, so the penalty actually reaches the
weights during fit.
"""

from __future__ import annotations

import jax.numpy as jnp


class Regularizer:
    def __call__(self, w):
        raise NotImplementedError

    def get_config(self) -> dict:
        return {"type": type(self).__name__, **self._rates()}

    def _rates(self) -> dict:
        return {}

    def __repr__(self):
        rates = ", ".join(f"{k}={v}" for k, v in self._rates().items())
        return f"{type(self).__name__}({rates})"


class L1(Regularizer):
    """rate * sum(|w|) — reference L1Regularizer."""

    def __init__(self, l1: float = 0.01):
        self.l1 = float(l1)

    def __call__(self, w):
        return self.l1 * jnp.sum(jnp.abs(w))

    def _rates(self):
        return {"l1": self.l1}


class L2(Regularizer):
    """rate * sum(w^2) — reference L2Regularizer."""

    def __init__(self, l2: float = 0.01):
        self.l2 = float(l2)

    def __call__(self, w):
        return self.l2 * jnp.sum(jnp.square(w))

    def _rates(self):
        return {"l2": self.l2}


class L1L2(Regularizer):
    """Combined penalty — reference L1L2Regularizer."""

    def __init__(self, l1: float = 0.01, l2: float = 0.01):
        self.l1, self.l2 = float(l1), float(l2)

    def __call__(self, w):
        return (self.l1 * jnp.sum(jnp.abs(w))
                + self.l2 * jnp.sum(jnp.square(w)))

    def _rates(self):
        return {"l1": self.l1, "l2": self.l2}


# aliases matching the reference's BigDL class names
L1Regularizer = L1
L2Regularizer = L2
L1L2Regularizer = L1L2


def get(spec):
    """Resolve None | Regularizer | custom callable | "l1"/"l2" |
    config dict.  Plain callables (Keras-style ``lambda w: ...``) pass
    through unchanged; they are applied but not serialized."""
    if spec is None or isinstance(spec, Regularizer) or (
            callable(spec) and not isinstance(spec, type)):
        return spec
    if isinstance(spec, str):
        key = spec.lower()
        if key == "l1":
            return L1()
        if key == "l2":
            return L2()
        if key in ("l1l2", "l1_l2"):
            return L1L2()
        raise ValueError(f"Unknown regularizer {spec!r}")
    if isinstance(spec, dict):
        cfg = dict(spec)
        kind = cfg.pop("type")
        return {"L1": L1, "L2": L2, "L1L2": L1L2}[kind](**cfg)
    raise TypeError(f"Cannot interpret regularizer {spec!r}")


def to_config(reg) -> dict:
    if reg is None:
        return None
    if not isinstance(reg, Regularizer):
        # custom callable: applied at runtime, not serializable — the
        # config round-trip drops it (documented in get())
        return None
    return reg.get_config()


class RegularizedLayerMixin:
    """Shared machinery for layers with W_regularizer/b_regularizer.

    Call ``_setup_regularizers`` at the end of ``__init__``; the layer
    becomes stateful when regularized and surfaces the penalty via
    ``state["aux_loss"]`` (summed into the training loss by
    ``build_train_step``).
    """

    def _setup_regularizers(self, W_regularizer, b_regularizer):
        self.W_regularizer = get(W_regularizer)
        self.b_regularizer = get(b_regularizer)
        if self.W_regularizer is not None or self.b_regularizer is not None:
            self.stateful = True

    def init_state(self, input_shape):
        if self.stateful:
            return {"aux_loss": jnp.zeros(())}
        return {}

    #: params key the W regularizer applies to (Embedding overrides)
    _reg_w_key = "W"

    def _penalty(self, params):
        # f32 accumulation regardless of compute dtype — a bf16 sum over
        # a large weight tensor drifts; mixed-precision practice applies
        # regularizers at master-weight precision
        pen = jnp.zeros(())
        if self.W_regularizer is not None:
            pen = pen + self.W_regularizer(
                params[self._reg_w_key].astype(jnp.float32))
        if self.b_regularizer is not None and getattr(self, "bias", False) \
                and "b" in params:
            pen = pen + self.b_regularizer(
                params["b"].astype(jnp.float32))
        return pen
