"""KerasNet / Sequential / Model: the user-facing training lifecycle.

Parity surface: reference zoo/.../pipeline/api/keras/models/Topology.scala —
``compile`` (:107-141), ``fit`` (:255-330), ``evaluate`` (:353),
``predict``/``predictClasses`` (:393-469), ``setTensorBoard`` (:167),
``setCheckpoint`` (:184), gradient clipping (:200-230), Sequential ``add``
(:768), functional Model over Variables (:653-689), plus saveModel/loadModel
(ZooModel.scala:78-124).

The lifecycle holds a Trainer (train/trainer.py) the way the reference holds
a BigDL Optimizer; incremental fit works because the Trainer keeps epoch
state across calls (Topology.scala:839-894 InternalOptimizer glue is
unnecessary — state is explicit here).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import jax

from ....core.graph import GraphModule, Input, Variable
from ....core.module import (Layer, get_layer_class, register_layer,
                             serial_class_name)
from ....data.dataset import Dataset
from ....train import triggers as trigger_lib
from ....train.trainer import Trainer
from . import metrics as metrics_lib
from . import objectives as objectives_lib
from . import optimizers as optimizers_lib


class KerasNet(Layer):
    """Abstract compiled-model lifecycle shared by Sequential and Model."""

    stateful = True
    stochastic = True

    def __init__(self, name=None):
        super().__init__(name=name)
        self.trainer: Optional[Trainer] = None
        self._inference_only = False
        self._compile_args: Optional[dict] = None
        self._tensorboard: Optional[tuple] = None
        self._summary_triggers: dict = {}
        self._checkpoint: Optional[tuple] = None
        self._clip_norm = None
        self._clip_value = None
        self._weights_loaded = False

    # ---- to be provided by subclasses ----
    def to_graph(self) -> GraphModule:
        raise NotImplementedError

    # ---- compile/fit lifecycle (Topology.scala:107-330) ----
    def compile(self, optimizer, loss, metrics: Sequence = (),
                mesh=None, strategy: str = "replicate", seed: int = 0,
                compute_dtype=None):
        loss_fn = objectives_lib.get(loss)
        opt = optimizers_lib.get(optimizer, clip_norm=self._clip_norm,
                                 clip_value=self._clip_value)
        # string metrics inherit the loss's label base, so e.g.
        # loss=ClassNLLCriterion(zero_based_label=False) +
        # metrics=["accuracy"] rebases the accuracy comparison too
        zero_based = getattr(loss_fn, "zero_based_label", True)
        metric_objs = [metrics_lib.get(m, zero_based_label=zero_based)
                       for m in metrics]
        prev_state = (self.trainer.state if self.trainer is not None
                      else None)
        # weights survive the trainer swap only when they carry meaning:
        # explicitly loaded/set, or produced by a previous real compile.
        # ensure_inference_ready's auto-init is NOT meaningful — adopting
        # it would silently override this compile's seed.
        meaningful = self._weights_loaded or not self._inference_only
        self.trainer = Trainer(self.to_graph(), loss_fn, opt,
                               metrics=metric_objs, mesh=mesh,
                               strategy=strategy, seed=seed,
                               compute_dtype=compute_dtype)
        if prev_state is not None and meaningful:
            try:
                self.trainer.adopt_weights(prev_state.params,
                                           prev_state.model_state)
            except ValueError as e:
                if self._weights_loaded:
                    # weights the user explicitly loaded/set must never be
                    # dropped silently
                    raise ValueError(
                        f"loaded weights no longer match the model "
                        f"architecture at compile time: {e}") from e
                # weights from a previous compile of a since-changed
                # architecture (e.g. add() after fit): fresh init
                pass
        if self._tensorboard:
            self.trainer.set_tensorboard(*self._tensorboard)
            self._apply_summary_triggers()
        if self._checkpoint:
            self.trainer.set_checkpoint(*self._checkpoint)
        self._compile_args = {"optimizer": optimizer, "loss": loss,
                              "metrics": list(metrics)}
        self._inference_only = False
        return self

    # ---- freeze / unfreeze (reference GraphNet freeze_up_to/unfreeze,
    # pyzoo net.py:85-104).  SINGLE source of truth: ``layer.trainable``
    # flags (the same flags GraphNet and the graph's stop_gradient path
    # use).  The Trainer derives an optimizer mask from the flags —
    # frozen layers receive EXACTLY zero updates (stop_gradient alone
    # would leave stateful optimizers moving them on stale momentum) —
    # and refreshes in place: weights, epoch/step counters AND
    # optimizer statistics all survive the toggle (the mask's state
    # structure is invariant under freeze/unfreeze). ----
    def _layers_by_name(self):
        out = {}
        for v in self.to_graph().nodes:
            if v.layer is not None:
                out.setdefault(v.layer.name, v.layer)
        return out

    def _resolve_layer_names(self, names):
        if isinstance(names, str):
            names = [names]
        known = self._layers_by_name()
        unknown = [n for n in names if n not in known]
        if unknown:
            raise ValueError(f"unknown layer names {unknown}; known: "
                             f"{sorted(known)}")
        return list(names), known

    def _sync_freeze(self):
        if self.trainer is not None:
            self.trainer.refresh_optimizer()
        return self

    def freeze(self, names):
        """Freeze the named layers (zero weight updates in training) —
        reference ``freeze`` semantics; takes effect immediately."""
        names, known = self._resolve_layer_names(names)
        for n in names:
            known[n].trainable = False
        return self._sync_freeze()

    def freeze_up_to(self, names):
        """Freeze every layer from the inputs up to (inclusive) the
        named layers — ANCESTORS only, parallel branches stay trainable
        (reference ``freeze_up_to`` / NetUtils.scala:216-277)."""
        names, _ = self._resolve_layer_names(names)
        graph = self.to_graph()
        targets = [v for v in graph.nodes
                   if v.layer is not None and v.layer.name in names]
        from ....core.graph import InputLayer
        for t in targets:
            for v in t.ancestors():
                if v.layer is not None and not isinstance(v.layer,
                                                          InputLayer):
                    v.layer.trainable = False
        return self._sync_freeze()

    def unfreeze(self, names=None):
        """Unfreeze the named layers (all when ``names`` is None) —
        reference ``unfreeze``."""
        if names is None:
            for layer in self._layers_by_name().values():
                layer.trainable = True
        else:
            names, known = self._resolve_layer_names(names)
            for n in names:
                known[n].trainable = True
        return self._sync_freeze()

    def frozen_layer_names(self) -> List[str]:
        return sorted(n for n, l in self._layers_by_name().items()
                      if not l.trainable)

    def ensure_inference_ready(self) -> Trainer:
        """Attach an inference-only trainer when the model was never
        compiled (reference predict works on a bare loaded model).  Does
        NOT satisfy _require_compiled — a later fit still demands a real
        compile with the user's loss/optimizer."""
        if self.trainer is None:
            self.trainer = Trainer(self.to_graph(), None,
                                   optimizers_lib.get("sgd"))
            self._inference_only = True
        self.trainer.ensure_initialized()
        return self.trainer

    def set_tensorboard(self, log_dir: str, app_name: str,
                        profile: bool = False, profile_steps: int = 10):
        """``profile=True`` additionally captures one jax.profiler trace
        per fit so TensorBoard shows step timelines (SURVEY §5 tracing
        parity)."""
        self._tensorboard = (log_dir, app_name, profile, profile_steps)
        if self.trainer is not None:
            self.trainer.set_tensorboard(log_dir, app_name,
                                         profile=profile,
                                         profile_steps=profile_steps)
            self._apply_summary_triggers()

    @property
    def train_summary(self):
        """The live TrainSummary writer — reference getTrainSummary.
        ``None`` until both set_tensorboard and compile have run; use
        ``set_summary_trigger`` on the model to queue a trigger at any
        point."""
        return None if self.trainer is None else self.trainer.train_summary

    def set_summary_trigger(self, tag: str, trigger):
        """Throttle a summary tag (BigDL setSummaryTrigger).  Safe to
        call before compile/set_tensorboard — the trigger is applied to
        the TrainSummary as soon as it exists."""
        self._summary_triggers[tag] = trigger
        if self.train_summary is not None:
            self.train_summary.set_summary_trigger(tag, trigger)
        return self

    def _apply_summary_triggers(self):
        if self.train_summary is not None:
            for tag, trig in self._summary_triggers.items():
                self.train_summary.set_summary_trigger(tag, trig)

    @property
    def val_summary(self):
        return None if self.trainer is None else self.trainer.val_summary

    def set_checkpoint(self, path: str, over_write: bool = True):
        self._checkpoint = (path, over_write)
        if self.trainer is not None:
            self.trainer.set_checkpoint(path, over_write)

    def set_gradient_clipping_by_l2_norm(self, clip_norm: float):
        """Parity: Topology.scala:219-224; call before compile."""
        self._clip_norm = float(clip_norm)

    def set_constant_gradient_clipping(self, min_value: float,
                                       max_value: float):
        """Parity: Topology.scala:207-213; call before compile."""
        self._clip_value = (float(min_value), float(max_value))

    def clear_gradient_clipping(self):
        """Parity: Topology.scala:200-205 / topology.py:88; call before
        compile."""
        self._clip_norm = None
        self._clip_value = None

    def get_layer(self, name: str):
        """Retrieve a layer by its unique name (topology.py:277)."""
        matches = [l for l in self.to_graph().layers if l.name == name]
        if not matches:
            raise ValueError(f"no layer named {name!r}")
        if len(matches) > 1:
            raise ValueError(
                f"{len(matches)} layers named {name!r} — names must be "
                "unique for get_layer")
        return matches[0]

    def _require_compiled(self):
        if self.trainer is None or self._inference_only:
            raise RuntimeError(
                "Model must be compiled before fit/evaluate "
                "(reference requires compile before fit too)")

    def fit(self, x, y=None, batch_size: int = 32, nb_epoch: int = 1,
            validation_data=None, shuffle: bool = True,
            verbose: bool = False, resume: bool = False):
        """x may be a Dataset or ndarray(s); mirrors fit(RDD/ImageSet/
        DataSet) overloads (Topology.scala:255-330).

        ``resume=True`` is the failure-recovery path (SURVEY §5: frequent
        async checkpoints + re-init from latest): when the
        ``set_checkpoint`` directory holds a snapshot, training state is
        restored from the newest one — with re-sharding, so a different
        mesh/strategy works — and ``nb_epoch`` MORE epochs run from
        there.  A fresh run (no snapshot yet) starts normally, so the
        same script is crash-safe without edits.
        """
        self._require_compiled()
        if resume:
            if not self._checkpoint:
                raise ValueError(
                    "fit(resume=True) needs set_checkpoint(path) first")
            from ....train.checkpoint import latest_tag
            ckpt_dir = self._checkpoint[0]
            if latest_tag(ckpt_dir) is not None:
                self.trainer.load_weights(ckpt_dir)
                import logging
                logging.getLogger("analytics_zoo_tpu").info(
                    "fit: resumed from %s at epoch %d step %d", ckpt_dir,
                    self.trainer.state.epoch, self.trainer.state.step)
        ds = x if isinstance(x, Dataset) else Dataset.from_ndarray(x, y)
        val_ds = None
        if validation_data is not None:
            val_ds = (validation_data if isinstance(validation_data, Dataset)
                      else Dataset.from_ndarray(*validation_data))
        start_epoch = self.trainer.state.epoch if self.trainer.state else 0
        return self.trainer.fit(
            ds, batch_size,
            end_trigger=trigger_lib.MaxEpoch(start_epoch + nb_epoch),
            validation_data=val_ds, shuffle=shuffle, verbose=verbose)

    def evaluate(self, x, y=None, batch_size: int = 32,
                 metrics=None) -> Dict[str, float]:
        """``metrics`` overrides the compiled metric set for this call
        (reference evaluate(rdd, batch, valMethods), Topology.scala:353)."""
        self._require_compiled()
        ds = x if isinstance(x, Dataset) else Dataset.from_ndarray(x, y)
        return self.trainer.evaluate(ds, batch_size, metrics=metrics)

    def predict(self, x, batch_size: int = 32, distributed: bool = True):
        self.ensure_inference_ready()
        return self.trainer.predict(x, batch_size)

    def transfer_weights_from(self, other: "KerasNet") -> "KerasNet":
        """Copy weights of layers shared (by name) with ``other`` — the
        transfer-learning step after graph surgery.  A Model re-rooted
        with ``new_graph``/new heads shares layer *instances* with its
        source, but weights live in each model's trainer, so the new
        model starts from random init until this pulls the trained
        entries across (the reference gets this implicitly because BigDL
        weights live inside module objects)."""
        src = other.ensure_inference_ready().state
        dst_trainer = self.ensure_inference_ready()
        dst = dst_trainer.state
        copied = []

        def merge(mine: dict, theirs: dict) -> dict:
            out = dict(mine)
            for k, v in theirs.items():
                if k not in out:
                    continue
                mine_shapes = jax.tree_util.tree_map(np.shape, out[k])
                their_shapes = jax.tree_util.tree_map(np.shape, v)
                if mine_shapes != their_shapes:
                    raise ValueError(
                        f"transfer_weights_from: layer {k!r} has shapes "
                        f"{their_shapes} in the source but {mine_shapes} "
                        "here")
                out[k] = v
                copied.append(k)
            return out

        merged_params = merge(dst.params, src.params)
        merged_state = merge(dst.model_state, src.model_state)
        if not copied:
            raise ValueError(
                "transfer_weights_from: no layer names in common — the "
                "models do not share layer instances")
        # adopt_weights re-places the merged tree under THIS trainer's
        # shardings (a bare device_put would keep the source placement —
        # wrong when the destination is mesh-sharded)
        dst_trainer.adopt_weights(merged_params, merged_state)
        self._weights_loaded = True
        return self

    def quantize(self) -> "Model":
        """Post-training int8 quantization: returns an inference-only
        functional Model whose Dense/Conv layers run int8 matmuls/convs
        with int32 accumulation on the MXU (reference ``*-quantize``
        registry variants; quantized-inference scheme wp-bigdl.md:186-196).
        Weights are per-output-channel symmetric int8 (4x smaller);
        activations quantize dynamically per batch inside the jit."""
        from ....ops.quantize import quantize_graph
        trainer = self.ensure_inference_ready()
        g = self.to_graph()
        qg, qparams, qstate = quantize_graph(
            g, trainer.state.params, trainer.state.model_state)
        out = (qg.output_vars[0] if qg.single_output
               else list(qg.output_vars))
        qm = Model(input=list(qg.input_vars), output=out,
                   name=f"{self.name}_int8")
        # build the inference trainer and adopt directly — going through
        # ensure_inference_ready would materialize a throwaway full init
        # that adopt_weights immediately overwrites.  Mesh/strategy carry
        # over: a model sharded because it does not fit replicated must
        # not come back fully replicated as int8.
        qm.trainer = Trainer(qm.to_graph(), None,
                             optimizers_lib.get("sgd"),
                             mesh=trainer.mesh,
                             strategy=trainer.strategy)
        qm._inference_only = True
        qm.trainer.adopt_weights(qparams, qstate)
        qm._weights_loaded = True
        return qm

    def to_serving(self, supported_concurrent_num: int = 1,
                   max_batch_size: int = 32, coalescing: bool = False,
                   max_wait_ms: float = 2.0, quantize: Optional[bool] = None,
                   warmup_shapes=None, replicas=1):
        """Wrap this net in an ``InferenceModel`` on the serving fast
        path (shape-bucketed executable cache; optional request
        coalescing; ``replicas="all"`` places the executables on every
        local device — see docs/serving.md).  ``warmup_shapes`` (a
        per-sample shape, or list of them for multi-input) AOT-compiles
        the whole bucket ladder before traffic arrives."""
        from ....pipeline.inference import InferenceModel
        im = InferenceModel(
            supported_concurrent_num=supported_concurrent_num,
            max_batch_size=max_batch_size, coalescing=coalescing,
            max_wait_ms=max_wait_ms, replicas=replicas)
        im.load_keras_net(self, quantize=quantize)
        if warmup_shapes is not None and im._cache is not None:
            # quantized handles serve on the exact-shape path (no
            # bucket ladder to pre-compile)
            im.warmup(warmup_shapes)
        return im

    def predict_classes(self, x, batch_size: int = 32,
                        zero_based_label: bool = True):
        """Parity: Topology.scala:469 (zero-based label toggle)."""
        probs = self.predict(x, batch_size)
        classes = np.argmax(probs, axis=-1)
        return classes if zero_based_label else classes + 1

    # ---- persistence (ZooModel.scala:78-124) ----
    def save_model(self, path: str, over_write: bool = True):
        os.makedirs(path, exist_ok=True)
        arch = {"class_name": type(self).__name__,
                "config": self.get_config()}
        arch_path = os.path.join(path, "architecture.json")
        if os.path.exists(arch_path) and not over_write:
            raise FileExistsError(path)
        with open(arch_path, "w") as f:
            json.dump(arch, f)
        if self.trainer is not None and self.trainer.state is not None:
            self.trainer.save_weights(os.path.join(path, "weights"))

    @staticmethod
    def load_model(path: str) -> "KerasNet":
        with open(os.path.join(path, "architecture.json")) as f:
            arch = json.load(f)
        cls = resolve_model_class(arch["class_name"])
        model = cls.from_config(arch["config"])
        weights_dir = os.path.join(path, "weights")
        if os.path.isdir(weights_dir):
            if model._compile_args is not None:
                model.compile(**model._compile_args)
                model.trainer.ensure_initialized()
            else:
                model.ensure_inference_ready()
            model.trainer.load_weights(weights_dir)
            model._weights_loaded = True
        return model

    def get_weights(self):
        self.ensure_inference_ready()
        return jax.device_get(self.trainer.state.params)

    def set_weights(self, params):
        self.ensure_inference_ready()
        own = self.trainer.state.params
        if (isinstance(params, dict) and isinstance(own, dict)
                and set(params) != set(own) and len(params) == len(own)):
            # weights from a structurally identical model whose layers got
            # different auto-names: remap by position (the reference
            # transfers weights positionally too) — but refuse silently
            # mis-shaped assignments
            remapped = {}
            for ok, pk in zip(own, params):
                own_shapes = jax.tree_util.tree_map(np.shape, own[ok])
                new_shapes = jax.tree_util.tree_map(np.shape, params[pk])
                if own_shapes != new_shapes:
                    raise ValueError(
                        f"set_weights: positional remap of {pk!r} onto "
                        f"{ok!r} has mismatched shapes {new_shapes} vs "
                        f"{own_shapes}")
                remapped[ok] = params[pk]
            params = remapped
        self.trainer.state.params = jax.device_put(params)
        self._weights_loaded = True

    def load_weights(self, directory: str, tag=None):
        """Load checkpointed weights into the model (marks them as user
        weights so a later compile preserves them)."""
        self.ensure_inference_ready()
        self.trainer.load_weights(directory, tag)
        self._weights_loaded = True
        return self

    # ---- summary (Topology.scala printNodeSummary parity) ----
    def summary(self) -> str:
        graph = self.to_graph()
        lines = [f"Model: {self.name}", "-" * 64]
        total = 0
        import jax.numpy as jnp
        rng = jax.random.PRNGKey(0)
        params, _ = graph.init(rng)
        for layer in graph.layers:
            p = params.get(layer.name, {})
            count = sum(int(np.prod(np.shape(leaf)))
                        for leaf in jax.tree_util.tree_leaves(p))
            total += count
            lines.append(f"{layer.name:<36} {type(layer).__name__:<20} "
                         f"params: {count}")
        lines.append("-" * 64)
        lines.append(f"Total params: {total}")
        text = "\n".join(lines)
        print(text)
        return text

    def save_graph_topology(self, log_path: str) -> str:
        """Write the model's graph topology for inspection — parity with
        the reference's ``saveGraphTopology`` (Topology.scala:536-546,
        which exports the graph to a TensorBoard log dir).

        Emits two files under ``log_path``:
        ``graph_topology.txt`` (node -> inputs with shapes, in topological
        order) and ``graph_topology.dot`` (Graphviz; render with
        ``dot -Tpng``).  Returns ``log_path``.
        """
        graph = self.to_graph()
        os.makedirs(log_path, exist_ok=True)

        def _label(v):
            kind = type(v.layer).__name__ if v.layer is not None else "Input"
            return f"{v.name} [{kind}] {tuple(v.shape) if v.shape else ''}"

        lines = [f"model: {self.name}", ""]
        for v in graph.nodes:
            src = ", ".join(i.name for i in v.inputs) or "(graph input)"
            lines.append(f"{_label(v)}  <-  {src}")
        with open(os.path.join(log_path, "graph_topology.txt"), "w") as f:
            f.write("\n".join(lines) + "\n")

        dot = ["digraph model {", "  rankdir=TB;",
               '  node [shape=box, fontsize=10];']
        for v in graph.nodes:
            dot.append(f'  n{v.node_id} [label="{_label(v)}"];')
            for i in v.inputs:
                dot.append(f"  n{i.node_id} -> n{v.node_id};")
        dot.append("}")
        with open(os.path.join(log_path, "graph_topology.dot"), "w") as f:
            f.write("\n".join(dot) + "\n")
        return log_path

    # ---- layer delegation so a compiled net can be nested as a Layer ----
    def init(self, rng, input_shape=None):
        return self.to_graph().init(rng, input_shape)

    def apply(self, params, state, inputs, training=False, rng=None):
        return self.to_graph().apply(params, state, inputs,
                                     training=training, rng=rng)

    def call(self, params, state, inputs, training=False, rng=None):
        return self.apply(params, state, inputs, training=training,
                          rng=rng)[0]

    def compute_output_shape(self, input_shape):
        return self.to_graph().compute_output_shape(input_shape)


@register_layer
class Sequential(KerasNet):
    """add()-style container (Topology.scala:716-837)."""

    def __init__(self, name=None):
        super().__init__(name=name)
        self._layers: List[Layer] = []
        self._graph: Optional[GraphModule] = None

    def add(self, layer: Layer) -> "Sequential":
        if not self._layers and getattr(layer, "batch_input_shape",
                                        None) is None \
                and not isinstance(layer, KerasNet):
            raise ValueError(
                "First layer needs input_shape (reference Sequential "
                "requires the same)")
        self._layers.append(layer)
        self._graph = None
        return self

    @property
    def layers(self):
        return list(self._layers)

    def to_graph(self) -> GraphModule:
        if self._graph is None:
            first = self._layers[0]
            shape = getattr(first, "batch_input_shape", None)
            if shape is None and isinstance(first, KerasNet):
                inner = first.to_graph()
                shape = inner.input_shapes[0]
            x = Input(tuple(shape[1:]), name=f"{self.name}_input")
            h = x
            for layer in self._layers:
                if isinstance(layer, KerasNet):
                    h = layer.to_graph()(h)
                else:
                    h = layer(h)
            self._graph = GraphModule(x, h, name=self.name)
        return self._graph

    def to_model(self) -> "Model":
        """Convert to the functional ``Model`` form
        (Topology.scala:805 / topology.py:316)."""
        g = self.to_graph()
        inp = g.input_vars[0] if len(g.input_vars) == 1 else g.input_vars
        out = (g.output_vars[0] if g.single_output and
               len(g.output_vars) == 1 else g.output_vars)
        model = Model(input=inp, output=out, name=self.name)
        if self.trainer is not None:
            model.trainer = self.trainer
            model._compile_args = self._compile_args
            model._inference_only = self._inference_only
        return model

    def get_config(self):
        return {
            "name": self.name,
            "layers": [{"class_name": serial_class_name(l),
                        "config": l.get_config()} for l in self._layers],
            "compile_args": self._compile_args,
        }

    @classmethod
    def from_config(cls, config):
        model = cls(name=config.get("name"))
        for spec in config["layers"]:
            layer_cls = get_layer_class(spec["class_name"])
            model.add(layer_cls.from_config(spec["config"]))
        model._compile_args = config.get("compile_args")
        return model


@register_layer
class Model(KerasNet):
    """Functional graph model over Variables (Topology.scala:509-714)."""

    def __init__(self, input=None, output=None, name=None):
        super().__init__(name=name)
        if input is None or output is None:
            raise ValueError("Model requires input and output Variables")
        self._graph = GraphModule(input, output, name=self.name)
        self.inputs = self._graph.input_vars
        self.outputs = self._graph.output_vars

    def to_graph(self) -> GraphModule:
        return self._graph

    def new_graph(self, outputs: List[str]) -> "Model":
        """Graph surgery: re-root on named intermediate outputs
        (reference GraphNet.new_graph, NetUtils.scala:216-277)."""
        by_name = {v.name: v for v in self._graph.nodes}
        outs = [by_name[n] for n in outputs]
        # one name -> single-output model (predict returns the array, not
        # a one-element list)
        return Model(input=self._graph.input_vars,
                     output=outs[0] if len(outs) == 1 else outs,
                     name=f"{self.name}_sub")

    def get_config(self):
        # serialize the node graph: topo-ordered nodes w/ layer configs
        nodes = []
        input_ids = [v.node_id for v in self._graph.input_vars]
        for v in self._graph.nodes:
            nodes.append({
                "id": v.node_id,
                "name": v.name,
                "layer": None if v.layer is None else {
                    "class_name": serial_class_name(v.layer),
                    "config": v.layer.get_config()},
                "inputs": [p.node_id for p in v.inputs],
                "shape": [d for d in v.shape],
            })
        return {"name": self.name, "nodes": nodes,
                "input_ids": input_ids,
                "output_ids": [v.node_id for v in self._graph.output_vars],
                "single_output": self._graph.single_output,
                "compile_args": self._compile_args}

    @classmethod
    def from_config(cls, config):
        from ....core.graph import InputLayer
        built: Dict[int, Variable] = {}
        layer_cache: Dict[str, Layer] = {}
        for spec in config["nodes"]:
            if spec["layer"] is None or \
                    spec["layer"]["class_name"] == "InputLayer":
                layer_cfg = (spec["layer"] or {}).get("config", {})
                shape = tuple(layer_cfg.get("input_shape") or
                              [d for d in spec["shape"][1:]])
                v = Input(shape, name=spec["name"])
                built[spec["id"]] = v
                continue
            lname = spec["layer"]["config"].get("name", spec["name"])
            if lname in layer_cache:
                layer = layer_cache[lname]
            else:
                layer_cls = get_layer_class(spec["layer"]["class_name"])
                layer = layer_cls.from_config(dict(spec["layer"]["config"]))
                layer_cache[lname] = layer
            parents = [built[i] for i in spec["inputs"]]
            built[spec["id"]] = layer(parents if len(parents) > 1
                                      else parents[0])
        model = cls(input=[built[i] for i in config["input_ids"]],
                    output=[built[i] for i in config["output_ids"]],
                    name=config.get("name"))
        # restore the saved output arity (older configs lack the key:
        # fall back to "one output means single")
        model._graph.single_output = config.get(
            "single_output", len(config["output_ids"]) == 1)
        model._compile_args = config.get("compile_args")
        return model


_MODEL_CLASSES = {"Sequential": Sequential, "Model": Model}


def resolve_model_class(name: str):
    """Model-class lookup for every load path (KerasNet.load_model,
    NNModel.load).  Zoo families register on models-package import — a
    cold process that loads a save before ever importing the zoo must
    not KeyError on registration order, so the unknown-name path
    imports it on demand (same pattern as get_layer_class's keras2
    on-demand import)."""
    if name not in _MODEL_CLASSES:
        import analytics_zoo_tpu.models  # noqa: F401
    return _MODEL_CLASSES[name]


def load_model(path: str) -> KerasNet:
    return KerasNet.load_model(path)
