"""Validation metrics.

Parity surface: reference zoo/.../pipeline/api/keras/metrics/{Accuracy,
Top5Accuracy, AUC}.scala.  Accuracy is zero-based-label aware
(Accuracy.scala:30); AUC uses the reference's threshold-sweep formulation
(AUC.scala:128, thresholdNum default 200).

Metrics are streaming: ``init() -> acc``, ``update(acc, y_true, y_pred,
mask=None) -> acc``, ``result(acc) -> scalar``.  The accumulator is a small
pytree of jnp scalars, so updates run inside the jitted eval step and only
``result`` pulls a host value.  ``mask`` is an optional per-sample 0/1
weight vector — the trailing partial batch of an evaluation is padded to
the compiled batch shape and masked out, so metrics cover the exact ``n``
samples (the reference evaluates the full set, Topology.scala:353).
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp


def _sample_mask(mask, n):
    """Resolve mask to a float (n,) weight vector (all-ones when None).
    When predictions flatten to batch*T elements (sequence outputs) the
    per-sample mask is repeated so each sample's T elements share its
    weight."""
    if mask is None:
        return jnp.ones((n,), jnp.float32)
    w = mask.reshape(-1).astype(jnp.float32)
    if w.shape[0] != n and n % w.shape[0] == 0:
        w = jnp.repeat(w, n // w.shape[0])
    return w


class Metric:
    name = "metric"

    def init(self):
        raise NotImplementedError

    def update(self, acc, y_true, y_pred, mask=None):
        raise NotImplementedError

    def result(self, acc):
        raise NotImplementedError


class Accuracy(Metric):
    """Classification accuracy; handles scalar/int labels and one-hot
    labels, binary (sigmoid) and multiclass (softmax) outputs.

    ``zero_based_label`` mirrors the reference's ``Accuracy.scala:30``
    parameter: pass ``False`` when integer labels are 1-based (the BigDL
    ClassNLLCriterion convention — e.g. ratings 1..5), so the argmax
    comparison is rebased instead of being systematically shifted."""

    name = "accuracy"

    def __init__(self, zero_based_label=True):
        self.zero_based_label = zero_based_label

    def init(self):
        return {"correct": jnp.zeros(()), "total": jnp.zeros(())}

    def update(self, acc, y_true, y_pred, mask=None):
        if y_pred.ndim >= 2 and y_pred.shape[-1] > 1:
            pred = jnp.argmax(y_pred, axis=-1)
            if y_true.ndim == y_pred.ndim and y_true.shape[-1] == y_pred.shape[-1]:
                true = jnp.argmax(y_true, axis=-1)
            else:
                true = jnp.squeeze(y_true).astype(jnp.int32)
                if not self.zero_based_label:
                    true = true - 1
                true = true.reshape(pred.shape)
        else:
            pred = (jnp.squeeze(y_pred, -1) if y_pred.ndim > 1 else
                    y_pred) > 0.5
            true = (jnp.squeeze(y_true, -1) if y_true.ndim > 1 else
                    y_true)
            if not self.zero_based_label:
                # 1-based binary labels {1, 2} -> {0, 1} before threshold
                true = true - 1
            true = true > 0.5
        w = _sample_mask(mask, pred.shape[0] if pred.ndim else 1)
        w = w.reshape((-1,) + (1,) * (pred.ndim - 1))
        per_elem = w * jnp.ones(pred.shape, jnp.float32)
        correct = jnp.sum((pred == true) * per_elem)
        return {"correct": acc["correct"] + correct,
                "total": acc["total"] + jnp.sum(per_elem)}

    def result(self, acc):
        return acc["correct"] / jnp.maximum(acc["total"], 1)


class Top5Accuracy(Metric):
    name = "top5accuracy"

    def __init__(self, zero_based_label=True):
        self.zero_based_label = zero_based_label

    def init(self):
        return {"correct": jnp.zeros(()), "total": jnp.zeros(())}

    def update(self, acc, y_true, y_pred, mask=None):
        true = jnp.squeeze(y_true).astype(jnp.int32).reshape(-1)
        if not self.zero_based_label:
            true = true - 1
        w = _sample_mask(mask, true.shape[0])
        top5 = jnp.argsort(y_pred, axis=-1)[..., -5:].reshape(len(true), 5)
        correct = jnp.sum(jnp.any(top5 == true[:, None], axis=-1) * w)
        return {"correct": acc["correct"] + correct,
                "total": acc["total"] + jnp.sum(w)}

    def result(self, acc):
        return acc["correct"] / jnp.maximum(acc["total"], 1)


class AUC(Metric):
    """Area under ROC via threshold sweep (reference AUC.scala:128)."""

    name = "auc"

    def __init__(self, threshold_num: int = 200):
        self.threshold_num = int(threshold_num)

    def init(self):
        n = self.threshold_num
        return {"tp": jnp.zeros((n,)), "fp": jnp.zeros((n,)),
                "pos": jnp.zeros(()), "neg": jnp.zeros(())}

    def update(self, acc, y_true, y_pred, mask=None):
        scores = y_pred
        if scores.ndim > 1 and scores.shape[-1] == 2:
            scores = scores[..., 1]  # binary softmax: P(positive class)
        scores = scores.reshape(-1)
        labels = y_true
        if labels.ndim > 1 and labels.shape[-1] == 2:
            labels = jnp.argmax(labels, axis=-1)
        labels = labels.reshape(-1) > 0.5
        if scores.shape[0] != labels.shape[0]:
            raise ValueError(
                f"AUC is a binary metric: y_pred {y_pred.shape} does not "
                f"reduce to one score per sample of y_true {y_true.shape}")
        w = _sample_mask(mask, scores.shape[0])
        thresholds = jnp.linspace(0.0, 1.0, self.threshold_num)
        above = scores[None, :] >= thresholds[:, None]  # (n_thresh, n)
        pos_w = labels * w
        neg_w = (~labels) * w
        tp = jnp.sum(above * pos_w[None, :], axis=1)
        fp = jnp.sum(above * neg_w[None, :], axis=1)
        return {"tp": acc["tp"] + tp, "fp": acc["fp"] + fp,
                "pos": acc["pos"] + jnp.sum(pos_w),
                "neg": acc["neg"] + jnp.sum(neg_w)}

    def result(self, acc):
        tpr = acc["tp"] / jnp.maximum(acc["pos"], 1)
        fpr = acc["fp"] / jnp.maximum(acc["neg"], 1)
        # integrate TPR over FPR (thresholds ascending -> rates descending)
        return -jnp.trapezoid(tpr, fpr)


class Loss(Metric):
    """Mean loss over the validation set (reference uses BigDL Loss)."""

    name = "loss"

    def __init__(self, loss_fn):
        self.loss_fn = loss_fn

    def init(self):
        return {"sum": jnp.zeros(()), "total": jnp.zeros(())}

    def update(self, acc, y_true, y_pred, mask=None):
        from .objectives import _batch_mean
        # per-position sequence losses collapse to per-sample
        per_sample = _batch_mean(self.loss_fn(y_true, y_pred))
        w = _sample_mask(mask, per_sample.shape[0])
        # masked-out padded samples may be NaN (e.g. out-of-range label
        # guards on zero-padding); NaN * 0 is NaN, so zero them first
        per_sample = jnp.where(w > 0, per_sample, 0.0)
        return {"sum": acc["sum"] + jnp.sum(per_sample * w),
                "total": acc["total"] + jnp.sum(w)}

    def result(self, acc):
        return acc["sum"] / jnp.maximum(acc["total"], 1)


class MAE(Metric):
    """Mean absolute error.

    Against a multi-class head (trailing dim > 1), **integer** targets
    one rank lower are compared class-index-wise (``|argmax(pred) -
    label|`` — the reference NCF notebook's MAE-on-log-softmax usage);
    **float** targets take the elementwise path (one target broadcast
    against each output).  Class labels must therefore be integer-dtype:
    ratings stored as float against a log-softmax head will compute
    elementwise |log-prob − rating|, which is not a class-distance.
    Cast labels with ``.astype(np.int32)`` for class-index MAE."""

    name = "mae"

    def __init__(self, zero_based_label=True):
        self.zero_based_label = zero_based_label

    def init(self):
        return {"sum": jnp.zeros(()), "total": jnp.zeros(())}

    def update(self, acc, y_true, y_pred, mask=None):
        if y_pred.ndim == y_true.ndim + 1:
            if (y_pred.shape[-1] > 1
                    and jnp.issubdtype(y_true.dtype, jnp.integer)):
                # class-distribution output vs INTEGER label (the
                # reference NCF notebook validates a 5-class log-softmax
                # with MAE): compare the predicted class to the label.
                y_pred = jnp.argmax(y_pred, axis=-1).astype(jnp.float32)
                if not self.zero_based_label:
                    y_true = y_true - 1
                y_true = y_true.astype(jnp.float32)
            elif y_pred.shape[-1] == 1:
                # (N, 1) regression head vs (N,) target: align ranks so
                # the subtraction doesn't broadcast to (N, N)
                y_pred = y_pred.squeeze(-1)
            else:
                # FLOAT target one rank below a multi-output head: stay
                # on the elementwise path — one target per sample,
                # compared against each of the k outputs (not the
                # class-index path, and not last-axis misalignment).
                # Dtypes are static, so this warning fires at TRACE
                # time — a float-stored class-label vector (ratings as
                # float32) silently changing semantics is the trap.
                import warnings
                warnings.warn(
                    "MAE against a multi-output head with FLOAT targets "
                    "uses elementwise error; if the targets are class "
                    "labels (e.g. ratings), cast them to an integer "
                    "dtype for class-index MAE.", stacklevel=2)
                y_true = y_true[..., None]
        err = jnp.abs(y_true - y_pred)
        w = _sample_mask(mask, err.shape[0] if err.ndim else 1)
        w = w.reshape((-1,) + (1,) * (err.ndim - 1))
        per_elem = w * jnp.ones(err.shape, jnp.float32)
        return {"sum": acc["sum"] + jnp.sum(err * per_elem),
                "total": acc["total"] + jnp.sum(per_elem)}

    def result(self, acc):
        return acc["sum"] / jnp.maximum(acc["total"], 1)


class _RankingMetric(Metric):
    """Shared machinery for grouped ranking metrics (BigDL HitRatio /
    NDCG, bigdl.optim ValidationMethods used by implicit-feedback NCF).

    The evaluation batch is consecutive groups of ``1 + neg_num``
    user-item pairs — one positive (label 1) and ``neg_num`` sampled
    negatives (label 0), the layout ``get_negative_samples`` produces.
    The positive's rank among its group's scores decides the credit.
    Batches must be a multiple of the group size; a masked (padded)
    sample voids its whole group.
    """

    _base_name = "ranking"

    def __init__(self, k: int = 10, neg_num: int = 100):
        self.k = int(k)
        self.neg_num = int(neg_num)
        # result key encodes k (BigDL names its results "HitRate@10");
        # two instances at different k therefore don't collide
        self.name = f"{self._base_name}@{self.k}"

    def init(self):
        return {"sum": jnp.zeros(()), "total": jnp.zeros(())}

    def _rank_and_weight(self, y_true, y_pred, mask):
        group = self.neg_num + 1
        if y_pred.ndim >= 2 and y_pred.shape[-1] > 1:
            # class-distribution output (e.g. 2-class log-softmax):
            # score = last column (the "interaction" class)
            y_pred = y_pred[..., -1]
        scores = y_pred.reshape(-1)
        labels = y_true.reshape(-1)
        n = scores.shape[0]
        if n % group:
            raise ValueError(
                f"{self.name}: batch of {n} pairs is not a multiple of "
                f"group size 1+neg_num={group}")
        w = _sample_mask(mask, n).reshape(-1, group)
        g_scores = scores.reshape(-1, group)
        g_labels = labels.reshape(-1, group).astype(jnp.float32)
        # positive's score per group (one label-1 row per group)
        pos = jnp.sum(g_scores * g_labels, axis=1)
        rank = 1 + jnp.sum(
            (g_scores > pos[:, None]) & (g_labels < 0.5), axis=1)
        g_w = jnp.min(w, axis=1)  # padded tail voids the group
        return rank, g_w

    def result(self, acc):
        return acc["sum"] / jnp.maximum(acc["total"], 1)


class HitRatio(_RankingMetric):
    """hit@k over (1 positive + neg_num negatives) groups — parity with
    BigDL ``HitRatio(k, negNum)``.  Result key: ``hit_ratio@k``."""

    _base_name = "hit_ratio"

    def update(self, acc, y_true, y_pred, mask=None):
        rank, w = self._rank_and_weight(y_true, y_pred, mask)
        hits = (rank <= self.k).astype(jnp.float32)
        return {"sum": acc["sum"] + jnp.sum(hits * w),
                "total": acc["total"] + jnp.sum(w)}


class NDCG(_RankingMetric):
    """Normalized discounted cumulative gain at k for a single positive
    per group — parity with BigDL ``NDCG(k, negNum)``:
    ndcg = log(2) / log(1 + rank) when rank <= k else 0.
    Result key: ``ndcg@k``."""

    _base_name = "ndcg"

    def update(self, acc, y_true, y_pred, mask=None):
        rank, w = self._rank_and_weight(y_true, y_pred, mask)
        gain = jnp.where(rank <= self.k,
                         jnp.log(2.0) / jnp.log(1.0 + rank), 0.0)
        return {"sum": acc["sum"] + jnp.sum(gain * w),
                "total": acc["total"] + jnp.sum(w)}


def get(name, zero_based_label=True):
    """Resolve a metric name/instance.

    ``zero_based_label`` seeds STRING-constructed label-consuming metrics
    (accuracy/top5/mae) so that ``compile(loss=ClassNLLCriterion(
    zero_based_label=False), metrics=["accuracy"])`` reports a correctly
    rebased accuracy instead of a silently base-shifted one.  Metric
    instances pass through untouched — an explicit instance's own flag
    always wins."""
    if isinstance(name, Metric):
        return name
    key = str(name).lower()
    if key in ("accuracy", "acc"):
        return Accuracy(zero_based_label=zero_based_label)
    if key in ("top5accuracy", "top5", "top5acc"):
        return Top5Accuracy(zero_based_label=zero_based_label)
    if key == "auc":
        return AUC()
    if key == "mae":
        return MAE(zero_based_label=zero_based_label)
    if key in ("hitratio", "hit_ratio", "hitrate"):
        return HitRatio()
    if key == "ndcg":
        return NDCG()
    raise ValueError(f"Unknown metric {name!r}")
