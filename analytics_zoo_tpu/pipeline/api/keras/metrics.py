"""Validation metrics.

Parity surface: reference zoo/.../pipeline/api/keras/metrics/{Accuracy,
Top5Accuracy, AUC}.scala.  Accuracy is zero-based-label aware
(Accuracy.scala:30); AUC uses the reference's threshold-sweep formulation
(AUC.scala:128, thresholdNum default 200).

Metrics are streaming: ``init() -> acc``, ``update(acc, y_true, y_pred) ->
acc``, ``result(acc) -> scalar``.  The accumulator is a small pytree of jnp
scalars, so updates run inside the jitted eval step and only ``result`` pulls
a host value.
"""

from __future__ import annotations

from typing import Any, Dict

import jax.numpy as jnp


class Metric:
    name = "metric"

    def init(self):
        raise NotImplementedError

    def update(self, acc, y_true, y_pred):
        raise NotImplementedError

    def result(self, acc):
        raise NotImplementedError


class Accuracy(Metric):
    """Classification accuracy; handles scalar/int labels (zero-based) and
    one-hot labels, binary (sigmoid) and multiclass (softmax) outputs."""

    name = "accuracy"

    def init(self):
        return {"correct": jnp.zeros(()), "total": jnp.zeros(())}

    def update(self, acc, y_true, y_pred):
        if y_pred.ndim >= 2 and y_pred.shape[-1] > 1:
            pred = jnp.argmax(y_pred, axis=-1)
            if y_true.ndim == y_pred.ndim and y_true.shape[-1] == y_pred.shape[-1]:
                true = jnp.argmax(y_true, axis=-1)
            else:
                true = jnp.squeeze(y_true).astype(jnp.int32)
                true = true.reshape(pred.shape)
        else:
            pred = (jnp.squeeze(y_pred, -1) if y_pred.ndim > 1 else
                    y_pred) > 0.5
            true = (jnp.squeeze(y_true, -1) if y_true.ndim > 1 else
                    y_true) > 0.5
        correct = jnp.sum(pred == true)
        return {"correct": acc["correct"] + correct,
                "total": acc["total"] + pred.size}

    def result(self, acc):
        return acc["correct"] / jnp.maximum(acc["total"], 1)


class Top5Accuracy(Metric):
    name = "top5accuracy"

    def init(self):
        return {"correct": jnp.zeros(()), "total": jnp.zeros(())}

    def update(self, acc, y_true, y_pred):
        true = jnp.squeeze(y_true).astype(jnp.int32).reshape(-1)
        top5 = jnp.argsort(y_pred, axis=-1)[..., -5:].reshape(len(true), 5)
        correct = jnp.sum(jnp.any(top5 == true[:, None], axis=-1))
        return {"correct": acc["correct"] + correct,
                "total": acc["total"] + len(true)}

    def result(self, acc):
        return acc["correct"] / jnp.maximum(acc["total"], 1)


class AUC(Metric):
    """Area under ROC via threshold sweep (reference AUC.scala:128)."""

    name = "auc"

    def __init__(self, threshold_num: int = 200):
        self.threshold_num = int(threshold_num)

    def init(self):
        n = self.threshold_num
        return {"tp": jnp.zeros((n,)), "fp": jnp.zeros((n,)),
                "pos": jnp.zeros(()), "neg": jnp.zeros(())}

    def update(self, acc, y_true, y_pred):
        scores = y_pred
        if scores.ndim > 1 and scores.shape[-1] == 2:
            scores = scores[..., 1]  # binary softmax: P(positive class)
        scores = scores.reshape(-1)
        labels = y_true
        if labels.ndim > 1 and labels.shape[-1] == 2:
            labels = jnp.argmax(labels, axis=-1)
        labels = labels.reshape(-1) > 0.5
        if scores.shape[0] != labels.shape[0]:
            raise ValueError(
                f"AUC is a binary metric: y_pred {y_pred.shape} does not "
                f"reduce to one score per sample of y_true {y_true.shape}")
        thresholds = jnp.linspace(0.0, 1.0, self.threshold_num)
        above = scores[None, :] >= thresholds[:, None]  # (n_thresh, n)
        tp = jnp.sum(above & labels[None, :], axis=1)
        fp = jnp.sum(above & ~labels[None, :], axis=1)
        return {"tp": acc["tp"] + tp, "fp": acc["fp"] + fp,
                "pos": acc["pos"] + jnp.sum(labels),
                "neg": acc["neg"] + jnp.sum(~labels)}

    def result(self, acc):
        tpr = acc["tp"] / jnp.maximum(acc["pos"], 1)
        fpr = acc["fp"] / jnp.maximum(acc["neg"], 1)
        # integrate TPR over FPR (thresholds ascending -> rates descending)
        return -jnp.trapezoid(tpr, fpr)


class Loss(Metric):
    """Mean loss over the validation set (reference uses BigDL Loss)."""

    name = "loss"

    def __init__(self, loss_fn):
        self.loss_fn = loss_fn

    def init(self):
        return {"sum": jnp.zeros(()), "total": jnp.zeros(())}

    def update(self, acc, y_true, y_pred):
        per_sample = self.loss_fn(y_true, y_pred)
        return {"sum": acc["sum"] + jnp.sum(per_sample),
                "total": acc["total"] + per_sample.shape[0]}

    def result(self, acc):
        return acc["sum"] / jnp.maximum(acc["total"], 1)


class MAE(Metric):
    name = "mae"

    def init(self):
        return {"sum": jnp.zeros(()), "total": jnp.zeros(())}

    def update(self, acc, y_true, y_pred):
        return {"sum": acc["sum"] + jnp.sum(jnp.abs(y_true - y_pred)),
                "total": acc["total"] + y_pred.size}

    def result(self, acc):
        return acc["sum"] / jnp.maximum(acc["total"], 1)


def get(name):
    if isinstance(name, Metric):
        return name
    key = str(name).lower()
    if key in ("accuracy", "acc"):
        return Accuracy()
    if key in ("top5accuracy", "top5", "top5acc"):
        return Top5Accuracy()
    if key == "auc":
        return AUC()
    if key == "mae":
        return MAE()
    raise ValueError(f"Unknown metric {name!r}")
