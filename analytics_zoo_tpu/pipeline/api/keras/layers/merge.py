"""Merge layer and functional merge helpers.

Parity surface: reference zoo/.../pipeline/api/keras/layers/Merge.scala with
modes sum/mul/max/min/ave/sub/div/concat/dot/cosine, plus the keras2-style
Maximum/Minimum/Average wrappers (zoo/.../pipeline/api/keras2/layers).
"""

from __future__ import annotations

import jax.numpy as jnp

from .....core.graph import broadcast_shapes
from .....core.module import Layer, register_layer


@register_layer
class Merge(Layer):
    """Merge a list of inputs into one tensor (reference Merge.scala)."""

    def __init__(self, layers=None, mode="sum", concat_axis=-1,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.mode = mode
        self.concat_axis = int(concat_axis)
        self.layers = layers  # Sequential-embedded branch layers (optional)

    def call(self, params, state, inputs, training=False, rng=None):
        xs = list(inputs)
        m = self.mode
        if m == "sum":
            out = xs[0]
            for x in xs[1:]:
                out = out + x
            return out
        if m == "mul":
            out = xs[0]
            for x in xs[1:]:
                out = out * x
            return out
        if m == "max":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.maximum(out, x)
            return out
        if m == "min":
            out = xs[0]
            for x in xs[1:]:
                out = jnp.minimum(out, x)
            return out
        if m == "ave":
            return sum(xs) / float(len(xs))
        if m == "sub":
            return xs[0] - xs[1]
        if m == "div":
            return xs[0] / xs[1]
        if m == "concat":
            return jnp.concatenate(xs, axis=self.concat_axis)
        if m == "dot":
            return jnp.sum(xs[0] * xs[1], axis=-1, keepdims=True)
        if m == "cosine":
            a = xs[0] / jnp.maximum(
                jnp.linalg.norm(xs[0], axis=-1, keepdims=True), 1e-12)
            b = xs[1] / jnp.maximum(
                jnp.linalg.norm(xs[1], axis=-1, keepdims=True), 1e-12)
            return jnp.sum(a * b, axis=-1, keepdims=True)
        raise ValueError(f"Unknown merge mode {self.mode!r}")

    def compute_output_shape(self, input_shape):
        shapes = [tuple(s) for s in input_shape]
        if self.mode == "concat":
            s = list(shapes[0])
            ax = self.concat_axis % len(s)
            total = 0
            for sh in shapes:
                if sh[ax] is None:
                    total = None
                    break
                total += sh[ax]
            s[ax] = total
            return tuple(s)
        if self.mode in ("dot", "cosine"):
            return (shapes[0][0], 1)
        out = shapes[0]
        for s in shapes[1:]:
            out = broadcast_shapes(out, s)
        return out

    def get_config(self):
        cfg = super().get_config()
        cfg.update(mode=self.mode, concat_axis=self.concat_axis)
        return cfg


def merge(inputs, mode="sum", concat_axis=-1, name=None):
    """Functional merge over Variables (reference keras merge helper)."""
    return Merge(mode=mode, concat_axis=concat_axis, name=name)(list(inputs))
