"""Attention layers — the TPU-era extension the reference lacks
(SURVEY §5: "attention does not exist in the layer set"); long-context
support is first-class here, so the ops-level stack
(``ops/attention.py`` flash kernel, ``parallel/ring_attention``) gets a
Keras-level consumer.

Design note (the transpose-tax fix, PERF_NOTES r4): q/k/v are projected
DIRECTLY into the (batch, heads, seq, head_dim) layout via
``einsum("bse,ehd->bhsd", x, W)`` — XLA folds the layout into the
projection matmul's output, and the pallas kernel's batch/head fold
becomes a free reshape.  No materialized (b,s,h,d)→(b,h,s,d) transposes
anywhere in the block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core import initializers
from .....core.module import Layer, register_layer
from .....ops.attention import attention_bhsd


@register_layer
class MultiHeadSelfAttention(Layer):
    """Multi-head self-attention over (batch, seq, d_model) inputs.

    - ``n_heads`` × ``head_dim`` (default ``d_model // n_heads``)
    - ``causal=True`` masks future positions (decoder-style)
    - ``implementation``: "auto" (pallas flash kernel on TPU, blockwise
      XLA elsewhere), "flash", "blockwise", "naive", or "ring" —
      sequence-parallel ring attention over the mesh's ``seq`` axis
      (``parallel/ring_attention``): activations stay sharded along the
      sequence, KV blocks rotate around the ring, so contexts beyond
      one chip's memory train like any other layer.  Requires the
      active mesh to carry a ``seq`` axis.

    Padding masks (right-padded variable-length batches — the
    reference's text domain pads to a fixed sequenceLength,
    TextClassifier.scala:34): pass a TWO-input list ``[x, lengths]``
    where ``lengths`` is (batch,) valid token counts.  Keys past each
    sequence's length are masked in every implementation (including
    inside the pallas flash kernels and across the ring); padded QUERY
    positions still emit (garbage) outputs — mask them downstream, as
    sequence losses and masked pooling do.  Composes with ``causal``.
    """

    def __init__(self, n_heads, head_dim=None, causal=True,
                 implementation="auto", init="glorot_uniform",
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.n_heads = int(n_heads)
        self.head_dim = None if head_dim is None else int(head_dim)
        self.causal = bool(causal)
        self.implementation = implementation
        self.init_name = init

    def _dims(self, d_model):
        hd = self.head_dim or d_model // self.n_heads
        if hd * self.n_heads != d_model and self.head_dim is None:
            raise ValueError(
                f"d_model ({d_model}) not divisible by n_heads "
                f"({self.n_heads}); pass head_dim explicitly")
        return hd

    def init_params(self, rng, input_shape):
        if (isinstance(input_shape, (list, tuple)) and input_shape
                and isinstance(input_shape[0], (list, tuple))):
            input_shape = input_shape[0]  # [x, lengths] two-input form
        d_model = input_shape[-1]
        hd = self._dims(d_model)
        init = initializers.get(self.init_name)
        ks = jax.random.split(rng, 4)
        return {
            # (d_model, heads, head_dim): the bhsd projection layout
            "Wq": init(ks[0], (d_model, self.n_heads, hd)),
            "Wk": init(ks[1], (d_model, self.n_heads, hd)),
            "Wv": init(ks[2], (d_model, self.n_heads, hd)),
            # (heads, head_dim, d_model): output projection
            "Wo": init(ks[3], (self.n_heads, hd, d_model)),
        }

    def call(self, params, state, inputs, training=False, rng=None):
        lengths = None
        if isinstance(inputs, (list, tuple)):
            if len(inputs) != 2:
                raise ValueError(
                    "MultiHeadSelfAttention takes either one input "
                    "(batch, seq, d_model) or two ([x, lengths]); got "
                    f"{len(inputs)} inputs")
            inputs, lengths = inputs
            if lengths.ndim == 2 and lengths.shape[-1] == 1:
                lengths = lengths[:, 0]  # accept (batch, 1) columns
        if self.implementation == "ring":
            # sequence parallelism: project into the ring kernel's
            # (b, s, h, d) contract — still a pure einsum, no transpose
            from .....parallel.mesh import get_active_mesh
            from .....parallel.ring_attention import ring_attention_sharded
            # the ACTIVE mesh: the one compile(mesh=...) handed the
            # Trainer (set around every step trace/call), falling back
            # to the process default
            mesh = get_active_mesh()
            if mesh is None or "seq" not in mesh.axis_names:
                raise ValueError(
                    "implementation='ring' needs the active mesh to "
                    "carry a 'seq' axis (create_mesh({'seq': n, ...}))")
            seq_size = mesh.shape["seq"]
            if inputs.shape[-2] % seq_size:
                raise ValueError(
                    f"sequence length {inputs.shape[-2]} is not "
                    f"divisible by the mesh's seq axis ({seq_size})")
            q = jnp.einsum("bse,ehd->bshd", inputs, params["Wq"])
            k = jnp.einsum("bse,ehd->bshd", inputs, params["Wk"])
            v = jnp.einsum("bse,ehd->bshd", inputs, params["Wv"])
            o = ring_attention_sharded(q, k, v, mesh, causal=self.causal,
                                       kv_lengths=lengths)
            return jnp.einsum("bshd,hde->bse", o, params["Wo"])
        # project straight into (b, h, s, d) — layout rides the matmul
        q = jnp.einsum("bse,ehd->bhsd", inputs, params["Wq"])
        k = jnp.einsum("bse,ehd->bhsd", inputs, params["Wk"])
        v = jnp.einsum("bse,ehd->bhsd", inputs, params["Wv"])
        o = attention_bhsd(q, k, v, causal=self.causal,
                           implementation=self.implementation,
                           kv_lengths=lengths)
        return jnp.einsum("bhsd,hde->bse", o, params["Wo"])

    def compute_output_shape(self, input_shape):
        if (isinstance(input_shape, (list, tuple)) and input_shape
                and isinstance(input_shape[0], (list, tuple))):
            return tuple(input_shape[0])  # [x, lengths] two-input form
        return tuple(input_shape)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(n_heads=self.n_heads, head_dim=self.head_dim,
                   causal=self.causal, implementation=self.implementation,
                   init=self.init_name)
        return cfg


@register_layer
class PositionalEmbedding(Layer):
    """Learned positional table added to a (batch, seq, d_model) input:
    ``y = x + table[:seq]``.  ``max_len`` bounds the trainable table;
    shorter sequences slice it (static shapes under jit)."""

    def __init__(self, max_len, init="uniform", input_shape=None,
                 name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.max_len = int(max_len)
        self.init_name = init

    def init_params(self, rng, input_shape):
        d_model = input_shape[-1]
        table = initializers.get(self.init_name)(
            rng, (self.max_len, d_model))
        return {"table": table * 0.02 if self.init_name == "uniform"
                else table}

    def call(self, params, state, inputs, training=False, rng=None):
        s = inputs.shape[-2]
        if s > self.max_len:
            raise ValueError(
                f"sequence length {s} exceeds max_len {self.max_len}")
        return inputs + params["table"][:s].astype(inputs.dtype)

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(max_len=self.max_len, init=self.init_name)
        return cfg
