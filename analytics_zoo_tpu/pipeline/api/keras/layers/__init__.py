from .core import (
    Dense, SparseDense, Activation, Dropout, SpatialDropout1D,
    SpatialDropout2D, SpatialDropout3D, Flatten, Reshape, Permute,
    RepeatVector, Masking, Highway, MaxoutDense, TimeDistributed)
from .convolutional import (
    Convolution1D, Convolution2D, Convolution3D, AtrousConvolution1D,
    AtrousConvolution2D, ShareConvolution2D, SeparableConvolution2D,
    Deconvolution2D, LocallyConnected1D, LocallyConnected2D,
    ZeroPadding1D, ZeroPadding2D, ZeroPadding3D, Cropping1D, Cropping2D,
    Cropping3D, UpSampling1D, UpSampling2D, UpSampling3D, ResizeBilinear,
    SpaceToDepth2D)
from .pooling import (
    MaxPooling1D, MaxPooling2D, MaxPooling3D, AveragePooling1D,
    AveragePooling2D, AveragePooling3D, GlobalMaxPooling1D,
    GlobalMaxPooling2D, GlobalMaxPooling3D, GlobalAveragePooling1D,
    GlobalAveragePooling2D, GlobalAveragePooling3D)
from .normalization import (BatchNormalization, WithinChannelLRN2D, LRN2D,
                            LayerNorm)
from .embedding import Embedding, SparseEmbedding, WordEmbedding
from .merge import Merge, merge
from .advanced_activations import (ELU, LeakyReLU, PReLU, SReLU,
                                   ThresholdedReLU)
from .noise import GaussianNoise, GaussianDropout
from .recurrent import SimpleRNN, LSTM, GRU, ConvLSTM2D, Bidirectional
from .torch_style import (
    AddConstant, MulConstant, BinaryThreshold, Threshold, HardShrink,
    SoftShrink, HardTanh, RReLU, Exp, Log, Sqrt, Square, Negative, Identity,
    Power, Mul, CAdd, CMul, Scale, GaussianSampler, KerasLayerWrapper,
    Narrow, Select, Squeeze)
from .moe import SwitchMoE
from .attention import MultiHeadSelfAttention, PositionalEmbedding
from ..engine import Sequential, Model
from .....core.graph import Input, InputLayer
