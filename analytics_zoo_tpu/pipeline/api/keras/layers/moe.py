"""SwitchMoE: mixture-of-experts as a Keras-API layer.

Extension scope (no reference analog — SURVEY §2.10: the reference is
data-parallel only): wraps the functional switch-MoE block
(``analytics_zoo_tpu.parallel.expert``) in the layer contract so
Sequential/Model users get an MoE FFN with one ``add``.  When the
active mesh (the one ``compile(mesh=...)`` hands the trainer) carries
an ``expert`` axis that divides the expert and token counts, the layer
runs EXPERT-PARALLEL automatically (``moe_sharded``: experts sharded,
tokens by all_to_all, per-shard capacity); otherwise it runs the
single-device formulation with replicated experts.

Input (batch, seq, d_model) or (batch, d_model); output the same shape
with a residual connection (so capacity-dropped tokens pass through
unchanged).  The load-balancing aux loss (scaled by ``aux_weight``) is surfaced
through the layer state under the reserved key ``aux_loss``, which
``build_train_step`` sums into the training loss inside the gradient
closure — the router receives the Switch balancing gradient with no
user wiring.
"""

from __future__ import annotations

import jax.numpy as jnp

from .....core.module import Layer, register_layer
from .....observability.log import get_logger
from .....parallel.expert import (MoEParams, expert_capacity,
                                  init_moe_params, moe_sharded,
                                  switch_moe)

#: layer name -> reason, recorded whenever a SwitchMoE falls back to the
#: replicated formulation DESPITE an expert mesh axis being present — a
#: silent perf cliff otherwise (VERDICT r4 #6).  The strategy report
#: surfaces a snapshot; ``clear_fallback_log`` resets between compiles.
EXPERT_FALLBACKS: dict = {}
_slog = get_logger("analytics_zoo_tpu.moe")


def clear_fallback_log():
    EXPERT_FALLBACKS.clear()


def _note_fallback(name: str, reason: str):
    if name not in EXPERT_FALLBACKS:
        # warn once per layer (at trace time — once per compile, not
        # per step)
        _slog.warning(
            "SwitchMoE: expert mesh axis present but not usable — "
            "running REPLICATED (every device computes all experts). "
            "This is a perf cliff at scale; fix the divisibility to "
            "get expert parallelism.", layer=name, reason=reason)
    EXPERT_FALLBACKS[name] = reason


@register_layer
class SwitchMoE(Layer):
    """Switch-routed MoE FFN with residual: y = x + MoE(x)."""

    stateful = True

    def __init__(self, n_experts: int = 8, hidden_dim: int = None,
                 capacity_factor: float = 1.25, aux_weight: float = 0.01,
                 residual: bool = True, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.n_experts = int(n_experts)
        self.hidden_dim = hidden_dim
        self.capacity_factor = float(capacity_factor)
        # the Switch paper's load-balancing coefficient; the trainer sums
        # every layer's state["aux_loss"] into the training loss
        self.aux_weight = float(aux_weight)
        # residual=False emits bare MoE(x) so pre-norm stacks can
        # compose LN -> MoE -> Dropout -> Merge like any other sublayer
        # (capacity-dropped tokens then contribute zero, which the
        # OUTER residual passes through unchanged — same semantics)
        self.residual = bool(residual)

    def _dims(self, input_shape):
        d = input_shape[-1]
        h = self.hidden_dim or 4 * d
        return d, h

    def init_params(self, rng, input_shape):
        d, h = self._dims(input_shape)
        p = init_moe_params(rng, d, h, self.n_experts)
        return dict(p._asdict())

    def init_state(self, input_shape):
        return {"aux_loss": jnp.zeros(())}

    def call(self, params, state, inputs, training=False, rng=None):
        d = inputs.shape[-1]
        flat = inputs.reshape(-1, d)
        p = MoEParams(**{k: params[k]
                         for k in MoEParams._fields})
        # opportunistic expert parallelism: when the ACTIVE mesh (the
        # one compile(mesh=...) handed the trainer) carries an 'expert'
        # axis that divides both the expert count and the token count,
        # experts shard over it and tokens travel by all_to_all;
        # otherwise the single-device formulation runs (replicated
        # experts — always correct)
        from .....parallel.mesh import get_active_mesh
        mesh = get_active_mesh()
        esize = (mesh.shape["expert"]
                 if mesh is not None and "expert" in mesh.axis_names
                 else 0)
        if esize > 1 and self.n_experts % esize == 0 \
                and flat.shape[0] % esize == 0:
            out, aux = moe_sharded(
                flat, p, mesh, capacity_factor=self.capacity_factor)
        else:
            if esize > 1:
                _note_fallback(
                    self.name,
                    (f"expert count {self.n_experts} is not divisible "
                     f"by the axis size {esize}"
                     if self.n_experts % esize else
                     f"token count {flat.shape[0]} is not divisible by "
                     f"the axis size {esize}"))
            cap = expert_capacity(flat.shape[0], self.n_experts,
                                  self.capacity_factor)
            out, aux = switch_moe(flat, p, capacity=cap)
        y = out.reshape(inputs.shape)
        if self.residual:
            y = inputs + y
        return y, {"aux_loss": self.aux_weight * aux}

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(n_experts=self.n_experts, hidden_dim=self.hidden_dim,
                   capacity_factor=self.capacity_factor,
                   aux_weight=self.aux_weight, residual=self.residual)
        return cfg
