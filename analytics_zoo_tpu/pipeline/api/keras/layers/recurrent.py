"""Recurrent layers: SimpleRNN, LSTM, GRU, ConvLSTM2D, Bidirectional.

Parity surface: reference zoo/.../pipeline/api/keras/layers/{SimpleRNN, LSTM,
GRU, ConvLSTM2D, Bidirectional}.scala with Keras-1 semantics
(inner_activation default hard_sigmoid, return_sequences, go_backwards).

TPU-first structure: the time loop is one ``lax.scan`` (static trip count, no
Python unrolling), and the input projection for ALL timesteps is hoisted out
of the scan as a single large matmul — the MXU sees one (B*T, D)x(D, 4H)
GEMM instead of T small ones; only the recurrent H×H matmul stays inside the
scan, which is the minimum the data dependence allows.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from .....core import initializers
from .....core import shapes as shape_utils
from .....core.module import Layer, register_layer, remat_apply
from .. import activations


class _RecurrentBase(Layer):
    gate_count = 1

    def __init__(self, output_dim, activation="tanh",
                 inner_activation="hard_sigmoid", init="glorot_uniform",
                 inner_init="orthogonal", return_sequences=False,
                 go_backwards=False, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.output_dim = int(output_dim)
        self.activation_name = activation
        self.activation = activations.get(activation)
        self.inner_activation_name = inner_activation
        self.inner_activation = activations.get(inner_activation)
        self.init_name = init
        self.inner_init_name = inner_init
        self.return_sequences = bool(return_sequences)
        self.go_backwards = bool(go_backwards)

    def init_params(self, rng, input_shape):
        d, h, g = input_shape[-1], self.output_dim, self.gate_count
        k1, k2 = jax.random.split(rng)
        return {
            "W": initializers.get(self.init_name)(k1, (d, g * h)),
            "U": initializers.get(self.inner_init_name)(k2, (h, g * h)),
            "b": jnp.zeros((g * h,)),
        }

    def initial_carry(self, batch):
        h = jnp.zeros((batch, self.output_dim))
        return h

    def step(self, params, carry, z_t):
        raise NotImplementedError

    def call(self, params, state, inputs, training=False, rng=None):
        x = inputs
        if self.go_backwards:
            x = jnp.flip(x, axis=1)
        b = x.shape[0]
        # hoisted input projection: one big MXU GEMM over (B*T, D)
        z = x @ params["W"] + params["b"]  # (b, t, g*h)
        z_t = jnp.swapaxes(z, 0, 1)  # (t, b, g*h) for scan

        def body(carry, zt):
            new_carry, out = self.step(params, carry, zt)
            return new_carry, out

        _, outputs = lax.scan(body, self.initial_carry(b), z_t)
        outputs = jnp.swapaxes(outputs, 0, 1)  # (b, t, h)
        if self.return_sequences:
            return outputs
        return outputs[:, -1, :]

    def compute_output_shape(self, input_shape):
        if self.return_sequences:
            return (input_shape[0], input_shape[1], self.output_dim)
        return (input_shape[0], self.output_dim)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(output_dim=self.output_dim,
                   activation=self.activation_name,
                   inner_activation=self.inner_activation_name,
                   init=self.init_name, inner_init=self.inner_init_name,
                   return_sequences=self.return_sequences,
                   go_backwards=self.go_backwards)
        return cfg


@register_layer
class SimpleRNN(_RecurrentBase):
    """Reference SimpleRNN.scala."""

    gate_count = 1

    def step(self, params, carry, zt):
        h = self.activation(zt + carry @ params["U"])
        return h, h

    def get_config(self):
        cfg = super().get_config()
        cfg.pop("inner_activation", None)
        return cfg


@register_layer
class LSTM(_RecurrentBase):
    """Reference LSTM.scala; gate order [i, f, c, o] (Keras-1)."""

    gate_count = 4

    def initial_carry(self, batch):
        h = jnp.zeros((batch, self.output_dim))
        c = jnp.zeros((batch, self.output_dim))
        return (h, c)

    def step(self, params, carry, zt):
        h_prev, c_prev = carry
        z = zt + h_prev @ params["U"]
        n = self.output_dim
        i = self.inner_activation(z[:, :n])
        f = self.inner_activation(z[:, n:2 * n])
        g = self.activation(z[:, 2 * n:3 * n])
        o = self.inner_activation(z[:, 3 * n:])
        c = f * c_prev + i * g
        h = o * self.activation(c)
        return (h, c), h


@register_layer
class GRU(_RecurrentBase):
    """Reference GRU.scala; gate order [z, r, h] (Keras-1)."""

    gate_count = 3

    def step(self, params, carry, zt):
        n = self.output_dim
        U = params["U"]
        z_gate = self.inner_activation(zt[:, :n] + carry @ U[:, :n])
        r_gate = self.inner_activation(
            zt[:, n:2 * n] + carry @ U[:, n:2 * n])
        hh = self.activation(zt[:, 2 * n:] + (r_gate * carry) @ U[:, 2 * n:])
        h = z_gate * carry + (1.0 - z_gate) * hh
        return h, h


@register_layer
class ConvLSTM2D(Layer):
    """Convolutional LSTM (reference ConvLSTM2D.scala); channels-last NHWC.

    Gate convolutions for all 4 gates are fused into one conv with 4*filters
    output channels (one MXU-friendly conv per step instead of eight).
    """

    def __init__(self, nb_filter, nb_kernel=3, activation="tanh",
                 inner_activation="hard_sigmoid", border_mode="same",
                 subsample=1, return_sequences=False, go_backwards=False,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.kernel = shape_utils.normalize_tuple(nb_kernel, 2)
        self.activation = activations.get(activation)
        self.activation_name = activation
        self.inner_activation = activations.get(inner_activation)
        self.inner_activation_name = inner_activation
        self.border_mode = border_mode
        self.subsample = shape_utils.normalize_tuple(subsample, 2)
        self.return_sequences = bool(return_sequences)
        self.go_backwards = bool(go_backwards)

    def init_params(self, rng, input_shape):
        # input: (b, t, h, w, c)
        c = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        return {
            "W": initializers.glorot_uniform(
                k1, self.kernel + (c, 4 * self.nb_filter)),
            "U": initializers.glorot_uniform(
                k2, self.kernel + (self.nb_filter, 4 * self.nb_filter)),
            "b": jnp.zeros((4 * self.nb_filter,)),
        }

    def _conv(self, x, w, strides=(1, 1)):
        return lax.conv_general_dilated(
            x, w, window_strides=strides,
            padding="SAME" if self.border_mode == "same" else "VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    def call(self, params, state, inputs, training=False, rng=None):
        x = inputs
        if self.go_backwards:
            x = jnp.flip(x, axis=1)
        b = x.shape[0]
        x_t = jnp.swapaxes(x, 0, 1)  # (t, b, h, w, c)
        # spatial dims after the strided input conv
        probe = self._conv(x_t[0], params["W"], self.subsample)
        oh, ow = probe.shape[1], probe.shape[2]
        h0 = jnp.zeros((b, oh, ow, self.nb_filter))
        c0 = jnp.zeros((b, oh, ow, self.nb_filter))
        n = self.nb_filter

        def body(carry, xt):
            h_prev, c_prev = carry
            z = self._conv(xt, params["W"], self.subsample) \
                + self._conv(h_prev, params["U"]) + params["b"]
            i = self.inner_activation(z[..., :n])
            f = self.inner_activation(z[..., n:2 * n])
            g = self.activation(z[..., 2 * n:3 * n])
            o = self.inner_activation(z[..., 3 * n:])
            c_new = f * c_prev + i * g
            h_new = o * self.activation(c_new)
            return (h_new, c_new), h_new

        _, outputs = lax.scan(body, (h0, c0), x_t)
        outputs = jnp.swapaxes(outputs, 0, 1)
        if self.return_sequences:
            return outputs
        return outputs[:, -1]

    def compute_output_shape(self, input_shape):
        b, t, h, w, _ = input_shape
        oh = shape_utils.conv_output_length(
            h, self.kernel[0], self.border_mode, self.subsample[0])
        ow = shape_utils.conv_output_length(
            w, self.kernel[1], self.border_mode, self.subsample[1])
        if self.return_sequences:
            return (b, t, oh, ow, self.nb_filter)
        return (b, oh, ow, self.nb_filter)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(nb_filter=self.nb_filter, nb_kernel=list(self.kernel),
                   activation=self.activation_name,
                   inner_activation=self.inner_activation_name,
                   border_mode=self.border_mode,
                   subsample=list(self.subsample),
                   return_sequences=self.return_sequences,
                   go_backwards=self.go_backwards)
        return cfg


@register_layer
class Bidirectional(Layer):
    """Bidirectional wrapper (reference Bidirectional.scala)."""

    def __init__(self, layer=None, merge_mode="concat", input_shape=None,
                 name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.layer = layer
        self.merge_mode = merge_mode
        # clone config for the backward direction
        cfg = dict(layer.get_config())
        cfg.pop("name", None)
        cfg["go_backwards"] = not cfg.get("go_backwards", False)
        self.backward_layer = type(layer).from_config(cfg)

    def init_params(self, rng, input_shape):
        k1, k2 = jax.random.split(rng)
        return {
            "forward": self.layer.init_params(k1, input_shape),
            "backward": self.backward_layer.init_params(k2, input_shape),
        }

    def call(self, params, state, inputs, training=False, rng=None):
        # the user's remat flag lives on the visible (forward) layer;
        # the backward clone was built in __init__, possibly before the
        # flag was set, so extend it via force= (a flag set directly on
        # backward_layer is honored too, never clobbered)
        fwd = remat_apply(self.layer, params["forward"], {}, inputs,
                          training=training, rng=rng)[0]
        bwd = remat_apply(self.backward_layer, params["backward"], {},
                          inputs, training=training, rng=rng,
                          force=self.layer.remat)[0]
        if self.layer.return_sequences:
            bwd = jnp.flip(bwd, axis=1)  # re-align timesteps
        if self.merge_mode == "concat":
            return jnp.concatenate([fwd, bwd], axis=-1)
        if self.merge_mode == "sum":
            return fwd + bwd
        if self.merge_mode == "mul":
            return fwd * bwd
        if self.merge_mode == "ave":
            return (fwd + bwd) / 2.0
        raise ValueError(f"Unknown merge_mode {self.merge_mode!r}")

    def compute_output_shape(self, input_shape):
        out = self.layer.compute_output_shape(input_shape)
        if self.merge_mode == "concat":
            return tuple(out[:-1]) + (out[-1] * 2,)
        return out

    def get_config(self):
        from .....core.module import serial_class_name
        cfg = super().get_config()
        cfg["merge_mode"] = self.merge_mode
        cfg["layer"] = {"class_name": serial_class_name(self.layer),
                        "config": self.layer.get_config()}
        return cfg

    @classmethod
    def from_config(cls, config):
        from .....core.module import (get_layer_class, pop_base_flags,
                                      set_base_flags)
        config = dict(config)
        inner = config.pop("layer")
        flags = pop_base_flags(config)
        layer = get_layer_class(inner["class_name"]).from_config(
            inner["config"])
        return set_base_flags(cls(layer=layer, **config), flags)
