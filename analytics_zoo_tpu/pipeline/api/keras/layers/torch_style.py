"""Torch-style Keras-1 layers: elementwise math, thresholds, tensor surgery.

Parity surface: reference zoo/.../pipeline/api/keras/layers/{AddConstant,
BinaryThreshold, CAdd, CMul, Exp, GaussianSampler, HardShrink, HardTanh,
Identity, KerasLayerWrapper, Log, Mul, MulConstant, Narrow, Negative, Power,
RReLU, Select, SoftShrink, Sqrt, Square, Squeeze, Threshold, Scale}.scala
(python mirror pyzoo/zoo/pipeline/api/keras/layers/torch.py).

Dim conventions follow the reference exactly: ``dim``/``dims`` are 0-based
indices over the FULL shape including the batch axis at 0; the batch axis may
never be narrowed/selected/squeezed; for Narrow/Select ``-1`` means the last
axis (Narrow.scala:47-55, Select.scala:50-60), while Squeeze requires
non-negative dims as in the reference (Squeeze.scala:52-56 ``require(dim >=
0)``).

All of these are single fused XLA elementwise ops or static slices — they
melt into neighbouring matmuls at compile time, so there is no per-layer
kernel cost on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.module import Layer, register_layer


class _Elementwise(Layer):
    """Shared base for stateless identity-output-shape elementwise layers."""

    def compute_output_shape(self, input_shape):
        return tuple(input_shape)


@register_layer
class AddConstant(_Elementwise):
    """y = x + constant (reference AddConstant.scala:25-33)."""

    def __init__(self, constant, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.constant = float(constant)

    def call(self, params, state, inputs, training=False, rng=None):
        return inputs + self.constant

    def get_config(self):
        cfg = super().get_config()
        cfg["constant"] = self.constant
        return cfg


@register_layer
class MulConstant(_Elementwise):
    """y = x * constant (reference MulConstant.scala:25-33)."""

    def __init__(self, constant, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.constant = float(constant)

    def call(self, params, state, inputs, training=False, rng=None):
        return inputs * self.constant

    def get_config(self):
        cfg = super().get_config()
        cfg["constant"] = self.constant
        return cfg


@register_layer
class BinaryThreshold(_Elementwise):
    """y = 1 if x > value else 0 (reference BinaryThreshold.scala:25-33)."""

    def __init__(self, value=1e-6, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.value = float(value)

    def call(self, params, state, inputs, training=False, rng=None):
        return (inputs > self.value).astype(inputs.dtype)

    def get_config(self):
        cfg = super().get_config()
        cfg["value"] = self.value
        return cfg


@register_layer
class Threshold(_Elementwise):
    """y = x if x > th else v (reference Threshold.scala:25-35)."""

    def __init__(self, th=1e-6, v=0.0, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.th = float(th)
        self.v = float(v)

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.where(inputs > self.th, inputs, self.v)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(th=self.th, v=self.v)
        return cfg


@register_layer
class HardShrink(_Elementwise):
    """y = x if |x| > value else 0 (reference HardShrink.scala:25-33)."""

    def __init__(self, value=0.5, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.value = float(value)

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.where(jnp.abs(inputs) > self.value, inputs, 0.0)

    def get_config(self):
        cfg = super().get_config()
        cfg["value"] = self.value
        return cfg


@register_layer
class SoftShrink(_Elementwise):
    """Shrink towards zero by value, zero inside the band
    (reference SoftShrink.scala:25-33)."""

    def __init__(self, value=0.5, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.value = float(value)

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.where(
            inputs > self.value, inputs - self.value,
            jnp.where(inputs < -self.value, inputs + self.value, 0.0))

    def get_config(self):
        cfg = super().get_config()
        cfg["value"] = self.value
        return cfg


@register_layer
class HardTanh(_Elementwise):
    """Clip to [min_value, max_value] (reference HardTanh.scala:25-35)."""

    def __init__(self, min_value=-1.0, max_value=1.0, input_shape=None,
                 name=None):
        super().__init__(input_shape=input_shape, name=name)
        if max_value <= min_value:
            raise ValueError("max_value must be > min_value")
        self.min_value = float(min_value)
        self.max_value = float(max_value)

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.clip(inputs, self.min_value, self.max_value)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(min_value=self.min_value, max_value=self.max_value)
        return cfg


@register_layer
class RReLU(_Elementwise):
    """Randomized leaky ReLU: negative slope ~ U[lower, upper] in training,
    fixed mean slope at inference (reference RReLU.scala:25-34)."""

    stochastic = True

    def __init__(self, lower=1.0 / 8, upper=1.0 / 3, input_shape=None,
                 name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.lower = float(lower)
        self.upper = float(upper)

    def call(self, params, state, inputs, training=False, rng=None):
        if training and rng is not None:
            slope = jax.random.uniform(
                rng, inputs.shape, minval=self.lower, maxval=self.upper)
        else:
            slope = (self.lower + self.upper) / 2.0
        return jnp.where(inputs >= 0, inputs, inputs * slope)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(lower=self.lower, upper=self.upper)
        return cfg


@register_layer
class Exp(_Elementwise):
    """Reference Exp.scala:25-32."""

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.exp(inputs)


@register_layer
class Log(_Elementwise):
    """Reference Log.scala:25-32."""

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.log(inputs)


@register_layer
class Sqrt(_Elementwise):
    """Reference Sqrt.scala:25-32."""

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.sqrt(inputs)


@register_layer
class Square(_Elementwise):
    """Reference Square.scala:25-32."""

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.square(inputs)


@register_layer
class Negative(_Elementwise):
    """Reference Negative.scala:25-32."""

    def call(self, params, state, inputs, training=False, rng=None):
        return -inputs


@register_layer
class Identity(_Elementwise):
    """Reference Identity.scala:25-30."""

    def call(self, params, state, inputs, training=False, rng=None):
        return inputs


@register_layer
class Power(_Elementwise):
    """y = (shift + scale * x) ** power (reference Power.scala:25-35)."""

    def __init__(self, power, scale=1.0, shift=0.0, input_shape=None,
                 name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.power = float(power)
        self.scale = float(scale)
        self.shift = float(shift)

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.power(self.shift + self.scale * inputs, self.power)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(power=self.power, scale=self.scale, shift=self.shift)
        return cfg


@register_layer
class Mul(_Elementwise):
    """Learnable scalar multiply (reference Mul.scala:25-32)."""

    def init_params(self, rng, input_shape):
        return {"w": jnp.ones(())}

    def call(self, params, state, inputs, training=False, rng=None):
        return inputs * params["w"]


@register_layer
class CAdd(_Elementwise):
    """Learnable per-element bias of shape ``size``, broadcast against the
    input (reference CAdd.scala:25-36).  ``size`` includes the batch axis
    as in the reference (typically 1 there)."""

    def __init__(self, size, b_regularizer=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.size = tuple(int(s) for s in size)
        self.b_regularizer = b_regularizer

    def init_params(self, rng, input_shape):
        return {"b": jnp.zeros(self.size)}

    def call(self, params, state, inputs, training=False, rng=None):
        return inputs + params["b"]

    def get_config(self):
        cfg = super().get_config()
        cfg["size"] = list(self.size)
        return cfg


@register_layer
class CMul(_Elementwise):
    """Learnable per-element scale of shape ``size``
    (reference CMul.scala:25-36)."""

    def __init__(self, size, w_regularizer=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.size = tuple(int(s) for s in size)
        self.w_regularizer = w_regularizer

    def init_params(self, rng, input_shape):
        return {"w": jnp.ones(self.size)}

    def call(self, params, state, inputs, training=False, rng=None):
        return inputs * params["w"]

    def get_config(self):
        cfg = super().get_config()
        cfg["size"] = list(self.size)
        return cfg


@register_layer
class Scale(_Elementwise):
    """CMul followed by CAdd with the same ``size``
    (reference Scale.scala:25-40)."""

    def __init__(self, size, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.size = tuple(int(s) for s in size)

    def init_params(self, rng, input_shape):
        return {"w": jnp.ones(self.size), "b": jnp.zeros(self.size)}

    def call(self, params, state, inputs, training=False, rng=None):
        return inputs * params["w"] + params["b"]

    def get_config(self):
        cfg = super().get_config()
        cfg["size"] = list(self.size)
        return cfg


@register_layer
class GaussianSampler(Layer):
    """Sample from N(mean, exp(log_var)) given input [mean, log_var] — the
    VAE reparameterization trick (reference GaussianSampler.scala:25-32).
    Deterministic (returns the mean) when not training, so inference stays
    reproducible under jit."""

    stochastic = True

    def __init__(self, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)

    def call(self, params, state, inputs, training=False, rng=None):
        mean, log_var = inputs
        if not training or rng is None:
            return mean
        eps = jax.random.normal(rng, mean.shape, dtype=mean.dtype)
        return mean + jnp.exp(log_var * 0.5) * eps

    def compute_output_shape(self, input_shape):
        # input_shape is a list of two identical shapes
        return tuple(input_shape[0])


@register_layer
class KerasLayerWrapper(Layer):
    """Wrap an arbitrary function (or another Layer) as a Keras layer —
    the reference wraps raw BigDL modules (KerasLayerWrapper.scala:25-31);
    here the "torch layer" is any jax-traceable callable."""

    def __init__(self, fn, output_shape=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.fn = fn
        self._output_shape = output_shape

    def call(self, params, state, inputs, training=False, rng=None):
        return self.fn(inputs)

    def compute_output_shape(self, input_shape):
        if self._output_shape is not None:
            return (input_shape[0],) + tuple(self._output_shape)
        # graph shapes carry a None batch dim; substitute 1 for tracing
        # and restore it in the result
        concrete = tuple(1 if s is None else s for s in input_shape)
        out = jax.eval_shape(
            self.fn, jax.ShapeDtypeStruct(concrete, jnp.float32))
        out_shape = tuple(out.shape)
        if input_shape[0] is None:
            out_shape = (None,) + out_shape[1:]
        return out_shape

    def get_config(self):
        raise NotImplementedError(
            "KerasLayerWrapper wraps an arbitrary python callable and "
            "cannot be config-serialized; save weights instead")


def _positive_dim(dim, ndim, layer):
    positive = dim + ndim if dim < 0 else dim
    if not 0 <= positive < ndim:
        raise ValueError(f"{layer}: invalid dim {dim} for {ndim}D input")
    if positive == 0:
        raise ValueError(f"{layer}: cannot touch the batch dimension")
    return positive


@register_layer
class Narrow(Layer):
    """Static slice of ``length`` elements starting at ``offset`` along
    ``dim`` (reference Narrow.scala:25-60; 0-based dims over the full
    shape, batch untouchable, negative length means 'to the end')."""

    def __init__(self, dim, offset, length=1, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.dim = int(dim)
        self.offset = int(offset)
        self.length = int(length)

    def _resolve(self, full_shape):
        d = _positive_dim(self.dim, len(full_shape), "Narrow")
        size = full_shape[d]
        length = self.length
        if length < 0:
            length = length + size - self.offset + 1
        if not (0 <= self.offset and self.offset + length <= size):
            raise ValueError(
                f"Narrow: offset {self.offset} + length {length} out of "
                f"range for axis size {size}")
        return d, length

    def call(self, params, state, inputs, training=False, rng=None):
        d, length = self._resolve(inputs.shape)
        return jax.lax.slice_in_dim(inputs, self.offset,
                                    self.offset + length, axis=d)

    def compute_output_shape(self, input_shape):
        d, length = self._resolve(input_shape)
        out = list(input_shape)
        out[d] = length
        return tuple(out)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(dim=self.dim, offset=self.offset, length=self.length)
        return cfg


@register_layer
class Select(Layer):
    """Select one index along ``dim``, dropping the axis
    (reference Select.scala:25-60)."""

    def __init__(self, dim, index, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.dim = int(dim)
        self.index = int(index)

    def call(self, params, state, inputs, training=False, rng=None):
        d = _positive_dim(self.dim, inputs.ndim, "Select")
        idx = self.index + inputs.shape[d] if self.index < 0 else self.index
        return jax.lax.index_in_dim(inputs, idx, axis=d, keepdims=False)

    def compute_output_shape(self, input_shape):
        d = _positive_dim(self.dim, len(input_shape), "Select")
        return tuple(s for i, s in enumerate(input_shape) if i != d)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(dim=self.dim, index=self.index)
        return cfg


@register_layer
class Squeeze(Layer):
    """Drop singleton axes (all non-batch singletons when ``dims`` is None;
    reference Squeeze.scala:25-60)."""

    def __init__(self, dims=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        if dims is not None and not hasattr(dims, "__len__"):
            dims = (dims,)
        self.dims = tuple(int(d) for d in dims) if dims is not None else None
        if self.dims is not None and any(d <= 0 for d in self.dims):
            raise ValueError(
                "Squeeze dims must be positive (0 is the batch axis)")

    def _axes(self, full_shape):
        if self.dims is None:
            axes = tuple(i for i, s in enumerate(full_shape)
                         if i > 0 and s == 1)
        else:
            for d in self.dims:
                if full_shape[d] != 1:
                    raise ValueError(
                        f"Squeeze: axis {d} has size {full_shape[d]} != 1")
            axes = self.dims
        return axes

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.squeeze(inputs, axis=self._axes(inputs.shape))

    def compute_output_shape(self, input_shape):
        axes = set(self._axes(tuple(input_shape)))
        return tuple(s for i, s in enumerate(input_shape) if i not in axes)

    def get_config(self):
        cfg = super().get_config()
        cfg["dims"] = list(self.dims) if self.dims is not None else None
        return cfg
