"""Pooling layers (Max/Avg/Global × 1D/2D/3D).

Parity surface: reference zoo/.../pipeline/api/keras/layers/{MaxPooling1D/2D/3D,
AveragePooling1D/2D/3D, GlobalMaxPooling1D/2D/3D, GlobalAveragePooling1D/2D/3D}
.scala.  All lower to ``lax.reduce_window`` in channels-last layout.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from .....core import shapes as shape_utils
from .....core.module import Layer, register_layer


class _PoolND(Layer):
    rank = 2
    mode = "max"  # or "avg"

    def __init__(self, pool_size=2, strides=None, border_mode="valid",
                 dim_ordering=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.pool_size = shape_utils.normalize_tuple(pool_size, self.rank)
        self.strides = (shape_utils.normalize_tuple(strides, self.rank)
                        if strides is not None else self.pool_size)
        self.border_mode = border_mode
        self.data_format = shape_utils.normalize_data_format(dim_ordering)

    def _to_cl(self, x):
        if self.data_format == "channels_first":
            return jnp.transpose(
                x, (0,) + tuple(range(2, 2 + self.rank)) + (1,))
        return x

    def _from_cl(self, x):
        if self.data_format == "channels_first":
            return jnp.transpose(
                x, (0, self.rank + 1) + tuple(range(1, self.rank + 1)))
        return x

    def call(self, params, state, inputs, training=False, rng=None):
        x = self._to_cl(inputs)
        window = (1,) + self.pool_size + (1,)
        strides = (1,) + self.strides + (1,)
        padding = "SAME" if self.border_mode == "same" else "VALID"
        if self.mode == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                  padding)
        else:
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, padding)
            if padding == "SAME":
                ones = jnp.ones_like(x)
                counts = lax.reduce_window(ones, 0.0, lax.add, window,
                                           strides, padding)
                y = y / counts
            else:
                y = y / float(np.prod(self.pool_size))
        return self._from_cl(y)

    def compute_output_shape(self, input_shape):
        if self.data_format == "channels_first":
            cl = (input_shape[0],) + tuple(input_shape[2:]) + (input_shape[1],)
        else:
            cl = tuple(input_shape)
        spatial = [
            shape_utils.pool_output_length(
                cl[1 + i], self.pool_size[i], self.border_mode,
                self.strides[i]) for i in range(self.rank)]
        out = (cl[0],) + tuple(spatial) + (cl[-1],)
        if self.data_format == "channels_first":
            return (out[0], out[-1]) + tuple(out[1:-1])
        return out

    def get_config(self):
        cfg = super().get_config()
        cfg.update(pool_size=list(self.pool_size), strides=list(self.strides),
                   border_mode=self.border_mode,
                   dim_ordering=self.data_format)
        return cfg


@register_layer
class MaxPooling1D(_PoolND):
    rank, mode = 1, "max"

    def __init__(self, pool_length=2, stride=None, border_mode="valid",
                 input_shape=None, name=None):
        super().__init__(pool_size=pool_length, strides=stride,
                         border_mode=border_mode, input_shape=input_shape,
                         name=name)

    def get_config(self):
        # 1D ctors speak Keras-1 arg names (pool_length/stride), not the
        # shared _PoolND names — emit what from_config can consume
        cfg = Layer.get_config(self)
        cfg.update(pool_length=self.pool_size[0], stride=self.strides[0],
                   border_mode=self.border_mode)
        return cfg


@register_layer
class AveragePooling1D(_PoolND):
    rank, mode = 1, "avg"

    def __init__(self, pool_length=2, stride=None, border_mode="valid",
                 input_shape=None, name=None):
        super().__init__(pool_size=pool_length, strides=stride,
                         border_mode=border_mode, input_shape=input_shape,
                         name=name)

    def get_config(self):
        cfg = Layer.get_config(self)
        cfg.update(pool_length=self.pool_size[0], stride=self.strides[0],
                   border_mode=self.border_mode)
        return cfg


@register_layer
class MaxPooling2D(_PoolND):
    rank, mode = 2, "max"


@register_layer
class AveragePooling2D(_PoolND):
    rank, mode = 2, "avg"


@register_layer
class MaxPooling3D(_PoolND):
    rank, mode = 3, "max"

    def __init__(self, pool_size=(2, 2, 2), strides=None, border_mode="valid",
                 dim_ordering=None, input_shape=None, name=None):
        super().__init__(pool_size=pool_size, strides=strides,
                         border_mode=border_mode, dim_ordering=dim_ordering,
                         input_shape=input_shape, name=name)


@register_layer
class AveragePooling3D(_PoolND):
    rank, mode = 3, "avg"

    def __init__(self, pool_size=(2, 2, 2), strides=None, border_mode="valid",
                 dim_ordering=None, input_shape=None, name=None):
        super().__init__(pool_size=pool_size, strides=strides,
                         border_mode=border_mode, dim_ordering=dim_ordering,
                         input_shape=input_shape, name=name)


class _GlobalPoolND(Layer):
    rank = 2
    mode = "max"

    def __init__(self, dim_ordering=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.data_format = shape_utils.normalize_data_format(dim_ordering)

    def call(self, params, state, inputs, training=False, rng=None):
        if self.data_format == "channels_last":
            axes = tuple(range(1, 1 + self.rank))
        else:
            axes = tuple(range(2, 2 + self.rank))
        fn = jnp.max if self.mode == "max" else jnp.mean
        return fn(inputs, axis=axes)

    def compute_output_shape(self, input_shape):
        ch = (input_shape[-1] if self.data_format == "channels_last"
              else input_shape[1])
        return (input_shape[0], ch)

    def get_config(self):
        cfg = super().get_config()
        cfg["dim_ordering"] = self.data_format
        return cfg


@register_layer
class GlobalMaxPooling1D(_GlobalPoolND):
    rank, mode = 1, "max"


@register_layer
class GlobalAveragePooling1D(_GlobalPoolND):
    rank, mode = 1, "avg"


@register_layer
class GlobalMaxPooling2D(_GlobalPoolND):
    rank, mode = 2, "max"


@register_layer
class GlobalAveragePooling2D(_GlobalPoolND):
    rank, mode = 2, "avg"


@register_layer
class GlobalMaxPooling3D(_GlobalPoolND):
    rank, mode = 3, "max"


@register_layer
class GlobalAveragePooling3D(_GlobalPoolND):
    rank, mode = 3, "avg"
