"""Core Keras-1 layers: Dense, Activation, Dropout, Flatten, reshape family.

Parity surface: reference zoo/.../pipeline/api/keras/layers/{Dense, Activation,
Dropout, Flatten, Reshape, Permute, RepeatVector, Highway, MaxoutDense,
Masking, SparseDense}.scala.  Implementations are direct jnp — Dense is a
single MXU matmul; dropout uses explicit rng threading so training steps stay
pure and reproducible under jit.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from .....core import initializers
from .....core import shapes as shape_utils
from .....core.module import Layer, register_layer, remat_apply
from .. import regularizers
from .. import activations


@register_layer
class Dense(regularizers.RegularizedLayerMixin, Layer):
    """Fully connected layer: ``y = act(x @ W + b)``.

    Reference: zoo/.../keras/layers/Dense.scala.  Weight layout is
    (in, out) — row-major matmul feeding the MXU directly.
    """

    def __init__(self, output_dim, init="glorot_uniform", activation=None,
                 W_regularizer=None, b_regularizer=None, bias=True,
                 input_dim=None, input_shape=None, name=None):
        if input_dim is not None and input_shape is None:
            input_shape = (input_dim,)
        super().__init__(input_shape=input_shape, name=name)
        self.output_dim = int(output_dim)
        self.init_name = init
        self.activation_name = activation if not callable(activation) else None
        self.activation = activations.get(activation)
        self.bias = bias
        self._setup_regularizers(W_regularizer, b_regularizer)

    def init_params(self, rng, input_shape):
        in_dim = input_shape[-1]
        k_rng, _ = jax.random.split(rng)
        params = {"W": initializers.get(self.init_name)(
            k_rng, (in_dim, self.output_dim))}
        if self.bias:
            params["b"] = jnp.zeros((self.output_dim,))
        return params

    def call(self, params, state, inputs, training=False, rng=None):
        y = inputs @ params["W"]
        if self.bias:
            y = y + params["b"]
        if self.activation is not None:
            y = self.activation(y)
        if self.stateful:
            return y, {"aux_loss": self._penalty(params)}
        return y

    def compute_output_shape(self, input_shape):
        return tuple(input_shape[:-1]) + (self.output_dim,)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(output_dim=self.output_dim, init=self.init_name,
                   activation=self.activation_name, bias=self.bias,
                   W_regularizer=regularizers.to_config(self.W_regularizer),
                   b_regularizer=regularizers.to_config(self.b_regularizer))
        return cfg


@register_layer
class SparseDense(Dense):
    """Dense accepting sparse-style (indices bags) or dense input.

    Reference: zoo/.../keras/layers/SparseDense.scala.  On TPU a "sparse
    tensor" is represented densely (XLA has no sparse layouts); the API is
    kept for parity and simply densifies.
    """


@register_layer
class Activation(Layer):
    def __init__(self, activation=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.activation_name = activation
        self.activation = activations.get(activation)

    def call(self, params, state, inputs, training=False, rng=None):
        return self.activation(inputs)

    def get_config(self):
        cfg = super().get_config()
        cfg["activation"] = self.activation_name
        return cfg


@register_layer
class Dropout(Layer):
    """Inverted dropout; identity at inference (reference Dropout.scala)."""

    stochastic = True

    def __init__(self, p=0.5, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.p = float(p)

    def call(self, params, state, inputs, training=False, rng=None):
        if not training or self.p <= 0.0 or rng is None:
            return inputs
        keep = 1.0 - self.p
        mask = jax.random.bernoulli(rng, keep, inputs.shape)
        return jnp.where(mask, inputs / keep, 0.0)

    def get_config(self):
        cfg = super().get_config()
        cfg["p"] = self.p
        return cfg


@register_layer
class SpatialDropout1D(Dropout):
    """Drop entire feature channels across timesteps (reference SpatialDropout1D.scala)."""

    def call(self, params, state, inputs, training=False, rng=None):
        if not training or self.p <= 0.0 or rng is None:
            return inputs
        keep = 1.0 - self.p
        b, _, c = inputs.shape
        mask = jax.random.bernoulli(rng, keep, (b, 1, c))
        return jnp.where(mask, inputs / keep, 0.0)


@register_layer
class SpatialDropout2D(Dropout):
    """Drop entire channels of a 4D tensor (reference SpatialDropout2D.scala)."""

    def __init__(self, p=0.5, dim_ordering=None, input_shape=None, name=None):
        super().__init__(p=p, input_shape=input_shape, name=name)
        self.data_format = shape_utils.normalize_data_format(dim_ordering)

    def call(self, params, state, inputs, training=False, rng=None):
        if not training or self.p <= 0.0 or rng is None:
            return inputs
        keep = 1.0 - self.p
        b = inputs.shape[0]
        if self.data_format == "channels_last":
            mask_shape = (b, 1, 1, inputs.shape[3])
        else:
            mask_shape = (b, inputs.shape[1], 1, 1)
        mask = jax.random.bernoulli(rng, keep, mask_shape)
        return jnp.where(mask, inputs / keep, 0.0)


@register_layer
class SpatialDropout3D(Dropout):
    def __init__(self, p=0.5, dim_ordering=None, input_shape=None, name=None):
        super().__init__(p=p, input_shape=input_shape, name=name)
        self.data_format = shape_utils.normalize_data_format(dim_ordering)

    def call(self, params, state, inputs, training=False, rng=None):
        if not training or self.p <= 0.0 or rng is None:
            return inputs
        keep = 1.0 - self.p
        b = inputs.shape[0]
        if self.data_format == "channels_last":
            mask_shape = (b, 1, 1, 1, inputs.shape[4])
        else:
            mask_shape = (b, inputs.shape[1], 1, 1, 1)
        mask = jax.random.bernoulli(rng, keep, mask_shape)
        return jnp.where(mask, inputs / keep, 0.0)


@register_layer
class Flatten(Layer):
    """Flatten all non-batch dims (reference Flatten.scala)."""

    def call(self, params, state, inputs, training=False, rng=None):
        return inputs.reshape(inputs.shape[0], -1)

    def compute_output_shape(self, input_shape):
        dims = input_shape[1:]
        if any(d is None for d in dims):
            return (input_shape[0], None)
        return (input_shape[0], int(np.prod(dims)))


@register_layer
class Reshape(Layer):
    """Reshape non-batch dims; one dim may be -1 (reference Reshape.scala)."""

    def __init__(self, target_shape=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.target_shape = tuple(int(d) for d in target_shape)

    def call(self, params, state, inputs, training=False, rng=None):
        return inputs.reshape((inputs.shape[0],) + self.target_shape)

    def compute_output_shape(self, input_shape):
        dims = input_shape[1:]
        tgt = list(self.target_shape)
        if -1 in tgt:
            known = int(np.prod([d for d in tgt if d != -1]))
            total = int(np.prod(dims)) if all(d is not None for d in dims) else None
            tgt[tgt.index(-1)] = total // known if total else None
        return (input_shape[0],) + tuple(tgt)

    def get_config(self):
        cfg = super().get_config()
        cfg["target_shape"] = list(self.target_shape)
        return cfg


@register_layer
class Permute(Layer):
    """Permute non-batch dims; dims are 1-indexed as in Keras-1 (reference Permute.scala)."""

    def __init__(self, dims=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.dims = tuple(int(d) for d in dims)

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.transpose(inputs, (0,) + self.dims)

    def compute_output_shape(self, input_shape):
        return (input_shape[0],) + tuple(input_shape[d] for d in self.dims)

    def get_config(self):
        cfg = super().get_config()
        cfg["dims"] = list(self.dims)
        return cfg


@register_layer
class RepeatVector(Layer):
    """(batch, features) -> (batch, n, features) (reference RepeatVector.scala)."""

    def __init__(self, n=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.n = int(n)

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.repeat(inputs[:, None, :], self.n, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.n, input_shape[1])

    def get_config(self):
        cfg = super().get_config()
        cfg["n"] = self.n
        return cfg


@register_layer
class Masking(Layer):
    """Zero out timesteps equal to mask_value (reference Masking.scala).

    Under jit, masks are dense multiplicative tensors, not ragged metadata.
    """

    def __init__(self, mask_value=0.0, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.mask_value = float(mask_value)

    def call(self, params, state, inputs, training=False, rng=None):
        keep = jnp.any(inputs != self.mask_value, axis=-1, keepdims=True)
        return jnp.where(keep, inputs, 0.0)

    def get_config(self):
        cfg = super().get_config()
        cfg["mask_value"] = self.mask_value
        return cfg


@register_layer
class Highway(Layer):
    """Highway network layer (reference Highway.scala): y = t*h + (1-t)*x."""

    def __init__(self, activation="tanh", bias=True, input_shape=None,
                 name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.activation_name = activation
        self.activation = activations.get(activation or "linear")
        self.bias = bias

    def init_params(self, rng, input_shape):
        d = input_shape[-1]
        k1, k2 = jax.random.split(rng)
        params = {
            "W_h": initializers.glorot_uniform(k1, (d, d)),
            "W_t": initializers.glorot_uniform(k2, (d, d)),
        }
        if self.bias:
            params["b_h"] = jnp.zeros((d,))
            # negative transform-gate bias biases toward carry at init
            params["b_t"] = -2.0 * jnp.ones((d,))
        return params

    def call(self, params, state, inputs, training=False, rng=None):
        h = inputs @ params["W_h"]
        t = inputs @ params["W_t"]
        if self.bias:
            h = h + params["b_h"]
            t = t + params["b_t"]
        h = self.activation(h)
        t = jax.nn.sigmoid(t)
        return t * h + (1.0 - t) * inputs

    def get_config(self):
        cfg = super().get_config()
        cfg.update(activation=self.activation_name, bias=self.bias)
        return cfg


@register_layer
class MaxoutDense(Layer):
    """Maxout over nb_feature linear maps (reference MaxoutDense.scala)."""

    def __init__(self, output_dim, nb_feature=4, bias=True, input_shape=None,
                 name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.output_dim = int(output_dim)
        self.nb_feature = int(nb_feature)
        self.bias = bias

    def init_params(self, rng, input_shape):
        d = input_shape[-1]
        params = {"W": initializers.glorot_uniform(
            rng, (self.nb_feature, d, self.output_dim))}
        if self.bias:
            params["b"] = jnp.zeros((self.nb_feature, self.output_dim))
        return params

    def call(self, params, state, inputs, training=False, rng=None):
        y = jnp.einsum("bd,kdo->bko", inputs, params["W"])
        if self.bias:
            y = y + params["b"]
        return jnp.max(y, axis=1)

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self.output_dim)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(output_dim=self.output_dim, nb_feature=self.nb_feature,
                   bias=self.bias)
        return cfg


@register_layer
class TimeDistributed(Layer):
    """Apply an inner layer to every timestep (reference TimeDistributed.scala).

    Implemented by folding time into batch — one big MXU-friendly op instead
    of a per-step loop.
    """

    stateful = True
    stochastic = True

    def __init__(self, layer=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.layer = layer

    def init(self, rng, input_shape):
        inner_shape = (input_shape[0],) + tuple(input_shape[2:])
        return self.layer.init(rng, inner_shape)

    def apply(self, params, state, inputs, training=False, rng=None):
        b, t = inputs.shape[0], inputs.shape[1]
        flat = inputs.reshape((b * t,) + inputs.shape[2:])
        out, new_state = remat_apply(self.layer, params, state, flat,
                                     training=training, rng=rng)
        return out.reshape((b, t) + out.shape[1:]), new_state

    def call(self, params, state, inputs, training=False, rng=None):
        return self.apply(params, state, inputs, training=training, rng=rng)[0]

    def compute_output_shape(self, input_shape):
        inner_in = (input_shape[0],) + tuple(input_shape[2:])
        inner_out = self.layer.compute_output_shape(inner_in)
        return (input_shape[0], input_shape[1]) + tuple(inner_out[1:])

    def get_config(self):
        from .....core.module import serial_class_name
        cfg = super().get_config()
        cfg["layer"] = {"class_name": serial_class_name(self.layer),
                        "config": self.layer.get_config()}
        return cfg

    @classmethod
    def from_config(cls, config):
        from .....core.module import (get_layer_class, pop_base_flags,
                                      set_base_flags)
        config = dict(config)
        inner = config.pop("layer")
        flags = pop_base_flags(config)
        layer = get_layer_class(inner["class_name"]).from_config(
            inner["config"])
        return set_base_flags(cls(layer=layer, **config), flags)
