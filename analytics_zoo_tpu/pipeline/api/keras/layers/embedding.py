"""Embedding layers: Embedding, SparseEmbedding, WordEmbedding.

Parity surface: reference zoo/.../pipeline/api/keras/layers/{Embedding,
SparseEmbedding, WordEmbedding}.scala.  WordEmbedding reproduces the frozen
pretrained-GloVe path (WordEmbedding.scala:48-141): parse a GloVe text file
into an index + matrix, serve lookups from a non-trainable state buffer.

Lookups are ``jnp.take`` — XLA lowers them to efficient dynamic-gather on
TPU; embedding tables large enough to shard ride the standard param-sharding
rules in parallel/sharding.py.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .....core import initializers
from .....core.module import Layer, register_layer
from .. import regularizers


@register_layer
class Embedding(regularizers.RegularizedLayerMixin, Layer):
    """Trainable lookup table (reference Embedding.scala, incl. its
    wRegularizer arg)."""

    _reg_w_key = "embeddings"

    def __init__(self, input_dim, output_dim, init="uniform",
                 input_length=None, W_regularizer=None, input_shape=None,
                 name=None):
        if input_length is not None and input_shape is None:
            input_shape = (input_length,)
        super().__init__(input_shape=input_shape, name=name)
        self.input_dim = int(input_dim)
        self.output_dim = int(output_dim)
        self.init_name = init
        self._setup_regularizers(W_regularizer, None)

    def init_params(self, rng, input_shape):
        return {"embeddings": initializers.get(self.init_name)(
            rng, (self.input_dim, self.output_dim))}

    def call(self, params, state, inputs, training=False, rng=None):
        idx = inputs.astype(jnp.int32)
        y = jnp.take(params["embeddings"], idx, axis=0)
        if self.stateful:
            return y, {"aux_loss": self._penalty(params)}
        return y

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(input_dim=self.input_dim, output_dim=self.output_dim,
                   init=self.init_name,
                   W_regularizer=regularizers.to_config(
                       self.W_regularizer))
        return cfg


@register_layer
class SparseEmbedding(Embedding):
    """Embedding fed by sparse-style id bags (reference SparseEmbedding.scala).

    On TPU, ids arrive densely padded; semantics match Embedding.
    """


@register_layer
class WordEmbedding(Layer):
    """Frozen pretrained word embeddings (reference WordEmbedding.scala:48-141).

    The table lives in state (non-trainable), so the optimizer never touches
    it and it is replicated/sharded like any other buffer.
    """

    stateful = True

    def __init__(self, embedding_file=None, word_index=None, trainable=False,
                 input_length=None, input_shape=None, name=None,
                 _table=None, _output_dim=None):
        if input_length is not None and input_shape is None:
            input_shape = (input_length,)
        super().__init__(input_shape=input_shape, name=name)
        self.embedding_file = embedding_file
        self.word_index = word_index
        if _table is not None:
            self._table = np.asarray(_table, dtype=np.float32)
        elif embedding_file is not None:
            wi = word_index or WordEmbedding.get_word_index(embedding_file)
            self.word_index = wi
            self._table = _build_table(embedding_file, wi)
        else:
            raise ValueError("WordEmbedding needs embedding_file or _table")
        self.output_dim = self._table.shape[1]

    @staticmethod
    def get_word_index(embedding_file) -> Dict[str, int]:
        """Parse word→1-based-index from a GloVe-format file
        (reference WordEmbedding.scala:104-141)."""
        index = {}
        with open(embedding_file, "r", encoding="utf-8") as f:
            for i, line in enumerate(f):
                word = line.split(" ", 1)[0]
                index[word] = i + 1  # 0 reserved for padding/unknown
        return index

    def init_state(self, input_shape):
        return {"table": jnp.asarray(self._table)}

    def apply(self, params, state, inputs, training=False, rng=None):
        idx = inputs.astype(jnp.int32)
        return jnp.take(state["table"], idx, axis=0), state

    def call(self, params, state, inputs, training=False, rng=None):
        return self.apply(params, state, inputs, training=training,
                          rng=rng)[0]

    def compute_output_shape(self, input_shape):
        return tuple(input_shape) + (self.output_dim,)

    def get_config(self):
        cfg = super().get_config()
        cfg["_table"] = np.asarray(self._table).tolist()
        return cfg


def _build_table(embedding_file, word_index) -> np.ndarray:
    """Rows ordered by index; row 0 is the zero (padding/unknown) vector."""
    vectors = {}
    dim = None
    with open(embedding_file, "r", encoding="utf-8") as f:
        for line in f:
            parts = line.rstrip().split(" ")
            word, vec = parts[0], np.asarray(parts[1:], dtype=np.float32)
            dim = dim or len(vec)
            if word in word_index:
                vectors[word_index[word]] = vec
    n = max(word_index.values()) + 1
    table = np.zeros((n, dim), dtype=np.float32)
    for idx, vec in vectors.items():
        table[idx] = vec
    return table
