"""Noise regularization layers.

Parity surface: reference zoo/.../pipeline/api/keras/layers/{GaussianNoise,
GaussianDropout}.scala.  Both are identity at inference; noise threads through
the explicit layer rng so runs are reproducible under jit.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.module import Layer, register_layer


@register_layer
class GaussianNoise(Layer):
    stochastic = True

    def __init__(self, sigma=0.1, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.sigma = float(sigma)

    def call(self, params, state, inputs, training=False, rng=None):
        if not training or rng is None:
            return inputs
        return inputs + self.sigma * jax.random.normal(rng, inputs.shape)

    def get_config(self):
        cfg = super().get_config()
        cfg["sigma"] = self.sigma
        return cfg


@register_layer
class GaussianDropout(Layer):
    stochastic = True

    def __init__(self, p=0.5, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.p = float(p)

    def call(self, params, state, inputs, training=False, rng=None):
        if not training or rng is None or self.p <= 0:
            return inputs
        stddev = (self.p / (1.0 - self.p)) ** 0.5
        return inputs * (
            1.0 + stddev * jax.random.normal(rng, inputs.shape))

    def get_config(self):
        cfg = super().get_config()
        cfg["p"] = self.p
        return cfg
