"""Advanced activation layers.

Parity surface: reference zoo/.../pipeline/api/keras/layers/{ELU, LeakyReLU,
PReLU, SReLU, ThresholdedReLU}.scala.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.module import Layer, register_layer


@register_layer
class ELU(Layer):
    def __init__(self, alpha=1.0, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.alpha = float(alpha)

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.where(inputs > 0, inputs,
                         self.alpha * (jnp.exp(inputs) - 1.0))

    def get_config(self):
        cfg = super().get_config()
        cfg["alpha"] = self.alpha
        return cfg


@register_layer
class LeakyReLU(Layer):
    def __init__(self, alpha=0.3, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.alpha = float(alpha)

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.where(inputs > 0, inputs, self.alpha * inputs)

    def get_config(self):
        cfg = super().get_config()
        cfg["alpha"] = self.alpha
        return cfg


@register_layer
class ThresholdedReLU(Layer):
    def __init__(self, theta=1.0, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.theta = float(theta)

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.where(inputs > self.theta, inputs, 0.0)

    def get_config(self):
        cfg = super().get_config()
        cfg["theta"] = self.theta
        return cfg


@register_layer
class PReLU(Layer):
    """Learnable per-channel leak (reference PReLU semantics)."""

    def __init__(self, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)

    def init_params(self, rng, input_shape):
        return {"alpha": 0.25 * jnp.ones((input_shape[-1],))}

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.where(inputs > 0, inputs, params["alpha"] * inputs)


@register_layer
class SReLU(Layer):
    """S-shaped ReLU with 4 learnable per-channel params
    (reference SReLU.scala)."""

    def __init__(self, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)

    def init_params(self, rng, input_shape):
        n = input_shape[-1]
        return {
            "t_left": jnp.zeros((n,)),
            "a_left": jnp.zeros((n,)),
            "t_right": jnp.ones((n,)),
            "a_right": jnp.ones((n,)),
        }

    def call(self, params, state, inputs, training=False, rng=None):
        tl, al = params["t_left"], params["a_left"]
        tr, ar = params["t_right"], params["a_right"]
        y = jnp.where(inputs < tl, tl + al * (inputs - tl), inputs)
        return jnp.where(inputs > tr, tr + ar * (inputs - tr), y)
