"""Convolution layer family (Keras-1 surface, NHWC/NDHWC TPU layouts).

Parity surface: reference zoo/.../pipeline/api/keras/layers/{Convolution1D,
Convolution2D, Convolution3D, AtrousConvolution1D/2D, SeparableConvolution2D,
Deconvolution2D, ShareConvolution2D, Cropping*, ZeroPadding*, UpSampling*,
ResizeBilinear, LocallyConnected1D/2D}.scala.

All convs lower to one ``lax.conv_general_dilated`` with channels-last
dimension numbers — the layout XLA:TPU tiles directly onto the MXU.
``dim_ordering="th"`` inputs are accepted for reference parity and transposed
at the boundary once, not per-op.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from .....core import initializers
from .....core import shapes as shape_utils
from .....core.module import Layer, register_layer
from .. import activations
from .. import regularizers

_DN = {  # channels-last conv dimension numbers per spatial rank
    1: ("NWC", "WIO", "NWC"),
    2: ("NHWC", "HWIO", "NHWC"),
    3: ("NDHWC", "DHWIO", "NDHWC"),
}


def _padding(border_mode: str, rank: int):
    if border_mode == "same":
        return "SAME"
    if border_mode == "valid":
        return "VALID"
    if border_mode == "causal":
        return None  # handled by explicit pre-pad in Conv1D
    raise ValueError(f"Unsupported border_mode {border_mode!r}")


class _ConvND(regularizers.RegularizedLayerMixin, Layer):
    """Shared machinery for 1/2/3-D convolutions."""

    rank: int = 2

    def __init__(self, nb_filter, kernel_size, init="glorot_uniform",
                 activation=None, border_mode="valid", subsample=1,
                 dilation=1, dim_ordering=None, bias=True,
                 W_regularizer=None, b_regularizer=None,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.kernel_size = shape_utils.normalize_tuple(
            kernel_size, self.rank, "kernel_size")
        self.subsample = shape_utils.normalize_tuple(
            subsample, self.rank, "subsample")
        self.dilation = shape_utils.normalize_tuple(
            dilation, self.rank, "dilation")
        self.border_mode = border_mode
        self.init_name = init
        self.activation_name = activation if not callable(activation) else None
        self.activation = activations.get(activation)
        self.bias = bias
        self.data_format = shape_utils.normalize_data_format(dim_ordering)
        self._setup_regularizers(W_regularizer, b_regularizer)

    # -- layout helpers: everything internal is channels-last --
    def _to_cl(self, x):
        if self.data_format == "channels_first":
            perm = (0,) + tuple(range(2, 2 + self.rank)) + (1,)
            return jnp.transpose(x, perm)
        return x

    def _from_cl(self, x):
        if self.data_format == "channels_first":
            perm = (0, self.rank + 1) + tuple(range(1, self.rank + 1))
            return jnp.transpose(x, perm)
        return x

    def _cl_shape(self, input_shape):
        if self.data_format == "channels_first":
            return (input_shape[0],) + tuple(input_shape[2:]) + (input_shape[1],)
        return tuple(input_shape)

    def init_params(self, rng, input_shape):
        in_ch = self._cl_shape(input_shape)[-1]
        w_shape = self.kernel_size + (in_ch, self.nb_filter)
        params = {"W": initializers.get(self.init_name)(rng, w_shape)}
        if self.bias:
            params["b"] = jnp.zeros((self.nb_filter,))
        return params

    def _resolve_padding(self, x):
        """(possibly pre-padded x, lax padding spec) for this conv's
        border mode — shared with the int8 inference path so float and
        quantized convs cannot drift."""
        pad = _padding(self.border_mode, self.rank)
        if self.border_mode == "causal":  # Conv1D only
            left = self.dilation[0] * (self.kernel_size[0] - 1)
            x = jnp.pad(x, ((0, 0), (left, 0), (0, 0)))
            pad = "VALID"
        return x, pad

    def _conv(self, x, w):
        x, pad = self._resolve_padding(x)
        return lax.conv_general_dilated(
            x, w, window_strides=self.subsample, padding=pad,
            rhs_dilation=self.dilation,
            dimension_numbers=_DN[self.rank])

    def call(self, params, state, inputs, training=False, rng=None):
        x = self._to_cl(inputs)
        y = self._conv(x, params["W"])
        if self.bias:
            y = y + params["b"]
        if self.activation is not None:
            y = self.activation(y)
        y = self._from_cl(y)
        if self.stateful:
            return y, {"aux_loss": self._penalty(params)}
        return y

    def compute_output_shape(self, input_shape):
        cl = self._cl_shape(input_shape)
        spatial = [
            shape_utils.conv_output_length(
                cl[1 + i], self.kernel_size[i], self.border_mode,
                self.subsample[i], self.dilation[i])
            for i in range(self.rank)
        ]
        out_cl = (cl[0],) + tuple(spatial) + (self.nb_filter,)
        if self.data_format == "channels_first":
            return (out_cl[0], out_cl[-1]) + tuple(out_cl[1:-1])
        return out_cl

    def get_config(self):
        cfg = super().get_config()
        cfg.update(nb_filter=self.nb_filter,
                   kernel_size=list(self.kernel_size), init=self.init_name,
                   activation=self.activation_name,
                   border_mode=self.border_mode,
                   subsample=list(self.subsample),
                   dilation=list(self.dilation), bias=self.bias,
                   dim_ordering=self.data_format,
                   W_regularizer=regularizers.to_config(self.W_regularizer),
                   b_regularizer=regularizers.to_config(self.b_regularizer))
        return cfg


@register_layer
class Convolution1D(_ConvND):
    """Reference Convolution1D.scala; input (batch, steps, channels)."""

    rank = 1

    def __init__(self, nb_filter, filter_length=3, kernel_size=None, **kw):
        super().__init__(nb_filter, kernel_size or filter_length, **kw)


@register_layer
class Convolution2D(_ConvND):
    """Reference Convolution2D.scala."""

    rank = 2

    def __init__(self, nb_filter, nb_row=3, nb_col=3, kernel_size=None, **kw):
        super().__init__(nb_filter, kernel_size or (nb_row, nb_col), **kw)


@register_layer
class Convolution3D(_ConvND):
    """Reference Convolution3D.scala."""

    rank = 3

    def __init__(self, nb_filter, kernel_dim1=3, kernel_dim2=3, kernel_dim3=3,
                 kernel_size=None, **kw):
        super().__init__(
            nb_filter, kernel_size or (kernel_dim1, kernel_dim2, kernel_dim3),
            **kw)


@register_layer
class AtrousConvolution1D(Convolution1D):
    """Dilated 1D conv (reference AtrousConvolution1D.scala)."""

    def __init__(self, nb_filter, filter_length=3, atrous_rate=1, **kw):
        kw.setdefault("dilation", atrous_rate)
        super().__init__(nb_filter, filter_length, **kw)


@register_layer
class AtrousConvolution2D(Convolution2D):
    """Dilated 2D conv (reference AtrousConvolution2D.scala)."""

    def __init__(self, nb_filter, nb_row=3, nb_col=3, atrous_rate=(1, 1),
                 **kw):
        kw.setdefault("dilation", atrous_rate)
        super().__init__(nb_filter, nb_row, nb_col, **kw)


@register_layer
class ShareConvolution2D(Convolution2D):
    """Weight-shared conv (reference ShareConvolution2D.scala).

    Weight sharing in this framework is "call the same layer instance twice"
    — the graph engine maps one params entry per instance — so this is
    behaviourally Convolution2D.
    """


@register_layer
class SeparableConvolution2D(Layer):
    """Depthwise-separable conv (reference SeparableConvolution2D.scala)."""

    def __init__(self, nb_filter, nb_row=3, nb_col=3, init="glorot_uniform",
                 activation=None, border_mode="valid", subsample=(1, 1),
                 depth_multiplier=1, dim_ordering=None, bias=True,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.kernel_size = (int(nb_row), int(nb_col))
        self.subsample = shape_utils.normalize_tuple(subsample, 2)
        self.border_mode = border_mode
        self.depth_multiplier = int(depth_multiplier)
        self.init_name = init
        self.activation_name = activation if not callable(activation) else None
        self.activation = activations.get(activation)
        self.bias = bias
        self.data_format = shape_utils.normalize_data_format(dim_ordering)

    def _cl_shape(self, s):
        if self.data_format == "channels_first":
            return (s[0], s[2], s[3], s[1])
        return tuple(s)

    def init_params(self, rng, input_shape):
        in_ch = self._cl_shape(input_shape)[-1]
        k1, k2 = jax.random.split(rng)
        params = {
            "depthwise": initializers.get(self.init_name)(
                k1, self.kernel_size + (1, in_ch * self.depth_multiplier)),
            "pointwise": initializers.get(self.init_name)(
                k2, (1, 1, in_ch * self.depth_multiplier, self.nb_filter)),
        }
        if self.bias:
            params["b"] = jnp.zeros((self.nb_filter,))
        return params

    def call(self, params, state, inputs, training=False, rng=None):
        x = inputs
        if self.data_format == "channels_first":
            x = jnp.transpose(x, (0, 2, 3, 1))
        in_ch = x.shape[-1]
        pad = "SAME" if self.border_mode == "same" else "VALID"
        y = lax.conv_general_dilated(
            x, params["depthwise"], window_strides=self.subsample,
            padding=pad, dimension_numbers=_DN[2],
            feature_group_count=in_ch)
        y = lax.conv_general_dilated(
            y, params["pointwise"], window_strides=(1, 1), padding="VALID",
            dimension_numbers=_DN[2])
        if self.bias:
            y = y + params["b"]
        if self.activation is not None:
            y = self.activation(y)
        if self.data_format == "channels_first":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y

    def compute_output_shape(self, input_shape):
        cl = self._cl_shape(input_shape)
        spatial = [
            shape_utils.conv_output_length(
                cl[1 + i], self.kernel_size[i], self.border_mode,
                self.subsample[i]) for i in range(2)]
        out = (cl[0],) + tuple(spatial) + (self.nb_filter,)
        if self.data_format == "channels_first":
            return (out[0], out[3], out[1], out[2])
        return out

    def get_config(self):
        cfg = super().get_config()
        cfg.update(nb_filter=self.nb_filter, nb_row=self.kernel_size[0],
                   nb_col=self.kernel_size[1], init=self.init_name,
                   activation=self.activation_name,
                   border_mode=self.border_mode,
                   subsample=list(self.subsample),
                   depth_multiplier=self.depth_multiplier, bias=self.bias,
                   dim_ordering=self.data_format)
        return cfg


@register_layer
class Deconvolution2D(Layer):
    """Transposed 2D conv (reference Deconvolution2D.scala)."""

    def __init__(self, nb_filter, nb_row=3, nb_col=3, init="glorot_uniform",
                 activation=None, border_mode="valid", subsample=(1, 1),
                 dim_ordering=None, bias=True, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.kernel_size = (int(nb_row), int(nb_col))
        self.subsample = shape_utils.normalize_tuple(subsample, 2)
        self.border_mode = border_mode
        self.init_name = init
        self.activation_name = activation if not callable(activation) else None
        self.activation = activations.get(activation)
        self.bias = bias
        self.data_format = shape_utils.normalize_data_format(dim_ordering)

    def _cl_shape(self, s):
        if self.data_format == "channels_first":
            return (s[0], s[2], s[3], s[1])
        return tuple(s)

    def init_params(self, rng, input_shape):
        in_ch = self._cl_shape(input_shape)[-1]
        params = {"W": initializers.get(self.init_name)(
            rng, self.kernel_size + (in_ch, self.nb_filter))}
        if self.bias:
            params["b"] = jnp.zeros((self.nb_filter,))
        return params

    def call(self, params, state, inputs, training=False, rng=None):
        x = inputs
        if self.data_format == "channels_first":
            x = jnp.transpose(x, (0, 2, 3, 1))
        pad = "SAME" if self.border_mode == "same" else "VALID"
        y = lax.conv_transpose(
            x, params["W"], strides=self.subsample, padding=pad,
            dimension_numbers=_DN[2])
        if self.bias:
            y = y + params["b"]
        if self.activation is not None:
            y = self.activation(y)
        if self.data_format == "channels_first":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y

    def compute_output_shape(self, input_shape):
        cl = self._cl_shape(input_shape)
        spatial = [
            shape_utils.deconv_output_length(
                cl[1 + i], self.kernel_size[i], self.border_mode,
                self.subsample[i]) for i in range(2)]
        out = (cl[0],) + tuple(spatial) + (self.nb_filter,)
        if self.data_format == "channels_first":
            return (out[0], out[3], out[1], out[2])
        return out

    def get_config(self):
        cfg = super().get_config()
        cfg.update(nb_filter=self.nb_filter, nb_row=self.kernel_size[0],
                   nb_col=self.kernel_size[1], init=self.init_name,
                   activation=self.activation_name,
                   border_mode=self.border_mode,
                   subsample=list(self.subsample), bias=self.bias,
                   dim_ordering=self.data_format)
        return cfg


@register_layer
class LocallyConnected1D(Layer):
    """Conv1D with unshared weights (reference LocallyConnected1D.scala)."""

    def __init__(self, nb_filter, filter_length=3, activation=None,
                 border_mode="valid", subsample_length=1, bias=True,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.filter_length = int(filter_length)
        self.subsample = int(subsample_length)
        self.border_mode = border_mode
        self.activation_name = activation if not callable(activation) else None
        self.activation = activations.get(activation)
        self.bias = bias

    def _out_steps(self, steps):
        return shape_utils.conv_output_length(
            steps, self.filter_length, self.border_mode, self.subsample)

    def init_params(self, rng, input_shape):
        steps, ch = input_shape[1], input_shape[2]
        out_steps = self._out_steps(steps)
        params = {"W": initializers.glorot_uniform(
            rng, (out_steps, self.filter_length * ch, self.nb_filter))}
        if self.bias:
            params["b"] = jnp.zeros((out_steps, self.nb_filter))
        return params

    def call(self, params, state, inputs, training=False, rng=None):
        # extract patches: (b, out_steps, filter_length*ch)
        out_steps = params["W"].shape[0]
        idx = (jnp.arange(out_steps)[:, None] * self.subsample
               + jnp.arange(self.filter_length)[None, :])
        patches = inputs[:, idx, :]  # (b, out_steps, fl, ch)
        patches = patches.reshape(inputs.shape[0], out_steps, -1)
        y = jnp.einsum("bsk,sko->bso", patches, params["W"])
        if self.bias:
            y = y + params["b"]
        if self.activation is not None:
            y = self.activation(y)
        return y

    def compute_output_shape(self, input_shape):
        return (input_shape[0], self._out_steps(input_shape[1]),
                self.nb_filter)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(nb_filter=self.nb_filter, filter_length=self.filter_length,
                   activation=self.activation_name,
                   border_mode=self.border_mode,
                   subsample_length=self.subsample, bias=self.bias)
        return cfg


@register_layer
class LocallyConnected2D(Layer):
    """Conv2D with unshared weights (reference LocallyConnected2D.scala)."""

    def __init__(self, nb_filter, nb_row=3, nb_col=3, activation=None,
                 border_mode="valid", subsample=(1, 1), dim_ordering=None,
                 bias=True, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.nb_filter = int(nb_filter)
        self.kernel_size = (int(nb_row), int(nb_col))
        self.subsample = shape_utils.normalize_tuple(subsample, 2)
        self.border_mode = border_mode
        self.activation_name = activation if not callable(activation) else None
        self.activation = activations.get(activation)
        self.bias = bias
        self.data_format = shape_utils.normalize_data_format(dim_ordering)

    def _cl_shape(self, s):
        if self.data_format == "channels_first":
            return (s[0], s[2], s[3], s[1])
        return tuple(s)

    def _out_spatial(self, cl):
        return tuple(
            shape_utils.conv_output_length(
                cl[1 + i], self.kernel_size[i], self.border_mode,
                self.subsample[i]) for i in range(2))

    def init_params(self, rng, input_shape):
        cl = self._cl_shape(input_shape)
        oh, ow = self._out_spatial(cl)
        k = self.kernel_size[0] * self.kernel_size[1] * cl[-1]
        params = {"W": initializers.glorot_uniform(
            rng, (oh * ow, k, self.nb_filter))}
        if self.bias:
            params["b"] = jnp.zeros((oh * ow, self.nb_filter))
        return params

    def call(self, params, state, inputs, training=False, rng=None):
        x = inputs
        if self.data_format == "channels_first":
            x = jnp.transpose(x, (0, 2, 3, 1))
        b, h, w, c = x.shape
        oh, ow = self._out_spatial((b, h, w, c))
        kh, kw = self.kernel_size
        sh, sw = self.subsample
        ri = jnp.arange(oh)[:, None] * sh + jnp.arange(kh)[None, :]
        ci = jnp.arange(ow)[:, None] * sw + jnp.arange(kw)[None, :]
        patches = x[:, ri[:, None, :, None], ci[None, :, None, :], :]
        patches = patches.reshape(b, oh * ow, kh * kw * c)
        y = jnp.einsum("bsk,sko->bso", patches, params["W"])
        if self.bias:
            y = y + params["b"]
        y = y.reshape(b, oh, ow, self.nb_filter)
        if self.activation is not None:
            y = self.activation(y)
        if self.data_format == "channels_first":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y

    def compute_output_shape(self, input_shape):
        cl = self._cl_shape(input_shape)
        oh, ow = self._out_spatial(cl)
        out = (cl[0], oh, ow, self.nb_filter)
        if self.data_format == "channels_first":
            return (out[0], out[3], out[1], out[2])
        return out

    def get_config(self):
        cfg = super().get_config()
        cfg.update(nb_filter=self.nb_filter, nb_row=self.kernel_size[0],
                   nb_col=self.kernel_size[1],
                   activation=self.activation_name,
                   border_mode=self.border_mode,
                   subsample=list(self.subsample), bias=self.bias,
                   dim_ordering=self.data_format)
        return cfg


class _PadCropBase(Layer):
    def __init__(self, dim_ordering=None, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.data_format = shape_utils.normalize_data_format(dim_ordering)


@register_layer
class ZeroPadding1D(Layer):
    """Reference ZeroPadding1D.scala."""

    def __init__(self, padding=1, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.padding = shape_utils.normalize_tuple(padding, 2) \
            if not isinstance(padding, int) else (padding, padding)

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.pad(inputs, ((0, 0), self.padding, (0, 0)))

    def compute_output_shape(self, input_shape):
        steps = input_shape[1]
        steps = None if steps is None else steps + sum(self.padding)
        return (input_shape[0], steps, input_shape[2])

    def get_config(self):
        cfg = super().get_config()
        cfg["padding"] = list(self.padding)
        return cfg


@register_layer
class ZeroPadding2D(_PadCropBase):
    """Reference ZeroPadding2D.scala."""

    def __init__(self, padding=(1, 1), dim_ordering=None, input_shape=None,
                 name=None):
        super().__init__(dim_ordering=dim_ordering, input_shape=input_shape,
                         name=name)
        if len(padding) == 2:
            self.padding = ((padding[0], padding[0]),
                            (padding[1], padding[1]))
        else:
            self.padding = ((padding[0], padding[1]),
                            (padding[2], padding[3]))

    def call(self, params, state, inputs, training=False, rng=None):
        if self.data_format == "channels_last":
            pads = ((0, 0),) + self.padding + ((0, 0),)
        else:
            pads = ((0, 0), (0, 0)) + self.padding
        return jnp.pad(inputs, pads)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        axes = (1, 2) if self.data_format == "channels_last" else (2, 3)
        for ax, (lo, hi) in zip(axes, self.padding):
            if s[ax] is not None:
                s[ax] += lo + hi
        return tuple(s)

    def get_config(self):
        cfg = super().get_config()
        cfg["padding"] = [p for pair in self.padding for p in pair]
        cfg["dim_ordering"] = self.data_format
        return cfg


@register_layer
class ZeroPadding3D(_PadCropBase):
    """Reference ZeroPadding3D.scala."""

    def __init__(self, padding=(1, 1, 1), dim_ordering=None, input_shape=None,
                 name=None):
        super().__init__(dim_ordering=dim_ordering, input_shape=input_shape,
                         name=name)
        self.padding = tuple(int(p) for p in padding)

    def call(self, params, state, inputs, training=False, rng=None):
        p = [(x, x) for x in self.padding]
        if self.data_format == "channels_last":
            pads = [(0, 0)] + p + [(0, 0)]
        else:
            pads = [(0, 0), (0, 0)] + p
        return jnp.pad(inputs, pads)

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        axes = (1, 2, 3) if self.data_format == "channels_last" else (2, 3, 4)
        for ax, p in zip(axes, self.padding):
            if s[ax] is not None:
                s[ax] += 2 * p
        return tuple(s)

    def get_config(self):
        cfg = super().get_config()
        cfg["padding"] = list(self.padding)
        cfg["dim_ordering"] = self.data_format
        return cfg


@register_layer
class Cropping1D(Layer):
    """Reference Cropping1D.scala."""

    def __init__(self, cropping=(1, 1), input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.cropping = tuple(int(c) for c in cropping)

    def call(self, params, state, inputs, training=False, rng=None):
        lo, hi = self.cropping
        return inputs[:, lo:inputs.shape[1] - hi, :]

    def compute_output_shape(self, input_shape):
        steps = input_shape[1]
        if steps is not None:
            steps -= self.cropping[0] + self.cropping[1]
        return (input_shape[0], steps, input_shape[2])

    def get_config(self):
        cfg = super().get_config()
        cfg["cropping"] = list(self.cropping)
        return cfg


@register_layer
class Cropping2D(_PadCropBase):
    """Reference Cropping2D.scala."""

    def __init__(self, cropping=((0, 0), (0, 0)), dim_ordering=None,
                 input_shape=None, name=None):
        super().__init__(dim_ordering=dim_ordering, input_shape=input_shape,
                         name=name)
        self.cropping = tuple(tuple(int(x) for x in c) for c in cropping)

    def call(self, params, state, inputs, training=False, rng=None):
        (t, b), (l, r) = self.cropping
        if self.data_format == "channels_last":
            return inputs[:, t:inputs.shape[1] - b, l:inputs.shape[2] - r, :]
        return inputs[:, :, t:inputs.shape[2] - b, l:inputs.shape[3] - r]

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        axes = (1, 2) if self.data_format == "channels_last" else (2, 3)
        for ax, (lo, hi) in zip(axes, self.cropping):
            if s[ax] is not None:
                s[ax] -= lo + hi
        return tuple(s)

    def get_config(self):
        cfg = super().get_config()
        cfg["cropping"] = [list(c) for c in self.cropping]
        cfg["dim_ordering"] = self.data_format
        return cfg


@register_layer
class Cropping3D(_PadCropBase):
    """Reference Cropping3D.scala."""

    def __init__(self, cropping=((1, 1), (1, 1), (1, 1)), dim_ordering=None,
                 input_shape=None, name=None):
        super().__init__(dim_ordering=dim_ordering, input_shape=input_shape,
                         name=name)
        self.cropping = tuple(tuple(int(x) for x in c) for c in cropping)

    def call(self, params, state, inputs, training=False, rng=None):
        (a0, b0), (a1, b1), (a2, b2) = self.cropping
        if self.data_format == "channels_last":
            return inputs[:, a0:inputs.shape[1] - b0,
                          a1:inputs.shape[2] - b1,
                          a2:inputs.shape[3] - b2, :]
        return inputs[:, :, a0:inputs.shape[2] - b0,
                      a1:inputs.shape[3] - b1, a2:inputs.shape[4] - b2]

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        axes = (1, 2, 3) if self.data_format == "channels_last" else (2, 3, 4)
        for ax, (lo, hi) in zip(axes, self.cropping):
            if s[ax] is not None:
                s[ax] -= lo + hi
        return tuple(s)

    def get_config(self):
        cfg = super().get_config()
        cfg["cropping"] = [list(c) for c in self.cropping]
        cfg["dim_ordering"] = self.data_format
        return cfg


@register_layer
class UpSampling1D(Layer):
    """Reference UpSampling1D.scala."""

    def __init__(self, length=2, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.length = int(length)

    def call(self, params, state, inputs, training=False, rng=None):
        return jnp.repeat(inputs, self.length, axis=1)

    def compute_output_shape(self, input_shape):
        steps = input_shape[1]
        return (input_shape[0],
                None if steps is None else steps * self.length,
                input_shape[2])

    def get_config(self):
        cfg = super().get_config()
        cfg["length"] = self.length
        return cfg


@register_layer
class SpaceToDepth2D(_PadCropBase):
    """Rearrange (H, W, C) -> (H/b, W/b, b*b*C) by b x b blocks.

    Not part of the reference Keras-1 set; this is the TPU stem helper
    (the MLPerf-ResNet pattern): packing 2x2 pixel blocks into channels
    turns the C=3 7x7/s2 stem conv into a C=12 4x4/s1 conv the MXU runs
    at far higher utilization.  Packed channel index is
    (r * b + s) * C + c for block-local offset (r, s).
    """

    def __init__(self, block_size=2, dim_ordering=None, input_shape=None,
                 name=None):
        super().__init__(dim_ordering=dim_ordering, input_shape=input_shape,
                         name=name)
        self.block_size = int(block_size)

    def call(self, params, state, inputs, training=False, rng=None):
        b = self.block_size
        cf = self.data_format == "channels_first"
        x = jnp.transpose(inputs, (0, 2, 3, 1)) if cf else inputs
        n, h, w, c = x.shape
        if h % b or w % b:
            raise ValueError(
                f"SpaceToDepth2D: spatial dims ({h}, {w}) not divisible "
                f"by block_size {b}")
        y = x.reshape(n, h // b, b, w // b, b, c)
        y = jnp.transpose(y, (0, 1, 3, 2, 4, 5))
        y = y.reshape(n, h // b, w // b, b * b * c)
        return jnp.transpose(y, (0, 3, 1, 2)) if cf else y

    def compute_output_shape(self, input_shape):
        b = self.block_size
        if self.data_format == "channels_first":
            n, c, h, w = input_shape
        else:
            n, h, w, c = input_shape
        if (h is not None and h % b) or (w is not None and w % b):
            # fail at model construction, not deep inside the jit trace
            raise ValueError(
                f"SpaceToDepth2D: spatial dims ({h}, {w}) not divisible "
                f"by block_size {b}")
        if self.data_format == "channels_first":
            return (n, c * b * b, h // b, w // b)
        return (n, h // b, w // b, c * b * b)

    def get_config(self):
        cfg = super().get_config()
        cfg["block_size"] = self.block_size
        cfg["dim_ordering"] = self.data_format
        return cfg


@register_layer
class UpSampling2D(_PadCropBase):
    """Reference UpSampling2D.scala."""

    def __init__(self, size=(2, 2), dim_ordering=None, input_shape=None,
                 name=None):
        super().__init__(dim_ordering=dim_ordering, input_shape=input_shape,
                         name=name)
        self.size = shape_utils.normalize_tuple(size, 2)

    def call(self, params, state, inputs, training=False, rng=None):
        axes = (1, 2) if self.data_format == "channels_last" else (2, 3)
        y = jnp.repeat(inputs, self.size[0], axis=axes[0])
        return jnp.repeat(y, self.size[1], axis=axes[1])

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        axes = (1, 2) if self.data_format == "channels_last" else (2, 3)
        for ax, k in zip(axes, self.size):
            if s[ax] is not None:
                s[ax] *= k
        return tuple(s)

    def get_config(self):
        cfg = super().get_config()
        cfg["size"] = list(self.size)
        cfg["dim_ordering"] = self.data_format
        return cfg


@register_layer
class UpSampling3D(_PadCropBase):
    """Reference UpSampling3D.scala."""

    def __init__(self, size=(2, 2, 2), dim_ordering=None, input_shape=None,
                 name=None):
        super().__init__(dim_ordering=dim_ordering, input_shape=input_shape,
                         name=name)
        self.size = shape_utils.normalize_tuple(size, 3)

    def call(self, params, state, inputs, training=False, rng=None):
        axes = (1, 2, 3) if self.data_format == "channels_last" else (2, 3, 4)
        y = inputs
        for ax, k in zip(axes, self.size):
            y = jnp.repeat(y, k, axis=ax)
        return y

    def compute_output_shape(self, input_shape):
        s = list(input_shape)
        axes = (1, 2, 3) if self.data_format == "channels_last" else (2, 3, 4)
        for ax, k in zip(axes, self.size):
            if s[ax] is not None:
                s[ax] *= k
        return tuple(s)

    def get_config(self):
        cfg = super().get_config()
        cfg["size"] = list(self.size)
        cfg["dim_ordering"] = self.data_format
        return cfg


@register_layer
class ResizeBilinear(_PadCropBase):
    """Bilinear resize (reference ResizeBilinear.scala) via jax.image."""

    def __init__(self, output_height=None, output_width=None,
                 align_corners=False, dim_ordering=None, input_shape=None,
                 name=None):
        super().__init__(dim_ordering=dim_ordering, input_shape=input_shape,
                         name=name)
        self.output_height = int(output_height)
        self.output_width = int(output_width)
        self.align_corners = align_corners

    def call(self, params, state, inputs, training=False, rng=None):
        if self.data_format == "channels_last":
            shape = (inputs.shape[0], self.output_height, self.output_width,
                     inputs.shape[3])
        else:
            shape = (inputs.shape[0], inputs.shape[1], self.output_height,
                     self.output_width)
        return jax.image.resize(inputs, shape, method="bilinear")

    def compute_output_shape(self, input_shape):
        if self.data_format == "channels_last":
            return (input_shape[0], self.output_height, self.output_width,
                    input_shape[3])
        return (input_shape[0], input_shape[1], self.output_height,
                self.output_width)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(output_height=self.output_height,
                   output_width=self.output_width,
                   align_corners=self.align_corners,
                   dim_ordering=self.data_format)
        return cfg
