"""Normalization layers.

Parity surface: reference zoo/.../pipeline/api/keras/layers/
{BatchNormalization, WithinChannelLRN2D}.scala.  BatchNorm carries its moving
stats in the layer *state* collection (non-trainable pytree), updated
functionally — the jit-safe analogue of BigDL's mutable runningMean/runningVar
buffers.  Cross-replica statistics: when training data-parallel under jit with
a sharded batch axis, XLA computes global batch statistics automatically
because ``jnp.mean`` over a sharded axis lowers to a psum over ICI.
"""

from __future__ import annotations

import jax.numpy as jnp

from .....core import shapes as shape_utils
from .....core.module import Layer, register_layer


@register_layer
class BatchNormalization(Layer):
    stateful = True

    def __init__(self, epsilon=1e-3, momentum=0.99, beta_init="zero",
                 gamma_init="one", dim_ordering=None, input_shape=None,
                 name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.epsilon = float(epsilon)
        self.momentum = float(momentum)
        self.data_format = shape_utils.normalize_data_format(dim_ordering)

    def _channel_axis(self, ndim):
        return 1 if self.data_format == "channels_first" and ndim > 2 else -1

    def _num_features(self, input_shape):
        return input_shape[self._channel_axis(len(input_shape))]

    def init_params(self, rng, input_shape):
        n = self._num_features(input_shape)
        return {"gamma": jnp.ones((n,)), "beta": jnp.zeros((n,))}

    def init_state(self, input_shape):
        n = self._num_features(input_shape)
        # ``count`` = number of EMA updates applied, used to DEBIAS the
        # moving statistics at inference (below).  Imported pretrained
        # stats are already-converged averages: loaders set count=inf so
        # the debias denominator is exactly 1 and they pass through
        # untouched (models/weight_loading.py).
        return {"moving_mean": jnp.zeros((n,)),
                "moving_var": jnp.ones((n,)),
                "count": jnp.zeros((), jnp.float32)}

    def apply(self, params, state, inputs, training=False, rng=None):
        from .....ops.batchnorm import (batch_norm_train,
                                        batch_norm_inference)
        ndim = inputs.ndim
        ch_axis = self._channel_axis(ndim) % ndim

        if training:
            # restructured train-mode core (ops/batchnorm.py): one-pass
            # fused statistics + closed-form custom VJP — statistics
            # accumulate in f32 regardless of compute dtype, and the
            # moving-stat update is stop-gradient (BigDL running stats).
            # USE_NAIVE is the bench's A/B switch (trace-time).
            from .....ops import batchnorm as bn_lib
            bn_fn = (bn_lib.batch_norm_train_naive if bn_lib.USE_NAIVE
                     else batch_norm_train)
            out, mean, var = bn_fn(
                inputs, params["gamma"], params["beta"],
                self.epsilon, ch_axis)
            m = self.momentum
            new_state = {
                "moving_mean": m * state["moving_mean"] + (1 - m) * mean,
                "moving_var": m * state["moving_var"] + (1 - m) * var,
            }
            if "count" in state:
                new_state["count"] = state["count"] + 1.0
        else:
            mean = state["moving_mean"]
            var = state["moving_var"]
            cnt = state.get("count")
            if cnt is not None:
                # Debias against the (0, 1) init, Adam-style: after t
                # updates the EMA still carries weight m^t on its init
                # value — with the Keras-1 default m=0.99 that is 37 %
                # after 100 steps, which through a deep BN stack makes
                # short-trained models evaluate near chance even though
                # training converged.  ema_t = m^t·init + (1−m^t)·avg,
                # so the unbiased batch-stat average is
                # (ema_t − m^t·init) / (1 − m^t); count=0 falls back to
                # the init and count=inf (imported stats) is exact
                # pass-through.
                m = self.momentum
                decay = jnp.power(m, cnt)
                denom = jnp.maximum(1.0 - decay, 1e-12)
                mean = jnp.where(cnt > 0, mean / denom,
                                 jnp.zeros_like(mean))
                var = jnp.where(cnt > 0, (var - decay) / denom,
                                jnp.ones_like(var))
            out = batch_norm_inference(
                inputs, params["gamma"], params["beta"],
                mean, var, self.epsilon, ch_axis)
            new_state = state
        return out, new_state

    def call(self, params, state, inputs, training=False, rng=None):
        return self.apply(params, state, inputs, training=training,
                          rng=rng)[0]

    def get_config(self):
        cfg = super().get_config()
        cfg.update(epsilon=self.epsilon, momentum=self.momentum,
                   dim_ordering=self.data_format)
        return cfg


@register_layer
class WithinChannelLRN2D(Layer):
    """Local response normalization within channels (reference WithinChannelLRN2D.scala)."""

    def __init__(self, size=5, alpha=1.0, beta=0.75, input_shape=None,
                 name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.size = int(size)
        self.alpha = float(alpha)
        self.beta = float(beta)

    def call(self, params, state, inputs, training=False, rng=None):
        from jax import lax
        # average squares over a size×size spatial window, per channel (NHWC)
        sq = jnp.square(inputs)
        window = (1, self.size, self.size, 1)
        summed = lax.reduce_window(sq, 0.0, lax.add, window, (1, 1, 1, 1),
                                   "SAME")
        counts = lax.reduce_window(jnp.ones_like(sq), 0.0, lax.add, window,
                                   (1, 1, 1, 1), "SAME")
        scale = (1.0 + self.alpha * summed / counts) ** self.beta
        return inputs / scale

    def get_config(self):
        cfg = super().get_config()
        cfg.update(size=self.size, alpha=self.alpha, beta=self.beta)
        return cfg


@register_layer
class LRN2D(Layer):
    """Cross-channel local response normalization (AlexNet-style)."""

    def __init__(self, alpha=1e-4, k=1.0, beta=0.75, n=5, dim_ordering=None,
                 input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.alpha, self.k, self.beta, self.n = (
            float(alpha), float(k), float(beta), int(n))
        self.data_format = shape_utils.normalize_data_format(dim_ordering)

    def call(self, params, state, inputs, training=False, rng=None):
        x = inputs
        if self.data_format == "channels_first":
            x = jnp.moveaxis(x, 1, -1)
        sq = jnp.square(x)
        half = self.n // 2
        pads = [(0, 0)] * (x.ndim - 1) + [(half, half)]
        padded = jnp.pad(sq, pads)
        acc = sum(
            padded[..., i:i + x.shape[-1]] for i in range(self.n))
        y = x / (self.k + self.alpha / self.n * acc) ** self.beta
        if self.data_format == "channels_first":
            y = jnp.moveaxis(y, -1, 1)
        return y

    def get_config(self):
        cfg = super().get_config()
        cfg.update(alpha=self.alpha, k=self.k, beta=self.beta, n=self.n,
                   dim_ordering=self.data_format)
        return cfg


@register_layer
class LayerNorm(Layer):
    """Layer normalization over the feature axis (TPU-era extension;
    required by the attention/transformer stack in ops/attention.py)."""

    def __init__(self, epsilon=1e-5, input_shape=None, name=None):
        super().__init__(input_shape=input_shape, name=name)
        self.epsilon = float(epsilon)

    def init_params(self, rng, input_shape):
        n = input_shape[-1]
        return {"gamma": jnp.ones((n,)), "beta": jnp.zeros((n,))}

    def call(self, params, state, inputs, training=False, rng=None):
        mean = jnp.mean(inputs, axis=-1, keepdims=True)
        var = jnp.var(inputs, axis=-1, keepdims=True)
        y = (inputs - mean) / jnp.sqrt(var + self.epsilon)
        return y * params["gamma"] + params["beta"]

    def get_config(self):
        cfg = super().get_config()
        cfg["epsilon"] = self.epsilon
        return cfg
