"""Keras-2-style layer skin: keras-2 argument names over the keras-1 impls.

Parity surface: reference zoo/.../pipeline/api/keras2/layers/*.scala and
pyzoo/zoo/pipeline/api/keras2/layers/ — Dense(units=...), Conv1D/Conv2D
(filters=..., kernel_size=..., strides=..., padding=...), pooling
(pool_size/strides/padding), Dropout(rate=...), Cropping1D,
LocallyConnected1D, and the Maximum/Minimum/Average merge layers with their
functional helpers (merge.py:44,82,121).

Each class subclasses the keras-1 implementation (the same structure the
reference uses: keras2.Dense extends klayers1.Dense, Dense.scala:33-44) and
re-emits get_config in keras-2 vocabulary.  ``serial_name`` disambiguates
the registry entries from the keras-1 classes of the same name.
"""

from __future__ import annotations

from ....core.module import Layer as _BaseLayer, register_layer
from ..keras import regularizers as _reg
from ..keras.layers import convolutional as k1conv
from ..keras.layers import core as k1core
from ..keras.layers import pooling as k1pool
from ..keras.layers.merge import Merge as _K1Merge
from ..keras.layers.pooling import (  # identical in both APIs; re-exported
    GlobalMaxPooling1D, GlobalMaxPooling2D, GlobalMaxPooling3D,
    GlobalAveragePooling1D, GlobalAveragePooling2D, GlobalAveragePooling3D)

Activation = k1core.Activation  # same signature in keras-1 and keras-2
Flatten = k1core.Flatten


@register_layer
class Dense(k1core.Dense):
    """Reference keras2 Dense.scala:33-47 (units/kernel_initializer/
    use_bias naming)."""

    serial_name = "Keras2Dense"

    def __init__(self, units, activation=None,
                 kernel_initializer="glorot_uniform", use_bias=True,
                 kernel_regularizer=None, bias_regularizer=None,
                 input_shape=None, name=None):
        super().__init__(output_dim=units, init=kernel_initializer,
                         activation=activation, bias=use_bias,
                         W_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer,
                         input_shape=input_shape, name=name)

    def get_config(self):
        cfg = _BaseLayer.get_config(self)
        cfg.update(units=self.output_dim, activation=self.activation_name,
                   kernel_initializer=self.init_name, use_bias=self.bias,
                   kernel_regularizer=_reg.to_config(self.W_regularizer),
                   bias_regularizer=_reg.to_config(self.b_regularizer))
        return cfg


@register_layer
class Dropout(k1core.Dropout):
    """Reference keras2 Dropout.scala (rate naming)."""

    serial_name = "Keras2Dropout"

    def __init__(self, rate, input_shape=None, name=None):
        super().__init__(p=rate, input_shape=input_shape, name=name)

    def get_config(self):
        cfg = _BaseLayer.get_config(self)
        cfg["rate"] = self.p
        return cfg


@register_layer
class Conv1D(k1conv.Convolution1D):
    """Reference keras2 Conv1D.scala:33-47."""

    serial_name = "Keras2Conv1D"

    def __init__(self, filters, kernel_size, strides=1, padding="valid",
                 activation=None, use_bias=True,
                 kernel_initializer="glorot_uniform",
                 kernel_regularizer=None, bias_regularizer=None,
                 input_shape=None, name=None):
        super().__init__(nb_filter=filters, filter_length=kernel_size,
                         init=kernel_initializer, activation=activation,
                         border_mode=padding, subsample=strides,
                         bias=use_bias, W_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer,
                         input_shape=input_shape, name=name)

    def get_config(self):
        cfg = _BaseLayer.get_config(self)
        cfg.update(filters=self.nb_filter, kernel_size=self.kernel_size[0],
                   strides=self.subsample[0], padding=self.border_mode,
                   activation=self.activation_name, use_bias=self.bias,
                   kernel_initializer=self.init_name,
                   kernel_regularizer=_reg.to_config(self.W_regularizer),
                   bias_regularizer=_reg.to_config(self.b_regularizer))
        return cfg


@register_layer
class Conv2D(k1conv.Convolution2D):
    """Reference keras2 Conv2D.scala:34-49."""

    serial_name = "Keras2Conv2D"

    def __init__(self, filters, kernel_size, strides=(1, 1),
                 padding="valid", activation=None, use_bias=True,
                 kernel_initializer="glorot_uniform",
                 kernel_regularizer=None, bias_regularizer=None,
                 data_format=None, input_shape=None, name=None):
        super().__init__(nb_filter=filters, kernel_size=kernel_size,
                         init=kernel_initializer, activation=activation,
                         border_mode=padding, subsample=strides,
                         dim_ordering=data_format, bias=use_bias,
                         W_regularizer=kernel_regularizer,
                         b_regularizer=bias_regularizer,
                         input_shape=input_shape, name=name)

    def get_config(self):
        cfg = _BaseLayer.get_config(self)
        cfg.update(filters=self.nb_filter,
                   kernel_size=list(self.kernel_size),
                   strides=list(self.subsample), padding=self.border_mode,
                   activation=self.activation_name, use_bias=self.bias,
                   kernel_initializer=self.init_name,
                   data_format=self.data_format,
                   kernel_regularizer=_reg.to_config(self.W_regularizer),
                   bias_regularizer=_reg.to_config(self.b_regularizer))
        return cfg


@register_layer
class Cropping1D(k1conv.Cropping1D):
    """Same semantics in both APIs (reference keras2 Cropping1D.scala)."""

    serial_name = "Keras2Cropping1D"


@register_layer
class LocallyConnected1D(k1conv.LocallyConnected1D):
    """Reference keras2 LocallyConnected1D.scala:31-44."""

    serial_name = "Keras2LocallyConnected1D"

    def __init__(self, filters, kernel_size, strides=1, padding="valid",
                 activation=None, use_bias=True, kernel_regularizer=None,
                 bias_regularizer=None, input_shape=None, name=None):
        super().__init__(nb_filter=filters, filter_length=kernel_size,
                         activation=activation, border_mode=padding,
                         subsample_length=strides, bias=use_bias,
                         input_shape=input_shape, name=name)

    def get_config(self):
        cfg = _BaseLayer.get_config(self)
        cfg.update(filters=self.nb_filter, kernel_size=self.filter_length,
                   strides=self.subsample, padding=self.border_mode,
                   activation=self.activation_name, use_bias=self.bias)
        return cfg


@register_layer
class MaxPooling1D(k1pool.MaxPooling1D):
    """Reference keras2 MaxPooling1D.scala:31-40 (pool_size/strides)."""

    serial_name = "Keras2MaxPooling1D"

    def __init__(self, pool_size=2, strides=None, padding="valid",
                 input_shape=None, name=None):
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, input_shape=input_shape,
                         name=name)

    def get_config(self):
        cfg = _BaseLayer.get_config(self)
        cfg.update(pool_size=self.pool_size[0], strides=self.strides[0],
                   padding=self.border_mode)
        return cfg


@register_layer
class AveragePooling1D(k1pool.AveragePooling1D):
    """Reference keras2 AveragePooling1D.scala:31-40."""

    serial_name = "Keras2AveragePooling1D"

    def __init__(self, pool_size=2, strides=None, padding="valid",
                 input_shape=None, name=None):
        super().__init__(pool_length=pool_size, stride=strides,
                         border_mode=padding, input_shape=input_shape,
                         name=name)

    def get_config(self):
        cfg = _BaseLayer.get_config(self)
        cfg.update(pool_size=self.pool_size[0], strides=self.strides[0],
                   padding=self.border_mode)
        return cfg


class _FixedMerge(_K1Merge):
    """Merge with the mode baked in (reference keras2 merge layers extend
    Merge with a fixed mode, Maximum.scala:28-32)."""

    merge_mode: str = None

    def __init__(self, input_shape=None, name=None):
        super().__init__(layers=None, mode=self.merge_mode,
                         input_shape=input_shape, name=name)

    def get_config(self):
        return _BaseLayer.get_config(self)


@register_layer
class Maximum(_FixedMerge):
    """Elementwise max over inputs (reference keras2 Maximum.scala)."""

    serial_name = "Keras2Maximum"
    merge_mode = "max"


@register_layer
class Minimum(_FixedMerge):
    """Elementwise min over inputs (reference keras2 Minimum.scala)."""

    serial_name = "Keras2Minimum"
    merge_mode = "min"


@register_layer
class Average(_FixedMerge):
    """Elementwise mean over inputs (reference keras2 Average.scala)."""

    serial_name = "Keras2Average"
    merge_mode = "ave"


def maximum(inputs, **kwargs):
    """Functional helper (reference keras2 merge.py:44)."""
    return Maximum(**kwargs)(list(inputs))


def minimum(inputs, **kwargs):
    """Functional helper (reference keras2 merge.py:82)."""
    return Minimum(**kwargs)(list(inputs))


def average(inputs, **kwargs):
    """Functional helper (reference keras2 merge.py:121)."""
    return Average(**kwargs)(list(inputs))
