from .layers import (
    Dense, Activation, Dropout, Flatten, Conv1D, Conv2D, Cropping1D,
    LocallyConnected1D, MaxPooling1D, AveragePooling1D,
    GlobalMaxPooling1D, GlobalMaxPooling2D, GlobalMaxPooling3D,
    GlobalAveragePooling1D, GlobalAveragePooling2D, GlobalAveragePooling3D,
    Maximum, Minimum, Average, maximum, minimum, average)
from ..keras.engine import Sequential, Model
from ....core.graph import Input
