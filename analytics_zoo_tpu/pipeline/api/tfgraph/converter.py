"""Frozen TF GraphDef → pure JAX function.

This module replaces the reference's embedded TF runtime (TFNet.scala:201-369
runs a TF-Java ``Session`` per forward/backward inside each Spark task) with
an ahead-of-time conversion: each GraphDef node maps to a jnp/lax expression,
so the whole user graph becomes one traceable JAX function that XLA fuses
and tiles for the MXU, and that ``jax.grad`` differentiates directly.

Design notes:
* Shape-math subgraphs (Const/Shape/Pack/Range arithmetic feeding Reshape,
  StridedSlice, Tile, ...) are evaluated with *numpy* so they stay static
  under ``jit`` — the XLA precondition of static shapes is preserved even
  for graphs that compute shapes dynamically in TF.
* Variables (V1 ``VariableV2`` and V2 resource ``VarHandleOp`` /
  ``ReadVariableOp``) become entries of a params pytree, making any
  converted training graph trainable with jax.grad + optax.
* Random ops draw from a threaded ``jax.random`` key folded per-node, so
  dropout-style training graphs are deterministic given the step rng.
* Data-dependent TF control flow (Switch/Merge/While) is rejected with a
  clear error: under XLA it must be expressed as lax control flow.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax


def _tf():
    try:
        import tensorflow  # noqa: F401
        return tensorflow
    except ImportError as e:  # pragma: no cover - env has TF
        raise ImportError(
            "TF interop requires tensorflow to parse GraphDefs; it is not "
            "installed in this environment") from e


# ---------------------------------------------------------------------------
# attrs + refs

def _attr(node, key, default=None):
    """Python-ify an AttrValue (int/float/bool/str/list/dtype/ndarray)."""
    if key not in node.attr:
        return default
    a = node.attr[key]
    which = a.WhichOneof("value")
    if which is None:
        return default
    if which == "i":
        return int(a.i)
    if which == "f":
        return float(a.f)
    if which == "b":
        return bool(a.b)
    if which == "s":
        return a.s.decode("utf-8", "replace")
    if which == "type":
        return _np_dtype(a.type)
    if which == "shape":
        return tuple(d.size for d in a.shape.dim)
    if which == "tensor":
        return _tf().make_ndarray(a.tensor)
    if which == "list":
        lst = a.list
        if len(lst.i):
            return [int(v) for v in lst.i]
        if len(lst.f):
            return [float(v) for v in lst.f]
        if len(lst.s):
            return [v.decode("utf-8", "replace") for v in lst.s]
        if len(lst.b):
            return [bool(v) for v in lst.b]
        return []
    raise ValueError(f"unhandled attr kind {which} for {key}")


def _np_dtype(enum):
    return np.dtype(_tf().dtypes.as_dtype(enum).as_numpy_dtype)


def _parse_ref(ref: str) -> Optional[Tuple[str, int]]:
    """'name:idx' -> (name, idx); control deps ('^name') -> None."""
    if ref.startswith("^"):
        return None
    name, _, idx = ref.partition(":")
    return name, int(idx) if idx else 0


def _norm_tensor_name(name: str) -> Tuple[str, int]:
    r = _parse_ref(name)
    assert r is not None, name
    return r


# ---------------------------------------------------------------------------
# static (host-side numpy) vs traced values: shared with the ONNX importer

from .._convert_util import (ConvertCtx as _Ctx, is_static as _is_static,
                             np_or_jnp as _nb, require_static as _static,
                             static_ints as _ints)

# op handlers.  signature: handler(ctx, node, args) -> output | tuple


def _param(ctx, node):
    if node.name not in ctx.params:
        raise KeyError(
            f"variable '{node.name}' has no value in params "
            f"(have: {sorted(ctx.params)})")
    return ctx.params[node.name]


def _ew(jnp_fn, np_fn=None):
    """Elementwise unary handler."""
    def h(ctx, node, args):
        (x,) = args
        if np_fn is not None and _is_static(x):
            return np_fn(x)
        return jnp_fn(x)
    return h


def _bin(jnp_fn, np_fn):
    f = _nb(np_fn, jnp_fn)
    return lambda ctx, node, args: f(*args)


def _conv_dims(node):
    df = _attr(node, "data_format", "NHWC")
    strides = _attr(node, "strides", [1, 1, 1, 1])
    dil = _attr(node, "dilations", [1, 1, 1, 1])
    if df == "NCHW":
        sp = (2, 3)
    else:
        sp = (1, 2)
    return df, tuple(strides[i] for i in sp), tuple(dil[i] for i in sp), sp


def _conv_padding(node, sp):
    p = _attr(node, "padding", "VALID")
    if p == "EXPLICIT":
        ep = _attr(node, "explicit_paddings")
        pairs = [(ep[2 * i], ep[2 * i + 1]) for i in range(len(ep) // 2)]
        return [pairs[i] for i in sp]
    return p


def _conv2d(ctx, node, args):
    x, w = args
    df, strides, dil, sp = _conv_dims(node)
    pad = _conv_padding(node, sp)
    return lax.conv_general_dilated(
        x, w, strides, pad, rhs_dilation=dil,
        dimension_numbers=(df, "HWIO", df))


def _depthwise_conv2d(ctx, node, args):
    x, w = args
    df, strides, dil, sp = _conv_dims(node)
    pad = _conv_padding(node, sp)
    h, wd, cin, mult = w.shape
    w = jnp.reshape(w, (h, wd, 1, cin * mult))
    return lax.conv_general_dilated(
        x, w, strides, pad, rhs_dilation=dil,
        dimension_numbers=(df, "HWIO", df), feature_group_count=cin)


def _conv2d_backprop_input(ctx, node, args):
    input_sizes, w, dy = args
    df, strides, dil, sp = _conv_dims(node)
    pad = _attr(node, "padding", "VALID")
    out = lax.conv_transpose(
        dy, w, strides, pad, rhs_dilation=dil,
        dimension_numbers=(df, "HWIO", df), transpose_kernel=True)
    want = tuple(_ints(input_sizes, "Conv2DBackpropInput input_sizes"))
    if tuple(out.shape) != want:  # SAME deconv can overshoot; center-crop
        slices = tuple(slice(0, s) for s in want)
        out = out[slices]
    return out


def _pool_spec(node):
    df = _attr(node, "data_format", "NHWC")
    ks = _attr(node, "ksize")
    st = _attr(node, "strides")
    pad = _attr(node, "padding", "VALID")
    return df, tuple(ks), tuple(st), pad


def _maxpool(ctx, node, args):
    (x,) = args
    df, ks, st, pad = _pool_spec(node)
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else \
        jnp.iinfo(x.dtype).min
    return lax.reduce_window(x, jnp.array(init, x.dtype), lax.max,
                             ks, st, pad)


def _avgpool(ctx, node, args):
    (x,) = args
    df, ks, st, pad = _pool_spec(node)
    summed = lax.reduce_window(x, jnp.zeros((), x.dtype), lax.add, ks, st,
                               pad)
    if pad == "VALID":
        denom = np.prod(ks)
        return summed / jnp.asarray(denom, x.dtype)
    # TF excludes padded elements from the average under SAME
    ones = jnp.ones(x.shape, x.dtype)
    counts = lax.reduce_window(ones, jnp.zeros((), x.dtype), lax.add, ks,
                               st, pad)
    return summed / counts


def _matmul(ctx, node, args):
    a, b = args
    if _attr(node, "transpose_a", False):
        a = jnp.swapaxes(a, -1, -2)
    if _attr(node, "transpose_b", False):
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


def _batch_matmul(ctx, node, args):
    a, b = args
    if _attr(node, "adj_x", False):
        a = jnp.swapaxes(a, -1, -2)
    if _attr(node, "adj_y", False):
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


def _bias_add(ctx, node, args):
    x, b = args
    if _attr(node, "data_format", "NHWC") == "NCHW" and x.ndim > 1:
        shape = (1, -1) + (1,) * (x.ndim - 2)
        return x + jnp.reshape(b, shape)
    return x + b


def _reduction(jnp_fn, np_fn):
    def h(ctx, node, args):
        x, axes = args
        keep = bool(_attr(node, "keep_dims", _attr(node, "keepdims", False)))
        # NB: TF reduce over axis=[] is a no-op, NOT reduce-all — keep the
        # empty tuple (axis=() is the numpy/jnp no-op spelling)
        ax = tuple(_ints(axes, "reduction axes"))
        if _is_static(x):
            return np_fn(np.asarray(x), axis=ax, keepdims=keep)
        return jnp_fn(x, axis=ax, keepdims=keep)
    return h


def _fused_batch_norm(ctx, node, args):
    x, scale, offset, mean, var = args
    eps = _attr(node, "epsilon", 1e-3)
    df = _attr(node, "data_format", "NHWC")
    axis = 1 if df == "NCHW" else x.ndim - 1
    red = tuple(i for i in range(x.ndim) if i != axis)
    is_training = bool(_attr(node, "is_training", True))
    if is_training and (mean is None or np.size(np.asarray(mean)) == 0
                        or ctx.training):
        m = jnp.mean(x, axis=red)
        v = jnp.var(x, axis=red)
    else:
        m, v = mean, var
    bshape = tuple(x.shape[i] if i == axis else 1 for i in range(x.ndim))
    rs = lambda t: jnp.reshape(t, bshape)
    y = (x - rs(m)) * rs(scale) * lax.rsqrt(rs(v) + eps) + rs(offset)
    return (y, m, v, m, v, jnp.zeros((), x.dtype))


def _strided_slice(ctx, node, args):
    x, begin, end, strides = args
    begin = _ints(begin, "StridedSlice begin")
    end = _ints(end, "StridedSlice end")
    strides = _ints(strides, "StridedSlice strides")
    bm = _attr(node, "begin_mask", 0)
    em = _attr(node, "end_mask", 0)
    elm = _attr(node, "ellipsis_mask", 0)
    nam = _attr(node, "new_axis_mask", 0)
    sam = _attr(node, "shrink_axis_mask", 0)
    ndim = x.ndim if not _is_static(x) else np.asarray(x).ndim
    spec_len = len(begin)
    n_spec_dims = sum(1 for i in range(spec_len)
                      if not (nam >> i) & 1 and not (elm >> i) & 1)
    idx: List[Any] = []
    for i in range(spec_len):
        if (elm >> i) & 1:
            idx.extend([slice(None)] * (ndim - n_spec_dims))
        elif (nam >> i) & 1:
            idx.append(np.newaxis)
        elif (sam >> i) & 1:
            idx.append(begin[i])
        else:
            b = None if (bm >> i) & 1 else begin[i]
            e = None if (em >> i) & 1 else end[i]
            s = strides[i]
            idx.append(slice(b, e, s))
    out = (np.asarray(x) if _is_static(x) else x)[tuple(idx)]
    return out


def _tf_slice(ctx, node, args):
    x, begin, size = args
    begin = _ints(begin, "Slice begin")
    size = _ints(size, "Slice size")
    shape = np.asarray(x).shape if _is_static(x) else x.shape
    idx = tuple(slice(b, shape[i] if s == -1 else b + s)
                for i, (b, s) in enumerate(zip(begin, size)))
    return (np.asarray(x) if _is_static(x) else x)[idx]


def _gather(ctx, node, args):
    params, indices = args[0], args[1]
    axis = _ints(args[2], "Gather axis")[0] if len(args) > 2 else 0
    batch_dims = _attr(node, "batch_dims", 0)
    if batch_dims:
        return jnp.take_along_axis(params, indices, axis=axis)
    f = _nb(lambda p, i: np.take(p, i, axis=axis),
            lambda p, i: jnp.take(p, i, axis=axis))
    return f(params, indices)


def _concat(axis_first: bool):
    def h(ctx, node, args):
        if axis_first:
            axis, vals = args[0], args[1:]
        else:
            axis, vals = args[-1], args[:-1]
        ax = _ints(axis, "Concat axis")[0]
        if all(_is_static(v) for v in vals):
            return np.concatenate([np.asarray(v) for v in vals], axis=ax)
        return jnp.concatenate(vals, axis=ax)
    return h


def _split(ctx, node, args):
    axis, value = args
    n = _attr(node, "num_split")
    ax = _ints(axis, "Split axis")[0]
    return tuple(jnp.split(value, n, axis=ax))


def _split_v(ctx, node, args):
    value, sizes, axis = args
    sizes = _ints(sizes, "SplitV sizes")
    ax = _ints(axis, "SplitV axis")[0]
    points = np.cumsum(sizes)[:-1].tolist()
    return tuple(jnp.split(value, points, axis=ax))


def _pack(ctx, node, args):
    ax = _attr(node, "axis", 0)
    if all(_is_static(a) for a in args):
        return np.stack([np.asarray(a) for a in args], axis=ax)
    return jnp.stack(args, axis=ax)


def _unpack(ctx, node, args):
    (x,) = args
    ax = _attr(node, "axis", 0)
    n = _attr(node, "num")
    moved = jnp.moveaxis(x, ax, 0)
    return tuple(moved[i] for i in range(n))


def _softmax_xent(ctx, node, args):
    logits, labels = args
    logp = jax.nn.log_softmax(logits, axis=-1)
    loss = -jnp.sum(labels * logp, axis=-1)
    grad = jax.nn.softmax(logits, axis=-1) - labels
    return (loss, grad)


def _sparse_softmax_xent(ctx, node, args):
    logits, labels = args
    logp = jax.nn.log_softmax(logits, axis=-1)
    loss = -jnp.take_along_axis(
        logp, labels.astype(jnp.int32)[..., None], axis=-1)[..., 0]
    grad = jax.nn.softmax(logits, axis=-1) - jax.nn.one_hot(
        labels, logits.shape[-1], dtype=logits.dtype)
    return (loss, grad)


def _random_uniform(ctx, node, args):
    shape = tuple(_ints(args[0], "RandomUniform shape"))
    dt = _attr(node, "dtype", np.dtype("float32"))
    return jax.random.uniform(ctx.next_rng(), shape, dtype=dt)


def _random_normal(ctx, node, args):
    shape = tuple(_ints(args[0], "RandomStandardNormal shape"))
    dt = _attr(node, "dtype", np.dtype("float32"))
    return jax.random.normal(ctx.next_rng(), shape, dtype=dt)


def _resize(method: str):
    def h(ctx, node, args):
        x, size = args
        h_w = _ints(size, "Resize size")
        shape = (x.shape[0], h_w[0], h_w[1], x.shape[3])
        return jax.image.resize(x, shape, method=method)
    return h


def _cast(ctx, node, args):
    (x,) = args
    dt = _attr(node, "DstT")
    if _is_static(x):
        return np.asarray(x).astype(dt)
    return x.astype(dt)


def _reshape(ctx, node, args):
    x, shape = args
    tgt = _ints(shape, "Reshape shape")
    if _is_static(x):
        return np.reshape(np.asarray(x), tgt)
    return jnp.reshape(x, tgt)


def _one_hot(ctx, node, args):
    indices, depth, on, off = args
    ax = _attr(node, "axis", -1)
    d = _ints(depth, "OneHot depth")[0]
    oh = jax.nn.one_hot(indices, d, axis=ax)
    return oh * on + (1.0 - oh) * off


def _top_k(ctx, node, args):
    x = args[0]
    k = _ints(args[1], "TopKV2 k")[0] if len(args) > 1 else \
        _attr(node, "k")
    vals, idxs = lax.top_k(x, k)
    return (vals, idxs.astype(jnp.int32))


def _select(ctx, node, args):
    c, t, f = args
    if not _is_static(c) or not _is_static(t) or not _is_static(f):
        c, t, f = (jnp.asarray(v) for v in (c, t, f))
        if c.ndim == 1 and t.ndim > 1 and c.shape[0] == t.shape[0]:
            c = c.reshape((-1,) + (1,) * (t.ndim - 1))  # V1 Select rule
        return jnp.where(c, t, f)
    return np.where(c, t, f)


_H: Dict[str, Any] = {
    # plumbing
    "Const": lambda ctx, node, args: _attr(node, "value"),
    "Identity": lambda ctx, node, args: args[0],
    "IdentityN": lambda ctx, node, args: tuple(args),
    "Snapshot": lambda ctx, node, args: args[0],
    "StopGradient": lambda ctx, node, args: lax.stop_gradient(args[0]),
    "PreventGradient": lambda ctx, node, args: lax.stop_gradient(args[0]),
    "CheckNumerics": lambda ctx, node, args: args[0],
    "NoOp": lambda ctx, node, args: None,
    "Cast": _cast,
    # variables
    "VariableV2": lambda ctx, node, args: _param(ctx, node),
    "Variable": lambda ctx, node, args: _param(ctx, node),
    "VarHandleOp": lambda ctx, node, args: node.name,  # handle = its name
    "ReadVariableOp": lambda ctx, node, args: ctx.params[args[0]],
    # shape math
    "Shape": lambda ctx, node, args: np.asarray(
        np.asarray(args[0]).shape if _is_static(args[0])
        else args[0].shape, dtype=np.int32),
    "Rank": lambda ctx, node, args: np.int32(
        (np.asarray(args[0]) if _is_static(args[0]) else args[0]).ndim),
    "Size": lambda ctx, node, args: np.int32(int(np.prod(
        (np.asarray(args[0]) if _is_static(args[0]) else args[0]).shape))),
    "Reshape": _reshape,
    "Squeeze": lambda ctx, node, args: jnp.squeeze(
        args[0], axis=tuple(_attr(node, "squeeze_dims", []) or []) or None),
    "ExpandDims": lambda ctx, node, args: (
        np.expand_dims(np.asarray(args[0]), _ints(args[1], "axis")[0])
        if _is_static(args[0])
        else jnp.expand_dims(args[0], _ints(args[1], "axis")[0])),
    "Transpose": lambda ctx, node, args: jnp.transpose(
        args[0], axes=_ints(args[1], "Transpose perm")),
    "Pad": lambda ctx, node, args: jnp.pad(
        args[0], [tuple(p) for p in np.asarray(
            _static(args[1], "Pad paddings"))]),
    "PadV2": lambda ctx, node, args: jnp.pad(
        args[0], [tuple(p) for p in np.asarray(
            _static(args[1], "Pad paddings"))],
        constant_values=args[2]),
    "MirrorPad": lambda ctx, node, args: jnp.pad(
        args[0], [tuple(p) for p in np.asarray(
            _static(args[1], "Pad paddings"))],
        mode="reflect" if _attr(node, "mode") == "REFLECT"
        else "symmetric"),
    "ConcatV2": _concat(axis_first=False),
    "Concat": _concat(axis_first=True),
    "Split": _split,
    "SplitV": _split_v,
    "Pack": _pack,
    "Unpack": _unpack,
    "Tile": lambda ctx, node, args: jnp.tile(
        args[0], _ints(args[1], "Tile multiples")),
    "Slice": _tf_slice,
    "StridedSlice": _strided_slice,
    "GatherV2": _gather,
    "Gather": _gather,
    "BroadcastTo": lambda ctx, node, args: jnp.broadcast_to(
        args[0], tuple(_ints(args[1], "BroadcastTo shape"))),
    "Fill": lambda ctx, node, args: jnp.full(
        tuple(_ints(args[0], "Fill dims")), args[1]),
    "ZerosLike": lambda ctx, node, args: jnp.zeros_like(args[0]),
    "OnesLike": lambda ctx, node, args: jnp.ones_like(args[0]),
    "Range": lambda ctx, node, args: np.arange(
        *[_static(a, "Range arg").item() for a in args],
        dtype=np.asarray(_static(args[0], "Range")).dtype)
        if all(_is_static(a) for a in args)
        else jnp.arange(args[0], args[1], args[2]),
    "OneHot": _one_hot,
    # math: binary
    "Add": _bin(jnp.add, np.add),
    "AddV2": _bin(jnp.add, np.add),
    "AddN": lambda ctx, node, args: sum(args[1:], args[0]),
    "Sub": _bin(jnp.subtract, np.subtract),
    "Mul": _bin(jnp.multiply, np.multiply),
    "RealDiv": _bin(jnp.divide, np.divide),
    "Div": _bin(jnp.divide, np.divide),
    "DivNoNan": lambda ctx, node, args: jnp.where(
        args[1] == 0, jnp.zeros_like(args[0]), args[0] / args[1]),
    "FloorDiv": _bin(jnp.floor_divide, np.floor_divide),
    "FloorMod": _bin(jnp.mod, np.mod),
    "Pow": _bin(jnp.power, np.power),
    "SquaredDifference": _bin(lambda a, b: jnp.square(a - b),
                              lambda a, b: np.square(a - b)),
    "Maximum": _bin(jnp.maximum, np.maximum),
    "Minimum": _bin(jnp.minimum, np.minimum),
    # math: unary
    "Neg": _ew(jnp.negative, np.negative),
    "Abs": _ew(jnp.abs, np.abs),
    "Square": _ew(jnp.square, np.square),
    "Sqrt": _ew(jnp.sqrt),
    "Rsqrt": _ew(lax.rsqrt),
    "Exp": _ew(jnp.exp),
    "Log": _ew(jnp.log),
    "Log1p": _ew(jnp.log1p),
    "Sign": _ew(jnp.sign, np.sign),
    "Floor": _ew(jnp.floor, np.floor),
    "Ceil": _ew(jnp.ceil, np.ceil),
    "Round": _ew(jnp.round, np.round),
    "Reciprocal": _ew(jnp.reciprocal),
    "Erf": _ew(lax.erf),
    "Sin": _ew(jnp.sin),
    "Cos": _ew(jnp.cos),
    "Tanh": _ew(jnp.tanh),
    "Sigmoid": _ew(jax.nn.sigmoid),
    # NN
    "MatMul": _matmul,
    "BatchMatMul": _batch_matmul,
    "BatchMatMulV2": _batch_matmul,
    "Einsum": lambda ctx, node, args: jnp.einsum(
        _attr(node, "equation"), *args),
    "Conv2D": _conv2d,
    "DepthwiseConv2dNative": _depthwise_conv2d,
    "Conv2DBackpropInput": _conv2d_backprop_input,
    "BiasAdd": _bias_add,
    "MaxPool": _maxpool,
    "AvgPool": _avgpool,
    "Relu": _ew(jax.nn.relu),
    "Relu6": _ew(lambda x: jnp.clip(x, 0, 6)),
    "LeakyRelu": lambda ctx, node, args: jax.nn.leaky_relu(
        args[0], _attr(node, "alpha", 0.2)),
    "Elu": _ew(jax.nn.elu),
    "Selu": _ew(jax.nn.selu),
    "Softplus": _ew(jax.nn.softplus),
    "Softsign": _ew(jax.nn.soft_sign),
    "Softmax": _ew(lambda x: jax.nn.softmax(x, axis=-1)),
    "LogSoftmax": _ew(lambda x: jax.nn.log_softmax(x, axis=-1)),
    "L2Loss": _ew(lambda x: 0.5 * jnp.sum(jnp.square(x))),
    "FusedBatchNorm": _fused_batch_norm,
    "FusedBatchNormV2": _fused_batch_norm,
    "FusedBatchNormV3": _fused_batch_norm,
    "SoftmaxCrossEntropyWithLogits": _softmax_xent,
    "SparseSoftmaxCrossEntropyWithLogits": _sparse_softmax_xent,
    "ResizeBilinear": _resize("bilinear"),
    "ResizeNearestNeighbor": _resize("nearest"),
    # reductions
    "Mean": _reduction(jnp.mean, np.mean),
    "Sum": _reduction(jnp.sum, np.sum),
    "Max": _reduction(jnp.max, np.max),
    "Min": _reduction(jnp.min, np.min),
    "Prod": _reduction(jnp.prod, np.prod),
    "All": _reduction(jnp.all, np.all),
    "Any": _reduction(jnp.any, np.any),
    "ArgMax": lambda ctx, node, args: jnp.argmax(
        args[0], axis=_ints(args[1], "ArgMax axis")[0]).astype(
            _attr(node, "output_type", np.dtype("int64"))),
    "ArgMin": lambda ctx, node, args: jnp.argmin(
        args[0], axis=_ints(args[1], "ArgMin axis")[0]).astype(
            _attr(node, "output_type", np.dtype("int64"))),
    "TopKV2": _top_k,
    # comparison / logic
    "Greater": _bin(jnp.greater, np.greater),
    "GreaterEqual": _bin(jnp.greater_equal, np.greater_equal),
    "Less": _bin(jnp.less, np.less),
    "LessEqual": _bin(jnp.less_equal, np.less_equal),
    "Equal": _bin(jnp.equal, np.equal),
    "NotEqual": _bin(jnp.not_equal, np.not_equal),
    "LogicalAnd": _bin(jnp.logical_and, np.logical_and),
    "LogicalOr": _bin(jnp.logical_or, np.logical_or),
    "LogicalNot": _ew(jnp.logical_not, np.logical_not),
    "Select": _select,
    "SelectV2": lambda ctx, node, args: jnp.where(*args),
    # random
    "RandomUniform": _random_uniform,
    "RandomStandardNormal": _random_normal,
}

_VAR_OPS = {"VariableV2", "Variable", "VarHandleOp"}
_CONTROL_FLOW = {"Switch", "Merge", "Enter", "Exit", "NextIteration",
                 "LoopCond", "While", "StatelessWhile", "If", "StatelessIf"}


class ConvertedGraph:
    """A TF GraphDef compiled to a callable JAX function.

    ``fn = ConvertedGraph(gd, inputs, outputs)`` then
    ``fn(params, *input_arrays, rng=None, training=False) -> [outputs]``.

    ``inputs`` / ``outputs`` are TF tensor names (``"node:0"`` or
    ``"node"``).  ``variable_names`` lists the reachable variable nodes —
    ``params`` must map each name to an array (empty for frozen graphs).
    """

    def __init__(self, graph_def, inputs: Sequence[str],
                 outputs: Sequence[str]):
        self._nodes = {n.name: n for n in graph_def.node}
        self._input_refs = [_norm_tensor_name(n) for n in inputs]
        self._output_refs = [_norm_tensor_name(n) for n in outputs]
        self.input_names = list(inputs)
        self.output_names = list(outputs)
        self._order = self._toposort()
        self.variable_names = [n for n in self._order
                               if self._nodes[n].op in _VAR_OPS]
        for name in self._order:
            op = self._nodes[name].op
            if op in _CONTROL_FLOW:
                raise NotImplementedError(
                    f"TF control-flow op {op} (node {name}) is not "
                    "supported: express loops/conds as lax control flow "
                    "in a jax function instead")
            if op not in _H and op != "Placeholder" and \
                    op != "PlaceholderWithDefault":
                raise NotImplementedError(
                    f"unsupported TF op {op!r} (node {name!r}); supported: "
                    f"{sorted(_H)}")

    def _data_inputs(self, node) -> List[Tuple[str, int]]:
        refs = []
        for raw in node.input:
            r = _parse_ref(raw)
            if r is not None:
                refs.append(r)
        return refs

    def _toposort(self) -> List[str]:
        fed = {name for name, _ in self._input_refs}
        order: List[str] = []
        seen: Dict[str, int] = {}  # 0=visiting, 1=done
        stack = [(name, False) for name, _ in reversed(self._output_refs)]
        while stack:
            name, processed = stack.pop()
            if processed:
                seen[name] = 1
                order.append(name)
                continue
            if seen.get(name) == 1:
                continue
            if seen.get(name) == 0:
                continue
            seen[name] = 0
            stack.append((name, True))
            if name in fed:
                continue
            if name not in self._nodes:
                raise KeyError(f"graph has no node {name!r}")
            for dep, _ in reversed(self._data_inputs(self._nodes[name])):
                if seen.get(dep) != 1:
                    stack.append((dep, False))
        return order

    def __call__(self, params: Dict[str, Any], *input_values,
                 rng=None, training: bool = False):
        if len(input_values) != len(self._input_refs):
            raise ValueError(
                f"expected {len(self._input_refs)} inputs "
                f"({self.input_names}), got {len(input_values)}")
        env: Dict[Tuple[str, int], Any] = dict(
            zip(self._input_refs, input_values))
        fed = {name for name, _ in self._input_refs}
        ctx = _Ctx(params, rng, training)
        for name in self._order:
            if name in fed:
                continue
            node = self._nodes[name]
            if node.op == "Placeholder":
                raise ValueError(
                    f"placeholder {name!r} reachable from outputs but not "
                    f"listed in inputs {self.input_names}")
            args = [env[r] for r in self._data_inputs(node)]
            if node.op == "PlaceholderWithDefault":
                out = args[0]
            else:
                out = _H[node.op](ctx, node, args)
            if isinstance(out, tuple):
                for i, v in enumerate(out):
                    env[(name, i)] = v
            else:
                env[(name, 0)] = out
        return [env[r] for r in self._output_refs]


def convert_graph_def(graph_def, inputs: Sequence[str],
                      outputs: Sequence[str]) -> ConvertedGraph:
    """Convert a (frozen or variable-bearing) GraphDef to a JAX callable."""
    return ConvertedGraph(graph_def, inputs, outputs)
