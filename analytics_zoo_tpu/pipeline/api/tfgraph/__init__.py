"""TF interop: run user-written TensorFlow graphs on TPU via JAX.

Parity surface (SURVEY.md §2.5, the north-star path): the reference ships a
user TF graph to executors and drives it with the BigDL data-parallel
optimizer (``TFDataset`` / ``TFOptimizer`` / ``TFPredictor``,
pyzoo/zoo/pipeline/api/net.py:326-551; ``TFNet``
zoo/.../pipeline/api/net/TFNet.scala:47-754 embeds a TF-Java session as a
trainable module; ``export_tf`` pyzoo/zoo/util/tf.py:29-300 freezes graphs
and generates backward graphs symbolically).

The TPU-native design *replaces the embedded TF runtime entirely*: a frozen
GraphDef is converted, op by op, into a pure JAX function
(:mod:`.converter`), so the user's TF graph compiles into the same XLA SPMD
step function as native models — gradients come from ``jax.grad`` (the
reference's export-time ``tf.gradients`` machinery and its
grads-smuggled-through-forward-outputs protocol, TFTrainingHelper.scala:81-120,
disappear), and data parallelism is sharded-batch ``psum`` over ICI instead
of Spark shuffle AllReduce.
"""

from .converter import ConvertedGraph, convert_graph_def  # noqa: F401
from .dataset import TFDataset  # noqa: F401
from .net import TFNet, export_tf  # noqa: F401
from .optimizer import TFOptimizer, TFPredictor  # noqa: F401
