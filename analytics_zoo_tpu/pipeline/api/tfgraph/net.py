"""TFNet: a frozen TF graph as a framework Layer + export_tf.

Parity surface: reference ``TFNet`` (zoo/.../api/net/TFNet.scala:47-754) is a
BigDL module embedding a TF-Java session — forward marshals tensors through
JNI per call (TFNet.scala:201-281).  Here the graph is converted ONCE to a
JAX function (:mod:`.converter`), so "forward" is an XLA computation fused
with whatever surrounds it, and gradients flow through it natively (the
reference needed an exported backward graph + gradWeights smuggling,
TFNet.scala:301-369).

``export_tf`` mirrors pyzoo/zoo/util/tf.py:29-300: freeze variables to
constants, strip unused nodes, write ``frozen_inference_graph.pb`` +
``graph_meta.json``.  The reference's backward-graph generation
(tf.py:116-187) is intentionally absent — jax.grad supersedes it.
"""

from __future__ import annotations

import json
import os
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ....core.module import Layer, register_layer
from .converter import ConvertedGraph

_FROZEN_PB = "frozen_inference_graph.pb"
_META = "graph_meta.json"


def export_tf(sess, folder: str, inputs: Sequence, outputs: Sequence):
    """Freeze ``sess``'s graph to constants and write pb + meta
    (reference export_tf, pyzoo/zoo/util/tf.py:29-114)."""
    import tensorflow as tf

    input_names = [t.name if hasattr(t, "name") else str(t)
                   for t in inputs]
    output_names = [t.name if hasattr(t, "name") else str(t)
                    for t in outputs]
    graph_def = sess.graph.as_graph_def()
    out_ops = [n.split(":")[0] for n in output_names]
    frozen = tf.compat.v1.graph_util.convert_variables_to_constants(
        sess, graph_def, out_ops)
    frozen = tf.compat.v1.graph_util.extract_sub_graph(frozen, out_ops)
    os.makedirs(folder, exist_ok=True)
    with open(os.path.join(folder, _FROZEN_PB), "wb") as f:
        f.write(frozen.SerializeToString())
    with open(os.path.join(folder, _META), "w") as f:
        json.dump({"input_names": input_names,
                   "output_names": output_names,
                   "temp_tensors": [], "variables": [],
                   "grad_variables": [], "grad_inputs": []}, f)
    return folder


@register_layer
class TFNet(Layer):
    """A TF graph embedded as a layer of this framework.

    Construction mirrors the reference object TFNet (TFNet.scala:549-611):
    from an export folder (pb + graph_meta.json), a raw .pb path with
    explicit input/output names, or live from a session.  When the graph
    still carries variables, they become trainable params of the layer.
    """

    def __init__(self, path: Optional[str] = None,
                 input_names: Optional[Sequence[str]] = None,
                 output_names: Optional[Sequence[str]] = None,
                 graph_def=None,
                 initial_params: Optional[dict] = None,
                 name: Optional[str] = None):
        super().__init__(name=name)
        if graph_def is None:
            graph_def, input_names, output_names = _load_graph(
                path, input_names, output_names)
        self._graph_path = path
        self.fn = ConvertedGraph(graph_def, list(input_names),
                                 list(output_names))
        self._initial_params = dict(initial_params or {})
        missing = [v for v in self.fn.variable_names
                   if v not in self._initial_params]
        if missing:
            raise ValueError(
                f"graph has variables with no values: {missing}; freeze "
                "the graph (export_tf / from_session) or pass "
                "initial_params")

    @classmethod
    def from_session(cls, sess, inputs: Sequence, outputs: Sequence,
                     freeze: bool = True) -> "TFNet":
        """Convert the session's graph; by default variables are frozen
        into constants (reference TFNet.fromSession).  With
        ``freeze=False`` variable values become trainable layer params."""
        import tensorflow as tf

        input_names = [t.name if hasattr(t, "name") else str(t)
                       for t in inputs]
        output_names = [t.name if hasattr(t, "name") else str(t)
                        for t in outputs]
        gd = sess.graph.as_graph_def()
        if freeze:
            out_ops = [n.split(":")[0] for n in output_names]
            gd = tf.compat.v1.graph_util.convert_variables_to_constants(
                sess, gd, out_ops)
            return cls(graph_def=gd, input_names=input_names,
                       output_names=output_names)
        net = cls.__new__(cls)
        Layer.__init__(net)
        net._graph_path = None
        net.fn = ConvertedGraph(gd, input_names, output_names)
        values = {}
        var_ops = {v.op.name: v for v in
                   sess.graph.get_collection(
                       tf.compat.v1.GraphKeys.GLOBAL_VARIABLES)}
        with sess.graph.as_default():
            for vname in net.fn.variable_names:
                if vname not in var_ops:
                    raise ValueError(f"no live variable for node {vname!r}")
                values[vname] = np.asarray(
                    sess.run(var_ops[vname].value()))
        net._initial_params = values
        return net

    # ---- Layer contract ------------------------------------------------
    stochastic = True  # converted graphs may contain dropout

    def init_params(self, rng, input_shape):
        return {k: jnp.asarray(v) for k, v in self._initial_params.items()}

    def call(self, params, state, inputs, training=False, rng=None):
        xs = inputs if isinstance(inputs, (tuple, list)) else (inputs,)
        outs = self.fn(params, *xs, rng=rng, training=training)
        return outs[0] if len(outs) == 1 else tuple(outs)

    def compute_output_shape(self, input_shape):
        shapes = input_shape if isinstance(input_shape[0], (tuple, list)) \
            else [input_shape]
        dummies = [jax.ShapeDtypeStruct((2,) + tuple(s[1:]), jnp.float32)
                   for s in shapes]
        params = {k: jax.ShapeDtypeStruct(np.shape(v), jnp.float32)
                  for k, v in self._initial_params.items()}
        out = jax.eval_shape(
            lambda p, *xs: self.fn(p, *xs,
                                   rng=jax.random.PRNGKey(0)
                                   if self.fn else None),
            params, *dummies)
        outs = [(None,) + tuple(o.shape[1:]) for o in out]
        return outs[0] if len(outs) == 1 else outs

    # ---- convenience inference (reference TFNet predict path) ----------
    def predict(self, x, batch_per_thread: int = 32) -> np.ndarray:
        # cache params + the jitted forward across calls — a fresh jit
        # closure per call would recompile the graph every predict()
        if getattr(self, "_predict_cache", None) is None:
            # frozen graphs may retain dropout/random nodes (the
            # reference's TF runtime just executed them at inference);
            # feed a fixed key
            self._predict_cache = (
                self.init_params(jax.random.PRNGKey(0), None),
                jax.jit(lambda p, *a: self.fn(
                    p, *a, rng=jax.random.PRNGKey(0))))
        params, fwd = self._predict_cache
        xs = x if isinstance(x, (tuple, list)) else (x,)
        outs = []
        n = len(xs[0])
        bs = batch_per_thread
        for i in range(0, n, bs):
            batch = [np.asarray(a[i:i + bs]) for a in xs]
            outs.append([np.asarray(o) for o in fwd(params, *batch)])
        cat = [np.concatenate([o[j] for o in outs])
               for j in range(len(outs[0]))]
        return cat[0] if len(cat) == 1 else cat


def _load_graph(path, input_names, output_names):
    import tensorflow as tf

    if os.path.isdir(path):
        meta_path = os.path.join(path, _META)
        with open(meta_path) as f:
            meta = json.load(f)
        input_names = meta["input_names"]
        output_names = meta["output_names"]
        pb = os.path.join(path, _FROZEN_PB)
    else:
        pb = path
        if input_names is None or output_names is None:
            raise ValueError(
                "loading a bare .pb requires input_names and output_names")
    gd = tf.compat.v1.GraphDef()
    with open(pb, "rb") as f:
        gd.ParseFromString(f.read())
    return gd, input_names, output_names
