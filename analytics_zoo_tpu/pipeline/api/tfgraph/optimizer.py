"""TFOptimizer / TFPredictor: train & serve user TF graphs on TPU.

Parity surface: reference ``TFOptimizer`` (pyzoo/zoo/pipeline/api/net.py:
326-430) exports the user's loss graph *plus a symbolically generated
backward graph*, wraps it in a TFTrainingHelper BigDL layer whose forward
smuggles gradients out through extra outputs, pairs it with
IdentityCriterion, and runs the BigDL DistriOptimizer (2 Spark jobs per
step); afterwards it copies trained weights back into the live tf.Session
(net.py:426-429).  ``TFPredictor`` (net.py:523-551) freezes outputs and maps
the dataset RDD through a TFNet.

TPU translation: the loss graph converts to a JAX scalar function;
``jax.grad`` replaces the exported backward graph; the IdentityCriterion
trick survives as ``loss_fn = λ(y, ŷ): ŷ`` feeding the shared SPMD
``Trainer`` (grad → psum over ICI → optax update, one compiled step);
weights still get pushed back into the user's session at the end.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from ....core.module import Layer
from ....data.dataset import Dataset
from ....train import triggers as trigger_lib
from ....train.trainer import Trainer
from ..keras import optimizers as keras_optimizers
from .converter import ConvertedGraph
from .dataset import TFDataset, find_dataset


def _find_placeholder_names(tensors) -> List[str]:
    """Graph-walk discovery of the placeholders feeding ``tensors``
    (reference _find_placeholders, net.py:271-305)."""
    seen, out, stack = set(), [], [t.op for t in tensors]
    while stack:
        op = stack.pop()
        if op.name in seen:
            continue
        seen.add(op.name)
        if op.type == "Placeholder":
            out.append(op.name)
        stack.extend(i.op for i in op.inputs)
    return sorted(out)


def _reachable_param_values(sess, conv: ConvertedGraph) -> Dict[str, Any]:
    """Read live values for every variable node the converted graph
    touches (V1 and resource variables)."""
    import tensorflow as tf

    var_ops = {}
    for coll in (tf.compat.v1.GraphKeys.GLOBAL_VARIABLES,
                 tf.compat.v1.GraphKeys.LOCAL_VARIABLES):
        for v in sess.graph.get_collection(coll):
            var_ops[v.op.name] = v
    values = {}
    with sess.graph.as_default():
        for name in conv.variable_names:
            if name not in var_ops:
                raise ValueError(
                    f"graph variable {name!r} has no live tf.Variable; "
                    "run the variable initializer first")
            values[name] = np.asarray(sess.run(var_ops[name].value()))
    return values


class _GraphModel(Layer):
    """Adapter: a converted loss graph as a Trainer-compatible model.

    The dataset feeds ALL slots (features AND labels — the loss graph
    consumes labels as placeholders, like the reference where labels ride
    the miniBatch into TFTrainingHelper); output is the scalar loss, and
    the Trainer's loss_fn is identity (IdentityCriterion parity,
    TFTrainingHelper.scala:158-171)."""

    stochastic = True

    def __init__(self, conv: ConvertedGraph, trainable: Dict[str, Any],
                 frozen: Dict[str, Any]):
        super().__init__(name="tf_graph_model")
        self.conv = conv
        self._trainable = trainable
        self._frozen = frozen

    def init_params(self, rng, input_shape):
        return {k: jnp.asarray(v) for k, v in self._trainable.items()}

    def call(self, params, state, inputs, training=False, rng=None):
        xs = inputs if isinstance(inputs, (tuple, list)) else (inputs,)
        full = dict(params)
        full.update({k: jnp.asarray(v) for k, v in self._frozen.items()})
        outs = self.conv(full, *xs, rng=rng, training=training)
        return outs[0] if len(outs) == 1 else tuple(outs)

    def compute_output_shape(self, input_shape):
        return ()


class TFOptimizer:
    """Drive a user-written TF loss graph data-parallel on the TPU mesh."""

    def __init__(self, loss, optim_method="sgd", sess=None,
                 val_outputs: Optional[Sequence] = None,
                 val_labels: Optional[Sequence] = None,
                 val_method=None, clip_norm: Optional[float] = None,
                 clip_value=None, metrics: Sequence = ()):
        import tensorflow as tf

        self.loss = loss
        graph = loss.graph
        self._owns_session = sess is None
        if sess is None:
            sess = tf.compat.v1.Session(graph=graph)
            with graph.as_default():
                sess.run(tf.compat.v1.global_variables_initializer())
        self.sess = sess

        ph_names = _find_placeholder_names([loss])
        self.dataset, _ = find_dataset(graph, ph_names)
        input_names = [ph.name for ph in self.dataset.tensors]
        self._conv = ConvertedGraph(graph.as_graph_def(), input_names,
                                    [loss.name])
        values = _reachable_param_values(sess, self._conv)
        trainable_ops = {v.op.name: v for v in graph.get_collection(
            tf.compat.v1.GraphKeys.TRAINABLE_VARIABLES)}
        self._trainable_vars = {n: v for n, v in trainable_ops.items()
                                if n in values}
        trainable = {n: values[n] for n in self._trainable_vars}
        frozen = {n: v for n, v in values.items()
                  if n not in self._trainable_vars}
        self._model = _GraphModel(self._conv, trainable, frozen)

        optimizer = keras_optimizers.get(optim_method, clip_norm=clip_norm,
                                         clip_value=clip_value)
        self.trainer = Trainer(self._model, loss_fn=lambda y, yp: yp,
                               optimizer=optimizer)

        # validation graph: outputs vs labels through user-chosen metrics
        self._val = None
        if val_outputs is not None and val_labels is not None:
            methods = val_method if isinstance(val_method, (list, tuple)) \
                else [val_method] if val_method is not None else []
            vconv = ConvertedGraph(
                graph.as_graph_def(), input_names,
                [t.name for t in val_outputs] + [t.name for t in val_labels])
            self._val = (vconv, len(val_outputs), list(methods) or
                         list(metrics))

    # -- reference API ---------------------------------------------------
    def set_train_summary(self, summary):
        self.trainer.train_summary = summary

    def set_val_summary(self, summary):
        self.trainer.val_summary = summary

    def set_checkpoint(self, path: str, over_write: bool = True,
                       trigger=None):
        self.trainer.set_checkpoint(path, over_write, trigger)

    def optimize(self, end_trigger=None, shuffle: bool = True,
                 verbose: bool = False):
        """Run to ``end_trigger`` (default: one epoch), then write trained
        weights back into the live tf.Session (reference net.py:419-429)."""
        ds = Dataset(tuple(self.dataset.arrays))
        history = self.trainer.fit(
            ds, self.dataset.batch_size,
            end_trigger=end_trigger or trigger_lib.MaxEpoch(
                self.trainer.state.epoch + 1
                if self.trainer.state else 1),
            shuffle=shuffle, verbose=verbose)
        if self._val is not None:
            history.setdefault("val", []).append(self.evaluate())
        self._push_weights_to_session()
        return history

    def evaluate(self, batch_size: Optional[int] = None) -> Dict[str, float]:
        """Run the validation outputs/labels graph over the validation
        arrays (or training arrays when none were given) and apply the
        metrics (reference TFValidationMethod, TFTrainingHelper.scala:
        173-217)."""
        if self._val is None:
            raise ValueError("no val_outputs/val_labels configured")
        vconv, n_out, methods = self._val
        arrays = self.dataset.val_arrays or self.dataset.arrays
        bs = batch_size or self.dataset.batch_size
        params = {**{k: jnp.asarray(v) for k, v in
                     self._current_trainable().items()},
                  **{k: jnp.asarray(v)
                     for k, v in self._model._frozen.items()}}
        fwd = jax.jit(lambda p, *xs: vconv(p, *xs,
                                           rng=jax.random.PRNGKey(0)))
        accs = [m.init() for m in methods]
        n = len(arrays[0])
        for i in range(0, n - n % bs or n, bs):
            batch = [jnp.asarray(a[i:i + bs]) for a in arrays]
            outs = fwd(params, *batch)
            y_pred = outs[:n_out]
            y_true = outs[n_out:]
            accs = [m.update(a, y_true[0] if len(y_true) == 1 else y_true,
                             y_pred[0] if len(y_pred) == 1 else y_pred)
                    for m, a in zip(methods, accs)]
        return {m.name: float(m.result(a))
                for m, a in zip(methods, accs)}

    # -- weight sync back to TF ------------------------------------------
    def _current_trainable(self) -> Dict[str, np.ndarray]:
        if self.trainer.state is None:
            return self._model._trainable
        return {k: np.asarray(v)
                for k, v in jax.device_get(
                    self.trainer.state.params).items()}

    def _push_weights_to_session(self):
        import tensorflow as tf

        values = self._current_trainable()
        # placeholders + assign ops are built once and reused: per-call
        # construction would grow the user's graph on every optimize()
        # (and fail outright on a finalized graph)
        if getattr(self, "_assign_cache", None) is None:
            with self.sess.graph.as_default():
                cache = {}
                for name, var in self._trainable_vars.items():
                    ph = tf.compat.v1.placeholder(var.dtype.base_dtype,
                                                  var.shape)
                    cache[name] = (ph, var.assign(ph))
                self._assign_cache = cache
        names = list(self._trainable_vars)
        self.sess.run([self._assign_cache[n][1] for n in names],
                      feed_dict={self._assign_cache[n][0]: values[n]
                                 for n in names})


class TFPredictor:
    """Distributed inference over a TFDataset (reference net.py:523-551)."""

    def __init__(self, sess, outputs: Sequence, dataset:
                 Optional[TFDataset] = None):
        ph_names = _find_placeholder_names(list(outputs))
        if dataset is None:
            dataset, _ = find_dataset(sess.graph, ph_names)
        self.dataset = dataset
        input_names = [ph.name for ph in dataset.tensors]
        conv = ConvertedGraph(sess.graph.as_graph_def(), input_names,
                              [t.name for t in outputs])
        self._params = {k: jnp.asarray(v) for k, v in
                        _reachable_param_values(sess, conv).items()}
        self._fwd = jax.jit(lambda p, *xs: conv(p, *xs))

    def predict(self) -> Any:
        arrays = self.dataset.arrays
        bs = self.dataset.batch_size
        n = len(arrays[0])
        outs: List[List[np.ndarray]] = []
        for i in range(0, n, bs):
            batch = [jnp.asarray(a[i:i + bs]) for a in arrays]
            outs.append([np.asarray(o)
                         for o in self._fwd(self._params, *batch)])
        cat = [np.concatenate([o[j] for o in outs])
               for j in range(len(outs[0]))]
        return cat[0] if len(cat) == 1 else cat
