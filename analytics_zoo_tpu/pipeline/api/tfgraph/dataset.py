"""TFDataset: the distributed input-pipeline handle for TF-graph training.

Parity surface: reference ``TFDataset`` (pyzoo/zoo/pipeline/api/net.py:432-509)
wraps an RDD of ndarray lists, creates TF placeholders shaped
``[None] + shape`` (or ``batch_size / total_core_num`` when hard-coded),
registers itself in a TF collection keyed by placeholder name so
``TFOptimizer`` can find it, and enforces ``batch_size % total cores == 0``.

TPU translation: the "RDD" is any host iterable/ndarray; "total cores" is
the data-parallel device count of the mesh (the per-device batch invariant
on a pod is the same invariant, SURVEY §5); registration uses a TF graph
collection exactly like the reference so graph-walking discovery works.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

import numpy as np

from ....data.dataset import check_batch_divisibility
from ....parallel import mesh as mesh_lib

_COLLECTION = "analytics_zoo_tpu_tfdataset"


class TFDataset:
    """Input pipeline feeding a user-written TF graph trained on TPU."""

    def __init__(self, arrays: Sequence[np.ndarray], batch_size: int = -1,
                 batch_per_core: int = -1, has_label: bool = True,
                 val_arrays: Optional[Sequence[np.ndarray]] = None):
        if (batch_size > 0) == (batch_per_core > 0):
            raise ValueError(
                "set exactly one of batch_size (global, training) or "
                "batch_per_core (inference)")
        n_cores = max(mesh_lib.dp_size(mesh_lib.get_default_mesh()), 1)
        if batch_size > 0:
            check_batch_divisibility(batch_size, n_cores)
            self.batch_size = batch_size
        else:
            self.batch_size = batch_per_core * n_cores
        self.has_label = has_label
        self.arrays = [np.asarray(a) for a in arrays]
        self.val_arrays = ([np.asarray(a) for a in val_arrays]
                           if val_arrays is not None else None)
        self._placeholders: Optional[List[Any]] = None

    # -- constructors (reference from_rdd :496 / from_ndarray) ----------
    @classmethod
    def from_ndarray(cls, tensors, batch_size: int = -1,
                     batch_per_core: int = -1, has_label: bool = True,
                     val_tensors=None) -> "TFDataset":
        if isinstance(tensors, np.ndarray):
            tensors = [tensors]
        return cls(list(tensors), batch_size, batch_per_core, has_label,
                   val_arrays=val_tensors)

    @classmethod
    def from_rdd(cls, rdd, names=None, shapes=None, types=None,
                 batch_size: int = -1, batch_per_core: int = -1,
                 has_label: bool = True, val_rdd=None) -> "TFDataset":
        """Reference from_rdd: here an 'rdd' is any iterable of
        ndarray-lists (one element per sample)."""
        samples = [s if isinstance(s, (list, tuple)) else [s]
                   for s in rdd]
        arrays = [np.stack([np.asarray(s[i]) for s in samples])
                  for i in range(len(samples[0]))]
        val_arrays = None
        if val_rdd is not None:
            vs = [s if isinstance(s, (list, tuple)) else [s]
                  for s in val_rdd]
            val_arrays = [np.stack([np.asarray(s[i]) for s in vs])
                          for i in range(len(vs[0]))]
        return cls(arrays, batch_size, batch_per_core, has_label,
                   val_arrays=val_arrays)

    # -- TF-graph side --------------------------------------------------
    @property
    def tensors(self) -> List[Any]:
        """Per-slot ``tf.placeholder`` list, shaped [None]+shape, created
        in the current default graph and registered for discovery
        (reference net.py:449-471)."""
        import tensorflow as tf

        if self._placeholders is None:
            g = tf.compat.v1.get_default_graph()
            phs = []
            for i, a in enumerate(self.arrays):
                ph = tf.compat.v1.placeholder(
                    tf.dtypes.as_dtype(a.dtype), [None] + list(a.shape[1:]),
                    name=f"zoo_tpu_input_{i}")
                g.add_to_collection(_COLLECTION, (ph.op.name, i, self))
                phs.append(ph)
            self._placeholders = phs
        return self._placeholders

    @property
    def feature_tensors(self) -> List[Any]:
        return self.tensors[:-1] if self.has_label else self.tensors

    @property
    def label_tensor(self):
        if not self.has_label:
            raise ValueError("dataset built with has_label=False")
        return self.tensors[-1]

    def get_num_partitions(self) -> int:
        return max(mesh_lib.dp_size(mesh_lib.get_default_mesh()), 1)


def find_dataset(graph, placeholder_names: Sequence[str]) -> Tuple[
        "TFDataset", List[int]]:
    """Locate the registered TFDataset behind discovered placeholders and
    return it plus each placeholder's slot index (reference
    _find_placeholders, net.py:271-305)."""
    registry = {name: (idx, ds)
                for name, idx, ds in graph.get_collection(_COLLECTION)}
    datasets = set()
    slots = []
    for name in placeholder_names:
        if name not in registry:
            raise ValueError(
                f"placeholder {name!r} feeds the loss but was not created "
                "by a TFDataset (use dataset.tensors as model inputs)")
        idx, ds = registry[name]
        slots.append(idx)
        datasets.add(id(ds))
        dataset = ds
    if len(datasets) != 1:
        raise ValueError("loss depends on more than one TFDataset")
    return dataset, slots
