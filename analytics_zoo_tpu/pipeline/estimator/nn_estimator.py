"""nnframes equivalent: ML-pipeline Estimator/Transformer over dataframes.

Parity surface: reference zoo/.../pipeline/nnframes/{NNEstimator.scala
(class :163, internalFit :359, getDataSet :330, params :44-143),
NNClassifier.scala:42-140, NNImageReader.scala:146-179} and the python
mirror pyzoo/zoo/pipeline/nnframes/nn_classifier.py.

The reference rides Spark ML (Estimator/Transformer over DataFrames, fit
drives the BigDL Optimizer).  Here the dataframe is pandas (the per-host
data plane; a Spark adapter is a thin collect-to-host away, per SURVEY §7
stage 8), fit drives the SPMD Trainer, and transform appends a prediction
column.  Param names/setters mirror the reference so pipeline code ports
1:1 (set_batch_size, set_max_epoch, set_learning_rate, set_optim_method,
set_end_when, set_validation, set_checkpoint, set_tensorboard, clipping).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional, Sequence

import numpy as np

from ...data.dataset import Dataset
from ...feature.common import (Preprocessing, SeqToTensor,
                               preprocessing_from_spec,
                               preprocessing_to_spec)
from ...train import triggers as trigger_lib
from ...train.trainer import Trainer
from ..api.keras import metrics as metrics_lib
from ..api.keras import objectives as objectives_lib
from ..api.keras import optimizers as optimizers_lib


class _Params:
    """Shared fluent params (reference NNEstimator.scala:44-143)."""

    def __init__(self):
        self.batch_size = 32
        self.max_epoch = 10
        self.end_when: Optional[trigger_lib.Trigger] = None
        self.learning_rate = 1e-3
        self.learning_rate_decay = 0.0
        self.optim_method: Any = "sgd"
        self.features_col = "features"
        self.label_col = "label"
        self.prediction_col = "prediction"
        self.caching_sample = True
        self.clip_norm: Optional[float] = None
        self.clip_value: Optional[tuple] = None
        self.validation: Optional[tuple] = None
        self.checkpoint: Optional[tuple] = None
        self.tensorboard: Optional[tuple] = None

    # fluent setters, snake_case of the reference's
    def set_batch_size(self, v):
        self.batch_size = int(v)
        return self

    def set_max_epoch(self, v):
        self.max_epoch = int(v)
        return self

    def set_end_when(self, trigger):
        self.end_when = trigger
        return self

    def set_learning_rate(self, v):
        self.learning_rate = float(v)
        return self

    def set_learning_rate_decay(self, v):
        self.learning_rate_decay = float(v)
        return self

    def set_optim_method(self, v):
        self.optim_method = v
        return self

    def set_features_col(self, v):
        self.features_col = v
        return self

    def set_label_col(self, v):
        self.label_col = v
        return self

    def set_prediction_col(self, v):
        self.prediction_col = v
        return self

    def set_caching_sample(self, v):
        self.caching_sample = bool(v)
        return self

    def set_gradient_clipping_by_l2_norm(self, v):
        self.clip_norm = float(v)
        return self

    def set_constant_gradient_clipping(self, lo, hi):
        self.clip_value = (float(lo), float(hi))
        return self

    def set_validation(self, trigger, df, metrics, batch_size):
        """Parity: setValidation(trigger, validationDF, vMethods, batch)."""
        self.validation = (trigger, df, list(metrics), int(batch_size))
        return self

    def set_checkpoint(self, path, trigger=None, over_write=True):
        self.checkpoint = (path, trigger or trigger_lib.EveryEpoch(),
                           over_write)
        return self

    def set_tensorboard(self, log_dir, app_name):
        self.tensorboard = (log_dir, app_name)
        return self


def _column_to_array(df, col) -> np.ndarray:
    vals = df[col].tolist()
    arrs = [np.atleast_1d(np.asarray(v, dtype=np.float32)) for v in vals]
    return np.asarray(arrs)


class NNEstimator(_Params):
    """fit(df) -> NNModel (reference NNEstimator.scala:163,359)."""

    def __init__(self, model, criterion,
                 sample_preprocessing: Optional[Preprocessing] = None,
                 feature_preprocessing: Optional[Preprocessing] = None,
                 label_preprocessing: Optional[Preprocessing] = None):
        super().__init__()
        self.model = model
        self.criterion = criterion
        self.sample_preprocessing = sample_preprocessing
        self.feature_preprocessing = feature_preprocessing
        self.label_preprocessing = label_preprocessing
        self.mesh = None
        self.last_trainer: Optional[Trainer] = None

    # ---- data path (getDataSet parity, NNEstimator.scala:330-357) ----
    def _to_dataset(self, df) -> Dataset:
        feats = _column_to_array(df, self.features_col)
        labels = (_column_to_array(df, self.label_col)
                  if self.label_col in df.columns else None)
        if self.feature_preprocessing is not None:
            feats = np.stack([
                np.asarray(self.feature_preprocessing.apply(f),
                           dtype=np.float32) for f in feats])
        if labels is not None and self.label_preprocessing is not None:
            labels = np.stack([
                np.asarray(self.label_preprocessing.apply(l),
                           dtype=np.float32) for l in labels])
        if self.sample_preprocessing is not None:
            pairs = [self.sample_preprocessing.apply(
                (f, None if labels is None else labels[i]))
                for i, f in enumerate(feats)]
            feats = np.stack([p[0] for p in pairs])
            if labels is not None:
                labels = np.stack([p[1] for p in pairs])
        return Dataset.from_ndarray(feats, labels)

    def _build_trainer(self) -> Trainer:
        spec = self.optim_method
        if isinstance(spec, str):
            spec = {"name": spec, "lr": self.learning_rate,
                    "decay": self.learning_rate_decay}
        opt = optimizers_lib.get(spec, clip_norm=self.clip_norm,
                                 clip_value=self.clip_value)
        loss_fn = objectives_lib.get(self.criterion)
        graph = (self.model.to_graph() if hasattr(self.model, "to_graph")
                 else self.model)
        metric_objs = []
        if self.validation:
            # string-built metrics inherit the criterion's label base
            # (same contract as KerasNet.compile / Trainer.evaluate)
            zero_based = getattr(loss_fn, "zero_based_label", True)
            metric_objs = [
                metrics_lib.get(m, zero_based_label=zero_based)
                for m in self.validation[2]]
        trainer = Trainer(graph, loss_fn, opt, metrics=metric_objs,
                          mesh=self.mesh)
        if self.tensorboard:
            trainer.set_tensorboard(*self.tensorboard)
        if self.checkpoint:
            path, trig, over_write = self.checkpoint
            trainer.set_checkpoint(path, over_write, trigger=trig)
        return trainer

    def fit(self, df) -> "NNModel":
        """internalFit parity (NNEstimator.scala:359-412)."""
        ds = self._to_dataset(df)
        trainer = self._build_trainer()
        end = self.end_when or trigger_lib.MaxEpoch(self.max_epoch)
        val_ds, val_trigger, val_bs = None, None, None
        if self.validation:
            val_trigger, val_df, _, val_bs = self.validation
            val_ds = self._to_dataset(val_df)
        trainer.fit(ds, self.batch_size, end_trigger=end,
                    validation_data=val_ds, validation_trigger=val_trigger,
                    validation_batch_size=val_bs)
        self.last_trainer = trainer
        model = self._model_class()(
            self.model, trainer=trainer,
            feature_preprocessing=self.feature_preprocessing,
            sample_preprocessing=self.sample_preprocessing)
        model.set_features_col(self.features_col)
        model.set_prediction_col(self.prediction_col)
        model.set_batch_size(self.batch_size)
        return model

    def _model_class(self) -> type:
        """Transformer class produced by fit; NNClassifier overrides."""
        return NNModel


class NNModel(_Params):
    """transform(df) appends predictions
    (reference NNModel, NNEstimator.scala:527-587)."""

    def __init__(self, model, trainer: Optional[Trainer] = None,
                 feature_preprocessing: Optional[Preprocessing] = None,
                 sample_preprocessing: Optional[Preprocessing] = None):
        super().__init__()
        self.model = model
        self.feature_preprocessing = feature_preprocessing
        self.sample_preprocessing = sample_preprocessing
        if trainer is None:
            graph = (model.to_graph() if hasattr(model, "to_graph")
                     else model)
            trainer = Trainer(graph, None, optimizers_lib.get("sgd"))
        self.trainer = trainer

    def _features(self, df) -> np.ndarray:
        feats = _column_to_array(df, self.features_col)
        if self.feature_preprocessing is not None:
            feats = np.stack([
                np.asarray(self.feature_preprocessing.apply(f),
                           dtype=np.float32) for f in feats])
        if self.sample_preprocessing is not None:
            feats = np.stack([
                np.asarray(self.sample_preprocessing.apply((f, None))[0],
                           dtype=np.float32) for f in feats])
        return feats

    def transform(self, df):
        feats = self._features(df)
        preds = np.asarray(self.trainer.predict(feats, self.batch_size))
        out = df.copy()
        out[self.prediction_col] = [self._format_prediction(p)
                                    for p in preds]
        return out

    def _format_prediction(self, p):
        return p.tolist()

    # ---- ML persistence (NNEstimator.scala:640-751) ----
    def save(self, path: str, over_write: bool = True):
        import json
        os.makedirs(path, exist_ok=True)
        meta = {
            "class_name": type(self).__name__,
            "model": {"class_name": type(self.model).__name__,
                      "config": self.model.get_config()},
            "feature_preprocessing":
                None if self.feature_preprocessing is None else
                preprocessing_to_spec(self.feature_preprocessing),
            "sample_preprocessing":
                None if self.sample_preprocessing is None else
                preprocessing_to_spec(self.sample_preprocessing),
            "features_col": self.features_col,
            "prediction_col": self.prediction_col,
            "batch_size": self.batch_size,
        }
        mpath = os.path.join(path, "nnmodel.json")
        if os.path.exists(mpath) and not over_write:
            raise FileExistsError(path)
        with open(mpath, "w") as f:
            json.dump(meta, f)
        self.trainer.ensure_initialized()
        # persist inference state only (params + model buffers): the
        # optimizer state is training-run detail and would pin load() to
        # the same optimizer type
        import jax as _jax
        from ...train.checkpoint import save_checkpoint
        st = self.trainer.state
        save_checkpoint(os.path.join(path, "weights"), "final",
                        _jax.device_get({"params": st.params,
                                         "model_state": st.model_state}))

    @classmethod
    def load(cls, path: str) -> "NNModel":
        import json
        from ..api.keras.engine import resolve_model_class
        from ...core.module import get_layer_class
        with open(os.path.join(path, "nnmodel.json")) as f:
            meta = json.load(f)
        mcls_name = meta["model"]["class_name"]
        try:
            mcls = resolve_model_class(mcls_name)
        except KeyError:
            mcls = get_layer_class(mcls_name)
        model = mcls.from_config(meta["model"]["config"])
        klass = NNClassifierModel if meta["class_name"] == \
            "NNClassifierModel" else cls
        obj = klass(
            model,
            feature_preprocessing=None
            if meta["feature_preprocessing"] is None else
            preprocessing_from_spec(meta["feature_preprocessing"]),
            sample_preprocessing=None
            if meta["sample_preprocessing"] is None else
            preprocessing_from_spec(meta["sample_preprocessing"]))
        obj.set_features_col(meta["features_col"])
        obj.set_prediction_col(meta["prediction_col"])
        obj.set_batch_size(meta["batch_size"])
        obj.trainer.ensure_initialized()
        import jax as _jax
        from ...train.checkpoint import restore_checkpoint
        st = obj.trainer.state
        tree = restore_checkpoint(
            os.path.join(path, "weights"),
            {"params": _jax.device_get(st.params),
             "model_state": _jax.device_get(st.model_state)})
        st.params = _jax.device_put(tree["params"])
        st.model_state = _jax.device_put(tree["model_state"])
        return obj


class NNClassifier(NNEstimator):
    """Classification sugar: scalar zero-based labels, argmax transform
    (reference NNClassifier.scala:42)."""

    def _model_class(self) -> type:
        return NNClassifierModel


class NNClassifierModel(NNModel):
    """Argmax over the network output (reference NNClassifier.scala:140)."""

    def _format_prediction(self, p):
        return float(np.argmax(p))


def read_images(path: str, with_label: bool = False,
                resize_h: Optional[int] = None,
                resize_w: Optional[int] = None):
    """NNImageReader parity (reference NNImageReader.scala:146-179): read
    images into a pandas DataFrame with columns image(+label)."""
    import pandas as pd
    from ...feature.image import ImageSet, ImageResize
    iset = ImageSet.read(path, with_label=with_label)
    if resize_h and resize_w:
        iset = iset.transform(ImageResize(resize_h, resize_w))
    rows = {
        "image": [f["image"] for f in iset.features],
        "uri": [f.get("uri") for f in iset.features],
    }
    if with_label:
        rows["label"] = [float(np.asarray(f["label"]).ravel()[0])
                         for f in iset.features]
    return pd.DataFrame(rows)


NNImageReader = read_images
