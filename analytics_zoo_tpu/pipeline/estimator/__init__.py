from .nn_estimator import (NNEstimator, NNModel, NNClassifier,
                           NNClassifierModel, NNImageReader, read_images)
