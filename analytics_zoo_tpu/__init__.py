"""analytics_zoo_tpu: a TPU-native analytics + AI framework.

A ground-up rebuild of the capabilities of Analytics Zoo (reference:
/root/reference, Intel Analytics Zoo ~v0.3.0) designed for TPU hardware:
JAX/XLA compute, pjit/Mesh SPMD parallelism, pallas kernels for hot ops,
and a functional layer/graph core in place of the JVM/BigDL engine.
"""

__version__ = "0.1.0"

from .common.context import (NNContext, ZooTpuConfig, init_nncontext,
                             initNNContext, get_nncontext, reset_nncontext)
from .core.graph import Input, Variable, GraphModule
from .core.module import Layer
from .data.dataset import Dataset
