"""``zoo-tpu-submit`` — the launcher entry point.

Parity surface: the reference ships shell launchers that prepare the
environment and submit the user's program to the cluster
(reference: scripts/spark-submit-with-zoo.sh:15-41, jupyter-with-zoo.sh).
The TPU-native analog prepares the ``jax.distributed`` env contract
(ZOO_TPU_COORDINATOR / NUM_PROCESSES / PROCESS_ID, consumed by
``init_nncontext`` → parallel/distributed.py) and runs the user script.

Three modes:

* single process (default)            — just run the script;
* pod process  (--process-id given)   — export the cluster env for THIS
  process of a multi-host pod, then run the script (invoke once per host,
  e.g. from your pod manifest);
* local fan-out (--num-processes N, no --process-id) — spawn N local
  worker processes forming a real jax.distributed cluster on this
  machine (CPU by default, ``--devices-per-process`` virtual devices
  each) — the reference's ``local[n]`` testing story at process
  granularity.

Local fan-out is a *supervisor*, the coarse-grained recovery loop of
the reference's failure story (wp-bigdl: relaunch the job from the last
complete checkpoint): any worker exiting nonzero — or a worker whose
heartbeat file goes stale past ``--watchdog-sec`` (a hang in a dead
collective), which gets SIGKILLed — tears down the whole pod
immediately (no survivor is ever left blocked in a collective until
timeout) and, within ``--max-restarts``, relaunches it with
``ZOO_RESUME=1`` so a checkpointing ``Trainer.fit`` resumes from the
newest complete snapshot.  Restarts back off exponentially from
``--restart-backoff``.  Every crash/watchdog incident additionally
harvests the workers' flight recorders (``ZOO_FLIGHTREC_DIR``,
exported per worker) into a ``pod_postmortem.json`` + aggregated
``pod_metrics.prom`` in the run directory — preserved even when the
pod recovers — so "why did rank 1 die" survives the reap.  See
``train/faults.py`` for the full worker-side env contract and
``docs/distributed-training.md`` for the semantics.

Examples:
  zoo-tpu-submit train.py --epochs 10
  zoo-tpu-submit --num-processes 2 --devices-per-process 4 train.py
  zoo-tpu-submit --num-processes 2 --max-restarts 3 --watchdog-sec 300 \\
      train.py
  zoo-tpu-submit --coordinator host0:9876 --num-processes 16 \\
      --process-id 3 train.py
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import re
import runpy
import socket
import subprocess
import sys
import tempfile
import time
from typing import List, Optional, Tuple

from . import envcontract
from .observability import flightrec
from .parallel.distributed import ENV_COORD, ENV_NPROC, ENV_PID
from .train import faults
from .train import metrics as train_metrics


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# worker-0 stderr signatures of the coordinator failing to bind the
# probed port (the _free_port TOCTOU race): retried with a fresh port,
# without consuming the crash-restart budget
_BIND_ERR_RE = re.compile(
    r"(?i)address already in use|errno 98|eaddrinuse|failed to bind|"
    r"bind failed|error binding")
_PORT_RETRIES = 3
_STARTUP_WINDOW_S = 60.0
_MAX_BACKOFF_S = 30.0


def _run_script(script: str, script_args: List[str]):
    sys.argv = [script] + list(script_args)
    runpy.run_path(script, run_name="__main__")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="zoo-tpu-submit",
        description="Run a training/inference script on TPU — single "
                    "process, one process of a pod, or a local "
                    "multi-process cluster.")
    parser.add_argument("--coordinator", default=None,
                        help="host:port of process 0 (pod mode)")
    parser.add_argument("--num-processes", type=int, default=1)
    parser.add_argument("--process-id", type=int, default=None,
                        help="this process's rank in the pod; omit with "
                             "--num-processes>1 to fan out locally")
    parser.add_argument("--devices-per-process", type=int, default=4,
                        help="virtual CPU devices per local worker "
                             "(local fan-out mode)")
    parser.add_argument("--platform", default=None,
                        help="force JAX_PLATFORMS (e.g. cpu)")
    parser.add_argument("--max-restarts", type=int, default=0,
                        help="local fan-out: relaunch a crashed/hung pod "
                             "up to this many times with ZOO_RESUME=1 "
                             "(0 = supervise + reap only)")
    parser.add_argument("--restart-backoff", type=float, default=1.0,
                        help="base seconds between relaunches "
                             "(doubles per restart, capped at 30s)")
    parser.add_argument("--watchdog-sec", type=float, default=0.0,
                        help="SIGKILL + relaunch the pod when a worker's "
                             "heartbeat file goes stale this long "
                             "(0 disables; heartbeats come from "
                             "Trainer.fit steps, so size the window "
                             "above your longest compile+step)")
    parser.add_argument("--summary-json", default=None,
                        help="write a supervision summary (restarts, "
                             "reasons, rc) to this path on exit")
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        try:  # an accelerator plugin can pre-empt the env var alone
            import jax
            jax.config.update("jax_platforms", args.platform)
        except Exception as e:
            logging.getLogger("analytics_zoo_tpu").warning(
                "could not force jax platform %r (%s) — an installed "
                "accelerator plugin may override it", args.platform, e)

    if args.num_processes <= 1:
        if args.process_id is not None or args.coordinator:
            parser.error("--process-id/--coordinator require "
                         "--num-processes > 1 (pod mode)")
        _run_script(args.script, args.script_args)
        return 0

    if args.process_id is not None:
        # one process of a real pod: export the env contract and run
        if not args.coordinator:
            parser.error("--coordinator is required with --process-id")
        os.environ[ENV_COORD] = args.coordinator
        os.environ[ENV_NPROC] = str(args.num_processes)
        os.environ[ENV_PID] = str(args.process_id)
        _run_script(args.script, args.script_args)
        return 0

    # local fan-out: a real jax.distributed cluster on this machine,
    # run under the supervisor (crash/hang detection, pod-wide reap,
    # bounded relaunch-with-resume).
    return _run_supervised(args)


def _flight_dir(run_dir: str) -> str:
    """The pod's shared flight-recorder directory: a pre-set
    ``ZOO_FLIGHTREC_DIR`` wins (drills harvest it themselves),
    otherwise it lives with the other supervision artifacts."""
    return (envcontract.env_str(flightrec.ENV_DIR)
            or os.path.join(run_dir, "flightrec"))


def _spawn_pod(args, coordinator: str, run_dir: str, incarnation: int,
               resume: bool) -> Tuple[list, List[str], List[str]]:
    """Launch all worker processes of one pod incarnation.  Worker
    stderr goes to per-worker files (replayed by the supervisor at pod
    end) so bind-race detection can read worker 0's traceback."""
    procs, hb_paths, err_paths = [], [], []
    for pid in range(args.num_processes):
        env = dict(os.environ)
        env[ENV_COORD] = coordinator
        env[ENV_NPROC] = str(args.num_processes)
        env[ENV_PID] = str(pid)
        # every worker records its black box under the shared pod dir;
        # _reap_pod's postmortem harvests it (observability/flightrec)
        env[flightrec.ENV_DIR] = _flight_dir(run_dir)
        env[faults.ENV_RESTART_COUNT] = str(incarnation)
        # local fan-out defaults to CPU workers — an inherited TPU
        # platform (e.g. a tunnel plugin) must not leak into the
        # simulated pod
        env["JAX_PLATFORMS"] = args.platform or "cpu"
        # --devices-per-process owns the worker topology: replace any
        # inherited host-platform device count rather than deferring to it
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       "", env.get("XLA_FLAGS", "")).strip()
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{args.devices_per_process}").strip()
        # supervision contract: a fresh heartbeat file per incarnation
        # (stale mtimes from the previous one must not mask a hang),
        # ZOO_RESUME only on relaunches (train/faults.py)
        hb = os.path.join(run_dir, f"hb_p{pid}.r{incarnation}")
        env[faults.ENV_HEARTBEAT] = hb
        hb_paths.append(hb)
        if resume:
            env[faults.ENV_RESUME] = "1"
        err = os.path.join(run_dir, f"stderr_p{pid}.r{incarnation}.log")
        err_paths.append(err)
        with open(err, "wb") as errf:
            procs.append(subprocess.Popen(
                [sys.executable, args.script] + list(args.script_args),
                env=env, stderr=errf))
    return procs, hb_paths, err_paths


def _supervise(procs: list, hb_paths: List[str], watchdog_sec: float,
               started: float, poll_s: float = 0.2):
    """Monitor one pod incarnation until it resolves.

    Returns ``("ok", None)`` when every worker exited zero,
    ``("exit", rank)`` on the first nonzero exit (partial pod death
    must be reaped immediately — survivors are blocked in collectives),
    or ``("watchdog", rank)`` when a live worker's heartbeat file is
    stale past the window.  Staleness only applies once the worker has
    created its heartbeat file (at jax.distributed join, then per
    training step) — the import/cluster-join phase is covered by worker
    exits, not mtimes."""
    while True:
        alive = False
        for rank, p in enumerate(procs):
            rc = p.poll()
            if rc is None:
                alive = True
            elif rc != 0:
                return "exit", rank
        if not alive:
            return "ok", None
        if watchdog_sec:
            now = time.time()
            for rank, (p, hb) in enumerate(zip(procs, hb_paths)):
                if p.poll() is not None:
                    continue
                try:
                    last = os.path.getmtime(hb)
                except OSError:
                    continue  # no heartbeat yet: still starting up
                if now - max(last, started) > watchdog_sec:
                    return "watchdog", rank
        time.sleep(poll_s)


def _reap_pod(procs: list, grace_s: float = 5.0,
              kill_first: Optional[int] = None) -> None:
    """Tear the whole pod down: SIGKILL the hung worker (if any), then
    terminate + grace-wait + kill the rest.  Runs on EVERY pod exit so
    a partial death never leaves survivors blocked in a collective
    until timeout — --max-restarts 0 included."""
    if kill_first is not None and procs[kill_first].poll() is None:
        procs[kill_first].kill()
    for p in procs:
        if p.poll() is None:
            p.terminate()
    deadline = time.time() + grace_s
    for p in procs:
        if p.poll() is None:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                pass
    for p in procs:
        if p.poll() is None:
            p.kill()
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass


def _replay_stderr(err_paths: List[str]) -> List[str]:
    """Copy each worker's captured stderr to our stderr (tests and
    humans both read the launcher's merged output) and return the text
    per worker for failure classification."""
    texts = []
    for rank, path in enumerate(err_paths):
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            data = b""
        text = data.decode("utf-8", "replace")
        texts.append(text)
        if text.strip():
            sys.stderr.write(f"--- worker {rank} stderr ---\n{text}")
            if not text.endswith("\n"):
                sys.stderr.write("\n")
            sys.stderr.flush()
    return texts


def _run_supervised(args) -> int:
    import shutil
    from .observability.log import get_logger
    slog = get_logger("analytics_zoo_tpu.launcher")
    run_dir = tempfile.mkdtemp(prefix="zoo-pod-")
    coordinator = args.coordinator or f"localhost:{_free_port()}"
    reasons: List[str] = []
    postmortems: List[str] = []
    rc = 1
    try:
        rc = _supervision_loop(args, slog, run_dir, coordinator,
                               reasons, postmortems)
    finally:
        restarts = sum(1 for r in reasons if r in ("exit", "watchdog"))
        port_retries = reasons.count("port")
        if args.summary_json:
            with open(args.summary_json, "w") as f:
                json.dump({"rc": rc, "restarts": restarts,
                           "port_retries": port_retries,
                           "reasons": reasons,
                           "postmortems": postmortems,
                           "metrics": train_metrics.snapshot()}, f)
        if rc == 0 and not postmortems:
            shutil.rmtree(run_dir, ignore_errors=True)
        else:
            # keep heartbeat/stderr/flight-recorder artifacts: even a
            # run that RECOVERED to rc 0 had an incident worth reading
            slog.info("supervision artifacts kept", run_dir=run_dir,
                      rc=rc, postmortems=postmortems)
    return rc


def _write_pod_postmortem(run_dir: str, outcome: str,
                          rank: Optional[int], incarnation: int,
                          procs: list, hb_ages: dict, slog,
                          stale_ranks: Optional[List[int]] = None
                          ) -> Optional[str]:
    """Harvest every worker's flight recorder and land the pod
    post-mortem: per-rank last steps, heartbeat timelines, final spans
    and log tails (flightrec.write_postmortem), merged with the
    supervisor-side evidence only it has — exit codes and
    heartbeat-file ages at reap time.  Also writes the aggregated
    pod-level scrape (``pod_metrics.prom``) beside it.  Best-effort:
    a postmortem failure must never eat the restart itself."""
    supervisor = {
        r: {"rc": p.returncode, "heartbeat_age_s": hb_ages.get(r)}
        for r, p in enumerate(procs)}
    path = os.path.join(run_dir, f"pod_postmortem.i{incarnation}.json")
    latest = os.path.join(run_dir, "pod_postmortem.json")
    try:
        pm = flightrec.write_postmortem(
            _flight_dir(run_dir), path, reason=outcome,
            failed_rank=rank, incarnation=incarnation,
            supervisor=supervisor,
            # a hung collective stalls EVERY participant's heartbeat;
            # the convicted rank is whichever the watchdog found first
            # — the full stale set is the honest evidence
            extra=({"stale_ranks": stale_ranks}
                   if stale_ranks is not None else None))
        flightrec.atomic_write(latest,
                               json.dumps(pm, indent=2, default=str))
    except Exception as e:
        slog.error("could not write pod postmortem", run_dir=run_dir,
                   error=f"{type(e).__name__}: {e}")
        return None
    try:
        from .observability import aggregate as _aggregate
        flightrec.atomic_write(
            os.path.join(run_dir, "pod_metrics.prom"),
            _aggregate.aggregate_dir(_flight_dir(run_dir)))
    except Exception:
        pass  # no snapshots yet is a legal postmortem state
    failed = pm.get("ranks", {}).get(str(rank), {})
    slog.error("pod postmortem written", path=path, reason=outcome,
               failed_rank=rank,
               last_step=failed.get("last_step"),
               heartbeat_age_s=failed.get("heartbeat_age_s"))
    return path


def _supervision_loop(args, slog, run_dir: str, coordinator: str,
                      reasons: List[str],
                      postmortems: Optional[List[str]] = None) -> int:
    restarts = 0
    port_retries = 0
    incarnation = 0
    rc = 1
    while True:
        started = time.time()
        procs, hb_paths, err_paths = _spawn_pod(
            args, coordinator, run_dir, incarnation,
            resume=restarts > 0)
        try:
            outcome, rank = _supervise(procs, hb_paths,
                                       args.watchdog_sec, started)
        except KeyboardInterrupt:
            # grace window first (mid-write checkpoint shards), then kill
            _reap_pod(procs, grace_s=10.0)
            _replay_stderr(err_paths)
            reasons.append("interrupt")
            rc = 130
            break
        if outcome == "ok":
            _replay_stderr(err_paths)
            rc = 0
            break
        # heartbeat-file ages sampled at detection time — reaping takes
        # up to the grace window and must not skew the postmortem.
        # stale_ranks = LIVE workers past the watchdog window (a hung
        # collective stalls every participant; an already-exited
        # worker's aging file is not a hang)
        now = time.time()
        hb_ages = {}
        stale_ranks = []
        for r, hb in enumerate(hb_paths):
            try:
                hb_ages[r] = round(now - os.path.getmtime(hb), 3)
            except OSError:
                hb_ages[r] = None  # worker died before its first beat
            if (outcome == "watchdog" and procs[r].poll() is None
                    and hb_ages[r] is not None
                    and hb_ages[r] > args.watchdog_sec):
                stale_ranks.append(r)
        failed_rc = procs[rank].returncode if outcome == "exit" else None
        _reap_pod(procs, grace_s=5.0,
                  kill_first=rank if outcome == "watchdog" else None)
        texts = _replay_stderr(err_paths)
        incarnation += 1
        # the documented _free_port race: worker 0 died at startup
        # failing to bind the probed coordinator port — retry the pod
        # on a fresh port without consuming the crash-restart budget
        if (outcome == "exit" and rank == 0 and not args.coordinator
                and time.time() - started < _STARTUP_WINDOW_S
                and port_retries < _PORT_RETRIES
                and _BIND_ERR_RE.search(texts[0] if texts else "")):
            port_retries += 1
            reasons.append("port")
            train_metrics.record_restart("port")
            coordinator = f"localhost:{_free_port()}"
            slog.warning("coordinator port collision — relaunching pod "
                         "on a fresh port", retry=port_retries,
                         coordinator=coordinator)
            continue
        # a real incident (crash or hang, not a bind race): harvest the
        # black boxes NOW — the next incarnation reuses the directory
        # namespace and a budget-exhausted exit must still explain itself
        pm = _write_pod_postmortem(
            run_dir, outcome, rank, incarnation - 1, procs, hb_ages,
            slog,
            stale_ranks=stale_ranks if outcome == "watchdog" else None)
        if pm and postmortems is not None:
            postmortems.append(pm)
        if restarts >= args.max_restarts:
            slog.error("pod failed and the restart budget is exhausted",
                       reason=outcome, rank=rank, rc=failed_rc,
                       restarts=restarts,
                       max_restarts=args.max_restarts)
            if failed_rc is None or failed_rc == 0:
                rc = 1
            elif failed_rc > 0:
                rc = failed_rc
            else:  # died on a signal: shell-style 128+N
                rc = 128 - failed_rc
            break
        restarts += 1
        reasons.append(outcome)
        train_metrics.record_restart(outcome)
        backoff = min(args.restart_backoff * (2 ** (restarts - 1)),
                      _MAX_BACKOFF_S)
        slog.warning("pod worker failed — relaunching with ZOO_RESUME",
                     reason=outcome, rank=rank, rc=failed_rc,
                     restart=restarts, max_restarts=args.max_restarts,
                     backoff_s=round(backoff, 3))
        time.sleep(backoff)
    return rc


def shell_main(argv: Optional[List[str]] = None) -> int:
    """``zoo-tpu-shell`` — the interactive-session launcher.

    Parity surface: reference ``scripts/jupyter-with-zoo.sh`` /
    ``pyspark-with-zoo.sh`` — open an interactive environment with the
    framework context already up.  ``zoo-tpu-shell`` starts an IPython
    (or plain) REPL with ``init_nncontext`` done and the common names
    bound; ``zoo-tpu-shell --jupyter`` execs Jupyter with the
    environment prepared the same way.
    """
    parser = argparse.ArgumentParser(
        prog="zoo-tpu-shell",
        description="Interactive REPL/Jupyter with the analytics-zoo-tpu "
                    "context initialized (reference jupyter-with-zoo.sh)")
    parser.add_argument("--jupyter", action="store_true",
                        help="launch jupyter notebook instead of a REPL")
    parser.add_argument("--app-name", default="zoo-tpu-shell")
    parser.add_argument("--platform", default=None,
                        help="force JAX_PLATFORMS (e.g. cpu)")
    parser.add_argument("--cpu-devices", type=int, default=None,
                        help="virtual CPU device count (sets "
                             "--xla_force_host_platform_device_count)")
    parser.add_argument("jupyter_args", nargs=argparse.REMAINDER,
                        help="passed through to jupyter")
    args = parser.parse_args(argv)

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    if args.cpu_devices:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", "")).strip()
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{args.cpu_devices}").strip()
    if args.platform and not args.jupyter:
        # an auto-registering accelerator plugin can pre-empt the env
        # var alone; pin the platform through jax.config too (env/flags
        # above are already set, so importing jax here is safe)
        import jax
        jax.config.update("jax_platforms", args.platform)

    if args.jupyter:
        # exec jupyter in the prepared environment (the reference sets
        # PYSPARK_DRIVER_PYTHON=jupyter; here the env vars above are the
        # whole contract)
        cmd = ["jupyter", "notebook"] + [
            a for a in args.jupyter_args if a != "--"]
        os.execvp(cmd[0], cmd)

    import analytics_zoo_tpu as zoo
    ctx = zoo.init_nncontext(args.app_name)
    import jax
    import jax.numpy as jnp
    import numpy as np
    ns = {"zoo": zoo, "ctx": ctx, "jax": jax, "jnp": jnp, "np": np}
    banner = (f"analytics-zoo-tpu shell — ctx up "
              f"(mesh {dict(zip(ctx.mesh.axis_names, ctx.mesh.devices.shape))})\n"
              "bound: zoo, ctx, jax, jnp, np")
    print(banner)
    try:
        from IPython import start_ipython
        # display_banner is a Bool trait — the banner prints above,
        # IPython's own is suppressed via --no-banner
        return start_ipython(argv=["--no-banner"], user_ns=ns) or 0
    except ImportError:
        import code
        code.interact(banner="", local=ns)
        return 0


if __name__ == "__main__":
    sys.exit(main())
