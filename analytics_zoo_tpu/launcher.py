"""``zoo-tpu-submit`` — the launcher entry point.

Parity surface: the reference ships shell launchers that prepare the
environment and submit the user's program to the cluster
(reference: scripts/spark-submit-with-zoo.sh:15-41, jupyter-with-zoo.sh).
The TPU-native analog prepares the ``jax.distributed`` env contract
(ZOO_TPU_COORDINATOR / NUM_PROCESSES / PROCESS_ID, consumed by
``init_nncontext`` → parallel/distributed.py) and runs the user script.

Three modes:

* single process (default)            — just run the script;
* pod process  (--process-id given)   — export the cluster env for THIS
  process of a multi-host pod, then run the script (invoke once per host,
  e.g. from your pod manifest);
* local fan-out (--num-processes N, no --process-id) — spawn N local
  worker processes forming a real jax.distributed cluster on this
  machine (CPU by default, ``--devices-per-process`` virtual devices
  each) — the reference's ``local[n]`` testing story at process
  granularity.

Examples:
  zoo-tpu-submit train.py --epochs 10
  zoo-tpu-submit --num-processes 2 --devices-per-process 4 train.py
  zoo-tpu-submit --coordinator host0:9876 --num-processes 16 \\
      --process-id 3 train.py
"""

from __future__ import annotations

import argparse
import logging
import os
import re
import runpy
import socket
import subprocess
import sys
from typing import List, Optional

from .parallel.distributed import ENV_COORD, ENV_NPROC, ENV_PID


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _run_script(script: str, script_args: List[str]):
    sys.argv = [script] + list(script_args)
    runpy.run_path(script, run_name="__main__")


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="zoo-tpu-submit",
        description="Run a training/inference script on TPU — single "
                    "process, one process of a pod, or a local "
                    "multi-process cluster.")
    parser.add_argument("--coordinator", default=None,
                        help="host:port of process 0 (pod mode)")
    parser.add_argument("--num-processes", type=int, default=1)
    parser.add_argument("--process-id", type=int, default=None,
                        help="this process's rank in the pod; omit with "
                             "--num-processes>1 to fan out locally")
    parser.add_argument("--devices-per-process", type=int, default=4,
                        help="virtual CPU devices per local worker "
                             "(local fan-out mode)")
    parser.add_argument("--platform", default=None,
                        help="force JAX_PLATFORMS (e.g. cpu)")
    parser.add_argument("script")
    parser.add_argument("script_args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        try:  # an accelerator plugin can pre-empt the env var alone
            import jax
            jax.config.update("jax_platforms", args.platform)
        except Exception as e:
            logging.getLogger("analytics_zoo_tpu").warning(
                "could not force jax platform %r (%s) — an installed "
                "accelerator plugin may override it", args.platform, e)

    if args.num_processes <= 1:
        if args.process_id is not None or args.coordinator:
            parser.error("--process-id/--coordinator require "
                         "--num-processes > 1 (pod mode)")
        _run_script(args.script, args.script_args)
        return 0

    if args.process_id is not None:
        # one process of a real pod: export the env contract and run
        if not args.coordinator:
            parser.error("--coordinator is required with --process-id")
        os.environ[ENV_COORD] = args.coordinator
        os.environ[ENV_NPROC] = str(args.num_processes)
        os.environ[ENV_PID] = str(args.process_id)
        _run_script(args.script, args.script_args)
        return 0

    # local fan-out: a real jax.distributed cluster on this machine.
    # The probed port can in principle be taken before worker 0 rebinds
    # it (collision surfaces as a startup error) — pass --coordinator
    # explicitly to pin a reserved port.
    coordinator = args.coordinator or f"localhost:{_free_port()}"
    procs = []
    for pid in range(args.num_processes):
        env = dict(os.environ)
        env[ENV_COORD] = coordinator
        env[ENV_NPROC] = str(args.num_processes)
        env[ENV_PID] = str(pid)
        # local fan-out defaults to CPU workers — an inherited TPU
        # platform (e.g. a tunnel plugin) must not leak into the
        # simulated pod
        env["JAX_PLATFORMS"] = args.platform or "cpu"
        # --devices-per-process owns the worker topology: replace any
        # inherited host-platform device count rather than deferring to it
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+",
                       "", env.get("XLA_FLAGS", "")).strip()
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{args.devices_per_process}").strip()
        procs.append(subprocess.Popen(
            [sys.executable, args.script] + list(args.script_args),
            env=env))
    rc = 0
    try:
        for p in procs:
            rc = p.wait() or rc
    except KeyboardInterrupt:
        for p in procs:
            if p.poll() is None:
                p.terminate()
        # give workers a grace window (mid-write checkpoint shards)
        # before the finally block hard-kills survivors
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass
        rc = 130
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return rc


def shell_main(argv: Optional[List[str]] = None) -> int:
    """``zoo-tpu-shell`` — the interactive-session launcher.

    Parity surface: reference ``scripts/jupyter-with-zoo.sh`` /
    ``pyspark-with-zoo.sh`` — open an interactive environment with the
    framework context already up.  ``zoo-tpu-shell`` starts an IPython
    (or plain) REPL with ``init_nncontext`` done and the common names
    bound; ``zoo-tpu-shell --jupyter`` execs Jupyter with the
    environment prepared the same way.
    """
    parser = argparse.ArgumentParser(
        prog="zoo-tpu-shell",
        description="Interactive REPL/Jupyter with the analytics-zoo-tpu "
                    "context initialized (reference jupyter-with-zoo.sh)")
    parser.add_argument("--jupyter", action="store_true",
                        help="launch jupyter notebook instead of a REPL")
    parser.add_argument("--app-name", default="zoo-tpu-shell")
    parser.add_argument("--platform", default=None,
                        help="force JAX_PLATFORMS (e.g. cpu)")
    parser.add_argument("--cpu-devices", type=int, default=None,
                        help="virtual CPU device count (sets "
                             "--xla_force_host_platform_device_count)")
    parser.add_argument("jupyter_args", nargs=argparse.REMAINDER,
                        help="passed through to jupyter")
    args = parser.parse_args(argv)

    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
    if args.cpu_devices:
        flags = re.sub(r"--xla_force_host_platform_device_count=\d+", "",
                       os.environ.get("XLA_FLAGS", "")).strip()
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{args.cpu_devices}").strip()
    if args.platform and not args.jupyter:
        # an auto-registering accelerator plugin can pre-empt the env
        # var alone; pin the platform through jax.config too (env/flags
        # above are already set, so importing jax here is safe)
        import jax
        jax.config.update("jax_platforms", args.platform)

    if args.jupyter:
        # exec jupyter in the prepared environment (the reference sets
        # PYSPARK_DRIVER_PYTHON=jupyter; here the env vars above are the
        # whole contract)
        cmd = ["jupyter", "notebook"] + [
            a for a in args.jupyter_args if a != "--"]
        os.execvp(cmd[0], cmd)

    import analytics_zoo_tpu as zoo
    ctx = zoo.init_nncontext(args.app_name)
    import jax
    import jax.numpy as jnp
    import numpy as np
    ns = {"zoo": zoo, "ctx": ctx, "jax": jax, "jnp": jnp, "np": np}
    banner = (f"analytics-zoo-tpu shell — ctx up "
              f"(mesh {dict(zip(ctx.mesh.axis_names, ctx.mesh.devices.shape))})\n"
              "bound: zoo, ctx, jax, jnp, np")
    print(banner)
    try:
        from IPython import start_ipython
        # display_banner is a Bool trait — the banner prints above,
        # IPython's own is suppressed via --no-banner
        return start_ipython(argv=["--no-banner"], user_ns=ns) or 0
    except ImportError:
        import code
        code.interact(banner="", local=ns)
        return 0


if __name__ == "__main__":
    sys.exit(main())
