"""Primitive symbolic ops over ``Variable`` graphs.

Parity surface: the ``AutoGrad`` op set of the reference (abs, sum, clip,
square, sqrt, maximum, mean, log, epsilon, exp, pow, softsign, softplus,
stack, expandDims, contiguous, mm, l2Normalize, batchDot — reference:
zoo/.../pipeline/api/autograd/math.scala:32-339) plus the Variable operator
overloads (math.scala:404-530).

Each op is a parameterless ``OpLayer`` node; the underlying computation is a
registered jnp function, so an expression graph lowers to straight-line jnp
code that XLA fuses.  Axis convention: axes index the FULL array including the
batch dimension (jnp semantics) — the reference's implicit-batch convention
does not survive contact with jit, and full-array axes are what users see in
every JAX program.
"""

from __future__ import annotations

import builtins
from typing import Any, Callable, Dict

import numpy as np
import jax.numpy as jnp

from ..core.graph import Variable, broadcast_shapes
from ..core.module import Layer, register_layer
from ..core import shapes as shape_utils

_OPS: Dict[str, Callable] = {}
_SHAPE_FNS: Dict[str, Callable] = {}


def def_op(name: str, fn: Callable, shape_fn: Callable = None):
    _OPS[name] = fn
    _SHAPE_FNS[name] = shape_fn or (lambda shapes, **kw: shapes[0])


@register_layer
class OpLayer(Layer):
    """Parameterless node applying a registered op to its inputs."""

    def __init__(self, op=None, op_kwargs=None, name=None, input_shape=None):
        super().__init__(name=name or None, input_shape=input_shape)
        self.op = op
        self.op_kwargs = dict(op_kwargs or {})

    def call(self, params, state, inputs, training=False, rng=None):
        xs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
        return _OPS[self.op](list(xs), **self.op_kwargs)

    def compute_output_shape(self, input_shape):
        shapes = (input_shape if isinstance(input_shape[0], (tuple, list))
                  else [input_shape])
        return _SHAPE_FNS[self.op]([tuple(s) for s in shapes],
                                   **self.op_kwargs)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(op=self.op, op_kwargs=self.op_kwargs)
        return cfg


@register_layer
class ConstantLayer(Layer):
    """Zero-input node producing a fixed array (graph-captured constant)."""

    is_source = True

    def __init__(self, value=None, name=None, input_shape=None):
        super().__init__(name=name, input_shape=input_shape)
        self.value = jnp.asarray(value)

    def call(self, params, state, inputs, training=False, rng=None):
        return self.value

    def compute_output_shape(self, input_shape):
        return tuple(self.value.shape)

    def get_config(self):
        cfg = super().get_config()
        cfg["value"] = np.asarray(self.value).tolist()
        return cfg


def constant(value, name=None) -> Variable:
    layer = ConstantLayer(value=value, name=name)
    return Variable(layer, (), tuple(jnp.shape(jnp.asarray(value))),
                    name=layer.name)


def _as_variable(x):
    if isinstance(x, Variable):
        return x
    return constant(x)


def _apply(op: str, variables, **op_kwargs):
    # polymorphic like the reference's AutoGrad object: on Variables the
    # op becomes a graph node; on plain arrays it evaluates eagerly, so
    # autograd-style expressions also work inside CustomLoss/Lambda
    # functions that receive jnp arrays
    if not builtins.any(isinstance(v, Variable) for v in variables):
        return _OPS[op]([jnp.asarray(v) for v in variables], **op_kwargs)
    vs = [_as_variable(v) for v in variables]
    layer = OpLayer(op=op, op_kwargs=op_kwargs)
    return Variable.from_layer(layer, vs if len(vs) > 1 else vs[0])


# ---------------- shape helpers ----------------

def _broadcast_shape_fn(shapes, **kw):
    out = shapes[0]
    for s in shapes[1:]:
        out = broadcast_shapes(out, s)
    return out


def _reduce_shape_fn(shapes, axis=None, keepdims=False, **kw):
    s = list(shapes[0])
    if axis is None:
        return () if not keepdims else tuple(1 for _ in s)
    axes = [axis] if isinstance(axis, int) else list(axis)
    axes = [a % len(s) for a in axes]
    if keepdims:
        for a in axes:
            s[a] = 1
        return tuple(s)
    return tuple(d for i, d in enumerate(s) if i not in axes)


# ---------------- binary elementwise ----------------

def_op("add", lambda xs: xs[0] + xs[1], _broadcast_shape_fn)
def_op("sub", lambda xs: xs[0] - xs[1], _broadcast_shape_fn)
def_op("mul", lambda xs: xs[0] * xs[1], _broadcast_shape_fn)
def_op("div", lambda xs: xs[0] / xs[1], _broadcast_shape_fn)
def_op("maximum", lambda xs: jnp.maximum(xs[0], xs[1]), _broadcast_shape_fn)
def_op("minimum", lambda xs: jnp.minimum(xs[0], xs[1]), _broadcast_shape_fn)


def add(x, y):
    return _apply("add", [x, y])


def sub(x, y):
    return _apply("sub", [x, y])


def mul(x, y):
    return _apply("mul", [x, y])


def div(x, y):
    return _apply("div", [x, y])


def maximum(x, y):
    return _apply("maximum", [x, y])


def minimum(x, y):
    return _apply("minimum", [x, y])


# ---------------- unary ----------------

def_op("neg", lambda xs: -xs[0])
def_op("abs", lambda xs: jnp.abs(xs[0]))
def_op("square", lambda xs: jnp.square(xs[0]))
def_op("sqrt", lambda xs: jnp.sqrt(xs[0]))
def_op("log", lambda xs: jnp.log(xs[0]))
def_op("exp", lambda xs: jnp.exp(xs[0]))
def_op("pow", lambda xs, p=2.0: jnp.power(xs[0], p))
def_op("softsign", lambda xs: xs[0] / (1.0 + jnp.abs(xs[0])))
def_op("softplus", lambda xs: jnp.logaddexp(xs[0], 0.0))
def_op("clip", lambda xs, min=None, max=None: jnp.clip(xs[0], min, max))
def_op("contiguous", lambda xs: xs[0])
def_op("relu", lambda xs: jnp.maximum(xs[0], 0.0))
def_op("sigmoid", lambda xs: 1.0 / (1.0 + jnp.exp(-xs[0])))
def_op("tanh", lambda xs: jnp.tanh(xs[0]))


def neg(x):
    return _apply("neg", [x])


def abs(x):  # noqa: A001 - parity with reference AutoGrad.abs
    return _apply("abs", [x])


def square(x):
    return _apply("square", [x])


def sqrt(x):
    return _apply("sqrt", [x])


def log(x):
    return _apply("log", [x])


def exp(x):
    return _apply("exp", [x])


def pow(x, p):  # noqa: A001
    return _apply("pow", [x], p=float(p))


def softsign(x):
    return _apply("softsign", [x])


def softplus(x):
    return _apply("softplus", [x])


def clip(x, min=None, max=None):  # noqa: A002
    return _apply("clip", [x], min=min, max=max)


def contiguous(x):
    return _apply("contiguous", [x])


def relu(x):
    return _apply("relu", [x])


def sigmoid(x):
    return _apply("sigmoid", [x])


def tanh(x):
    return _apply("tanh", [x])


def epsilon():
    """Fuzz factor, parity with AutoGrad.epsilon (math.scala:116)."""
    return 1e-7


# ---------------- reductions ----------------

def_op("sum", lambda xs, axis=None, keepdims=False:
       jnp.sum(xs[0], axis=axis, keepdims=keepdims), _reduce_shape_fn)
def_op("mean", lambda xs, axis=None, keepdims=False:
       jnp.mean(xs[0], axis=axis, keepdims=keepdims), _reduce_shape_fn)
def_op("max", lambda xs, axis=None, keepdims=False:
       jnp.max(xs[0], axis=axis, keepdims=keepdims), _reduce_shape_fn)
def_op("min", lambda xs, axis=None, keepdims=False:
       jnp.min(xs[0], axis=axis, keepdims=keepdims), _reduce_shape_fn)


def sum(x, axis=None, keepdims=False):  # noqa: A001
    return _apply("sum", [x], axis=axis, keepdims=keepdims)


def mean(x, axis=None, keepdims=False):
    return _apply("mean", [x], axis=axis, keepdims=keepdims)


def max(x, axis=None, keepdims=False):  # noqa: A001
    return _apply("max", [x], axis=axis, keepdims=keepdims)


def min(x, axis=None, keepdims=False):  # noqa: A001
    return _apply("min", [x], axis=axis, keepdims=keepdims)


# ---------------- shape manipulation ----------------

def _expand_dims_shape(shapes, axis=0, **kw):
    s = list(shapes[0])
    a = axis if axis >= 0 else len(s) + 1 + axis
    s.insert(a, 1)
    return tuple(s)


def _squeeze_shape(shapes, axis=None, **kw):
    s = list(shapes[0])
    a = axis % len(s)
    if s[a] not in (1, None):
        raise ValueError(f"Cannot squeeze axis {axis} of shape {shapes[0]}")
    return tuple(d for i, d in enumerate(s) if i != a)


def_op("expand_dims", lambda xs, axis=0: jnp.expand_dims(xs[0], axis),
       _expand_dims_shape)
def_op("squeeze", lambda xs, axis=None: jnp.squeeze(xs[0], axis),
       _squeeze_shape)


def expand_dims(x, axis=0):
    return _apply("expand_dims", [x], axis=axis)


def squeeze(x, axis):
    return _apply("squeeze", [x], axis=axis)


def _stack_shape(shapes, axis=0, **kw):
    s = list(shapes[0])
    a = axis if axis >= 0 else len(s) + 1 + axis
    s.insert(a, len(shapes))
    return tuple(s)


def_op("stack", lambda xs, axis=0: jnp.stack(xs, axis=axis), _stack_shape)


def stack(variables, axis=0):
    return _apply("stack", list(variables), axis=axis)


def _concat_shape(shapes, axis=-1, **kw):
    s = list(shapes[0])
    a = axis % len(s)
    total = 0
    for sh in shapes:
        if sh[a] is None:
            total = None
            break
        total += sh[a]
    s[a] = total
    return tuple(s)


def_op("concat", lambda xs, axis=-1: jnp.concatenate(xs, axis=axis),
       _concat_shape)


def concat(variables, axis=-1):
    return _apply("concat", list(variables), axis=axis)


def _slice_shape(shapes, dim=0, start=0, length=1, **kw):
    s = list(shapes[0])
    s[dim % len(s)] = length
    return tuple(s)


def_op("slice", lambda xs, dim=0, start=0, length=1:
       jnp.take(xs[0], jnp.arange(start, start + length), axis=dim),
       _slice_shape)


def slice(x, dim, start_index, length):  # noqa: A001
    return _apply("slice", [x], dim=dim, start=start_index, length=length)


def _index_select_shape(shapes, dim=0, index=0, **kw):
    s = list(shapes[0])
    del s[dim % len(s)]
    return tuple(s)


def_op("index_select", lambda xs, dim=0, index=0:
       jnp.take(xs[0], index, axis=dim), _index_select_shape)


def index_select(x, dim, index):
    return _apply("index_select", [x], dim=dim, index=index)


def _getitem_shape(shapes, item=None, **kw):
    probe = np.zeros([d if d is not None else 2 for d in shapes[0]])
    out = probe[_decode_item(item)].shape
    # restore None batch if the batch axis survived a full slice
    if (shapes[0] and shapes[0][0] is None and isinstance(item, (list, tuple))
            and item and item[0] == ["slice", None, None, None]):
        out = (None,) + tuple(out[1:])
    return tuple(out)


def _encode_item(item):
    items = item if isinstance(item, tuple) else (item,)
    enc = []
    for it in items:
        if isinstance(it, builtins.slice):
            enc.append(["slice", it.start, it.stop, it.step])
        else:
            enc.append(int(it))
    return enc


def _decode_item(enc):
    out = []
    for it in enc:
        if isinstance(it, (list, tuple)) and it and it[0] == "slice":
            out.append(builtins.slice(it[1], it[2], it[3]))
        else:
            out.append(it)
    return tuple(out)


def_op("getitem", lambda xs, item=None: xs[0][_decode_item(item)],
       _getitem_shape)


def getitem(x, item):
    return _apply("getitem", [x], item=_encode_item(item))


# ---------------- linear algebra ----------------

def _mm_shape(shapes, axes=None, **kw):
    a, b = shapes
    return tuple(a[:-1]) + (b[-1],)


def_op("mm", lambda xs, axes=None: jnp.matmul(xs[0], xs[1]), _mm_shape)


def mm(x, y, axes=None):
    """Matrix multiply (reference AutoGrad.mm, math.scala:230)."""
    return _apply("mm", [x, y])


def _batch_dot_shape(shapes, axes=None, **kw):
    a, b = shapes
    return tuple(a[:-1]) + (b[-1],)


def_op("batch_dot",
       lambda xs, axes=None: jnp.einsum("b...ik,b...kj->b...ij", xs[0], xs[1]),
       _batch_dot_shape)


def batch_dot(x, y, axes=None):
    return _apply("batch_dot", [x, y])


def_op("l2_normalize", lambda xs, axis=-1:
       xs[0] / jnp.sqrt(jnp.maximum(
           jnp.sum(jnp.square(xs[0]), axis=axis, keepdims=True), 1e-12)))


def l2_normalize(x, axis=-1):
    return _apply("l2_normalize", [x], axis=axis)
