"""Attention ops: naive, blockwise (online-softmax), and a pallas TPU
flash-attention kernel, plus a MultiHeadAttention layer.

The reference has NO attention anywhere (SURVEY §5: "attention does not
exist in the layer set") — this is the TPU-era extension the task brief
makes first-class (long-context support).  Three implementations share one
semantics:

* ``naive_attention`` — O(S²) materialized scores; the test oracle.
* ``blockwise_attention`` — lax.scan over key blocks with online softmax
  (running max/denominator), O(S) memory; works on any backend and is the
  building block ring attention reuses per-shard.
* ``flash_attention`` — pallas TPU kernel: grid over (batch·heads,
  q-blocks), VMEM-resident q/k/v blocks, online softmax in f32 accumulators
  feeding the MXU per block pair.

All take (batch, seq, heads, head_dim) and return the same shape.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30


def _clamp_lengths(kv_lengths, sk):
    """Normalize per-batch valid key lengths to f32 in [1, sk].

    The floor of 1 keeps fully-masked rows out of every implementation
    (softmax over an all-masked row is 0/0; the flash backward's
    exp(s − lse) replay would cancel the NEG_INF sentinel into phantom
    probabilities) — an "empty" sequence attends to position 0 and its
    output must be masked downstream, which padded batches do anyway."""
    lens = jnp.asarray(kv_lengths)
    if lens.ndim != 1:
        raise ValueError(
            f"kv_lengths must be (batch,), got shape {lens.shape}")
    return jnp.clip(lens.astype(jnp.float32), 1, sk)


def naive_attention(q, k, v, causal: bool = False, scale: float = None,
                    kv_lengths=None):
    """Materialized-scores attention (oracle).

    ``kv_lengths``: optional (batch,) valid key counts — keys at
    positions >= kv_lengths[b] are masked out (right-padded variable-
    length batches; the reference pads text to a fixed sequenceLength,
    TextClassifier.scala:34).  Padded QUERY rows still produce (garbage)
    outputs — mask them downstream, as sequence losses do."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if causal:
        mask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        scores = jnp.where(mask, scores, NEG_INF)
    if kv_lengths is not None:
        lens = _clamp_lengths(kv_lengths, sk)
        kmask = (jnp.arange(sk)[None, :] < lens[:, None])  # (b, sk)
        scores = jnp.where(kmask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def blockwise_attention(q, k, v, causal: bool = False,
                        block_k: int = 512, scale: float = None,
                        kv_lengths=None):
    """Online-softmax attention scanning key blocks: O(seq) memory.

    ``kv_lengths``: optional (batch,) valid key counts (see
    ``naive_attention``)."""
    b, sq, h, d = q.shape
    sk = k.shape[1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    block_k = min(block_k, sk)
    if sk % block_k != 0:
        raise ValueError(
            f"block_k ({block_k}) must divide the key length ({sk})")
    lens = (None if kv_lengths is None
            else _clamp_lengths(kv_lengths, sk))
    n_blocks = sk // block_k
    kb = k.reshape(b, n_blocks, block_k, h, d)
    vb = v.reshape(b, n_blocks, block_k, h, d)
    q_scaled = q * scale
    q_pos = jnp.arange(sq)

    def body(carry, blk):
        m_prev, l_prev, o_prev = carry
        k_blk, v_blk, blk_idx = blk
        scores = jnp.einsum("bqhd,bkhd->bhqk", q_scaled, k_blk)
        k_pos = blk_idx * block_k + jnp.arange(block_k)
        if causal:
            mask = q_pos[:, None] + (sk - sq) >= k_pos[None, :]
            scores = jnp.where(mask[None, None], scores, NEG_INF)
        if lens is not None:
            kmask = k_pos[None, :] < lens[:, None]  # (b, block_k)
            scores = jnp.where(kmask[:, None, None, :], scores, NEG_INF)
        m_blk = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(scores - m_new[..., None])
        correction = jnp.exp(m_prev - m_new)
        l_new = l_prev * correction + jnp.sum(p, axis=-1)
        o_new = (o_prev * correction[..., None]
                 + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk))
        return (m_new, l_new, o_new), None

    m0 = jnp.full((b, h, sq), NEG_INF)
    l0 = jnp.zeros((b, h, sq))
    o0 = jnp.zeros((b, h, sq, d))
    (m, l, o), _ = lax.scan(
        body, (m0, l0, o0),
        (jnp.swapaxes(kb, 0, 1), jnp.swapaxes(vb, 0, 1),
         jnp.arange(n_blocks)))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.swapaxes(out, 1, 2)  # (b, h, q, d) -> (b, q, h, d)


# ------------------------------------------------------------ pallas kernel

def _score_mask(scores, causal, lens_val, qi, j, block_q, block_k, sq, sk):
    """Compose the causal and key-padding masks onto one score block.
    ``lens_val`` is this (batch·head)'s valid key count (f32 scalar) or
    None when the call has no padding mask."""
    valid = None
    if causal or lens_val is not None:
        k_pos = j * block_k + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
    if causal:
        q_pos = qi * block_q + lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0) + (sk - sq)
        valid = q_pos >= k_pos
    if lens_val is not None:
        kmask = k_pos.astype(jnp.float32) < lens_val
        valid = kmask if valid is None else valid & kmask
    if valid is None:
        return scores
    return jnp.where(valid, scores, NEG_INF)


def _flash_fwd_kernel(q_ref, k_ref, v_ref, *rest, block_k: int,
                      sk: int, causal: bool, sq: int, scale: float,
                      block_q: int, masked: bool):
    """One (batch·head, q-block) cell: iterate key blocks in VMEM with
    online softmax.  Matmuls run at the INPUT dtype (bf16 on the MXU's
    native rate) with f32 accumulation via ``preferred_element_type`` —
    casting inputs up to f32 first (the round-2 version) forfeited ~4× of
    MXU throughput.  Softmax statistics stay f32 for stability.

    Also writes the row logsumexp (``lse_ref``, (1, block_q) f32) — the
    residual the custom-VJP backward kernels replay the softmax from
    without re-running the online reduction.

    ``masked=True`` adds a per-(batch·head) valid-key-count operand
    (``lens_ref``, (1, 1) f32): keys at positions >= the count are
    masked, and whole key blocks beyond it are skipped."""
    if masked:
        lens_ref, o_ref, lse_ref = rest
        lens_val = lens_ref[0, 0]
    else:
        (o_ref, lse_ref), lens_val = rest, None
    q = q_ref[...]  # (block_q, d), input dtype
    qi = pl.program_id(1)
    n_kblocks = sk // block_k

    def body(j, carry):
        m_prev, l_prev, o_prev = carry
        k_blk = k_ref[pl.dslice(j * block_k, block_k), :]
        v_blk = v_ref[pl.dslice(j * block_k, block_k), :]
        scores = lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        scores = _score_mask(scores, causal, lens_val, qi, j, block_q,
                             block_k, sq, sk)
        m_blk = jnp.max(scores, axis=-1)
        m_new = jnp.maximum(m_prev, m_blk)
        p = jnp.exp(scores - m_new[:, None])
        corr = jnp.exp(m_prev - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1)
        pv = lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        o_new = o_prev * corr[:, None] + pv
        return m_new, l_new, o_new

    d = q.shape[-1]
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    o0 = jnp.zeros((block_q, d), jnp.float32)
    if causal:
        # skip key blocks strictly after this q block's last position
        last_q = (qi + 1) * block_q - 1 + (sk - sq)
        n_iter = jnp.minimum(last_q // block_k + 1, n_kblocks)
    else:
        n_iter = n_kblocks
    if masked:
        # skip key blocks entirely past the valid length
        n_valid = jnp.ceil(lens_val / block_k).astype(jnp.int32)
        n_iter = jnp.minimum(n_iter, n_valid)
    m, l, o = lax.fori_loop(0, n_iter, body, (m0, l0, o0))
    l_safe = jnp.maximum(l, 1e-30)
    o_ref[...] = (o / l_safe[:, None]).astype(o_ref.dtype)
    lse_ref[0, :] = m + jnp.log(l_safe)


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         *rest, block_k: int, sk: int, causal: bool,
                         sq: int, scale: float, block_q: int,
                         masked: bool):
    """dq for one (batch·head, q-block) cell.  Replays the softmax from
    the saved logsumexp (p = exp(s - lse), exact — no renormalization
    pass), then dq += (p ∘ (do·vᵀ − Δ)) · k per key block, where
    Δ = rowsum(do ∘ o) is precomputed outside the kernel."""
    if masked:
        lens_ref, dq_ref = rest
        lens_val = lens_ref[0, 0]
    else:
        (dq_ref,), lens_val = rest, None
    q = q_ref[...]
    do = do_ref[...]
    lse = lse_ref[0, :]      # (block_q,) f32
    delta = delta_ref[0, :]  # (block_q,) f32
    qi = pl.program_id(1)
    n_kblocks = sk // block_k
    d = q.shape[-1]

    def body(j, dq_acc):
        k_blk = k_ref[pl.dslice(j * block_k, block_k), :]
        v_blk = v_ref[pl.dslice(j * block_k, block_k), :]
        s = lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _score_mask(s, causal, lens_val, qi, j, block_q, block_k,
                        sq, sk)
        p = jnp.exp(s - lse[:, None])  # masked scores underflow to 0
        dp = lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta[:, None]) * scale
        return dq_acc + lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        last_q = (qi + 1) * block_q - 1 + (sk - sq)
        n_iter = jnp.minimum(last_q // block_k + 1, n_kblocks)
    else:
        n_iter = n_kblocks
    if masked:
        n_valid = jnp.ceil(lens_val / block_k).astype(jnp.int32)
        n_iter = jnp.minimum(n_iter, n_valid)
    dq = lax.fori_loop(0, n_iter, body,
                       jnp.zeros((block_q, d), jnp.float32))
    dq_ref[...] = dq.astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          *rest, block_q: int, sq: int,
                          causal: bool, sk: int, scale: float,
                          block_k: int, masked: bool):
    """dk/dv for one (batch·head, k-block) cell: iterate q blocks (full-
    sequence q/do refs resident in VMEM), accumulating dv += pᵀ·do and
    dk += dsᵀ·q.  Causality skips q blocks entirely before this key
    block (start index), mirroring the forward's key-block skip.
    Padding-masked key blocks need no skip: their replayed p underflows
    to exactly 0, so dk/dv of padded keys come out zero."""
    if masked:
        lens_ref, dk_ref, dv_ref = rest
        lens_val = lens_ref[0, 0]
    else:
        (dk_ref, dv_ref), lens_val = rest, None
    k_blk = k_ref[...]
    v_blk = v_ref[...]
    kj = pl.program_id(1)
    n_qblocks = sq // block_q
    d = k_blk.shape[-1]

    def body(i, carry):
        dk_acc, dv_acc = carry
        q_blk = q_ref[pl.dslice(i * block_q, block_q), :]
        do_blk = do_ref[pl.dslice(i * block_q, block_q), :]
        lse_blk = lse_ref[0, pl.dslice(i * block_q, block_q)]
        delta_blk = delta_ref[0, pl.dslice(i * block_q, block_q)]
        s = lax.dot_general(
            q_blk, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale
        s = _score_mask(s, causal, lens_val, i, kj, block_q, block_k,
                        sq, sk)
        p = jnp.exp(s - lse_blk[:, None])
        dv_acc = dv_acc + lax.dot_general(
            p.astype(do_blk.dtype), do_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = lax.dot_general(
            do_blk, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_blk[:, None]) * scale
        dk_acc = dk_acc + lax.dot_general(
            ds.astype(q_blk.dtype), q_blk, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return dk_acc, dv_acc

    if causal:
        # first q block whose LAST row reaches this key block:
        # i·block_q + block_q − 1 + (sk − sq) ≥ kj·block_k
        start = jnp.maximum(0, (kj * block_k - (sk - sq)) // block_q)
    else:
        start = 0
    end = n_qblocks
    if masked:
        # a key block entirely past the valid length contributes zero
        # dk/dv — write the zeros without iterating (fwd/dq skip's dual)
        end = jnp.where(kj * block_k >= lens_val, start, end)
    z = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = lax.fori_loop(start, end, body, (z, z))
    dk_ref[...] = dk.astype(dk_ref.dtype)
    dv_ref[...] = dv.astype(dv_ref.dtype)


def _mega(interpret: bool) -> dict:
    """Megacore grid partitioning hints (harmless on one core)."""
    if interpret:
        return {}
    try:
        from jax.experimental.pallas import tpu as pltpu
        return {"compiler_params": pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"))}
    except (ImportError, AttributeError):
        return {}


def _flash_fwd_call(qf, kf, vf, lens, sq, sk, causal, masked, block_q,
                    block_k, scale, interpret):
    bh, _, d = qf.shape
    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k, sk=sk,
                               causal=causal, sq=sq, scale=scale,
                               block_q=block_q, masked=masked)
    in_specs = [
        pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
    ]
    args = [qf, kf, vf]
    if masked:
        in_specs.append(pl.BlockSpec((None, 1, 1), lambda i, j: (i, 0, 0)))
        args.append(lens)
    # per-row statistics (lse; lens/delta in the backward) carry an
    # explicit singleton dim — (bh, 1, sq) blocked (None, 1, block_q) —
    # because TPU lowering requires each of a block's minor two dims to
    # be tile-divisible (8/128) OR equal to the full array dim.  A 2-D
    # (bh, sq) stat blocked (1, block_q) puts a size-1 sublane against
    # bh and cannot lower (caught on the first live-chip run of the
    # custom-VJP path, r5).
    return pl.pallas_call(
        kernel,
        grid=(bh, sq // block_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, 1, block_q), lambda i, j: (i, 0, j)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sq, d), qf.dtype),
            jax.ShapeDtypeStruct((bh, 1, sq), jnp.float32),
        ],
        interpret=interpret,
        **_mega(interpret),
    )(*args)


# static config after the four differentiable-position operands (``lens``
# is a traced (bh, 1) f32 operand — lengths vary per batch at runtime —
# whose cotangent is defined as zero)
@functools.partial(jax.custom_vjp,
                   nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11))
def _flash_core(qf, kf, vf, lens, sq, sk, causal, masked, block_q,
                block_k, scale, interpret):
    """Flash attention on folded (batch·heads, seq, head_dim) arrays with
    a flash BACKWARD (pallas dq and dk/dv kernels) — plain ``jax.grad``
    of a ``pallas_call`` is unsupported (pallas has no general transpose
    rule), and recomputing through the XLA blockwise path would forfeit
    the kernel's advantage exactly where the training step spends ~2/3 of
    its attention FLOPs."""
    out, _ = _flash_fwd_call(qf, kf, vf, lens, sq, sk, causal, masked,
                             block_q, block_k, scale, interpret)
    return out


def _flash_core_fwd(qf, kf, vf, lens, sq, sk, causal, masked, block_q,
                    block_k, scale, interpret):
    out, lse = _flash_fwd_call(qf, kf, vf, lens, sq, sk, causal, masked,
                               block_q, block_k, scale, interpret)
    return out, (qf, kf, vf, lens, out, lse)


def _flash_core_bwd(sq, sk, causal, masked, block_q, block_k, scale,
                    interpret, res, do):
    qf, kf, vf, lens, out, lse = res
    bh, _, d = qf.shape
    do = do.astype(qf.dtype)
    # Δ_i = Σ_d do_id·o_id  (= Σ_j p_ij·dp_ij) — cheap elementwise, XLA
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, None, :]  # (bh, 1, sq), like lse
    # backward blocks: q-chunk stays at the forward's (which divides sq
    # by construction); key-chunk halves when possible — the dkv cell's
    # (block_q × block_k) f32 p/dp/ds live simultaneously.  A prime-ish
    # sk whose only small divisors are tiny keeps the forward's block
    # rather than degenerating to a per-element grid.
    bwd_bq = block_q
    bwd_bk = _largest_divisor(sk, min(block_k, 512))
    if bwd_bk < 8:
        bwd_bk = block_k

    dq_kernel = functools.partial(
        _flash_bwd_dq_kernel, block_k=bwd_bk, sk=sk, causal=causal, sq=sq,
        scale=scale, block_q=bwd_bq, masked=masked)
    dq_specs = [
        pl.BlockSpec((None, bwd_bq, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, sk, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, bwd_bq, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((None, 1, bwd_bq), lambda i, j: (i, 0, j)),
        pl.BlockSpec((None, 1, bwd_bq), lambda i, j: (i, 0, j)),
    ]
    dq_args = [qf, kf, vf, do, lse, delta]
    if masked:
        dq_specs.append(pl.BlockSpec((None, 1, 1), lambda i, j: (i, 0, 0)))
        dq_args.append(lens)
    dq = pl.pallas_call(
        dq_kernel,
        grid=(bh, sq // bwd_bq),
        in_specs=dq_specs,
        out_specs=pl.BlockSpec((None, bwd_bq, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, sq, d), qf.dtype),
        interpret=interpret,
        **_mega(interpret),
    )(*dq_args)

    dkv_kernel = functools.partial(
        _flash_bwd_dkv_kernel, block_q=bwd_bq, sq=sq, causal=causal,
        sk=sk, scale=scale, block_k=bwd_bk, masked=masked)
    dkv_specs = [
        pl.BlockSpec((None, sq, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, bwd_bk, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((None, bwd_bk, d), lambda i, j: (i, j, 0)),
        pl.BlockSpec((None, sq, d), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, 1, sq), lambda i, j: (i, 0, 0)),
        pl.BlockSpec((None, 1, sq), lambda i, j: (i, 0, 0)),
    ]
    dkv_args = [qf, kf, vf, do, lse, delta]
    if masked:
        dkv_specs.append(pl.BlockSpec((None, 1, 1), lambda i, j: (i, 0, 0)))
        dkv_args.append(lens)
    dk, dv = pl.pallas_call(
        dkv_kernel,
        grid=(bh, sk // bwd_bk),
        in_specs=dkv_specs,
        out_specs=[
            pl.BlockSpec((None, bwd_bk, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, bwd_bk, d), lambda i, j: (i, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, sk, d), kf.dtype),
            jax.ShapeDtypeStruct((bh, sk, d), vf.dtype),
        ],
        interpret=interpret,
        **_mega(interpret),
    )(*dkv_args)
    return dq, dk, dv, jnp.zeros_like(lens)


_flash_core.defvjp(_flash_core_fwd, _flash_core_bwd)


def flash_attention(q, k, v, causal: bool = False, block_q: int = 256,
                    block_k: int = 1024, scale: float = None,
                    interpret: bool = False, layout: str = "bshd",
                    kv_lengths=None):
    """Pallas TPU flash attention.

    Default blocks (q 256 × k 1024) are tuned on a v5e: measured (scan-
    loop methodology, r3) 14.2 vs 12.3 TFLOP/s for the XLA blockwise
    formulation at [4, 2048, 8, 128] and 42.9 vs 28.5 at [1, 8192, 8,
    128] — 1.50× at long sequence, and 1.64× over
    jax.experimental.pallas.ops.tpu.flash_attention at the 2048 shape.

    ``layout`` (VERDICT r3 #8 — the transpose tax):

    - ``"bshd"`` (default, the shared layout contract): q/k/v are
      (batch, seq, heads, head_dim).  The kernel's grid wants heads
      adjacent to batch, so each array is TRANSPOSED to (b, h, s, d) —
      a materialized copy, ~4 × b·s·h·d·2 bytes of HBM traffic per call
      at bf16 (~64 MB at [4, 2048, 8, 128]).  A 4-D BlockSpec over the
      raw (b, s, h, d) layout cannot lower: the block's minor-two dims
      must be (sublane=s, lane=d), but h sits between them, so any
      (block_q, 1, d) tile puts a size-1 h in the sublane slot
      (captured analysis, PERF_NOTES r3/r4).
    - ``"bhsd"``: q/k/v arrive (batch, heads, seq, head_dim).  Folding
      to the kernel's (b·h, s, d) is a pure reshape of two contiguous
      major axes — NO copy.  Transformer stacks should project straight
      into this layout (``einsum("bse,ehd->bhsd", x, W)``) so XLA folds
      the layout into the projection matmul's output and the transpose
      tax disappears end-to-end.

    ``interpret=True`` runs the kernel in the pallas interpreter (CPU
    testing — SURVEY §4's "local device = cluster" trick applied to
    kernels).

    ``kv_lengths``: optional (batch,) valid key counts — keys at
    positions >= kv_lengths[b] are masked INSIDE the kernels (forward
    and both backward kernels), and whole key blocks beyond the length
    are skipped.  See ``naive_attention`` for the padded-query caveat.

    Awkward (prime-ish) lengths with no block divisor >= 8 are handled
    by padding q/k/v up to a 128-multiple: padded keys ride the same
    kv_lengths masking, padded query rows are sliced off (their dout is
    zero through the slice's VJP, so real dk/dv are exact).  The one
    shape that still raises is causal attention at CROSS lengths
    (sq != sk) with no usable divisor — equal padding would break the
    q_pos = i + sk - sq alignment there.
    """
    if layout == "bshd":
        b, sq, h, d = q.shape
        sk = k.shape[1]
    elif layout == "bhsd":
        b, h, sq, d = q.shape
        sk = k.shape[2]
    else:
        raise ValueError(f"layout must be 'bshd' or 'bhsd', got {layout!r}")
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    # clamp to the sequence, then fall back to the largest divisor so any
    # seq length that has a usable block works with the tuned defaults
    # (e.g. 384 % 256 != 0 → block_q 128)
    cap_q, cap_k = block_q, block_k
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q:
        block_q = _largest_divisor(sq, block_q)
    if sk % block_k:
        block_k = _largest_divisor(sk, block_k)
    pad_q = pad_k = 0
    if min(block_q, block_k) < 8:
        # awkward (prime-ish) lengths: PAD up to a 128-multiple and
        # mask.  Padded keys ride the kv_lengths kernel masking (scores
        # masked, whole padded blocks skipped); padded query rows are
        # sliced off the output, and the slice's VJP zero-fills their
        # dout, so they contribute nothing to dk/dv of real keys.
        # Causal alignment (q_pos = i + sk − sq) survives because both
        # sides pad by the SAME amount — which requires sq == sk; the
        # causal cross-length case keeps the loud error.
        if causal and sq != sk:
            raise ValueError(
                f"causal flash attention at cross lengths (sq={sq}, "
                f"sk={sk}) needs a block divisor >= 8 on both — use "
                "blockwise/naive attention")
        if block_q < 8 or (causal and block_k < 8):
            pad_q = -sq % 128
        if block_k < 8 or (causal and block_q < 8):
            pad_k = -sk % 128
        block_q = _largest_divisor(sq + pad_q, min(cap_q, sq + pad_q))
        block_k = _largest_divisor(sk + pad_k, min(cap_k, sk + pad_k))
    if min(block_q, block_k) < 8:
        # only reachable via caller-supplied tiny block caps (padding
        # guarantees a >= 128 divisor otherwise) — keep the loud error
        # instead of handing the pallas kernel a sub-sublane tile
        raise ValueError(
            f"flash attention blocks (block_q={block_q}, "
            f"block_k={block_k}) must be >= 8 (TPU sublane tiling)")
    if causal and sq > sk:
        # rows aligned before the first key are FULLY masked; their
        # backward replay (p = exp(s − lse)) would cancel the finite
        # NEG_INF sentinel into phantom 1/n probabilities and corrupt
        # dk/dv of valid rows — and the forward's "output" for such rows
        # is meaningless anyway.  blockwise/naive keep the where-based
        # autodiff semantics for this degenerate shape.
        raise ValueError(
            f"causal flash attention needs sq <= sk (got sq={sq}, "
            f"sk={sk}): rows before the first key are fully masked — "
            "use blockwise/naive attention")
    if layout == "bshd":
        # fold batch and heads into the grid's first axis — a materialized
        # transpose (see docstring; pass layout="bhsd" to avoid it)
        qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
        kf = k.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
        vf = v.transpose(0, 2, 1, 3).reshape(b * h, sk, d)
    else:
        # contiguous major-axis fold: free
        qf = q.reshape(b * h, sq, d)
        kf = k.reshape(b * h, sk, d)
        vf = v.reshape(b * h, sk, d)

    if pad_q or pad_k:
        qf = jnp.pad(qf, ((0, 0), (0, pad_q), (0, 0)))
        kf = jnp.pad(kf, ((0, 0), (0, pad_k), (0, 0)))
        vf = jnp.pad(vf, ((0, 0), (0, pad_k), (0, 0)))
    masked = kv_lengths is not None or pad_k > 0
    if masked:
        # per-(batch·head) lengths, matching the b-major fold order;
        # clamped to the REAL key count so padded keys stay masked
        base = (_clamp_lengths(kv_lengths, sk) if kv_lengths is not None
                else jnp.full((b,), sk, jnp.float32))
        lens = jnp.repeat(base, h)[:, None, None]
    else:
        lens = jnp.zeros((b * h, 1, 1), jnp.float32)  # inert placeholder
    out = _flash_core(qf, kf, vf, lens, sq + pad_q, sk + pad_k, causal,
                      masked, block_q, block_k, scale, interpret)
    out = out[:, :sq] if pad_q else out
    if layout == "bshd":
        return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
    return out.reshape(b, h, sq, d)


def _largest_divisor(n: int, cap: int) -> int:
    """Largest divisor of n that is <= cap."""
    for d in range(min(cap, n), 0, -1):
        if n % d == 0:
            return d
    return 1


def _flash_supports(causal: bool, sq: int, sk: int) -> bool:
    """Can ``flash_attention`` (at its default block caps) run this
    shape?  Pad-and-mask covers every length except the causal CROSS
    shapes: sq > sk has fully-masked rows, and sq != sk with no block
    divisor >= 8 cannot pad both sides equally (the q_pos alignment).
    The single eligibility predicate for both dispatchers — keep in
    sync with flash_attention's internal raise."""
    if causal and sq > sk:
        return False
    if causal and sq != sk and min(_largest_divisor(sq, 256),
                                   _largest_divisor(sk, 1024)) < 8:
        return False
    return True


def attention_bhsd(q, k, v, causal: bool = False,
                   implementation: str = "auto", kv_lengths=None):
    """(b, h, s, d)-layout dispatch — the transpose-free fast path for
    transformer stacks that project qkv straight into bhsd
    (``einsum("bse,ehd->bhsd", ...)``; see flash_attention's layout
    note).  On TPU the pallas kernel consumes the layout directly; on
    other backends the arrays are transposed to the (b, s, h, d)
    contract around blockwise/naive (cheap on CPU, where this path is
    only a test oracle).

    ``kv_lengths``: optional (batch,) valid key counts — right-padded
    batches mask keys past their length in every implementation."""
    sq, sk = q.shape[2], k.shape[2]
    on_tpu = jax.devices()[0].platform == "tpu"
    if implementation == "flash" or (
            implementation == "auto" and on_tpu
            and _flash_supports(causal, sq, sk)):
        # awkward lengths pad-and-mask inside flash_attention; the one
        # unsupported shape (causal cross-length with no divisor)
        # RAISES there on explicit "flash" (never a silent O(S²)
        # naive fallback) and falls through to blockwise/naive on auto
        return flash_attention(q, k, v, causal=causal, layout="bhsd",
                               interpret=not on_tpu,
                               kv_lengths=kv_lengths)
    bq, bk = _largest_divisor(sq, 256), _largest_divisor(sk, 1024)
    qs, ks, vs = (a.transpose(0, 2, 1, 3) for a in (q, k, v))
    if implementation == "blockwise" or (
            implementation == "auto" and min(bq, bk) >= 8):
        out = blockwise_attention(qs, ks, vs, causal=causal, block_k=bk,
                                  kv_lengths=kv_lengths)
    elif implementation in ("auto", "naive"):
        out = naive_attention(qs, ks, vs, causal=causal,
                              kv_lengths=kv_lengths)
    else:
        raise ValueError(f"Unknown implementation {implementation!r}")
    return out.transpose(0, 2, 1, 3)


def attention(q, k, v, causal: bool = False, implementation: str = "auto",
              kv_lengths=None):
    """Dispatch: pallas on TPU (awkward lengths pad-and-mask inside
    flash_attention), blockwise elsewhere; lengths with no usable block
    divisor fall back to naive off-TPU (and for the causal cross-length
    shape flash cannot pad)."""
    sq, sk = q.shape[1], k.shape[1]
    if implementation == "auto":
        if (jax.devices()[0].platform == "tpu"
                and _flash_supports(causal, sq, sk)):
            return flash_attention(q, k, v, causal=causal,
                                   kv_lengths=kv_lengths)
        bq, bk = _largest_divisor(sq, 256), _largest_divisor(sk, 1024)
        if min(bq, bk) < 8:
            # prime-ish lengths: blocked kernels degenerate, use naive
            return naive_attention(q, k, v, causal=causal,
                                   kv_lengths=kv_lengths)
        return blockwise_attention(q, k, v, causal=causal, block_k=bk,
                                   kv_lengths=kv_lengths)
    if implementation == "flash":
        return flash_attention(q, k, v, causal=causal,
                               kv_lengths=kv_lengths)
    if implementation == "blockwise":
        return blockwise_attention(q, k, v, causal=causal,
                                   kv_lengths=kv_lengths)
    if implementation == "naive":
        return naive_attention(q, k, v, causal=causal,
                               kv_lengths=kv_lengths)
    raise ValueError(f"Unknown implementation {implementation!r}")
