from . import elementwise
from .attention import (attention, naive_attention, blockwise_attention,
                        flash_attention)
