from . import elementwise
