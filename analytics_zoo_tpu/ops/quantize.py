"""Post-training int8 quantization for inference.

Parity surface: the reference ships ``*-quantize`` model variants backed by
BigDL's 8-bit "local quantization windows" scheme (docs/docs/wp-bigdl.md:
186-196: up to 2x inference speedup, 4x model-size reduction, <0.1%
accuracy drop; registry names ObjectDetectionConfig.scala:33-44).

TPU-native design: weights are quantized **per output channel** (symmetric
absmax int8) ahead of time; activations are quantized **per sample,
dynamically** inside the traced function (see ``dynamic_quantize`` for
the measured accuracy rationale).  The matmul/conv itself runs in
int8 with int32 accumulation via ``preferred_element_type`` — XLA lowers
that onto the MXU's native int8 path — and one fused rescale
(x_scale * w_scale[channel]) returns to float.  Everything stays inside
one jit, so quantize/compute/dequantize fuse with neighbouring ops.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..core.graph import GraphModule, InputLayer, Variable
from ..core.module import Layer, Params, register_layer

_EPS = 1e-12


# ---------------------------------------------------------------------------
# primitives

def quantize_per_channel(w, out_axis: int = -1) -> Tuple[jnp.ndarray,
                                                         jnp.ndarray]:
    """Symmetric absmax int8 quantization per output channel.

    Returns (w_q int8 same shape, scale float32 of shape (out_channels,)):
    ``w ≈ w_q * scale`` broadcast along ``out_axis``.
    """
    w = jnp.asarray(w, jnp.float32)
    axis = out_axis % w.ndim
    red = tuple(i for i in range(w.ndim) if i != axis)
    absmax = jnp.max(jnp.abs(w), axis=red)
    scale = jnp.maximum(absmax / 127.0, _EPS)
    bshape = tuple(w.shape[i] if i == axis else 1 for i in range(w.ndim))
    wq = jnp.clip(jnp.round(w / jnp.reshape(scale, bshape)), -127, 127)
    return wq.astype(jnp.int8), scale.astype(jnp.float32)


def dynamic_quantize(x) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """PER-SAMPLE dynamic activation quantization (absmax, symmetric):
    the scale reduces over every axis except the leading batch axis and
    is returned keepdims-shaped ((b, 1, ..., 1)) so it broadcasts.

    Traced: scales are computed on-device per call, so no calibration
    pass is needed (BigDL's "local quantization window" played the same
    role per-block — per-sample is that idea at batch granularity).
    Why per-sample and not per-tensor: one outlier sample in a batch
    widens a per-tensor window for EVERY sample, quantizing the others
    coarsely.  Measured on a converged 57-conv inception-v1 (real
    digits, f32 acc 0.9547): per-tensor int8 dropped 1.26 pp while
    per-sample int8 matched f32 EXACTLY — and weight-only rounding also
    cost zero, i.e. the entire per-tensor loss was activation-window
    dilution.  Per-sample costs the same FLOPs (one amax reduce) and
    the rescale fuses identically."""
    x = jnp.asarray(x)
    # rank<2: no batch axis to keep — reduce over everything
    red = tuple(range(1, x.ndim)) if x.ndim > 1 else None
    scale = jnp.maximum(
        jnp.max(jnp.abs(x), axis=red, keepdims=True) / 127.0,
        _EPS).astype(jnp.float32)
    xq = jnp.clip(jnp.round(x / scale), -127, 127).astype(jnp.int8)
    return xq, scale


def int8_matmul(x, w_q, w_scale):
    """``x @ dequant(w_q)`` computed in int8 with int32 accumulation."""
    xq, xs = dynamic_quantize(x)
    acc = lax.dot_general(
        xq, w_q, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (xs * w_scale)


def int8_conv(x_cl, w_q, w_scale, strides, padding, rhs_dilation,
              dimension_numbers):
    """Channels-last conv in int8 with int32 accumulation; returns float32
    with the per-output-channel rescale applied."""
    xq, xs = dynamic_quantize(x_cl)
    acc = lax.conv_general_dilated(
        xq, w_q, window_strides=strides, padding=padding,
        rhs_dilation=rhs_dilation, dimension_numbers=dimension_numbers,
        preferred_element_type=jnp.int32)
    return acc.astype(jnp.float32) * (xs * w_scale)


# ---------------------------------------------------------------------------
# quantized layer wrappers

class _QuantizedLayer(Layer):
    """Base: holds pre-converted arrays; init returns them verbatim."""

    def __init__(self, src: Layer, initial: Params):
        # reuse the source layer's name so params/state keys line up in
        # the rebuilt graph
        super().__init__(name=src.name)
        self.src = src
        self._initial = dict(initial)
        self.trainable = False  # int8 weights are not a gradient surface

    def init_params(self, rng, input_shape):
        return dict(self._initial)

    def compute_output_shape(self, input_shape):
        return self.src.compute_output_shape(input_shape)

    def get_config(self):
        raise NotImplementedError(
            "quantized models are an inference-time artifact and are not "
            "serialized; save the float model and re-quantize after load")


@register_layer
class QuantizedDense(_QuantizedLayer):
    """int8 inference version of Dense (y = act(x @ W + b))."""

    @classmethod
    def from_layer(cls, dense, params: Params) -> "QuantizedDense":
        wq, scale = quantize_per_channel(params["W"], out_axis=-1)
        initial = {"Wq": wq, "w_scale": scale}
        if dense.bias:
            initial["b"] = jnp.asarray(params["b"], jnp.float32)
        return cls(dense, initial)

    def call(self, params, state, inputs, training=False, rng=None):
        y = int8_matmul(inputs, params["Wq"], params["w_scale"])
        if self.src.bias:
            y = y + params["b"]
        if self.src.activation is not None:
            y = self.src.activation(y)
        return y


@register_layer
class QuantizedConv(_QuantizedLayer):
    """int8 inference version of the standard _ConvND convolutions."""

    @classmethod
    def from_layer(cls, conv, params: Params) -> "QuantizedConv":
        wq, scale = quantize_per_channel(params["W"], out_axis=-1)
        initial = {"Wq": wq, "w_scale": scale}
        if conv.bias:
            initial["b"] = jnp.asarray(params["b"], jnp.float32)
        return cls(conv, initial)

    def call(self, params, state, inputs, training=False, rng=None):
        from ..pipeline.api.keras.layers.convolutional import _DN
        src = self.src
        x = src._to_cl(inputs)
        x, pad = src._resolve_padding(x)
        y = int8_conv(x, params["Wq"], params["w_scale"],
                      strides=src.subsample, padding=pad,
                      rhs_dilation=src.dilation,
                      dimension_numbers=_DN[src.rank])
        if src.bias:
            y = y + params["b"]
        if src.activation is not None:
            y = src.activation(y)
        return src._from_cl(y)


@register_layer
class QuantizedEmbedding(_QuantizedLayer):
    """int8 inference version of Embedding: the lookup table is stored
    int8 with a per-ROW scale (each token's vector has its own absmax
    window), dequantized after the gather — a 4x smaller table, and the
    gather itself moves 4x fewer bytes."""

    @classmethod
    def from_layer(cls, emb, params: Params) -> "QuantizedEmbedding":
        # rows are the output axis of a lookup: per-row scales
        tq, scale = quantize_per_channel(params["embeddings"], out_axis=0)
        return cls(emb, {"Eq": tq, "e_scale": scale})

    def call(self, params, state, inputs, training=False, rng=None):
        idx = inputs.astype(jnp.int32)
        vecs = jnp.take(params["Eq"], idx, axis=0).astype(jnp.float32)
        scales = jnp.take(params["e_scale"], idx, axis=0)
        return vecs * scales[..., None]


@register_layer
class QuantizedSeparableConv(_QuantizedLayer):
    """int8 inference version of SeparableConvolution2D.

    The PLAIN 1x1 pointwise conv — where virtually all the FLOPs and
    weights live — runs int8; the depthwise conv stays float (its weight
    is tiny and grouped convs don't hit the MXU's int8 path cleanly)."""

    @classmethod
    def from_layer(cls, sep, params: Params) -> "QuantizedSeparableConv":
        wq, scale = quantize_per_channel(params["pointwise"], out_axis=-1)
        initial = {"depthwise": jnp.asarray(params["depthwise"],
                                            jnp.float32),
                   "Pq": wq, "p_scale": scale}
        if sep.bias:
            initial["b"] = jnp.asarray(params["b"], jnp.float32)
        return cls(sep, initial)

    def call(self, params, state, inputs, training=False, rng=None):
        from ..pipeline.api.keras.layers.convolutional import _DN
        src = self.src
        x = inputs
        if src.data_format == "channels_first":
            x = jnp.transpose(x, (0, 2, 3, 1))
        in_ch = x.shape[-1]
        pad = "SAME" if src.border_mode == "same" else "VALID"
        y = lax.conv_general_dilated(
            x, params["depthwise"], window_strides=src.subsample,
            padding=pad, dimension_numbers=_DN[2],
            feature_group_count=in_ch)
        y = int8_conv(y, params["Pq"], params["p_scale"], strides=(1, 1),
                      padding="VALID", rhs_dilation=None,
                      dimension_numbers=_DN[2])
        if src.bias:
            y = y + params["b"]
        if src.activation is not None:
            y = src.activation(y)
        if src.data_format == "channels_first":
            y = jnp.transpose(y, (0, 3, 1, 2))
        return y


# ---------------------------------------------------------------------------
# graph transformation

def _quantizable(layer: Layer, params: Params) -> Optional[type]:
    """Return the quantized wrapper class for supported layers.

    Supported: Dense, plain _ConvND convolutions, Embedding lookups, and
    SeparableConvolution2D (pointwise part) — each only when the subclass
    *did not override the compute path* (custom call/_conv variants are
    left in float)."""
    from ..pipeline.api.keras.layers.convolutional import (
        _ConvND, SeparableConvolution2D)
    from ..pipeline.api.keras.layers.core import Dense
    from ..pipeline.api.keras.layers.embedding import Embedding
    if isinstance(layer, Embedding) \
            and type(layer).call is Embedding.call \
            and "embeddings" in params:
        return QuantizedEmbedding
    if isinstance(layer, SeparableConvolution2D) \
            and type(layer).call is SeparableConvolution2D.call \
            and "pointwise" in params:
        return QuantizedSeparableConv
    if "W" not in params or not jnp.issubdtype(
            jnp.asarray(params["W"]).dtype, jnp.floating):
        return None
    if isinstance(layer, Dense) and type(layer).call is Dense.call:
        return QuantizedDense
    if isinstance(layer, _ConvND) and type(layer).call is _ConvND.call \
            and type(layer)._conv is _ConvND._conv:
        return QuantizedConv
    return None


def quantize_graph(graph: GraphModule, params: Params,
                   state: Optional[Dict] = None
                   ) -> Tuple[GraphModule, Params, Dict]:
    """Rebuild ``graph`` with Dense/Conv layers swapped for int8 wrappers.

    Returns (new_graph, new_params, state): params of untouched layers are
    carried over under their original keys; quantized layers contribute
    their int8 weights + scales (4x smaller than the float originals).
    """
    new_of: Dict[int, Variable] = {}
    layer_map: Dict[int, Layer] = {}
    new_params: Params = {}
    for v in graph.nodes:
        if v.layer is None or isinstance(v.layer, InputLayer):
            new_of[v.node_id] = v  # share input nodes
            continue
        layer = v.layer
        if id(layer) not in layer_map:
            p = params.get(layer.name, {})
            qcls = _quantizable(layer, p)
            if qcls is not None:
                qlayer = qcls.from_layer(layer, p)
                layer_map[id(layer)] = qlayer
                new_params[qlayer.name] = qlayer._initial
            else:
                layer_map[id(layer)] = layer
                if p:
                    new_params[layer.name] = p
        nl = layer_map[id(layer)]
        ins = [new_of[p.node_id] for p in v.inputs]
        new_of[v.node_id] = Variable(nl, ins, v.shape)
    inputs = list(graph.input_vars)
    outputs = [new_of[o.node_id] for o in graph.output_vars]
    single = graph.single_output
    new_graph = GraphModule(inputs,
                            outputs[0] if single else outputs,
                            name=f"{graph.name}_int8")
    return new_graph, new_params, dict(state or {})


def quantized_size_bytes(params: Params) -> int:
    """Total serialized byte size of a params tree (reporting helper)."""
    return int(sum(np.asarray(p).nbytes
                   for p in jax.tree_util.tree_leaves(params)))
