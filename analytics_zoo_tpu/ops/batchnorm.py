"""Train-mode BatchNorm core with a hand-written VJP.

Why this exists (PERF_NOTES r3 / VERDICT r3 #2): train-mode BN batch
statistics cost ~10 ms of a 52 ms ResNet-50 step on the v5e.  The naive
formulation autodiffed by XLA has two structural inefficiencies:

1. ``jnp.var`` is two reduction passes over the activation (mean first,
   then ``mean((x - mean)**2)``), and the f32 cast of a bf16 activation
   doubles the bytes each pass reads.
2. The autodiff backward re-derives the chain through both passes,
   emitting more per-channel reductions than the closed form needs, and
   saves the f32-cast input as residual.

This kernel restructures both directions:

- **forward**: ONE fused reduction pass computes ``sum(x)`` and
  ``sum(x*x)`` together (multi-output reduction, f32 accumulation via
  dot-free elementwise + reduce; XLA fuses the pair), then
  ``var = E[x^2] - E[x]^2``.  The activation is read once, in its
  native dtype.
- **residuals**: ``xhat`` in the COMPUTE dtype (bf16 under mixed
  precision — half the bytes of the naive form's saved f32 x) plus the
  per-channel ``inv`` and ``gamma`` vectors.
- **backward**: the closed form needs exactly two per-channel
  reductions — ``sum(dy)`` and ``sum(dy * xhat)`` — which are ALSO
  dgamma/dbeta, so one fused pass over (dy, xhat) yields all reduction
  work, followed by one elementwise pass for
  ``dx = inv * gamma * (dy - mean(dy) - xhat * mean(dy * xhat))``.

Moving-statistics updates are *not* differentiated through (parity with
BigDL's SpatialBatchNormalization running stats and torch's BN): the
returned ``mean``/``var`` carry an implicit stop_gradient.

Numerical note: ``E[x^2] - E[x]^2`` cancels catastrophically only when
``|mean| >> std``; statistics accumulate in f32 (bf16 inputs are
upcast per-element inside the fused reduction, never materialized), the
same precision/structure cuDNN and tf.keras use.  ``var`` is clamped at
0 against tiny negative residuals.

Reference frame: BigDL SpatialBatchNormalization
(zoo/.../nn/SpatialBatchNormalization + keras BatchNormalization.scala)
computes identical mathematics engine-side; this is its TPU-shaped
restructuring, not a translation.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _reduce_axes_and_count(x, ch_axis):
    axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    n = 1
    for a in axes:
        n *= x.shape[a]
    return axes, n


from functools import partial


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def batch_norm_train(x, gamma, beta, eps, ch_axis):
    """Train-mode batch norm over every axis except ``ch_axis`` (static
    int; ``eps`` static float).

    Returns ``(out, mean, var)``; ``mean``/``var`` are f32 per-channel
    batch statistics for the caller's moving-average update and are NOT
    differentiated through.
    """
    out, mean, var, _, _ = _bn_forward(x, gamma, beta, eps, ch_axis)
    return out, mean, var


def _bn_forward(x, gamma, beta, eps, ch_axis):
    axes, n = _reduce_axes_and_count(x, ch_axis)
    x32 = x.astype(jnp.float32)
    # one fused pass: both reductions read x once (XLA multi-output fusion)
    s1 = jnp.sum(x32, axis=axes)
    s2 = jnp.sum(x32 * x32, axis=axes)
    mean = s1 / n
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    inv = jax.lax.rsqrt(var + eps)

    dt = x.dtype
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]
    mean_b = mean.astype(dt).reshape(bshape)
    inv_b = inv.astype(dt).reshape(bshape)
    xhat = (x - mean_b) * inv_b
    out = xhat * gamma.astype(dt).reshape(bshape) \
        + beta.astype(dt).reshape(bshape)
    return out, mean, var, xhat, inv


def _bn_fwd(x, gamma, beta, eps, ch_axis):
    out, mean, var, xhat, inv = _bn_forward(x, gamma, beta, eps, ch_axis)
    # residuals: compute-dtype xhat (bf16 under mixed precision) + two
    # per-channel vectors — about half the naive form's saved f32 x
    return (out, mean, var), (xhat, inv, gamma)


def _bn_bwd(eps, ch_axis, res, cts):
    xhat, inv, gamma = res
    dy = cts[0]  # mean/var cotangents are moving-stat updates: stop-grad
    axes, n = _reduce_axes_and_count(xhat, ch_axis)

    dy32 = dy.astype(jnp.float32)
    xhat32 = xhat.astype(jnp.float32)
    # ONE fused reduction pass over (dy, dy*xhat): these two vectors are
    # simultaneously dbeta, dgamma, and the backward's only reductions
    s_dy = jnp.sum(dy32, axis=axes)
    s_dyx = jnp.sum(dy32 * xhat32, axis=axes)

    dt = dy.dtype
    bshape = [1] * dy.ndim
    bshape[ch_axis] = dy.shape[ch_axis]
    mean_dy = (s_dy / n).astype(dt).reshape(bshape)
    mean_dyx = (s_dyx / n).astype(dt).reshape(bshape)
    scale = (inv.astype(dt).reshape(bshape)
             * gamma.astype(dt).reshape(bshape))
    dx = scale * (dy - mean_dy - xhat * mean_dyx)
    dgamma = s_dyx.astype(gamma.dtype)
    dbeta = s_dy.astype(gamma.dtype)
    return dx.astype(dt), dgamma, dbeta


batch_norm_train.defvjp(_bn_fwd, _bn_bwd)


# A/B switch for the perf harness: when True, BatchNormalization traces
# the pre-r4 naive formulation (jnp.mean + jnp.var + autodiff backward)
# instead of the restructured custom-VJP core.  Trace-time only — flip
# it between building two jitted step functions to measure both.
USE_NAIVE = False


def set_naive_bn(flag: bool):
    global USE_NAIVE
    USE_NAIVE = bool(flag)


def batch_norm_train_naive(x, gamma, beta, eps, ch_axis):
    """The pre-restructuring formulation (two reduction passes over an
    f32 cast, XLA-autodiff backward) — kept for the bench's A/B."""
    axes, _ = _reduce_axes_and_count(x, ch_axis)
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=axes)
    var = jnp.var(x32, axis=axes)
    dt = x.dtype
    inv = gamma.astype(dt).reshape(bshape) * (
        1.0 / jnp.sqrt(var.astype(dt).reshape(bshape) + eps))
    out = (x - mean.astype(dt).reshape(bshape)) * inv \
        + beta.astype(dt).reshape(bshape)
    return out, jax.lax.stop_gradient(mean), jax.lax.stop_gradient(var)


def batch_norm_inference(x, gamma, beta, mean, var, eps, ch_axis):
    """Eval-mode BN with moving statistics (plain XLA; fuses fully)."""
    dt = x.dtype
    bshape = [1] * x.ndim
    bshape[ch_axis] = x.shape[ch_axis]
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps).astype(dt)
    return (x - mean.astype(dt).reshape(bshape)) \
        * (inv.reshape(bshape) * gamma.astype(dt).reshape(bshape)) \
        + beta.astype(dt).reshape(bshape)
