"""Device mesh discovery and construction.

This replaces the reference's entire cluster bootstrap (NNContext /
SparkContext / Engine.init, reference: zoo/.../common/NNContext.scala:132-206):
on TPU the "cluster" is the device mesh, and the communication backend is
XLA collectives over ICI (intra-slice) and DCN (cross-slice) — there is no
Spark shuffle to configure.

Axis convention (superset of the reference's data-parallel-only world,
SURVEY §2.10):
  data   — data parallelism (gradient psum; the reference's AllReduce)
  fsdp   — parameter/optimizer sharding (ZeRO-style), rides ICI
  tensor — tensor/model parallelism within layers
  seq    — sequence/context parallelism (ring attention)
  expert — expert parallelism (MoE)
  pipe   — pipeline parallelism stages
"""

from __future__ import annotations

import math
from typing import Dict, Optional, Sequence, Tuple

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXES = ("data", "fsdp", "tensor", "seq", "expert", "pipe")


def create_mesh(axes: Optional[Dict[str, int]] = None,
                devices=None) -> Mesh:
    """Build a Mesh over ``devices`` with named axis sizes.

    With no arguments: all local devices on one ``data`` axis — the
    reference's data-parallel topology.  Axis sizes of -1 absorb the
    remaining devices.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    axes = dict(axes or {"data": n})
    # resolve a single -1 wildcard
    known = math.prod(v for v in axes.values() if v != -1)
    for k, v in axes.items():
        if v == -1:
            axes[k] = n // known
    total = math.prod(axes.values())
    if total != n:
        raise ValueError(
            f"Mesh axes {axes} need {total} devices, have {n}")
    names = tuple(axes.keys())
    shape = tuple(axes.values())
    dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, names)


def data_sharding(mesh: Mesh, batch_axes: Sequence[str] = ("data", "fsdp")):
    """NamedSharding for a batch: leading dim split over the data-ish axes
    present in the mesh, rest replicated."""
    present = tuple(a for a in batch_axes if a in mesh.axis_names
                    and mesh.shape[a] > 1)
    spec = P(present if present else None)
    return NamedSharding(mesh, spec)


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())


def dp_size(mesh: Mesh) -> int:
    size = 1
    for a in ("data", "fsdp"):
        if a in mesh.axis_names:
            size *= mesh.shape[a]
    return size


_DEFAULT_MESH: Optional[Mesh] = None
_ACTIVE_MESH: Optional[Mesh] = None


def set_default_mesh(mesh: Optional[Mesh]):
    global _DEFAULT_MESH
    _DEFAULT_MESH = mesh


class active_mesh:
    """Context manager marking the mesh a Trainer is tracing/executing
    under, so mesh-aware layers (ring attention) see the mesh passed to
    ``compile(mesh=...)`` rather than only the process default."""

    def __init__(self, mesh: Optional[Mesh]):
        self.mesh = mesh

    def __enter__(self):
        global _ACTIVE_MESH
        self._prev = _ACTIVE_MESH
        _ACTIVE_MESH = self.mesh
        return self.mesh

    def __exit__(self, *exc):
        global _ACTIVE_MESH
        _ACTIVE_MESH = self._prev
        return False


def get_active_mesh() -> Optional[Mesh]:
    """The mesh of the currently-executing Trainer (if inside one),
    else the process default — WITHOUT auto-creating one."""
    return _ACTIVE_MESH if _ACTIVE_MESH is not None else _DEFAULT_MESH


def get_default_mesh() -> Mesh:
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None:
        _DEFAULT_MESH = create_mesh()
    return _DEFAULT_MESH
