"""Version compat shims for the jax API surface the parallel layer uses.

Two drifts covered for ``shard_map``:

* its home: promoted out of ``jax.experimental`` late in the 0.4.x line —
  on the pinned 0.4.37 it still lives at
  ``jax.experimental.shard_map.shard_map``;
* its replication-check kwarg: renamed ``check_rep`` → ``check_vma``
  alongside the promotion.  Callers here use the NEW name; the shim
  translates for older jax.
"""

from __future__ import annotations

import inspect

try:  # jax >= 0.4.44 exports it at top level
    from jax import shard_map as _shard_map  # type: ignore[attr-defined]
except ImportError:  # pinned 0.4.37 path
    from jax.experimental.shard_map import shard_map as _shard_map

_PARAMS = inspect.signature(_shard_map).parameters

if "check_vma" in _PARAMS:
    shard_map = _shard_map
else:
    def shard_map(*args, check_vma=None, **kwargs):
        if check_vma is not None and "check_rep" not in kwargs:
            kwargs["check_rep"] = check_vma
        return _shard_map(*args, **kwargs)

from jax import lax as _lax


def axis_size(axis_name):
    """``lax.axis_size`` appeared after 0.4.37; ``psum`` of the literal 1
    is the portable spelling (constant-folded to the mapped axis size)."""
    if hasattr(_lax, "axis_size"):
        return _lax.axis_size(axis_name)
    return _lax.psum(1, axis_name)


__all__ = ["shard_map", "axis_size"]
