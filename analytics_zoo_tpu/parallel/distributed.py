"""Multi-host (pod) execution: jax.distributed bootstrap + per-host feeding.

The reference is, before anything else, a *distributed* training system:
synchronous data-parallel SGD where every Spark executor feeds its local
partition and gradients are AllReduced (reference: docs/docs/wp-bigdl.md:
113-160).  Its hard input contract — ``batch_size % total_core_num == 0``
(reference: pyzoo/zoo/pipeline/api/net.py:458-468) — is exactly the
per-host feeding invariant of a TPU pod: each host process feeds its local
shard of the global batch, and ``jax.make_array_from_process_local_data``
assembles the global device array without any cross-host data motion.

TPU-first shape: one JAX process per TPU host (the reference's "single
multi-threaded task per worker", wp-bigdl.md:169-171); the cluster
bootstrap is ``jax.distributed.initialize`` (coordinator + process id from
env), after which ``jax.devices()`` is the *global* device list and every
jit'd step is a pod-wide SPMD program with XLA-inserted collectives over
ICI/DCN — the entire "2 Spark jobs per iteration" structure collapses into
one compiled step.

Env contract (set by the ``zoo-tpu-submit`` launcher, or by the cloud
runtime on real pods where ``jax.distributed.initialize()`` auto-detects):

  ZOO_TPU_COORDINATOR   host:port of process 0  (alias JAX_COORDINATOR_ADDRESS)
  ZOO_TPU_NUM_PROCESSES number of host processes (alias JAX_NUM_PROCESSES)
  ZOO_TPU_PROCESS_ID    this process's rank      (alias JAX_PROCESS_ID)
"""

from __future__ import annotations

import logging
import os
from typing import Optional

from .. import envcontract

log = logging.getLogger("analytics_zoo_tpu")

ENV_COORD = "ZOO_TPU_COORDINATOR"
ENV_NPROC = "ZOO_TPU_NUM_PROCESSES"
ENV_PID = "ZOO_TPU_PROCESS_ID"

_INITIALIZED = False


def cluster_env_present() -> bool:
    """True when multi-process env vars are set (launcher or cloud)."""
    return bool(envcontract.env_str(ENV_COORD)
                or os.environ.get("JAX_COORDINATOR_ADDRESS")
                or envcontract.env_str(ENV_NPROC)
                or os.environ.get("JAX_NUM_PROCESSES"))


def maybe_initialize_distributed() -> bool:
    """Join the pod-wide cluster when cluster env vars are present.

    Must run before any other JAX call initializes the backend (the same
    ordering constraint as the reference's Engine.init-before-use,
    NNContext.scala:132-146).  Returns True when this process is part of a
    multi-process cluster after the call.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return True
    if not cluster_env_present():
        return False
    import jax

    coord = (envcontract.env_str(ENV_COORD)
             or os.environ.get("JAX_COORDINATOR_ADDRESS"))
    nproc = (envcontract.env_str(ENV_NPROC)
             or os.environ.get("JAX_NUM_PROCESSES"))
    pid = (envcontract.env_str(ENV_PID)
           or os.environ.get("JAX_PROCESS_ID"))
    requested = os.environ.get("JAX_PLATFORMS", "").strip()
    if requested:
        # honor the launcher's platform choice explicitly — an installed
        # accelerator plugin can otherwise pre-empt the env var and pull
        # a simulated pod onto the real device
        try:
            jax.config.update("jax_platforms", requested)
        except Exception as e:
            log.warning(
                "could not force jax platform %r (%s) — if the backend "
                "was already initialized on an accelerator plugin, this "
                "pod process may run on the wrong platform", requested, e)
    if requested == "cpu":
        # multi-process CPU (the test/dryrun substrate — SURVEY §4's
        # "local device = cluster" trick at process granularity) needs the
        # gloo collectives implementation
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # older/newer jaxlib without the option
            pass
    kwargs = {}
    if coord:
        kwargs["coordinator_address"] = coord
    if nproc:
        kwargs["num_processes"] = int(nproc)
    if pid is not None:
        kwargs["process_id"] = int(pid)
    try:
        jax.distributed.initialize(**kwargs)
    except RuntimeError as e:
        if "already initialized" not in str(e):
            raise
    _INITIALIZED = True
    log.info("jax.distributed: process %d/%d, %d local / %d global devices",
             jax.process_index(), jax.process_count(),
             jax.local_device_count(), jax.device_count())
    # first liveness touch at cluster join (local import: this module
    # loads during the train package's own import) — the supervisor's
    # watchdog then covers the first-compile window too, not just
    # steady-state steps (train/faults.py; size --watchdog-sec above
    # the longest compile + step)
    from ..train import faults
    faults.refresh()
    faults.heartbeat()
    return True


def process_count() -> int:
    import jax
    return jax.process_count()


def process_index() -> int:
    import jax
    return jax.process_index()


def is_coordinator() -> bool:
    return process_index() == 0


def put_global(a, sharding, batch_sharded: bool = True,
               batch_dim: int = 0):
    """Place a host-local array onto the (possibly multi-host) mesh.

    Single-process: a plain asynchronous ``device_put`` (per-shard: each
    device's slice transfers independently, so uploads overlap compute
    across the mesh).  Multi-process with ``batch_sharded``: ``a`` is
    this host's shard of the global batch along ``batch_dim``, and the
    global array is assembled from every process's local data — the
    TPU-native analog of the reference's partition→core feeding
    (net.py:458-468).  ``batch_dim`` is 0 for plain batches and 1 for
    gradient-accumulation microbatch layouts (accum, micro, ...), where
    the scanned leading axis is common to all processes.  With
    ``batch_sharded=False`` the same ``a`` must be provided by every
    process (replicated placement).
    """
    import jax

    if jax.process_count() == 1:
        return jax.device_put(a, sharding)
    if batch_sharded:
        global_shape = list(a.shape)
        global_shape[batch_dim] *= jax.process_count()
        return jax.make_array_from_process_local_data(
            sharding, a, tuple(global_shape))
    return jax.make_array_from_process_local_data(sharding, a,
                                                  tuple(a.shape))


def local_rows(arr):
    """Host numpy view of the rows of a batch-sharded global array that are
    addressable from this process (i.e. the rows this host fed) in global
    row order.  Handles outputs additionally sharded along trailing axes
    (tensor-parallel logits): trailing dims are assembled to their full
    global extent.  Single-process this is the whole array."""
    import numpy as np
    import jax

    if jax.process_count() == 1:
        return np.asarray(jax.device_get(arr))
    shards = list(arr.addressable_shards)
    if not shards[0].index:  # scalar / fully replicated
        return np.asarray(shards[0].data)
    # distinct leading-axis extents this host holds, in global order
    lead = sorted({((s.index[0].start or 0),
                    (s.index[0].stop if s.index[0].stop is not None
                     else arr.shape[0])) for s in shards})
    offsets = {}
    total = 0
    for start, stop in lead:
        offsets[start] = total
        total += stop - start
    out = np.empty((total,) + tuple(arr.shape[1:]), arr.dtype)
    for s in shards:
        start = s.index[0].start or 0
        stop = (s.index[0].stop if s.index[0].stop is not None
                else arr.shape[0])
        r0 = offsets[start]
        # trailing indices stay in global coordinates (out spans them)
        out[(slice(r0, r0 + (stop - start)),) + tuple(s.index[1:])] = \
            np.asarray(s.data)
    return out


