"""Parameter sharding rules: pytree -> NamedSharding tree.

The reference distributes weights by replication only (BigDL task-side
broadcast, wp-bigdl.md:142-160).  Here params can additionally be sharded:

* ``fsdp`` — ZeRO-style: shard every large param's biggest divisible axis
  over the fsdp mesh axis; XLA inserts all-gather on use and reduce-scatter
  on gradients (rides ICI).
* ``tensor`` — megatron-style rules by param-name pattern for the layers
  that support it (Dense kernels alternate column/row split).

Rules produce a sharding pytree consumed by ``jax.jit(in_shardings=...)``;
XLA then places all collectives — no hand-written communication.
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def replicated_tree(params, mesh: Mesh):
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda _: sharding, params)


def fsdp_tree(params, mesh: Mesh, axis: str = "fsdp",
              min_size: int = 2 ** 14):
    """Shard each large param along its largest axis divisible by the fsdp
    axis size; small params stay replicated (gather cost > memory win)."""
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return replicated_tree(params, mesh)
    n = mesh.shape[axis]

    def rule(p):
        shape = np.shape(p)
        if len(shape) == 0:
            # rank-0 leaf (scalar gain/temperature): nothing to shard,
            # and np.prod(()) must never reach the size test
            return NamedSharding(mesh, P())
        if np.prod(shape, dtype=np.int64) < min_size:
            return NamedSharding(mesh, P())
        # largest divisible axis; ties break toward the EARLIEST dim so
        # the choice is deterministic across shape permutations (a
        # square kernel must shard the same axis on every process — the
        # spec is part of the checkpoint/compile contract)
        cands = [(d, i) for i, d in enumerate(shape) if d % n == 0]
        if not cands:
            return NamedSharding(mesh, P())
        _, idx = min(cands, key=lambda c: (-c[0], c[1]))
        spec = [None] * len(shape)
        spec[idx] = axis
        return NamedSharding(mesh, P(*spec))

    return jax.tree_util.tree_map(rule, params)


def tensor_parallel_tree(params, mesh: Mesh, rules: Dict[str, Any],
                         axis: str = "tensor"):
    """Apply megatron-style rules: map param-path regex -> axis index to
    shard over the tensor axis.  Unmatched params replicate."""
    if axis not in mesh.axis_names or mesh.shape[axis] == 1:
        return replicated_tree(params, mesh)
    flat = jax.tree_util.tree_flatten_with_path(params)[0]
    treedef = jax.tree_util.tree_structure(params)
    out = []
    for path, leaf in flat:
        path_str = "/".join(str(getattr(k, "key", k)) for k in path)
        sharding = NamedSharding(mesh, P())
        for pattern, dim in rules.items():
            if re.search(pattern, path_str):
                shape = np.shape(leaf)
                if len(shape) > dim and shape[dim] % mesh.shape[axis] == 0:
                    spec = [None] * len(shape)
                    spec[dim] = axis
                    sharding = NamedSharding(mesh, P(*spec))
                break
        out.append(sharding)
    return jax.tree_util.tree_unflatten(treedef, out)


def combine_spec_trees(base, overlay):
    """Per-dimension merge of two NamedSharding trees.

    For each param, the overlay's axis assignments win on the dims they
    name; the base fills the remaining dims — UNLESS the base would reuse
    a mesh axis the overlay already consumed (a PartitionSpec may not
    mention one axis twice).  This keeps fsdp and tensor sharding on the
    *same* param consistent (e.g. a Dense kernel becomes
    P('fsdp', 'tensor')) instead of either/or — the either/or merge made
    GSPMD fully rematerialize activation gradients ("[SPMD] Involuntary
    full rematerialization") because the partitioner had to hop between
    disjoint shardings mid-backprop."""

    def _axes_of(spec):
        out = set()
        for entry in spec:
            if entry is None:
                continue
            if isinstance(entry, (tuple, list)):
                out.update(entry)
            else:
                out.add(entry)
        return out

    def combine(b, o):
        if o.spec == P():
            return b
        if b.spec == P():
            return o
        bspec, ospec = list(b.spec), list(o.spec)
        rank = max(len(bspec), len(ospec))
        bspec += [None] * (rank - len(bspec))
        ospec += [None] * (rank - len(ospec))
        taken = _axes_of(ospec)
        out = []
        for bb, oo in zip(bspec, ospec):
            if oo is not None:
                out.append(oo)
            elif bb is not None and not (_axes_of([bb]) & taken):
                out.append(bb)
            else:
                out.append(None)
        return NamedSharding(b.mesh, P(*out))

    return jax.tree_util.tree_map(combine, base, overlay)


def opt_state_sharding_tree(opt_state, params, param_shardings,
                            mesh: Mesh):
    """ZeRO-style optimizer-state plan: shard each moment WITH its param.

    Optax states embed param-shaped copies of the parameter tree (Adam's
    ``mu``/``nu``, momentum's ``trace``) under the parameter's own
    subtree path; everything else (step counts, schedule scalars) is
    housekeeping.  For every optimizer-state leaf whose tree path ENDS
    with a parameter's path and whose shape matches, return that
    parameter's sharding; all other leaves replicate.  The result is a
    sharding pytree with ``opt_state``'s structure, consumable directly
    as a ``jax.jit`` in/out sharding — the piece that turns "fsdp params"
    into "fsdp train state" (N replicated Adam moments -> 1/N per chip).
    """
    repl = NamedSharding(mesh, P())
    by_path: Dict[tuple, Any] = {}
    p_flat = jax.tree_util.tree_flatten_with_path(params)[0]
    s_leaves = jax.tree_util.tree_leaves(
        param_shardings, is_leaf=lambda l: isinstance(l, NamedSharding))
    for (path, leaf), sh in zip(p_flat, s_leaves):
        key = tuple(str(k) for k in path)
        by_path[key] = (tuple(np.shape(leaf)), sh)

    o_flat, treedef = jax.tree_util.tree_flatten_with_path(opt_state)
    out = []
    for path, leaf in o_flat:
        keys = tuple(str(k) for k in path)
        shape = tuple(np.shape(leaf))
        sharding = repl
        # deepest (longest) param-path suffix with a matching shape wins
        for klen in range(len(keys), 0, -1):
            hit = by_path.get(keys[-klen:])
            if hit is not None and hit[0] == shape:
                sharding = hit[1]
                break
        out.append(sharding)
    return jax.tree_util.tree_unflatten(treedef, out)


def shard_params(params, mesh: Mesh, strategy: str = "replicate",
                 tp_rules: Optional[Dict[str, int]] = None,
                 fsdp_min_size: int = 2 ** 14):
    """Resolve a named strategy into a sharding pytree."""
    if strategy in ("replicate", "dp"):
        tree = replicated_tree(params, mesh)
    elif strategy == "fsdp":
        tree = fsdp_tree(params, mesh, min_size=fsdp_min_size)
    elif strategy in ("tp", "tensor"):
        tree = tensor_parallel_tree(params, mesh, tp_rules or {})
    elif strategy in ("fsdp_tp", "fsdp+tp"):
        tree = combine_spec_trees(
            fsdp_tree(params, mesh, min_size=fsdp_min_size),
            tensor_parallel_tree(params, mesh, tp_rules or {}))
    else:
        raise ValueError(f"Unknown sharding strategy {strategy!r}")
    return tree
