"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

The reference has no sequence parallelism (SURVEY §2.10) — this is the
first-class long-context component of the TPU build.  Design: shard the
sequence axis of q/k/v across devices; each device computes online-softmax
attention of its local q block against the k/v shard it currently holds,
then rotates k/v around the ring with ``lax.ppermute`` over ICI.  After
n_devices steps every q block has seen every k/v block, with peak memory
O(seq/n) per device and communication overlapping compute (the
blockwise-parallel-transformers / ring-attention formulation).

Causality is handled with global positions: shard s of the sequence owns
positions [s·L, (s+1)·L); masks compare global q/k positions, so rotated
blocks that are entirely in the future contribute nothing.
"""

from __future__ import annotations

import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import axis_size, shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _local_attention_accumulate(q, k_blk, v_blk, q_offset, k_offset,
                                causal, scale, carry, kv_lengths=None):
    """One ring step: accumulate online-softmax stats for local q against
    one rotated k/v shard.  ``kv_lengths``: optional (batch,) GLOBAL
    valid key counts — global key positions >= kv_lengths[b] are masked
    (right-padded batches)."""
    m_prev, l_prev, o_prev = carry
    scores = jnp.einsum("bqhd,bkhd->bhqk", q * scale, k_blk)
    sq, sk = q.shape[1], k_blk.shape[1]
    k_pos = k_offset + jnp.arange(sk)
    if causal:
        q_pos = q_offset + jnp.arange(sq)
        mask = q_pos[:, None] >= k_pos[None, :]
        scores = jnp.where(mask[None, None], scores, -1e30)
    if kv_lengths is not None:
        kmask = k_pos[None, :] < kv_lengths[:, None]  # (b, sk)
        scores = jnp.where(kmask[:, None, None, :], scores, -1e30)
    m_blk = jnp.max(scores, axis=-1)
    m_new = jnp.maximum(m_prev, m_blk)
    p = jnp.exp(scores - m_new[..., None])
    corr = jnp.exp(m_prev - m_new)
    l_new = l_prev * corr + jnp.sum(p, axis=-1)
    o_new = (o_prev * corr[..., None]
             + jnp.einsum("bhqk,bkhd->bhqd", p, v_blk))
    return m_new, l_new, o_new


def ring_attention(q, k, v, axis_name: str = "seq", causal: bool = False,
                   scale: Optional[float] = None, kv_lengths=None,
                   block_k: int = 1024):
    """Call INSIDE shard_map with q/k/v sharded on their seq axis.

    Shapes (local): (batch, seq_local, heads, head_dim).
    ``kv_lengths``: optional (batch,) GLOBAL valid key counts,
    replicated across the ring (each sequence must have >= 1 valid
    token; clamp before calling — the sharded wrapper does).

    ``block_k`` sub-blocks each held K/V shard inside a ring step, so
    per-device peak memory is O(seq_local · block_k) score tiles rather
    than O(seq_local · shard) — without it the score matrix per step is
    (seq/n)², which quietly reintroduces quadratic per-device memory as
    sequences grow at fixed ring size (measured: ring_report r5)."""
    b, sq, h, d = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    n = axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    q_offset = my_idx * sq
    shard = k.shape[1]
    from ..ops.attention import _largest_divisor
    block_k = _largest_divisor(shard, min(block_k, shard))
    if block_k < 8:
        # prime-ish shard: a tiny divisor would degrade each ring step
        # to a per-element scan — keep the whole-shard matmul instead
        # (same guard as the flash path's bwd_bk floor)
        block_k = shard
    n_sub = shard // block_k

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(i, carry):
        k_cur, v_cur, stats = carry
        # the shard currently held started at ((my_idx - i) mod n)·L
        src = (my_idx - i) % n
        base = src * shard

        def sub(j, st):
            k_blk = lax.dynamic_slice_in_dim(k_cur, j * block_k,
                                             block_k, axis=1)
            v_blk = lax.dynamic_slice_in_dim(v_cur, j * block_k,
                                             block_k, axis=1)
            return _local_attention_accumulate(
                q, k_blk, v_blk, q_offset, base + j * block_k, causal,
                scale, st, kv_lengths=kv_lengths)

        stats = lax.fori_loop(0, n_sub, sub, stats)
        # rotate for the next step (last rotation is redundant but keeps
        # the loop uniform; XLA overlaps it with the epilogue)
        k_nxt = lax.ppermute(k_cur, axis_name, perm)
        v_nxt = lax.ppermute(v_cur, axis_name, perm)
        return k_nxt, v_nxt, stats

    m0 = jnp.full((b, h, sq), -1e30)
    l0 = jnp.zeros((b, h, sq))
    o0 = jnp.zeros((b, h, sq, d))
    _, _, (m, l, o) = lax.fori_loop(0, n, step, (k, v, (m0, l0, o0)))
    out = o / jnp.maximum(l[..., None], 1e-30)
    return jnp.swapaxes(out, 1, 2).astype(q.dtype)


def ring_attention_sharded(q, k, v, mesh: Mesh, axis_name: str = "seq",
                           causal: bool = False, kv_lengths=None):
    """Convenience wrapper: shard (b, s, h, d) arrays on the seq axis and
    run ring attention under shard_map.  ``kv_lengths``: optional
    (batch,) GLOBAL valid key counts (replicated over the ring)."""
    spec = P(None, axis_name, None, None)
    if kv_lengths is None:
        fn = shard_map(
            functools.partial(ring_attention, axis_name=axis_name,
                              causal=causal),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_vma=False)
        return fn(q, k, v)
    from ..ops.attention import _clamp_lengths
    lens = _clamp_lengths(kv_lengths, k.shape[1])
    fn = shard_map(
        lambda q_, k_, v_, l_: ring_attention(
            q_, k_, v_, axis_name=axis_name, causal=causal,
            kv_lengths=l_),
        mesh=mesh, in_specs=(spec, spec, spec, P(None)),
        out_specs=spec, check_vma=False)
    return fn(q, k, v, lens)
