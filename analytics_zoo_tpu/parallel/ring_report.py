"""Ring-attention scaling evidence (VERDICT r4 #7): sequence length vs
per-device memory vs throughput, ring vs single-device.

Ring attention's reason to exist is sequences that do NOT fit one
device: activations stay sharded seq/n per device and K/V shards rotate
over the ring, so per-device peak memory is O(seq/n) while a
single-device pass holds the full O(seq) activations (and naive
attention O(seq²) scores).  This module makes that claim MEASURED, not
asserted: for each sequence length it compiles both formulations and
reads XLA's own per-device memory analysis (temp + argument bytes),
then executes them for wall-time — on the virtual 8-device CPU mesh
(SURVEY §4's local-cluster trick) or a real slice alike.

Usage::

    python -m analytics_zoo_tpu.parallel.ring_report
"""

from __future__ import annotations

import json
import time
from typing import Dict, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .ring_attention import ring_attention_sharded


def _mem(compiled) -> Optional[int]:
    """Per-device temp+argument bytes from XLA's memory analysis."""
    from .report_util import memory_analysis_bytes
    m = memory_analysis_bytes(compiled)
    return None if m is None else m["temp"] + m["argument"]


def _time_call(fn, *args, iters=3) -> float:
    """Warm once, then average ``iters`` timed calls (ms).  Works on a
    jitted function or an AOT-compiled executable alike — pass the
    compiled object to avoid a second trace+compile through the jit
    cache."""
    jax.block_until_ready(fn(*args))  # warm (compiles if not AOT)
    t0 = time.time()
    for _ in range(iters):
        jax.block_until_ready(fn(*args))
    return (time.time() - t0) / iters * 1e3


def compare_ring(mesh=None, seq_lengths: Sequence[int] = (2048, 8192,
                                                          32768),
                 batch: int = 1, heads: int = 2, head_dim: int = 64,
                 causal: bool = True, run_single_up_to: int = 8192,
                 run_ring_up_to: int = 8192, iters: int = 1) -> Dict:
    """Ring (sharded over the mesh's ``seq`` axis) vs single-device
    blockwise attention across ``seq_lengths``.

    ``run_single_up_to`` / ``run_ring_up_to`` bound which lengths each
    formulation is EXECUTED at; beyond them only the compiled per-device
    memory analysis is reported.  The memory column is the evidence that
    matters (ring exists exactly so the single-device run stops being
    necessary); CPU-mesh wall times are structural, not absolute — on a
    real slice raise both caps.
    Returns {seq: {ring: {...}, single: {...}}} with per-device bytes
    and wall ms.
    """
    from . import mesh as mesh_lib
    from ..ops.attention import blockwise_attention

    mesh = mesh or mesh_lib.get_default_mesh()
    if "seq" not in mesh.axis_names:
        raise ValueError("mesh must carry a 'seq' axis "
                         "(create_mesh({'seq': n}))")
    n = mesh.shape["seq"]
    rows: Dict[str, Dict] = {}
    rng = np.random.default_rng(0)
    for seq in seq_lengths:
        if seq % n:
            raise ValueError(f"seq {seq} not divisible by ring size {n}")
        mk = lambda: jnp.asarray(
            rng.normal(size=(batch, seq, heads, head_dim)), jnp.float32)
        q, k, v = mk(), mk(), mk()

        ring_fn = jax.jit(lambda q, k, v: ring_attention_sharded(
            q, k, v, mesh, causal=causal))
        single_fn = jax.jit(lambda q, k, v: blockwise_attention(
            q, k, v, causal=causal, block_k=min(1024, seq)))

        entry: Dict = {"ring": {}, "single_device": {}}
        # time the AOT executable directly — calling the jitted fn
        # would re-trace and compile a second time
        ring_c = ring_fn.lower(q, k, v).compile()
        entry["ring"]["per_device_bytes"] = _mem(ring_c)
        if seq <= run_ring_up_to:
            entry["ring"]["wall_ms"] = round(
                _time_call(ring_c, q, k, v, iters=iters), 1)
        else:
            entry["ring"]["wall_ms"] = None
        single_c = single_fn.lower(q, k, v).compile()
        entry["single_device"]["per_device_bytes"] = _mem(single_c)
        if seq <= run_single_up_to:
            entry["single_device"]["wall_ms"] = round(
                _time_call(single_c, q, k, v, iters=iters), 1)
        else:
            entry["single_device"]["wall_ms"] = None
            entry["single_device"]["note"] = (
                "not executed — beyond the single-device budget "
                "(memory analysis only)")
        rb, sb = (entry["ring"]["per_device_bytes"],
                  entry["single_device"]["per_device_bytes"])
        if rb and sb:
            entry["memory_ratio_single_over_ring"] = round(sb / rb, 2)
        rows[str(seq)] = entry
    return {"mesh": dict(mesh.shape), "batch": batch, "heads": heads,
            "head_dim": head_dim, "causal": causal,
            "ring_devices": n, "rows": rows}


def main():
    from .report_util import force_cpu_mesh_env
    force_cpu_mesh_env()
    from . import mesh as mesh_lib
    mesh = mesh_lib.create_mesh({"seq": 8})
    print(json.dumps(compare_ring(mesh), indent=2))


if __name__ == "__main__":
    main()
