"""Sharding-strategy comparison: step time + collective mix per strategy.

Round-2 review flagged the parallelism strategies as "correctness-tested
but performance-blind": the dryrun proves each strategy lowers, but
nothing compared them.  This module compiles the SAME training step under
each strategy on the current mesh and reports, per strategy:

* measured step wall-time (after warm-up);
* the collective operations GSPMD inserted (all-reduce / all-gather /
  reduce-scatter / collective-permute counts from the optimized HLO) —
  the communication structure the "How to Scale Your Model" recipe says
  to inspect;
* XLA cost-model flops and peak memory estimate when available.

Usage (works on the virtual CPU mesh — SURVEY §4's local-cluster trick):

    python -m analytics_zoo_tpu.parallel.strategy_report
"""

from __future__ import annotations

import json
import re
import time
from typing import Dict, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .report_util import force_cpu_mesh_env, memory_analysis_bytes

COLLECTIVES = ("all-reduce", "all-gather", "reduce-scatter",
               "collective-permute", "all-to-all")


def _collective_counts(hlo_text: str) -> Dict[str, int]:
    counts = {}
    for op in COLLECTIVES:
        # count op instructions (start variants cover async collectives)
        n = len(re.findall(rf"\b{op}(?:-start)?(?:\.\d+)?\s*=", hlo_text))
        if n:
            counts[op] = n
    return counts


def compare_strategies(mesh=None,
                       strategies: Sequence[str] = ("replicate", "fsdp",
                                                    "fsdp_tp"),
                       batch: Optional[int] = None, image_size: int = 32,
                       num_classes: int = 16, steps: int = 3,
                       tp_rules=None, model_fn=None) -> Dict:
    """Compile + run a train step under each strategy on ``mesh`` and
    measure.  ``model_fn(input_shape, num_classes) -> Model`` defaults to
    the tiny ResNet-50.  Returns {strategy: {...metrics}}."""
    from . import mesh as mesh_lib
    from . import sharding as sharding_lib
    from ..pipeline.api.keras import objectives
    from ..train.trainer import build_train_step
    import optax

    if model_fn is None:
        from ..models.image.classification import resnet50
        model_fn = resnet50

    mesh = mesh or mesh_lib.get_default_mesh()
    dp = mesh_lib.dp_size(mesh)
    batch = batch or max(dp * 2, 8)
    model = model_fn(input_shape=(image_size, image_size, 3),
                     num_classes=num_classes)
    graph = model.to_graph()
    loss_fn = objectives.get("sparse_categorical_crossentropy")
    optimizer = optax.sgd(1e-2, momentum=0.9)
    step_fn = build_train_step(graph, loss_fn, optimizer, jit=False)
    rng = np.random.default_rng(0)
    x_host = rng.normal(size=(batch, image_size, image_size, 3)).astype(
        np.float32)
    y_host = rng.integers(0, num_classes, batch).astype(np.int32)
    batch_sharding = mesh_lib.data_sharding(mesh)
    repl = mesh_lib.replicated(mesh)
    key = jax.random.PRNGKey(0)

    report: Dict[str, Dict] = {}
    for strategy in strategies:
        params, state = graph.init(jax.random.PRNGKey(0))
        shardings = sharding_lib.shard_params(
            params, mesh, strategy,
            **({"tp_rules": tp_rules or {r"fc1000/W": 1}}
               if strategy in ("tensor", "fsdp_tp") else {}),
            **({"fsdp_min_size": 2 ** 10}
               if strategy in ("fsdp", "fsdp_tp") else {}))
        params = jax.tree_util.tree_map(jax.device_put, params, shardings)
        state = jax.device_put(state, repl)
        # optimizer state initialized from PLACED params so its moment
        # buffers share their shardings (same convention as the Trainer)
        # — the AOT executable requires outputs fed back as inputs to
        # keep exactly these shardings
        opt_state = jax.tree_util.tree_map(
            lambda leaf: (leaf if isinstance(leaf, jax.Array)
                          and hasattr(leaf.sharding, "spec")
                          else jax.device_put(leaf, repl)),
            optimizer.init(params))
        x = jax.device_put(x_host, batch_sharding)
        y = jax.device_put(y_host, batch_sharding)
        # pin outputs to the input shardings: the step is state→state, so
        # forcing the fixed point keeps the AOT executable's fed-back
        # arguments valid (GSPMD may otherwise re-shard e.g. a momentum
        # leaf on output and the exact-sharding AOT call rejects it)
        sh_of = lambda leaf: (leaf.sharding
                              if isinstance(leaf, jax.Array)
                              and hasattr(leaf.sharding, "spec") else repl)
        out_sh = (jax.tree_util.tree_map(sh_of, params),
                  jax.tree_util.tree_map(sh_of, state),
                  jax.tree_util.tree_map(sh_of, opt_state),
                  repl)
        jitted = jax.jit(step_fn, out_shardings=out_sh)
        # trace under the REPORT's mesh as the active mesh so mesh-aware
        # layers (SwitchMoE expert sharding, ring attention) take the
        # same path here as they would under a Trainer compiled with
        # this mesh — otherwise the report's collective counts could
        # disagree with real training
        from ..pipeline.api.keras.layers import moe as moe_layer
        moe_layer.clear_fallback_log()
        with mesh_lib.active_mesh(mesh):
            compiled = jitted.lower(params, state, opt_state, key, x,
                                    y).compile()
        entry: Dict = {}
        if moe_layer.EXPERT_FALLBACKS:
            # a SwitchMoE ran replicated despite an expert axis — the
            # report must say so next to the numbers it affects
            entry["moe_fallbacks"] = dict(moe_layer.EXPERT_FALLBACKS)
        try:
            entry["collectives"] = _collective_counts(compiled.as_text())
        except Exception:
            entry["collectives"] = None
        try:
            cost = compiled.cost_analysis()
            c = cost[0] if isinstance(cost, (list, tuple)) else cost
            if c:
                entry["flops"] = float(c.get("flops", 0))
                entry["bytes_accessed"] = float(c.get("bytes accessed", 0))
        except Exception:
            pass
        mem = memory_analysis_bytes(compiled)
        if mem is not None:
            entry["temp_bytes"] = mem["temp"]
            entry["argument_bytes"] = mem["argument"]
        # warm-up + timed steps through the AOT executable (calling
        # jitted(...) would re-trace and compile a second time)
        params, state, opt_state, loss = compiled(params, state,
                                                  opt_state, key, x, y)
        _ = float(loss)
        t0 = time.time()
        for _i in range(steps):
            params, state, opt_state, loss = compiled(params, state,
                                                      opt_state, key, x, y)
        _ = float(loss)
        entry["step_ms"] = round((time.time() - t0) / steps * 1e3, 2)
        # bytes of parameters each device holds (the fsdp win)
        entry["per_device_param_bytes"] = int(sum(
            leaf.addressable_shards[0].data.nbytes
            for leaf in jax.tree_util.tree_leaves(params)))
        report[strategy] = entry
        del params, state, opt_state
    return {"mesh": dict(mesh.shape), "batch": batch,
            "device_kind": getattr(jax.devices()[0], "device_kind",
                                   jax.devices()[0].platform),
            "strategies": report}


def main():
    force_cpu_mesh_env()
    from . import mesh as mesh_lib
    mesh = mesh_lib.create_mesh({"data": 2, "fsdp": 2, "tensor": 2})
    mesh_lib.set_default_mesh(mesh)
    print(json.dumps(compare_strategies(mesh), indent=2))


if __name__ == "__main__":
    main()
