"""Shared plumbing for the CLI report modules (strategy_report,
ring_report): the CPU-mesh bootstrap and the XLA memory-analysis
readout both reports need."""

from __future__ import annotations

import os
from typing import Optional


def force_cpu_mesh_env(device_count: int = 8) -> None:
    """Pin this process to a virtual multi-device CPU platform.

    Must run before the first jax backend use.  Sets JAX_PLATFORMS (the
    environment's TPU tunnel plugin pre-empts the env var alone, hence
    also jax.config) and injects the host-platform device count unless
    an XLA_FLAGS already carries one."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags +
            f" --xla_force_host_platform_device_count={device_count}"
        ).strip()
    import jax
    jax.config.update("jax_platforms",
                      os.environ.get("JAX_PLATFORMS", "cpu"))


def memory_analysis_bytes(compiled) -> Optional[dict]:
    """Per-device {temp, argument} bytes from a compiled executable's
    XLA memory analysis, or None when the backend doesn't expose it."""
    try:
        m = compiled.memory_analysis()
        if m is None:
            return None
        return {"temp": int(getattr(m, "temp_size_in_bytes", 0)),
                "argument": int(getattr(m, "argument_size_in_bytes", 0))}
    except Exception:
        return None
