"""Pipeline parallelism: GPipe-style microbatched execution over the
``pipe`` mesh axis.

The reference has no pipeline parallelism (SURVEY §2.10); like ring
attention and expert parallelism this is first-class TPU-native scope.
Stage s of a homogeneous layer stack lives on device s of the ``pipe``
axis; microbatches flow through the ring with ``lax.ppermute`` over ICI,
so at steady state every stage computes a different microbatch
concurrently — the schedule is the classic GPipe fill/steady/drain
(n_micro + n_stages - 1 steps).

Constraints (the standard homogeneous-pipeline shape):
  * every stage runs the SAME ``stage_fn`` with its own params slice
    (params pytree leaves carry a leading n_stages axis, sharded over
    ``pipe``);
  * activations keep one shape across stages (width-preserving blocks).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _pipeline_local(x, params, stage_fn: Callable, n_micro: int,
                    axis_name: str):
    """Per-device body under shard_map.  ``x`` is the full input
    (replicated); ``params`` is this stage's slice (leading axis
    squeezed by the P(axis_name) spec to size 1 -> index [0])."""
    n_stages = axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    local_params = jax.tree_util.tree_map(lambda p: p[0], params)

    mb = x.reshape((n_micro, x.shape[0] // n_micro) + x.shape[1:])
    mb_shape = mb.shape[1:]
    n_steps = n_micro + n_stages - 1
    # receive buffer + output accumulator
    recv0 = jnp.zeros(mb_shape, x.dtype)
    out0 = jnp.zeros((n_micro,) + mb_shape, x.dtype)
    # ring: stage s sends to s+1 (last stage's send is dropped)
    perm = [(i, (i + 1) % n_stages) for i in range(n_stages)]

    def step(carry, t):
        recv, out = carry
        inp = jnp.where(stage == 0, mb[jnp.minimum(t, n_micro - 1)], recv)
        y = stage_fn(local_params, inp)
        # last stage at step t finished microbatch t - (n_stages - 1)
        idx = t - (n_stages - 1)
        valid = (stage == n_stages - 1) & (idx >= 0)
        out = lax.dynamic_update_index_in_dim(
            out, jnp.where(valid, y, out[jnp.maximum(idx, 0)]),
            jnp.maximum(idx, 0), axis=0)
        recv = lax.ppermute(y, axis_name, perm)
        return (recv, out), None

    (_, out), _ = lax.scan(step, (recv0, out0), jnp.arange(n_steps))
    # only the last stage's accumulator is real; broadcast it to every
    # stage so the result is replicated over the pipe axis
    out = out * jnp.where(stage == n_stages - 1, 1.0, 0.0).astype(out.dtype)
    out = lax.psum(out, axis_name)
    return out.reshape(x.shape)


def pipeline_apply(stage_fn: Callable, stage_params, x, mesh: Mesh,
                   axis_name: str = "pipe",
                   n_microbatches: Optional[int] = None):
    """Run ``x`` through ``n_stages`` copies of ``stage_fn`` pipelined
    over the mesh's ``axis_name`` axis.

    ``stage_params``: pytree whose leaves have a leading n_stages axis
    (stage s uses leaf[s]); ``stage_fn(params_slice, x) -> y`` with
    ``y.shape == x.shape``.  Returns the output replicated across the
    pipe axis.  ``n_microbatches`` defaults to the stage count (GPipe's
    minimum for full overlap; more microbatches shrink the bubble).
    """
    n_stages = mesh.shape[axis_name]
    leaves = jax.tree_util.tree_leaves(stage_params)
    if not leaves or leaves[0].shape[0] != n_stages:
        raise ValueError(
            f"stage_params leaves need leading axis {n_stages} "
            f"(the {axis_name!r} mesh axis); got "
            f"{leaves[0].shape if leaves else 'no leaves'}")
    n_micro = n_stages if n_microbatches is None else n_microbatches
    if n_micro < 1:
        raise ValueError(f"n_microbatches must be >= 1, got {n_micro}")
    if x.shape[0] % n_micro:
        raise ValueError(
            f"batch ({x.shape[0]}) is not divisible by n_microbatches "
            f"({n_micro})")
    pspec = jax.tree_util.tree_map(lambda _: P(axis_name), stage_params)
    fn = shard_map(
        functools.partial(_pipeline_local, stage_fn=stage_fn,
                          n_micro=n_micro, axis_name=axis_name),
        mesh=mesh, in_specs=(P(), pspec), out_specs=P(),
        check_vma=False)
    return fn(x, stage_params)
