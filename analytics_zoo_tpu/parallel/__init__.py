from .mesh import (create_mesh, data_sharding, replicated, dp_size,
                   get_default_mesh, set_default_mesh)
from . import sharding
from .ring_attention import ring_attention, ring_attention_sharded
from .expert import (MoEParams, init_moe_params, switch_moe, moe_sharded,
                     expert_capacity)
from .pipeline import pipeline_apply
