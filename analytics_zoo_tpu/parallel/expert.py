"""Expert parallelism: switch-routed mixture-of-experts over the
``expert`` mesh axis.

The reference has no MoE (SURVEY §2.10 — data parallelism only); like
ring attention this is first-class TPU-native scope: experts live
sharded across devices, tokens travel to their expert via
``lax.all_to_all`` over ICI, and the whole dispatch→compute→combine is
one compiled SPMD program.

Design (Switch-Transformer-style top-1 routing with capacity):
  * gate: logits = x @ Wg over ALL experts; each token picks argmax;
  * capacity C bounds tokens per expert (static shapes under jit);
    tokens beyond capacity are dropped — their output is 0, which a
    residual connection turns into identity pass-through;
  * dispatch/combine are einsums against a (tokens, experts, capacity)
    one-hot — the standard dense-dispatch formulation;
  * expert-parallel path: dispatched blocks all_to_all from
    (token-shard, all experts) layout to (expert-shard, all tokens)
    layout, local experts apply, all_to_all back, combine.

``switch_moe`` is the single-device reference; ``moe_sharded`` runs the
same math with experts sharded over the mesh's ``expert`` axis.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
from jax import lax
from ._compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P


class MoEParams(NamedTuple):
    """Weights of a switch-MoE FFN block.

    gate:  (d_model, n_experts)
    w1:    (n_experts, d_model, d_hidden)
    b1:    (n_experts, d_hidden)
    w2:    (n_experts, d_hidden, d_model)
    b2:    (n_experts, d_model)
    """

    gate: jnp.ndarray
    w1: jnp.ndarray
    b1: jnp.ndarray
    w2: jnp.ndarray
    b2: jnp.ndarray


def init_moe_params(rng, d_model: int, d_hidden: int, n_experts: int,
                    dtype=jnp.float32) -> MoEParams:
    kg, k1, k2 = jax.random.split(rng, 3)
    s1 = 1.0 / math.sqrt(d_model)
    s2 = 1.0 / math.sqrt(d_hidden)
    return MoEParams(
        gate=jax.random.normal(kg, (d_model, n_experts), dtype) * s1,
        w1=jax.random.normal(k1, (n_experts, d_model, d_hidden),
                             dtype) * s1,
        b1=jnp.zeros((n_experts, d_hidden), dtype),
        w2=jax.random.normal(k2, (n_experts, d_hidden, d_model),
                             dtype) * s2,
        b2=jnp.zeros((n_experts, d_model), dtype))


def expert_capacity(n_tokens: int, n_experts: int,
                    capacity_factor: float) -> int:
    return max(1, int(math.ceil(n_tokens / n_experts * capacity_factor)))


def _route(x, gate_w, n_experts: int, capacity: int):
    """Top-1 routing -> (dispatch one-hot (T, E, C), combine weights
    (T, E, C), per-shard expert-load stats for the aux loss).

    Queue positions are computed with an int32 cumsum regardless of
    ``x.dtype`` — a bf16 cumsum is only exact to 256, after which
    colliding capacity slots silently sum multiple tokens into one
    expert row.  Only the final dispatch/combine tensors take x's dtype.
    """
    logits = (x @ gate_w).astype(jnp.float32)      # (T, E)
    probs = jax.nn.softmax(logits, axis=-1)
    expert_idx = jnp.argmax(probs, axis=-1)        # (T,)
    int_1h = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.int32)
    # position of each token within its expert's queue (exact int math)
    pos_in_expert = (jnp.cumsum(int_1h, axis=0) - 1) * int_1h
    keep = (pos_in_expert < capacity) * int_1h     # (T, E) 0/1
    pos = jnp.sum(pos_in_expert * keep, axis=-1)   # (T,)
    pos_1h = jax.nn.one_hot(pos, capacity, dtype=jnp.int32)
    dispatch = (keep[:, :, None] * pos_1h[:, None, :]).astype(x.dtype)
    gate_val = jnp.sum(probs * int_1h, axis=-1)    # (T,) f32
    combine = dispatch * gate_val.astype(x.dtype)[:, None, None]
    # Switch load-balancing stats: fraction routed / mean prob per expert
    f = jnp.mean(int_1h.astype(jnp.float32), axis=0)
    p = jnp.mean(probs, axis=0)
    return dispatch, combine, (f, p)


def _apply_experts(blocks, w1, b1, w2, b2):
    """blocks (E, C, d) through each expert's 2-layer relu FFN."""
    h = jnp.einsum("ecd,edh->ech", blocks, w1) + b1[:, None, :]
    h = jax.nn.relu(h)
    return jnp.einsum("ech,ehd->ecd", h, w2) + b2[:, None, :]


def switch_moe(x, params: MoEParams, capacity_factor: float = 1.25,
               capacity: Optional[int] = None):
    """Single-device reference: x (tokens, d_model) -> (out, aux_loss).

    Dropped (over-capacity) tokens produce 0 — add the residual outside.
    """
    t, d = x.shape
    n_experts = params.gate.shape[-1]
    c = capacity if capacity is not None else expert_capacity(
        t, n_experts, capacity_factor)
    dispatch, combine, (f, p) = _route(x, params.gate, n_experts, c)
    aux = n_experts * jnp.sum(f * p)
    blocks = jnp.einsum("tec,td->ecd", dispatch, x)       # (E, C, d)
    outs = _apply_experts(blocks, params.w1, params.b1, params.w2,
                          params.b2)
    return jnp.einsum("tec,ecd->td", combine, outs), aux


def _moe_local(x, params: MoEParams, n_experts: int, capacity: int,
               axis_name: str):
    """Per-device body under shard_map: x is this device's token shard,
    expert weights are this device's expert shard."""
    n = axis_size(axis_name)
    e_local = n_experts // n
    # routing needs ALL experts' gate columns — gate is replicated
    dispatch, combine, (f, p) = _route(x, params.gate, n_experts,
                                       capacity)
    # aux loss over GLOBAL routing stats (pmean f and p BEFORE the
    # product) so sharded and single-device training see the same
    # gate gradients even when routing is uneven across token shards
    f = lax.pmean(f, axis_name)
    p = lax.pmean(p, axis_name)
    aux = n_experts * jnp.sum(f * p)
    blocks = jnp.einsum("tec,td->ecd", dispatch, x)       # (E, C, d)
    # (E, C, d) -> (n, E_local, C, d): send each expert block to its
    # owner; receive every device's blocks for MY experts
    d = blocks.shape[-1]
    blocks = blocks.reshape(n, e_local, capacity, d)
    blocks = lax.all_to_all(blocks, axis_name, split_axis=0,
                            concat_axis=0, tiled=False)
    # now (n, E_local, C, d): axis 0 = SOURCE device.  Fold the source
    # axis into the expert queue: (E_local, n*C, d)
    blocks = jnp.transpose(blocks, (1, 0, 2, 3)).reshape(
        e_local, n * capacity, d)
    outs = _apply_experts(blocks, params.w1, params.b1, params.w2,
                          params.b2)
    # unfold and ship each source's results home
    outs = jnp.transpose(outs.reshape(e_local, n, capacity, d),
                         (1, 0, 2, 3))
    outs = lax.all_to_all(outs, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)
    # axis 0 = expert-OWNER device; global expert id = owner*E_local + e
    outs = outs.reshape(n_experts, capacity, d)
    y = jnp.einsum("tec,ecd->td", combine, outs)
    return y, aux


def moe_sharded(x, params: MoEParams, mesh: Mesh,
                axis_name: str = "expert",
                capacity_factor: float = 1.25):
    """Expert-parallel switch MoE: tokens sharded over ``axis_name``,
    experts sharded over the same axis (w1/b1/w2/b2 leading dim), gate
    replicated.  x: (tokens, d_model) global.

    Each device routes its token shard against ALL experts, all_to_all
    ships dispatched blocks to the expert owners over ICI, local experts
    run, and a second all_to_all brings results home.
    """
    n = mesh.shape[axis_name]
    t = x.shape[0]
    n_experts = params.gate.shape[-1]
    if n_experts % n:
        raise ValueError(
            f"n_experts ({n_experts}) is not divisible by the "
            f"{axis_name!r} axis size ({n})")
    if t % n:
        raise ValueError(
            f"tokens ({t}) are not divisible by the {axis_name!r} "
            f"axis size ({n})")
    # capacity per LOCAL token shard (same queue depth every device)
    capacity = expert_capacity(t // n, n_experts, capacity_factor)
    espec = P(axis_name)
    fn = shard_map(
        functools.partial(_moe_local, n_experts=n_experts,
                          capacity=capacity, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(axis_name), MoEParams(P(None, None), espec, espec,
                                          espec, espec)),
        out_specs=(P(axis_name), P()),
        check_vma=False)
    return fn(x, params)
