"""Fraud detection: imbalanced binary classification scored by AUC.

Reference analog: apps/fraud-detection (creditcard transactions, heavy
class imbalance, AUC as the metric of record).
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=6)
    ap.add_argument("--fraud-rate", type=float, default=0.03)
    args = ap.parse_args()

    from analytics_zoo_tpu.pipeline.api.keras.engine import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers.core import (
        Dense, Dropout)

    rs = np.random.RandomState(0)
    n, d = 4096, 12
    y = (rs.rand(n) < args.fraud_rate).astype(np.int32)
    x = rs.randn(n, d).astype(np.float32)
    x[y == 1] += rs.randn(int(y.sum()), d).astype(np.float32) * 0.5 + 1.2

    # oversample the minority class (the notebook's rebalancing step)
    fraud_idx = np.nonzero(y == 1)[0]
    boost = rs.choice(fraud_idx, size=len(fraud_idx) * 10)
    xb = np.concatenate([x, x[boost]])
    yb = np.concatenate([y, y[boost]])
    order = rs.permutation(len(xb))
    xb, yb = xb[order], yb[order]

    model = Sequential(name="fraud_mlp")
    model.add(Dense(32, activation="relu", input_shape=(d,)))
    model.add(Dropout(0.3))
    model.add(Dense(16, activation="relu"))
    model.add(Dense(2, activation="softmax"))
    model.compile(optimizer="adam",
                  loss="sparse_categorical_crossentropy",
                  metrics=["auc"])
    model.fit(xb, yb, batch_size=128, nb_epoch=args.epochs)

    result = model.evaluate(x, y, batch_size=256)
    print("held-out metrics:", result)
    assert result["auc"] > 0.8, "AUC should beat chance comfortably"


if __name__ == "__main__":
    main()
