"""Explicit-feedback Neural Collaborative Filtering on MovieLens.

Reference analog: apps/recommendation-ncf/ncf-explicit-feedback.ipynb —
load MovieLens ratings, 80/20 split, NeuralCF(class_num=5), Adam,
validation (MAE + loss) every epoch, TensorBoard summaries read back
into loss curves, then predict_user_item_pair / recommend_for_user /
recommend_for_item / evaluate(MAE), plus the implicit-feedback
HitRatio/NDCG protocol.

REAL DATA: pass ``--data /path/to/ml-1m`` (or a ratings file directly).
Both MovieLens wire formats parse:

- ml-1m ``ratings.dat``   — ``UserID::MovieID::Rating::Timestamp``
- ml-100k ``u.data``      — tab-separated ``user item rating ts``

Download (outside this sandbox):
``https://files.grouplens.org/datasets/movielens/ml-1m.zip``.
The reference notebook on ml-1m reaches validation MAE ≈ 0.75 and
accuracy ≈ 0.45 with this architecture/optimizer after a few epochs;
the implicit protocol's ballpark is HR@10 ≈ 0.5-0.6 at neg_num=99.

Without ``--data`` the app falls back to synthetic MovieLens-shaped
ratings (latent-factor affinity, same value ranges) so it always runs
to its metrics.
"""

import argparse
import os
import tempfile

import numpy as np


def load_movielens(path):
    """Parse MovieLens ratings: ml-1m ``ratings.dat`` (``::`` separated)
    or ml-100k ``u.data`` (tab separated).  ``path`` may be the dataset
    directory or the ratings file itself.  Returns int32 rows of
    (user, item, rating) with users/items 1-based, ratings 1..5."""
    if os.path.isdir(path):
        for cand in ("ratings.dat", "u.data"):
            f = os.path.join(path, cand)
            if os.path.exists(f):
                path = f
                break
        else:
            raise FileNotFoundError(
                f"no ratings.dat / u.data under {path}")
    rows = []
    with open(path, encoding="latin-1") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            parts = line.split("::") if "::" in line else line.split()
            u, i, r = int(parts[0]), int(parts[1]), int(float(parts[2]))
            rows.append((u, i, r))
    data = np.asarray(rows, np.int32)
    if not len(data):
        raise ValueError(f"no ratings parsed from {path}")
    return data


def synthetic_movielens(n_users, n_items, n_ratings, seed=0):
    rs = np.random.RandomState(seed)
    u_factors = rs.normal(size=(n_users + 1, 4))
    i_factors = rs.normal(size=(n_items + 1, 4))
    users = rs.randint(1, n_users + 1, n_ratings)
    items = rs.randint(1, n_items + 1, n_ratings)
    affinity = np.einsum("nd,nd->n", u_factors[users], i_factors[items])
    # map affinity quintiles onto ratings 1..5 with a little noise
    edges = np.quantile(affinity, [0.2, 0.4, 0.6, 0.8])
    ratings = 1 + np.searchsorted(edges, affinity)
    flip = rs.rand(n_ratings) < 0.1
    ratings = np.where(flip, rs.randint(1, 6, n_ratings), ratings)
    return np.stack([users, items, ratings], axis=1).astype(np.int32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None,
                    help="MovieLens dir or ratings file (ml-1m "
                         "ratings.dat / ml-100k u.data); synthetic "
                         "fallback when omitted")
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--users", type=int, default=100)
    ap.add_argument("--items", type=int, default=80)
    ap.add_argument("--ratings", type=int, default=4096)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--log-dir", default=None)
    args = ap.parse_args()

    from analytics_zoo_tpu.common import init_nncontext
    from analytics_zoo_tpu.models import NeuralCF, UserItemFeature
    from analytics_zoo_tpu.train.summary import read_scalars

    init_nncontext("NCF Example")
    if args.data:
        data = load_movielens(args.data)
        args.users = int(data[:, 0].max())
        args.items = int(data[:, 1].max())
        # real-data scale: the reference notebook's batch size (2800),
        # clamped so tiny subsets still make at least a few steps/epoch
        args.batch_size = max(args.batch_size,
                              min(2800, max(len(data) // 10, 1)))
        print(f"loaded MovieLens: {len(data)} ratings, "
              f"{args.users} users, {args.items} items")
    else:
        data = synthetic_movielens(args.users, args.items, args.ratings)
        print("synthetic fallback (pass --data for MovieLens)")
    print("ratings:", data.shape, "users", data[:, 0].min(), "..",
          data[:, 0].max(), "items", data[:, 1].min(), "..",
          data[:, 1].max(), "labels", np.unique(data[:, 2]))

    rs = np.random.RandomState(1)
    perm = rs.permutation(len(data))
    split = int(0.8 * len(data))
    train, val = data[perm[:split]], data[perm[split:]]

    x_train = train[:, :2]
    y_train = train[:, 2] - 1          # classes 0..4
    x_val, y_val = val[:, :2], val[:, 2] - 1

    ncf = NeuralCF(user_count=args.users, item_count=args.items,
                   num_classes=5, hidden_layers=(20, 10),
                   include_mf=False)
    # log-softmax head + ClassNLL, the reference notebook's pairing
    ncf.compile(optimizer="adam", loss="class_nll",
                metrics=["mae", "accuracy"])
    log_dir = args.log_dir or tempfile.mkdtemp(prefix="ncf-tb-")
    ncf.set_tensorboard(log_dir, "ncf")
    ncf.fit(x_train, y_train, batch_size=args.batch_size,
            nb_epoch=args.epochs, validation_data=(x_val, y_val))

    # read the summaries back, notebook-style loss curves as text
    loss = read_scalars(log_dir, "ncf", "Loss")
    val_mae = read_scalars(log_dir, "ncf", "mae", split="validation")
    if loss:
        print("train Loss points:", len(loss),
              "first %.3f last %.3f" % (loss[0][1], loss[-1][1]))
    if val_mae:
        print("val MAE per epoch:",
              ["%.3f" % v for _, v in val_mae])

    metrics = ncf.evaluate(x_val, y_val, batch_size=args.batch_size)
    print("validation metrics:", metrics)

    pairs = [UserItemFeature(int(u), int(i), np.array([u, i], np.int32))
             for u, i, _ in val[:200]]
    for p in ncf.predict_user_item_pair(pairs)[:5]:
        print("pair", p)
    print("-- top-3 items per user --")
    for r in ncf.recommend_for_user(pairs, max_items=3)[:6]:
        print(f"user {r.user_id}: item {r.item_id} "
              f"rating {r.prediction} (p={r.probability:.3f})")
    print("-- top-3 users per item --")
    for r in ncf.recommend_for_item(pairs, max_users=3)[:6]:
        print(f"item {r.item_id}: user {r.user_id} "
              f"rating {r.prediction} (p={r.probability:.3f})")

    # ---- implicit-feedback protocol: negative sampling + ranking ----
    # (the NCF paper's evaluation: rank the held-out positive among
    # sampled negatives; BigDL's getNegativeSamples + HitRatio/NDCG)
    from analytics_zoo_tpu.models import get_negative_samples
    from analytics_zoo_tpu.pipeline.api.keras.metrics import HitRatio, NDCG

    positives = [(int(u), int(i)) for u, i, r in data if r >= 4]
    # HOLD OUT the ranking-eval positives (random across users — ml-1m
    # is user-sorted, so a head slice would cover a handful of users)
    # before training, so HR/NDCG measure unseen positives
    neg_num, k = (99, 10) if args.data else (9, 3)
    n_eval = min(1000 if args.data else 100, len(positives) // 5 or 1)
    rs3 = np.random.RandomState(3)
    perm_p = rs3.permutation(len(positives))
    eval_pos = [positives[i] for i in perm_p[:n_eval]]
    train_pos = [positives[i] for i in perm_p[n_eval:]]
    negatives = get_negative_samples(train_pos, item_count=args.items,
                                     neg_per_pos=2, seed=2)
    xi = np.array(train_pos + negatives, np.int32)
    yi = np.concatenate([np.ones(len(train_pos)),
                         np.zeros(len(negatives))]).astype(np.int32)
    implicit = NeuralCF(user_count=args.users, item_count=args.items,
                        num_classes=2, hidden_layers=(20, 10),
                        include_mf=True, mf_embed=8)
    implicit.compile(optimizer="adam", loss="class_nll")
    perm2 = rs.permutation(len(xi))
    implicit.fit(xi[perm2], yi[perm2], batch_size=args.batch_size,
                 nb_epoch=args.epochs)
    ex, ey = [], []
    pos_set = set(positives)
    for u, i in eval_pos:
        ex.append((u, i)); ey.append(1)
        drawn, j = 0, 1
        while drawn < neg_num:
            cand = ((i + j - 1) % args.items) + 1
            j += 1
            if (u, cand) not in pos_set:
                ex.append((u, cand)); ey.append(0); drawn += 1
    ranked = implicit.evaluate(
        np.array(ex, np.int32), np.array(ey, np.int32),
        batch_size=(neg_num + 1) * 10,
        metrics=[HitRatio(k=k, neg_num=neg_num),
                 NDCG(k=k, neg_num=neg_num)])
    chance = k / (neg_num + 1)
    print(f"implicit feedback (held-out positives): "
          f"HitRatio@{k} {ranked[f'hit_ratio@{k}']:.3f} "
          f"NDCG@{k} {ranked[f'ndcg@{k}']:.3f} "
          f"(chance hit@{k} of {neg_num + 1} = {chance:.3f})")
    print("ncf app done")


if __name__ == "__main__":
    main()
