"""Time-series anomaly detection with an LSTM forecaster.

Reference analog: apps/anomaly-detection/anomaly-detection-nyc-taxi.ipynb
(LSTM on NYC taxi traffic): train on sliding windows, forecast one step
ahead, flag anomalies where the residual exceeds a quantile threshold.

REAL DATA: pass ``--data /path/to/nyc_taxi.csv`` — the Numenta Anomaly
Benchmark series (10 320 half-hourly taxi counts, Jul 2014 - Jan 2015).
Download (outside this sandbox):
``https://raw.githubusercontent.com/numenta/NAB/master/data/realKnownCause/nyc_taxi.csv``
(format: ``timestamp,value`` CSV with a header row).

NAB's labeled anomalies for this series (the ground truth the app
scores against) are the five published events: the NYC marathon
(2014-11-02), Thanksgiving (2014-11-27), Christmas (2014-12-25), New
Year's Day (2015-01-01), and the North American blizzard
(2015-01-26/27).  The reference notebook flags three of the five with
this architecture; the app reports detected/total plus precision.

Without ``--data`` a synthetic series with the same structure (daily +
weekly seasonality, injected anomalies) keeps the app runnable to a
metric anywhere.
"""

import argparse
import csv
import datetime as dt

import numpy as np

# NAB combined_windows for realKnownCause/nyc_taxi.csv (published labels)
NAB_ANOMALY_WINDOWS = [
    ("2014-10-30 15:30:00", "2014-11-03 22:30:00"),   # NYC marathon
    ("2014-11-25 12:00:00", "2014-11-29 19:00:00"),   # Thanksgiving
    ("2014-12-23 11:30:00", "2014-12-27 18:30:00"),   # Christmas
    ("2014-12-29 21:30:00", "2015-01-03 04:30:00"),   # New Year
    ("2015-01-24 20:30:00", "2015-01-29 03:30:00"),   # blizzard
]


def load_nyc_taxi(path):
    """Parse the NAB ``timestamp,value`` CSV.  Returns (series f32,
    timestamps list[datetime])."""
    ts, vals = [], []
    with open(path) as fh:
        for row in csv.reader(fh):
            if not row or row[0] == "timestamp":
                continue
            ts.append(dt.datetime.strptime(row[0], "%Y-%m-%d %H:%M:%S"))
            vals.append(float(row[1]))
    if not vals:
        raise ValueError(f"no rows parsed from {path}")
    return np.asarray(vals, np.float32), ts


def nab_truth_mask(timestamps):
    """Boolean mask: timestamp falls inside a labeled anomaly window."""
    windows = [(dt.datetime.strptime(a, "%Y-%m-%d %H:%M:%S"),
                dt.datetime.strptime(b, "%Y-%m-%d %H:%M:%S"))
               for a, b in NAB_ANOMALY_WINDOWS]
    return np.array([any(a <= t <= b for a, b in windows)
                     for t in timestamps])


def make_series(n=2000, seed=0):
    """Synthetic 'taxi traffic': daily + weekly periodicity + noise,
    with injected anomalies."""
    rs = np.random.RandomState(seed)
    t = np.arange(n)
    series = (10 + 4 * np.sin(2 * np.pi * t / 48)
              + 2 * np.sin(2 * np.pi * t / (48 * 7))
              + 0.4 * rs.randn(n))
    anomaly_idx = rs.choice(n // 2, 8, replace=False) + n // 2
    series[anomaly_idx] += rs.choice([-6, 6], 8)
    truth = np.zeros(n, bool)
    truth[anomaly_idx] = True
    return series.astype(np.float32), truth


def windows(series, lookback):
    x = np.stack([series[i:i + lookback]
                  for i in range(len(series) - lookback)])
    y = series[lookback:]
    return x[..., None], y[:, None]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None,
                    help="NAB nyc_taxi.csv; synthetic fallback if omitted")
    ap.add_argument("--lookback", type=int, default=24,
                    help="forecast window; raised to >=48 (one day of "
                         "half-hours) with --data unless already larger")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--quantile", type=float, default=0.995)
    args = ap.parse_args()

    from analytics_zoo_tpu.pipeline.api.keras.engine import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers.core import (
        Dense, Dropout)
    from analytics_zoo_tpu.pipeline.api.keras.layers.recurrent import LSTM

    if args.data:
        series, ts = load_nyc_taxi(args.data)
        truth = nab_truth_mask(ts)
        if args.lookback < 48:
            print(f"note: raising --lookback {args.lookback} -> 48 "
                  "(one day of half-hourly points)")
            args.lookback = 48
        print(f"loaded NYC taxi: {len(series)} points, "
              f"{truth.sum()} labeled-anomalous points in "
              f"{len(NAB_ANOMALY_WINDOWS)} windows")
    else:
        series, truth = make_series()
        print("synthetic fallback (pass --data for NAB nyc_taxi.csv)")

    mean, std = series.mean(), series.std()
    normed = (series - mean) / std
    x, y = windows(normed, args.lookback)
    split = len(x) // 2
    x_train, y_train = x[:split], y[:split]
    x_test, y_test = x[split:], y[split:]

    model = Sequential(name="anomaly_lstm")
    model.add(LSTM(32, input_shape=(args.lookback, 1)))
    model.add(Dropout(0.2))
    model.add(Dense(1))
    model.compile(optimizer="adam", loss="mean_squared_error")
    model.fit(x_train, y_train, batch_size=64, nb_epoch=args.epochs)

    pred = np.asarray(model.predict(x_test, batch_size=64))
    resid = np.abs(pred - y_test).ravel()
    threshold = np.quantile(resid, args.quantile)
    flagged_rel = np.nonzero(resid > threshold)[0]
    # map window index back to the flagged point's series position
    flagged_idx = flagged_rel + split + args.lookback

    test_truth = truth.copy()
    test_truth[:split + args.lookback] = False
    if args.data:
        # score against the labeled WINDOWS: a window counts as detected
        # if any flagged point falls inside it; precision = flagged
        # points that land in some window
        detected = 0
        win = [(dt.datetime.strptime(a, "%Y-%m-%d %H:%M:%S"),
                dt.datetime.strptime(b, "%Y-%m-%d %H:%M:%S"))
               for a, b in NAB_ANOMALY_WINDOWS]
        flagged_ts = [ts[i] for i in flagged_idx]
        for a, b in win:
            if any(a <= t <= b for t in flagged_ts):
                detected += 1
        in_window = sum(test_truth[i] for i in flagged_idx)
        precision = in_window / max(len(flagged_idx), 1)
        print(f"threshold={threshold:.3f}  flagged={len(flagged_idx)}  "
              f"windows detected={detected}/{len(win)}  "
              f"precision={precision:.2f}")
        print("(reference notebook ballpark: 3/5 windows with this "
              "architecture)")
    else:
        hits = int(np.sum(test_truth[flagged_idx]))
        total = int(test_truth.sum())
        print(f"threshold={threshold:.3f}  flagged={len(flagged_idx)}  "
              f"true anomalies hit={hits}/{total}")


if __name__ == "__main__":
    main()
