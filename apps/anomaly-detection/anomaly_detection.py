"""Time-series anomaly detection with an LSTM forecaster.

Reference analog: apps/anomaly-detection (LSTM on NYC taxi traffic):
train on sliding windows, forecast one step ahead, flag anomalies where
the residual exceeds a quantile threshold.
"""

import argparse

import numpy as np


def make_series(n=2000, seed=0):
    """Synthetic 'taxi traffic': daily + weekly periodicity + noise,
    with injected anomalies."""
    rs = np.random.RandomState(seed)
    t = np.arange(n)
    series = (10 + 4 * np.sin(2 * np.pi * t / 48)
              + 2 * np.sin(2 * np.pi * t / (48 * 7))
              + 0.4 * rs.randn(n))
    anomaly_idx = rs.choice(n // 2, 8, replace=False) + n // 2
    series[anomaly_idx] += rs.choice([-6, 6], 8)
    return series.astype(np.float32), set(anomaly_idx.tolist())


def windows(series, lookback):
    x = np.stack([series[i:i + lookback]
                  for i in range(len(series) - lookback)])
    y = series[lookback:]
    return x[..., None], y[:, None]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--lookback", type=int, default=24)
    ap.add_argument("--epochs", type=int, default=3)
    args = ap.parse_args()

    from analytics_zoo_tpu.pipeline.api.keras.engine import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers.core import (
        Dense, Dropout)
    from analytics_zoo_tpu.pipeline.api.keras.layers.recurrent import LSTM

    series, truth = make_series()
    mean, std = series.mean(), series.std()
    normed = (series - mean) / std
    x, y = windows(normed, args.lookback)
    split = len(x) // 2
    x_train, y_train = x[:split], y[:split]
    x_test, y_test = x[split:], y[split:]

    model = Sequential(name="anomaly_lstm")
    model.add(LSTM(32, input_shape=(args.lookback, 1)))
    model.add(Dropout(0.2))
    model.add(Dense(1))
    model.compile(optimizer="adam", loss="mean_squared_error")
    model.fit(x_train, y_train, batch_size=64, nb_epoch=args.epochs)

    pred = np.asarray(model.predict(x_test, batch_size=64))
    resid = np.abs(pred - y_test).ravel()
    threshold = np.quantile(resid, 0.995)
    flagged = {int(i) + split + args.lookback
               for i in np.nonzero(resid > threshold)[0]}
    hits = len(flagged & truth)
    print(f"threshold={threshold:.3f}  flagged={len(flagged)}  "
          f"true anomalies hit={hits}/{len(truth & set(range(split + args.lookback, len(series))))}")


if __name__ == "__main__":
    main()
