"""Transfer learning: freeze a pretrained backbone, retrain the head.

Reference analog: apps/dogs-vs-cats (load inception, freeze_up_to, add a
new head, short fine-tune).  A small CNN pretrained on task A stands in
for the downloaded checkpoint; GraphNet surgery is identical.
"""

import argparse

import numpy as np


def make_task(seed, n=256, size=16):
    rs = np.random.RandomState(seed)
    y = rs.randint(0, 2, n).astype(np.int32)
    x = rs.rand(n, size, size, 3).astype(np.float32) * 0.4
    # class signal: bright patch top-left vs bottom-right
    for i, yi in enumerate(y):
        if yi:
            x[i, :4, :4] += 0.6
        else:
            x[i, -4:, -4:] += 0.6
    return x, y


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    args = ap.parse_args()

    from analytics_zoo_tpu.core.graph import Input
    from analytics_zoo_tpu.pipeline.api.keras.engine import Model
    from analytics_zoo_tpu.pipeline.api.keras.layers.convolutional import (
        Convolution2D)
    from analytics_zoo_tpu.pipeline.api.keras.layers.core import Dense
    from analytics_zoo_tpu.pipeline.api.keras.layers.pooling import (
        GlobalAveragePooling2D)
    from analytics_zoo_tpu.pipeline.api.net import GraphNet

    # "pretrained" backbone + original head, trained on task A
    inp = Input((16, 16, 3), name="image")
    feat = Convolution2D(8, 3, 3, activation="relu",
                         name="backbone_conv1")(inp)
    feat = Convolution2D(16, 3, 3, activation="relu",
                         name="backbone_conv2")(feat)
    pooled = GlobalAveragePooling2D(name="backbone_pool")(feat)
    head_a = Dense(2, activation="softmax", name="head_a")(pooled)
    base = Model(input=inp, output=head_a, name="base")
    xa, ya = make_task(seed=0)
    base.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                 metrics=["accuracy"])
    base.fit(xa, ya, batch_size=32, nb_epoch=args.epochs)
    print("task A:", base.evaluate(xa, ya, batch_size=32))

    # surgery: re-root on the pooled features, freeze the backbone,
    # attach a new head for task B
    net = GraphNet.from_model(base)
    net.freeze_up_to(["backbone_pool"])
    print("frozen layers:", net.frozen_layer_names())
    trunk = net.new_graph(["backbone_pool"])
    features = trunk.outputs[0]
    head_b = Dense(2, activation="softmax", name="head_b")(features)
    tuned = Model(input=trunk.inputs, output=head_b, name="tuned")

    xb, yb = make_task(seed=7)
    tuned.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
    # pull the pretrained backbone weights into the re-rooted model
    tuned.transfer_weights_from(base)
    tuned.fit(xb, yb, batch_size=32, nb_epoch=args.epochs)
    print("task B (frozen backbone):",
          tuned.evaluate(xb, yb, batch_size=32))


if __name__ == "__main__":
    main()
