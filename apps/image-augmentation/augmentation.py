"""Image augmentation pipelines, 2D and 3D.

Reference analog: apps/image-augmentation and image-augmentation-3d:
chain feature-engineering transformers (the reference's ``->``
composition is ``>>`` here) over an ImageSet / 3D tensor.
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.parse_args()

    from analytics_zoo_tpu.feature.image.imageset import ImageSet
    from analytics_zoo_tpu.feature.image.transforms import (
        ImageBrightness, ImageCenterCrop, ImageChannelNormalize,
        ImageHFlip, ImageResize)

    rs = np.random.RandomState(0)
    images = (rs.rand(4, 40, 48, 3) * 255).astype(np.float32)
    pipeline = (ImageResize(32, 32)
                >> ImageCenterCrop(24, 24)
                >> ImageHFlip(probability=1.0)
                >> ImageBrightness(delta_low=10, delta_high=10)
                >> ImageChannelNormalize(123.0, 117.0, 104.0))

    out = ImageSet.from_arrays(images).transform(pipeline)
    arr = out.to_array()
    print("2D pipeline output:", arr.shape, "mean", float(arr.mean()))

    # 3D medical-style volume
    from analytics_zoo_tpu.feature.image3d.transforms import (
        CenterCrop3D, Rotate3D)
    volume = rs.rand(32, 32, 32).astype(np.float32)
    rotated = Rotate3D([0.0, 0.0, np.pi / 6]).apply({"image": volume})
    cropped = CenterCrop3D([16, 16, 16]).apply(rotated)
    print("3D pipeline output:", np.asarray(cropped["image"]).shape)


if __name__ == "__main__":
    main()
