"""TFNet app: image-classification inference from a user's TF graph.

Reference analog: apps/tfnet/image_classification_inference.ipynb —
load a frozen TF image-classification graph with TFNet and run
distributed inference over an ImageSet.  Here the "pretrained" graph is
a small TF CNN built in-process (no model download in this
environment), frozen via TFNet.from_session, and driven through the
same preprocess→forward→top-k flow.
"""

import argparse

import numpy as np


def build_tf_graph():
    import tensorflow.compat.v1 as tf
    tf.disable_eager_execution()
    graph = tf.Graph()
    with graph.as_default():
        x = tf.placeholder(tf.float32, [None, 32, 32, 3], name="input")
        k = tf.get_variable("k", [3, 3, 3, 8])
        b = tf.get_variable("b", [8])
        h = tf.nn.relu(tf.nn.bias_add(
            tf.nn.conv2d(x, k, strides=[1, 1, 1, 1], padding="SAME"), b))
        h = tf.nn.max_pool2d(h, 2, 2, padding="VALID")
        h = tf.reshape(h, [-1, 16 * 16 * 8])
        w = tf.get_variable("w", [16 * 16 * 8, 5])
        logits = tf.nn.bias_add(tf.matmul(h, w),
                                tf.get_variable("b2", [5]), name="logits")
        probs = tf.nn.softmax(logits, name="probs")
        sess = tf.Session(graph=graph)
        sess.run(tf.global_variables_initializer())
    return sess, x, probs


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=8)
    args = ap.parse_args()

    from analytics_zoo_tpu.pipeline.api.tfgraph.net import TFNet
    from analytics_zoo_tpu.feature.image import ImageSet
    from analytics_zoo_tpu.feature.image.transforms import (
        ImageChannelNormalize, ImageMatToTensor, ImageResize)

    sess, x, probs = build_tf_graph()
    net = TFNet.from_session(sess, inputs=[x], outputs=[probs])

    rs = np.random.RandomState(0)
    raw = (rs.rand(args.images, 48, 48, 3) * 255).astype(np.float32)
    pipeline = (ImageResize(32, 32)
                >> ImageChannelNormalize(123.0, 117.0, 104.0, 58.0, 57.0,
                                         57.0)
                >> ImageMatToTensor())
    image_set = ImageSet.from_arrays(raw).transform(pipeline)
    batch = image_set.to_array()

    out = np.asarray(net.predict(batch))
    top1 = out.argmax(axis=1)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, rtol=1e-4)
    for i in range(args.images):
        print(f"image {i}: class {int(top1[i])} "
              f"(p={float(out[i, top1[i]]):.3f})")
    print(f"tfnet inference done: {args.images} images, "
          f"{out.shape[1]} classes")


if __name__ == "__main__":
    main()
