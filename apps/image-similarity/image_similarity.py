"""Image similarity search over CNN embeddings.

Reference analog: apps/image-similarity (extract deep features, rank by
cosine similarity).  Embeddings come from an intermediate layer via
new_graph surgery.
"""

import argparse

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gallery", type=int, default=64)
    args = ap.parse_args()

    from analytics_zoo_tpu.core.graph import Input
    from analytics_zoo_tpu.pipeline.api.keras.engine import Model
    from analytics_zoo_tpu.pipeline.api.keras.layers.convolutional import (
        Convolution2D)
    from analytics_zoo_tpu.pipeline.api.keras.layers.core import Dense
    from analytics_zoo_tpu.pipeline.api.keras.layers.pooling import (
        GlobalAveragePooling2D)
    from analytics_zoo_tpu.pipeline.api.net import GraphNet

    size = 24
    inp = Input((size, size, 3), name="image")
    h = Convolution2D(8, 3, 3, activation="relu")(inp)
    h = Convolution2D(16, 3, 3, activation="relu")(h)
    emb = GlobalAveragePooling2D(name="embedding")(h)
    out = Dense(4, activation="softmax")(emb)
    model = Model(input=inp, output=out, name="feature_net")

    # gallery: 4 visual styles (color casts)
    rs = np.random.RandomState(0)
    styles = rs.rand(4, 1, 1, 3).astype(np.float32)
    labels = rs.randint(0, 4, args.gallery)
    gallery = (rs.rand(args.gallery, size, size, 3).astype(np.float32)
               * 0.3 + styles[labels])

    embedder = GraphNet.from_model(model).new_graph(["embedding"])
    feats = np.asarray(embedder.predict(gallery, batch_size=32))
    feats /= np.linalg.norm(feats, axis=1, keepdims=True) + 1e-8

    query_label = 2
    query = (rs.rand(1, size, size, 3).astype(np.float32) * 0.3
             + styles[query_label])
    q = np.asarray(embedder.predict(query, batch_size=1))
    q /= np.linalg.norm(q) + 1e-8

    sims = feats @ q.ravel()
    top = np.argsort(-sims)[:5]
    print("query style:", query_label)
    for rank, idx in enumerate(top):
        print(f"  #{rank + 1}: gallery[{idx}] style={labels[idx]} "
              f"cos={sims[idx]:.3f}")
    hit = (labels[top] == query_label).mean()
    print(f"top-5 purity: {hit:.2f}")


if __name__ == "__main__":
    main()
