"""Web-service sample: the serving CONTROL PLANE behind an HTTP endpoint.

Reference analog: apps/web-service-sample — a Spring web service
consuming the thread-safe POJO serving API
(AbstractInferenceModel.java:30-148).  Here the same role is played by
``ModelRegistry`` (analytics_zoo_tpu.serving): named + versioned
models, zero-downtime hot-swap, per-model admission control with
deadline-aware load shedding, and full observability (per-request
tracing, Prometheus metrics, XLA profiling hooks).

POST /predict {"instances": [[...], ...],              -> {"predictions": [...],
               "model": "default",       # optional        "model": ..., "version": ...,
               "deadline_ms": 250,       # optional        "request_id": ...}
               "class": "interactive"}   # optional priority class
POST /generate {"prompt": [ids] | [[ids], ...],        -> {"tokens": [[...], ...],
                "max_new_tokens": 8,                       "model": ..., "version": ...,
                "model": "lm",           # optional        "request_id": ...}
                "temperature": 0.8,      # optional, default 0 = greedy
                "top_k": 20,             # optional truncation
                "top_p": 0.95,           # optional nucleus truncation
                "seed": 7}               # replay seed, default 0
               # continuous-batching decode: requests share the slot
               # array per decode step (see docs/serving.md).  A fixed
               # (prompt, sampling, seed) replays the same tokens at
               # any occupancy; bad sampling values are a 400 with the
               # engine's ValueError message
POST /deploy  {"model": "default", "seed": 1,          -> {"model": ..., "version": v}
               "hidden": 16, "canary_fraction": 0.2}   # canary optional
POST /promote {"model": "default"}                     -> {"version": v}
GET  /metrics                                          -> registry.metrics() (JSON)
GET  /metrics?format=prometheus                        -> text exposition 0.0.4
GET  /traces                                           -> recent trace ring buffer
GET  /traces?id=<request_id>                           -> one trace (404 if aged out)
GET  /health                                           -> {"status": "ok"}

Every /predict response carries an ``X-Request-Id`` header (client's
own header is honored, else generated) matching the trace id in
``GET /traces`` — latency questions resolve to per-phase spans
(admission_queue/coalesce_wait/pad/device_put/execute/depad), not
guesswork.

Overload/miss surface: 429 Overloaded (queue full / draining),
504 DeadlineExceeded (shed or lapsed), 404 ModelNotFound — all with a
structured JSON body {"error": <code>, "message": ..., ...fields}.

Run standalone:  python web_service.py --port 8900
(then:  curl -d '{"instances": [[0.1, ...]]}' localhost:8900/predict)
With --self-test the app starts the server, fires concurrent client
traffic, HOT-SWAPS the model mid-traffic (zero failed requests, every
response tagged with exactly one version), checks /metrics (JSON and
Prometheus, round-tripped through the stdlib parser), verifies a
traced request's phases sum to its span wall time, and exits.
"""

import argparse
import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

import numpy as np

DEFAULT_MODEL = "default"
LM_MODEL = "lm"
LM_VOCAB = 32
LM_SEQ = 24
N_FEATURES = 8
N_CLASSES = 3
TRACE_RING = 512


def build_net(hidden: int = 16, seed: int = 0):
    """A small classifier (stand-in for a loaded zoo model; reference
    services load a pretrained BigDL/TF model).  ``seed`` varies the
    weights so a redeploy is an observably different version."""
    from analytics_zoo_tpu.pipeline.api.keras import Sequential, optimizers
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.train.trainer import Trainer

    net = Sequential()
    net.add(Dense(hidden, activation="relu", input_shape=(N_FEATURES,)))
    net.add(Dense(N_CLASSES, activation="softmax"))
    # attach the trainer ourselves to pin the init seed (so a redeploy
    # with a new seed is an observably different version)
    net.trainer = Trainer(net.to_graph(), None, optimizers.get("sgd"),
                          seed=seed)
    return net


def build_lm():
    """A miniature TransformerLM for the continuous-batching generate
    path (stand-in for a real chat model).  Random-initialized —
    the sample demonstrates the SERVING mechanics (slot admission,
    streaming, per-token metrics), not language quality."""
    from analytics_zoo_tpu.models import TransformerLM

    lm = TransformerLM(vocab_size=LM_VOCAB, seq_len=LM_SEQ, n_layers=1,
                       d_model=16, n_heads=2)
    lm.ensure_inference_ready()
    return lm


def build_registry(pager_resident=None):
    """The control plane + observability: one registry with a tracer,
    a Prometheus-exposable metrics registry fed by the control plane /
    tracer / XLA hooks, and the default model deployed and warmed
    before the server accepts traffic.  Returns (registry, obs) where
    ``obs`` = {"tracer", "metrics", "profile"}.

    ``pager_resident`` (or ``ZOO_PAGER_RESIDENT``) turns on the weight
    pager with that resident-model budget: deployments beyond it page
    out to host memory + the execstore and fault back in on first
    request (``zoo_model_resident`` / ``zoo_pager_*`` land in the
    scrape) — the serving-density recipe, one flag."""
    from analytics_zoo_tpu.observability import (MetricsRegistry, Tracer,
                                                 profile)
    from analytics_zoo_tpu.serving import ModelRegistry, registry_collector

    if pager_resident is None:
        env = os.environ.get("ZOO_PAGER_RESIDENT")
        try:
            pager_resident = int(env) if env else None
        except ValueError:
            # same degradation as the fleet worker: a typo'd env var
            # starts the server unpaged, it does not kill it
            print(f"ignoring malformed ZOO_PAGER_RESIDENT={env!r}",
                  flush=True)
            pager_resident = None
    pager = (None if pager_resident is None
             else {"max_resident": int(pager_resident)})
    tracer = Tracer(capacity=TRACE_RING)
    # replicas="all": every local device serves — on a multi-chip host
    # each chip holds the executables + params and the coalescer
    # schedules groups across them (run the self-test under
    # XLA_FLAGS=--xla_force_host_platform_device_count=N to see it on
    # CPU; scripts/smoke_serving.sh forces 2)
    # two admission tenants: interactive traffic outlives batch under
    # overload (higher priority -> shed last) and owns 90% of freed
    # slots (fair-share weight); requests opt in via {"class": ...}
    registry = ModelRegistry(max_queue=64, max_concurrency=4,
                             supported_concurrent_num=4,
                             max_batch_size=32, coalescing=True,
                             replicas="all",
                             priority_classes={
                                 "interactive": (10, 0.9),
                                 "batch": (0, 0.1)},
                             tracer=tracer, pager=pager)
    metrics = MetricsRegistry()
    metrics.register_collector(registry_collector(registry))
    metrics.register_collector(tracer.families)
    prof = profile.install()
    metrics.register_collector(prof.families)
    # persistent executable store (enabled via ZOO_EXECSTORE_DIR):
    # zoo_execstore_{hit,miss,write,invalid,evicted}_total land in the
    # same scrape, so a fleet dashboard can watch cold starts turn
    # into disk loads
    from analytics_zoo_tpu.serving import execstore
    store = execstore.current()
    if store is not None:
        metrics.register_collector(store.families)
    registry.deploy(DEFAULT_MODEL, build_net(),
                    warmup_shapes=(N_FEATURES,))
    # the LM behind /generate: a continuous-batching DecodeEngine
    # (decode_capacity slots) — no predict-ladder warmup (that path is
    # unused for an LM; the engine warms its own admit/step plans at
    # load, so the first stream never compiles).  Single-device: the
    # decode state is stateful, so replicas stay at 1.
    registry.deploy(LM_MODEL, build_lm(), decode_capacity=2,
                    decode_prompt_buckets=(8,), replicas=1)
    return registry, {"tracer": tracer, "metrics": metrics,
                      "profile": prof}


def make_handler(registry, obs=None):
    from analytics_zoo_tpu.serving import error_response

    tracer = (obs or {}).get("tracer")
    metrics = (obs or {}).get("metrics")

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _reply(self, code, payload, headers=None):
            body = json.dumps(payload).encode()
            self._reply_raw(code, body, "application/json", headers)

        def _reply_raw(self, code, body, content_type, headers=None):
            self.send_response(code)
            self.send_header("Content-Type", content_type)
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _body(self):
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n) or b"{}")

        def do_GET(self):
            try:
                self._do_get()
            except Exception as e:  # same structured surface as POST
                self._reply(*error_response(e))

        def _do_get(self):
            url = urlparse(self.path)
            query = parse_qs(url.query)
            if url.path == "/health":
                self._reply(200, {"status": "ok"})
            elif url.path == "/metrics":
                fmt = (query.get("format") or ["json"])[0]
                if fmt == "prometheus":
                    if metrics is None:
                        self._reply(404, {
                            "error": "prometheus exposition not wired"})
                        return
                    self._reply_raw(
                        200, metrics.render_prometheus().encode(),
                        "text/plain; version=0.0.4; charset=utf-8")
                else:
                    self._reply(200, registry.metrics())
            elif url.path == "/traces":
                if tracer is None:
                    self._reply(404, {"error": "tracing not wired"})
                    return
                trace_id = (query.get("id") or [None])[0]
                if trace_id is not None:
                    found = tracer.find(trace_id)
                    if found is None:
                        self._reply(404, {
                            "error": "trace not found (aged out of the "
                                     "ring buffer?)", "id": trace_id})
                    else:
                        self._reply(200, found)
                else:
                    n = int((query.get("n") or [50])[0])
                    self._reply(200, {
                        "traces": tracer.recent(n),
                        "phase_stats": tracer.phase_stats(),
                        "span_count": tracer.span_count})
            else:
                self._reply(404, {"error": "unknown path"})

        def do_POST(self):
            try:
                payload = self._body()
                if self.path == "/predict":
                    # prefix+counter, not uuid4 — a fresh uuid costs
                    # ~40us, material per request (PERF_NOTES §PR 4)
                    from analytics_zoo_tpu.observability.trace import \
                        new_trace_id
                    rid = (self.headers.get("X-Request-Id")
                           or new_trace_id())
                    x = np.asarray(payload["instances"], dtype=np.float32)
                    preds, info = registry.predict_ex(
                        payload.get("model", DEFAULT_MODEL), x,
                        deadline_ms=payload.get("deadline_ms"),
                        trace_id=rid,
                        priority_class=payload.get("class"))
                    self._reply(200, {
                        "predictions": np.asarray(preds).tolist(), **info},
                        headers={"X-Request-Id": rid})
                elif self.path == "/generate":
                    from analytics_zoo_tpu.observability.trace import \
                        new_trace_id
                    rid = (self.headers.get("X-Request-Id")
                           or new_trace_id())
                    prompt = np.asarray(payload["prompt"], dtype=np.int32)
                    if prompt.ndim == 1:
                        prompt = prompt[None, :]
                    # validate sampling BEFORE admission so a bad
                    # request 400s without consuming a slot — the
                    # same check the engine re-runs at submit
                    from analytics_zoo_tpu.pipeline.inference.decode \
                        import DecodeEngine
                    temp, top_k, top_p, seed = \
                        DecodeEngine.validate_sampling(
                            payload.get("temperature", 0.0),
                            payload.get("top_k"),
                            payload.get("top_p"),
                            payload.get("seed", 0))
                    toks, info = registry.generate_ex(
                        payload.get("model", LM_MODEL), prompt,
                        int(payload.get("max_new_tokens", 8)),
                        deadline_ms=payload.get("deadline_ms"),
                        trace_id=rid,
                        priority_class=payload.get("class"),
                        temperature=temp, top_k=top_k, top_p=top_p,
                        seed=seed)
                    self._reply(200, {
                        "tokens": [np.asarray(t).tolist() for t in toks],
                        **info}, headers={"X-Request-Id": rid})
                elif self.path == "/deploy":
                    name = payload.get("model", DEFAULT_MODEL)
                    net = build_net(hidden=int(payload.get("hidden", 16)),
                                    seed=int(payload.get("seed", 0)))
                    frac = payload.get("canary_fraction")
                    v = registry.deploy(
                        name, net, warmup_shapes=(N_FEATURES,),
                        canary_fraction=(None if frac is None
                                         else float(frac)))
                    self._reply(200, {"model": name, "version": v})
                elif self.path == "/promote":
                    name = payload.get("model", DEFAULT_MODEL)
                    self._reply(200, {"model": name,
                                      "version": registry.promote(name)})
                else:
                    self._reply(404, {"error": "unknown path"})
            except Exception as e:  # structured control-plane surface
                self._reply(*error_response(e))

    return Handler


def self_test(port: int):
    """Concurrent clients + a hot-swap mid-traffic: zero failed
    requests, every response tagged with exactly one version, /metrics
    coherent afterwards — then the observability checks: a traced
    request whose phase durations sum to ~its span wall, and the
    Prometheus exposition round-tripped through the stdlib parser."""
    from urllib.request import Request, urlopen

    from analytics_zoo_tpu.observability import parse_prometheus_text

    def call(path, payload=None, return_headers=False):
        if payload is None:
            req = f"http://127.0.0.1:{port}{path}"
        else:
            req = Request(f"http://127.0.0.1:{port}{path}",
                          data=json.dumps(payload).encode(),
                          headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=30) as resp:
            body = resp.read()
            if return_headers:
                return json.loads(body), dict(resp.headers)
        return json.loads(body)

    assert call("/health")["status"] == "ok"

    # payloads drawn up-front: RandomState is not thread-safe
    rs = np.random.RandomState(0)
    payloads = [rs.rand(4, N_FEATURES).tolist() for _ in range(8)]
    n_clients = 8
    results = [[] for _ in range(n_clients)]
    failures = []
    go, stop = threading.Event(), threading.Event()

    def client(i):
        go.wait()
        k = 0
        while not stop.is_set():
            try:
                out = call("/predict",
                           {"instances": payloads[(i + k) % len(payloads)]})
                results[i].append(out)
            except Exception as e:  # noqa: BLE001 — recorded, asserted 0
                failures.append((i, k, repr(e)))
            k += 1

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    go.set()
    try:
        # HOT-SWAP while the clients hammer: deploy a different net as
        # v2.  The deploy blocks through build + full-ladder warmup, so
        # the clients run against v1 that whole time; a short grace
        # afterwards guarantees post-swap traffic too.
        swap = call("/deploy", {"model": DEFAULT_MODEL, "seed": 7,
                                "hidden": 24})
        import time as _time
        _time.sleep(0.5)
    finally:
        # a failed deploy must fail the self-test, not strand the
        # clients looping forever
        stop.set()
        for t in threads:
            t.join()

    assert not failures, f"requests failed across the swap: {failures[:5]}"
    versions = set()
    total = 0
    for outs in results:
        assert outs, "a client never completed a request"
        for out in outs:
            total += 1
            preds = np.asarray(out["predictions"])
            assert preds.shape == (4, N_CLASSES)
            np.testing.assert_allclose(preds.sum(axis=1), 1.0, rtol=1e-4)
            versions.add(out["version"])  # tagged: old xor new, never both
    # traffic must actually straddle the swap: both versions observed
    assert versions == {1, swap["version"]}, versions

    m = call("/metrics")[DEFAULT_MODEL]
    assert m["active_version"] == swap["version"]
    assert m["swap_count"] >= 1
    assert m["admission"]["errors"] == 0
    assert m["admission"]["completed"] >= total
    assert m["serving"]["buckets"], "active version lost its fast path"
    # registry metric satellites: ISO deploy stamp + uptime + canary
    vstats = m["versions"][str(swap["version"])]  # JSON keys: strings
    assert "T" in vstats["deployed_at"], vstats["deployed_at"]
    assert vstats["uptime_s"] >= 0
    assert m["canary_fraction"] == 0.0
    # multi-replica serving: the new version is placed on every local
    # device, every replica is healthy, and the swap's traffic spread
    # across them (dispatch counts per replica are exported)
    import jax
    n_dev = len(jax.local_devices())
    assert m["serving"]["replicas"] == n_dev, m["serving"]["replicas"]
    if n_dev > 1:
        rd = m["serving"]["replica_dispatches"]
        assert len(rd) == n_dev and sum(rd.values()) > 0, rd
        assert not any(m["serving"]["replica_unhealthy"].values()), \
            m["serving"]["replica_unhealthy"]
        # one compile per bucket even though every device serves
        assert all(v == 1 for v in m["serving"]["misses"].values()), \
            m["serving"]["misses"]
        print(f"replica check: {n_dev} replicas, dispatches {rd}, "
              "all healthy, one compile per bucket OK")

    # ---- tracing: one trace per request, phases account for the wall.
    # A big batch (chunked over the bucket ladder) makes device work
    # dominate, so the untraced slack (future wake-up, JSON) stays
    # under 5% of the span wall; quiet retries absorb scheduler noise.
    big = rs.rand(128, N_FEATURES).tolist()
    best = None
    for _ in range(10):
        out, headers = call("/predict", {"instances": big},
                            return_headers=True)
        rid = headers.get("X-Request-Id")
        assert rid and out["request_id"] == rid
        tr = call(f"/traces?id={rid}")
        assert tr["trace_id"] == rid
        phase_names = {p["name"] for p in tr["phases"]}
        assert {"pad", "device_put", "execute", "depad"} <= phase_names, \
            phase_names
        assert tr["labels"]["model"] == DEFAULT_MODEL
        assert tr["labels"]["version"] == swap["version"]
        # The gate: phases must account for the span wall.  Primary
        # bar is the 95% ratio, but the UNTRACED slack is an absolute
        # cost (future wake-up + JSON render, microseconds) — under
        # full-suite scheduler load the best of 10 attempts has
        # landed at 94.99% of a small wall, which is noise, not a
        # coverage hole.  So an attempt whose uncovered gap stays
        # under an absolute 2 ms also qualifies — judged PER ATTEMPT,
        # or a qualifying-by-gap attempt could be shadowed by a
        # higher-coverage/larger-gap one.  A REAL hole (a phase not
        # recorded) leaves device-work milliseconds uncovered on this
        # 128-row request and still fails every attempt.
        if tr["coverage"] >= 0.95 or \
                tr["wall_ms"] - tr["phase_total_ms"] <= 2.0:
            best = tr
            break
        if best is None or tr["coverage"] > best["coverage"]:
            best = tr
    gap_ms = best["wall_ms"] - best["phase_total_ms"]
    assert best["coverage"] >= 0.95 or gap_ms <= 2.0, \
        f"phase durations cover only {best['coverage']:.1%} of the " \
        f"span wall ({best['wall_ms']:.2f} ms, {gap_ms:.2f} ms " \
        f"uncovered): {best['phases']}"
    print(f"trace check: request {best['trace_id']} wall "
          f"{best['wall_ms']:.2f} ms, phases sum "
          f"{best['phase_total_ms']:.2f} ms "
          f"(coverage {best['coverage']:.1%}) OK")

    # ---- continuous-batching generate: the LM model decodes through
    # the slot-array engine — deterministic (greedy), so two identical
    # requests must stream identical tokens, and the request must
    # carry the decode span phases (prefill -> decode_step)
    lm_prompt = [[1, 2, 3, 4, 5]]
    g1, gh = call("/generate", {"prompt": lm_prompt,
                                "max_new_tokens": 6},
                  return_headers=True)
    g2 = call("/generate", {"prompt": lm_prompt, "max_new_tokens": 6})
    assert g1["model"] == LM_MODEL and g1["version"] >= 1
    assert len(g1["tokens"]) == 1 and len(g1["tokens"][0]) == 6, g1
    assert g1["tokens"] == g2["tokens"], (g1, g2)
    gtr = call(f"/traces?id={gh['X-Request-Id']}")
    gphases = {p["name"] for p in gtr["phases"]}
    assert {"prefill", "decode_step"} <= gphases, gphases
    print(f"generate check: {LM_MODEL} streamed "
          f"{len(g1['tokens'][0])} tokens deterministically, decode "
          "span phases present OK")

    # ---- decode engine v2: sampled generation replays bit-identically
    # at a fixed (prompt, sampling params, seed), and bad sampling
    # values are a structured 400, never an admitted request
    sampled_req = {"prompt": lm_prompt, "max_new_tokens": 6,
                   "temperature": 0.9, "top_k": 12, "top_p": 0.95,
                   "seed": 1234}
    sg1 = call("/generate", dict(sampled_req))
    sg2 = call("/generate", dict(sampled_req))
    assert len(sg1["tokens"]) == 1 and len(sg1["tokens"][0]) == 6, sg1
    assert sg1["tokens"] == sg2["tokens"], (sg1, sg2)
    from urllib.error import HTTPError
    for bad in ({"temperature": -1}, {"temperature": "nan"},
                {"top_k": 0}, {"top_p": 1.5}, {"seed": -3}):
        try:
            call("/generate", {"prompt": lm_prompt,
                               "max_new_tokens": 4, **bad})
        except HTTPError as e:
            assert e.code == 400, (bad, e.code)
            body = json.loads(e.read())
            assert body["error"] == "ValueError", body
        else:
            raise AssertionError(
                f"bad sampling payload {bad} was not rejected")
    print("sampled generate check: fixed-seed replay bit-identical, "
          "5 bad sampling payloads rejected 400 OK")

    # ---- Prometheus exposition: scrape + round-trip the parser; the
    # per-model/version/bucket labels must survive.  A class-tagged
    # request FIRST, so the per-class families carry a non-default
    # series in the scrape (same for the /generate calls above — the
    # decode families must carry live series, not zeros).
    call("/predict", {"instances": payloads[0], "class": "batch"})
    with urlopen(f"http://127.0.0.1:{port}/metrics?format=prometheus",
                 timeout=30) as resp:
        assert resp.headers["Content-Type"].startswith("text/plain")
        text = resp.read().decode()
    parsed = parse_prometheus_text(text)  # raises on any bad line
    names = {k[0] for k in parsed["samples"]}
    required = ["zoo_model_requests_total", "zoo_bucket_hits_total",
                "zoo_trace_spans_total", "zoo_xla_compiles_total",
                "zoo_admission_completed_total",
                "zoo_shed_total", "zoo_class_admitted_total"]
    if n_dev > 1:
        # the replica families (active gauge included) only exist on
        # the multi-replica serving path
        required += ["zoo_replica_dispatches_total",
                     "zoo_replica_unhealthy", "zoo_model_replicas",
                     "zoo_model_replicas_active"]
    for name in required:
        assert name in names, f"{name} missing from exposition"
    labeled = [k for k in parsed["samples"]
               if k[0] == "zoo_model_requests_total"]
    assert any(dict(k[1]).get("model") == DEFAULT_MODEL
               and dict(k[1]).get("version") == str(swap["version"])
               for k in labeled), labeled
    admitted = [k for k in parsed["samples"]
                if k[0] == "zoo_class_admitted_total"]
    assert any(dict(k[1]).get("class") == "batch" for k in admitted), \
        admitted
    # the continuous-batching decode families must carry LIVE series
    # tagged with the LM model (the /generate calls above ran before
    # this scrape — the PR 6 scrape-order lesson): tokens/steps moved,
    # capacity reads the deployed slot count, occupancy is back to 0
    # on the now-idle engine
    for fam in ("zoo_decode_tokens_total", "zoo_decode_steps_total",
                "zoo_decode_slot_occupancy", "zoo_decode_slot_capacity"):
        assert fam in names, f"{fam} missing from exposition"
    dec = {k[0]: v for k, v in parsed["samples"].items()
           if k[0].startswith("zoo_decode_")
           and dict(k[1]).get("model") == LM_MODEL}
    assert dec.get("zoo_decode_tokens_total", 0) >= 12, dec
    assert dec.get("zoo_decode_steps_total", 0) > 0, dec
    assert dec.get("zoo_decode_slot_capacity") == 2, dec
    assert dec.get("zoo_decode_slot_occupancy") == 0, dec
    assert parsed["types"]["zoo_decode_tokens_total"] == "counter"
    assert parsed["types"]["zoo_decode_slot_occupancy"] == "gauge"
    print("decode scrape check: live zoo_decode_* series for "
          f"model={LM_MODEL} OK")
    assert parsed["types"]["zoo_model_requests_total"] == "counter"
    print(f"prometheus scrape OK ({len(parsed['samples'])} samples, "
          f"{len(names)} series names)")

    print(f"web-service self-test: {n_clients} concurrent clients, "
          f"hot-swap v1->v{swap['version']} mid-traffic, {total} requests, "
          f"0 failed, versions seen {sorted(versions)} OK")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--self-test", action="store_true")
    ap.add_argument("--pager-resident", type=int, default=None,
                    help="serving-density mode: page deployments "
                         "beyond this resident budget out to host "
                         "memory + the execstore (default: "
                         "$ZOO_PAGER_RESIDENT, else off)")
    args = ap.parse_args()

    registry, obs = build_registry(pager_resident=args.pager_resident)
    server = ThreadingHTTPServer(("127.0.0.1", args.port),
                                 make_handler(registry, obs))
    port = server.server_address[1]
    print(f"serving on http://127.0.0.1:{port} (POST /predict /deploy "
          "/promote, GET /health /metrics[?format=prometheus] /traces)",
          flush=True)
    if args.self_test:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            self_test(port)
        finally:
            server.shutdown()
            registry.shutdown()
            obs["profile"].close()
    else:
        try:
            server.serve_forever()
        finally:
            registry.shutdown()
            obs["profile"].close()


if __name__ == "__main__":
    main()
