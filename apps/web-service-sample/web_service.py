"""Web-service sample: the serving handle behind an HTTP endpoint.

Reference analog: apps/web-service-sample — a Spring web service
consuming the thread-safe POJO serving API
(AbstractInferenceModel.java:30-148: a queue of weight-sharing model
replicas serving concurrent requests).  Here the same role is played by
``InferenceModel`` (semaphore-bounded concurrency over one jitted
predict function) behind python's stdlib HTTP server.

POST /predict  {"instances": [[...], ...]}  ->  {"predictions": [...]}
GET  /health                                ->  {"status": "ok"}

Run standalone:  python web_service.py --port 8900
(then:  curl -d '{"instances": [[0.1, 0.2, ...]]}' localhost:8900/predict)
With --self-test the app starts the server, fires concurrent client
requests against it, verifies the responses, and exits.
"""

import argparse
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np


def build_model():
    """A small classifier served by the handle (stand-in for a loaded
    zoo model; reference services load a pretrained BigDL/TF model)."""
    from analytics_zoo_tpu.pipeline.api.keras import Sequential
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.pipeline.inference import InferenceModel

    net = Sequential()
    net.add(Dense(16, activation="relu", input_shape=(8,)))
    net.add(Dense(3, activation="softmax"))
    model = InferenceModel(supported_concurrent_num=4)
    model.load_keras_net(net)
    return model


def make_handler(model):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _reply(self, code, payload):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path == "/health":
                self._reply(200, {"status": "ok"})
            else:
                self._reply(404, {"error": "unknown path"})

        def do_POST(self):
            if self.path != "/predict":
                self._reply(404, {"error": "unknown path"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                x = np.asarray(payload["instances"], dtype=np.float32)
                preds = model.predict(x)
                self._reply(200, {"predictions":
                                  np.asarray(preds).tolist()})
            except Exception as e:  # client error surface
                self._reply(400, {"error": f"{type(e).__name__}: {e}"})

    return Handler


def self_test(port: int):
    from urllib.request import Request, urlopen

    def post(payload):
        req = Request(f"http://127.0.0.1:{port}/predict",
                      data=json.dumps(payload).encode(),
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    with urlopen(f"http://127.0.0.1:{port}/health", timeout=30) as r:
        assert json.loads(r.read())["status"] == "ok"

    # payloads drawn up-front: RandomState is not thread-safe
    rs = np.random.RandomState(0)
    payloads = [rs.rand(4, 8).tolist() for _ in range(8)]
    results = {}

    def client(i):
        out = post({"instances": payloads[i]})
        results[i] = np.asarray(out["predictions"])

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 8
    for preds in results.values():
        assert preds.shape == (4, 3)
        np.testing.assert_allclose(preds.sum(axis=1), 1.0, rtol=1e-4)
    print("web-service self-test: 8 concurrent clients OK")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args()

    model = build_model()
    server = ThreadingHTTPServer(("127.0.0.1", args.port),
                                 make_handler(model))
    port = server.server_address[1]
    print(f"serving on http://127.0.0.1:{port} "
          "(POST /predict, GET /health)", flush=True)
    if args.self_test:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            self_test(port)
        finally:
            server.shutdown()
    else:
        server.serve_forever()


if __name__ == "__main__":
    main()
