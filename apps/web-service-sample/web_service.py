"""Web-service sample: the serving CONTROL PLANE behind an HTTP endpoint.

Reference analog: apps/web-service-sample — a Spring web service
consuming the thread-safe POJO serving API
(AbstractInferenceModel.java:30-148).  Here the same role is played by
``ModelRegistry`` (analytics_zoo_tpu.serving): named + versioned
models, zero-downtime hot-swap, per-model admission control with
deadline-aware load shedding, and a metrics snapshot.

POST /predict {"instances": [[...], ...],              -> {"predictions": [...],
               "model": "default",       # optional        "model": ..., "version": ...}
               "deadline_ms": 250}       # optional
POST /deploy  {"model": "default", "seed": 1,          -> {"model": ..., "version": v}
               "hidden": 16, "canary_fraction": 0.2}   # canary optional
POST /promote {"model": "default"}                     -> {"version": v}
GET  /metrics                                          -> registry.metrics()
GET  /health                                           -> {"status": "ok"}

Overload/miss surface: 429 Overloaded (queue full / draining),
504 DeadlineExceeded (shed or lapsed), 404 ModelNotFound — all with a
structured JSON body {"error": <code>, "message": ..., ...fields}.

Run standalone:  python web_service.py --port 8900
(then:  curl -d '{"instances": [[0.1, ...]]}' localhost:8900/predict)
With --self-test the app starts the server, fires concurrent client
traffic, HOT-SWAPS the model mid-traffic (zero failed requests, every
response tagged with exactly one version), checks /metrics, and exits.
"""

import argparse
import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

DEFAULT_MODEL = "default"
N_FEATURES = 8
N_CLASSES = 3


def build_net(hidden: int = 16, seed: int = 0):
    """A small classifier (stand-in for a loaded zoo model; reference
    services load a pretrained BigDL/TF model).  ``seed`` varies the
    weights so a redeploy is an observably different version."""
    from analytics_zoo_tpu.pipeline.api.keras import Sequential, optimizers
    from analytics_zoo_tpu.pipeline.api.keras.layers import Dense
    from analytics_zoo_tpu.train.trainer import Trainer

    net = Sequential()
    net.add(Dense(hidden, activation="relu", input_shape=(N_FEATURES,)))
    net.add(Dense(N_CLASSES, activation="softmax"))
    # attach the trainer ourselves to pin the init seed (so a redeploy
    # with a new seed is an observably different version)
    net.trainer = Trainer(net.to_graph(), None, optimizers.get("sgd"),
                          seed=seed)
    return net


def build_registry():
    """The control plane: one registry, the default model deployed and
    warmed before the server accepts traffic."""
    from analytics_zoo_tpu.serving import ModelRegistry

    registry = ModelRegistry(max_queue=64, max_concurrency=4,
                             supported_concurrent_num=4,
                             max_batch_size=32, coalescing=True)
    registry.deploy(DEFAULT_MODEL, build_net(),
                    warmup_shapes=(N_FEATURES,))
    return registry


def make_handler(registry):
    from analytics_zoo_tpu.serving import error_response

    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *a):  # quiet
            pass

        def _reply(self, code, payload):
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _body(self):
            n = int(self.headers.get("Content-Length", 0))
            return json.loads(self.rfile.read(n) or b"{}")

        def do_GET(self):
            if self.path == "/health":
                self._reply(200, {"status": "ok"})
            elif self.path == "/metrics":
                self._reply(200, registry.metrics())
            else:
                self._reply(404, {"error": "unknown path"})

        def do_POST(self):
            try:
                payload = self._body()
                if self.path == "/predict":
                    x = np.asarray(payload["instances"], dtype=np.float32)
                    preds, info = registry.predict_ex(
                        payload.get("model", DEFAULT_MODEL), x,
                        deadline_ms=payload.get("deadline_ms"))
                    self._reply(200, {
                        "predictions": np.asarray(preds).tolist(), **info})
                elif self.path == "/deploy":
                    name = payload.get("model", DEFAULT_MODEL)
                    net = build_net(hidden=int(payload.get("hidden", 16)),
                                    seed=int(payload.get("seed", 0)))
                    frac = payload.get("canary_fraction")
                    v = registry.deploy(
                        name, net, warmup_shapes=(N_FEATURES,),
                        canary_fraction=(None if frac is None
                                         else float(frac)))
                    self._reply(200, {"model": name, "version": v})
                elif self.path == "/promote":
                    name = payload.get("model", DEFAULT_MODEL)
                    self._reply(200, {"model": name,
                                      "version": registry.promote(name)})
                else:
                    self._reply(404, {"error": "unknown path"})
            except Exception as e:  # structured control-plane surface
                self._reply(*error_response(e))

    return Handler


def self_test(port: int):
    """Concurrent clients + a hot-swap mid-traffic: zero failed
    requests, every response tagged with exactly one version, /metrics
    coherent afterwards."""
    from urllib.request import Request, urlopen

    def call(path, payload=None):
        if payload is None:
            with urlopen(f"http://127.0.0.1:{port}{path}",
                         timeout=30) as r:
                return json.loads(r.read())
        req = Request(f"http://127.0.0.1:{port}{path}",
                      data=json.dumps(payload).encode(),
                      headers={"Content-Type": "application/json"})
        with urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    assert call("/health")["status"] == "ok"

    # payloads drawn up-front: RandomState is not thread-safe
    rs = np.random.RandomState(0)
    payloads = [rs.rand(4, N_FEATURES).tolist() for _ in range(8)]
    n_clients = 8
    results = [[] for _ in range(n_clients)]
    failures = []
    go, stop = threading.Event(), threading.Event()

    def client(i):
        go.wait()
        k = 0
        while not stop.is_set():
            try:
                out = call("/predict",
                           {"instances": payloads[(i + k) % len(payloads)]})
                results[i].append(out)
            except Exception as e:  # noqa: BLE001 — recorded, asserted 0
                failures.append((i, k, repr(e)))
            k += 1

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(n_clients)]
    for t in threads:
        t.start()
    go.set()
    try:
        # HOT-SWAP while the clients hammer: deploy a different net as
        # v2.  The deploy blocks through build + full-ladder warmup, so
        # the clients run against v1 that whole time; a short grace
        # afterwards guarantees post-swap traffic too.
        swap = call("/deploy", {"model": DEFAULT_MODEL, "seed": 7,
                                "hidden": 24})
        import time as _time
        _time.sleep(0.5)
    finally:
        # a failed deploy must fail the self-test, not strand the
        # clients looping forever
        stop.set()
        for t in threads:
            t.join()

    assert not failures, f"requests failed across the swap: {failures[:5]}"
    versions = set()
    total = 0
    for outs in results:
        assert outs, "a client never completed a request"
        for out in outs:
            total += 1
            preds = np.asarray(out["predictions"])
            assert preds.shape == (4, N_CLASSES)
            np.testing.assert_allclose(preds.sum(axis=1), 1.0, rtol=1e-4)
            versions.add(out["version"])  # tagged: old xor new, never both
    # traffic must actually straddle the swap: both versions observed
    assert versions == {1, swap["version"]}, versions

    m = call("/metrics")[DEFAULT_MODEL]
    assert m["active_version"] == swap["version"]
    assert m["swap_count"] >= 1
    assert m["admission"]["errors"] == 0
    assert m["admission"]["completed"] >= total
    assert m["serving"]["buckets"], "active version lost its fast path"
    print(f"web-service self-test: {n_clients} concurrent clients, "
          f"hot-swap v1->v{swap['version']} mid-traffic, {total} requests, "
          f"0 failed, versions seen {sorted(versions)} OK")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--self-test", action="store_true")
    args = ap.parse_args()

    registry = build_registry()
    server = ThreadingHTTPServer(("127.0.0.1", args.port),
                                 make_handler(registry))
    port = server.server_address[1]
    print(f"serving on http://127.0.0.1:{port} (POST /predict /deploy "
          "/promote, GET /health /metrics)", flush=True)
    if args.self_test:
        t = threading.Thread(target=server.serve_forever, daemon=True)
        t.start()
        try:
            self_test(port)
        finally:
            server.shutdown()
            registry.shutdown()
    else:
        try:
            server.serve_forever()
        finally:
            registry.shutdown()


if __name__ == "__main__":
    main()
