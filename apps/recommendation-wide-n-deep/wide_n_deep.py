"""Wide & Deep recommender over MovieLens-style tabular features.

Reference analog: apps/recommendation-wide-n-deep/wide_n_deep.ipynb —
join ratings with user (gender/age/occupation) and item (genres) tables,
assemble wide ids (base + hashed cross columns), indicator / embedding /
continuous deep features via the feature-assembly helpers, train
WideAndDeep("wide_n_deep") with validation, then
predict_user_item_pair / recommend_for_user / recommend_for_item.

Runs on synthetic MovieLens-shaped tables (no network egress).
"""

import argparse

import numpy as np

GENDERS = ["F", "M"]
GENRES = ["Crime", "Romance", "Thriller", "Adventure", "Drama",
          "Children's", "War", "Documentary", "Fantasy", "Mystery",
          "Musical", "Animation", "Film-Noir", "Horror", "Western",
          "Comedy", "Action", "Sci-Fi"]
AGE_BUCKETS = [20, 30, 40, 50]
CROSS_BUCKETS = 100


def synthetic_tables(n_users, n_items, n_ratings, seed=0):
    rs = np.random.RandomState(seed)
    users = [{"userId": u, "gender": GENDERS[rs.randint(2)],
              "age": int(rs.randint(16, 65)),
              "occupation": int(rs.randint(0, 21))}
             for u in range(1, n_users + 1)]
    items = [{"itemId": i, "genre": GENRES[rs.randint(len(GENRES))]}
             for i in range(1, n_items + 1)]
    ratings = []
    for _ in range(n_ratings):
        u = users[rs.randint(n_users)]
        it = items[rs.randint(n_items)]
        # preference structure: young users like Action/Sci-Fi/Animation,
        # older users like Drama/Documentary/Romance
        young = u["age"] < 35
        likes = (it["genre"] in ("Action", "Sci-Fi", "Animation", "Comedy")
                 if young else
                 it["genre"] in ("Drama", "Documentary", "Romance", "War"))
        base = 4 if likes else 2
        label = int(np.clip(base + rs.randint(-1, 2), 1, 5))
        ratings.append({**u, **it, "label": label})
    return ratings


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--users", type=int, default=80)
    ap.add_argument("--items", type=int, default=60)
    ap.add_argument("--ratings", type=int, default=4096)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--model-type", default="wide_n_deep",
                    choices=["wide", "deep", "wide_n_deep"])
    args = ap.parse_args()

    from analytics_zoo_tpu.common import init_nncontext
    from analytics_zoo_tpu.models import (
        ColumnFeatureInfo, WideAndDeep, categorical_from_vocab_list,
        features_to_arrays, get_boundaries, hash_bucket,
        to_user_item_feature)

    init_nncontext("WideAndDeep Example")
    rows = synthetic_tables(args.users, args.items, args.ratings)

    # featurize each joined row (notebook's udf stage)
    for r in rows:
        r["gender_id"] = categorical_from_vocab_list(r["gender"], GENDERS)
        r["age_bucket"] = get_boundaries(r["age"], AGE_BUCKETS)
        r["genre_id"] = categorical_from_vocab_list(r["genre"], GENRES)
        r["age-gender"] = hash_bucket(
            f'{r["age_bucket"]}_{r["gender"]}', bucket_size=CROSS_BUCKETS)
        r["label0"] = r["label"] - 1  # zero-based classes

    column_info = ColumnFeatureInfo(
        wide_base_cols=["occupation", "gender_id"],
        wide_base_dims=[21, len(GENDERS)],
        wide_cross_cols=["age-gender"],
        wide_cross_dims=[CROSS_BUCKETS],
        indicator_cols=["genre_id", "gender_id"],
        indicator_dims=[len(GENRES), len(GENDERS)],
        embed_cols=["userId", "itemId"],
        embed_in_dims=[args.users, args.items],
        embed_out_dims=[16, 16],
        continuous_cols=["age"],
        label="label0")

    pairs = [to_user_item_feature(r, column_info) for r in rows]
    rs = np.random.RandomState(1)
    perm = rs.permutation(len(pairs))
    split = int(0.8 * len(pairs))
    train_pairs = [pairs[i] for i in perm[:split]]
    val_pairs = [pairs[i] for i in perm[split:]]
    x_train, y_train = features_to_arrays(train_pairs)
    x_val, y_val = features_to_arrays(val_pairs)
    print("train", len(train_pairs), "val", len(val_pairs),
          "wide width", x_train[0].shape, "deep width", x_train[1].shape)

    wnd = WideAndDeep(model_type=args.model_type, num_classes=5,
                      column_info=column_info, hidden_layers=(40, 20, 10))
    # log-softmax head + ClassNLL, the reference notebook's pairing
    wnd.compile(optimizer={"name": "adam", "lr": 1e-3},
                loss="class_nll", metrics=["mae", "accuracy"])
    if args.model_type != "wide_n_deep":
        idx = {"wide": 0, "deep": 1}[args.model_type]
        x_train, x_val = [x_train[idx]], [x_val[idx]]
    wnd.fit(x_train, y_train, batch_size=args.batch_size,
            nb_epoch=args.epochs, validation_data=(x_val, y_val))
    print("validation metrics:",
          wnd.evaluate(x_val, y_val, batch_size=args.batch_size))

    if args.model_type == "wide_n_deep":
        for p in wnd.predict_user_item_pair(val_pairs[:5]):
            print("pair", p)
        print("-- top-3 items per user --")
        for r in wnd.recommend_for_user(val_pairs, max_items=3)[:6]:
            print(f"user {r.user_id}: item {r.item_id} "
                  f"rating {r.prediction} (p={r.probability:.3f})")
        print("-- top-3 users per item --")
        for r in wnd.recommend_for_item(val_pairs, max_users=3)[:6]:
            print(f"item {r.item_id}: user {r.user_id} "
                  f"rating {r.prediction} (p={r.probability:.3f})")
    print("wide-n-deep app done")


if __name__ == "__main__":
    main()
